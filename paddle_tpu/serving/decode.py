"""Autoregressive decode runtime: KV-cache slot pool + continuous batching.

The serving stack's generation path. `InferenceServer` batches whole
forwards; a GPT completion served that way recomputes the full
[1, max_len] forward for every emitted token — O(T^2) model forwards at
batch 1. This module replaces that with the production decode shape:

  prefill  (one compiled program per PROMPT bucket): the prompt runs one
           causal forward and writes its per-layer K/V into a cache slot;
  decode   (ONE compiled program, ever): every engine tick runs a single
           fused step over ALL slots — each active slot contributes one
           query token against its cache row, masked by its own length.

The cache is a fixed pool of ``slots`` rows per layer
([slots, heads, max_len, d_head] persistable scope vars, device-resident
between steps). Admission writes a slot row, retirement just frees the
index — neither changes any compiled shape, so a churned request mix
holds the PR 7 strict-compile gate at zero steady-state recompiles by
construction. Decode is the bandwidth-bound regime (every token re-reads
the weights plus the cache; PAPERS "Operator Fusion in XLA"), which is
exactly why batching all slots into one step is the throughput lever:
the weight traffic amortizes over every live stream.

Layering: ``DecodeSession`` is the synchronous core (programs, cache
init, prefill / fused step) — ``gpt.greedy_generate`` drives a 1-slot
session inline; ``DecodeEngine`` owns the continuous-batching loop
(admission queue, slot scheduler, streaming) and is what
``InferenceServer.generate()`` fronts.
"""

from __future__ import annotations

import copy
import queue
import re
import threading
import time
from collections import deque

import numpy as np

import paddle_tpu.fluid as fluid

from ..fluid import flags as _flags
from ..fluid import profiler as _profiler
from ..models import gpt as _gpt
from ..observability import exporter as _obs_exporter
from ..observability import registry as _obs_registry
from ..observability import trace as _trace
from ..observability import xla_stats as _xla_stats
from .batcher import ServerOverloadedError, ServingError

__all__ = [
    "DecodeSession",
    "DecodeEngine",
    "GenerationStream",
    "prefill_ladder",
    "sample_token",
    "session_for_generate",
]


def _flag(name, override):
    return override if override is not None else _flags.get_flag(name)


def prefill_ladder(max_len, buckets=None):
    """Ascending prompt-length buckets, each a compiled prefill shape.
    ``buckets``: explicit list/CSV (``FLAGS_decode_prefill_buckets``), or
    None for the default powers-of-two ladder capped by (and always
    including) ``max_len`` — mirroring the batch ladder in buckets.py."""
    if isinstance(buckets, str):
        buckets = [int(b) for b in buckets.split(",") if b.strip()]
    if buckets:
        out = sorted(set(int(b) for b in buckets))
        if out[0] < 1:
            raise ValueError("prefill buckets must be positive: %r"
                             % (buckets,))
        kept = [b for b in out if b <= max_len]
        if len(kept) != len(out):
            import warnings

            # dropped, not fatal: FLAGS_decode_prefill_buckets may be
            # shared across engines with different max_len — but an
            # operator whose whole ladder exceeded max_len should hear
            # that every prompt will now pad to the full-length program
            warnings.warn(
                "prefill buckets %r exceed max_len %d and were dropped"
                "%s" % (
                    [b for b in out if b > max_len], max_len,
                    "; every prompt now pads to the full-length program"
                    if not kept else "",
                ), stacklevel=2)
        out = kept
        if not out or out[-1] != max_len:
            out.append(int(max_len))
        return out
    out = []
    b = 8
    while b < max_len:
        out.append(b)
        b *= 2
    out.append(int(max_len))
    return out


class DecodeSession(object):
    """Synchronous KV-cache decode core over one Executor + scope.

    Builds the bucketed prefill programs and the single fused decode-step
    program (all under fresh ``unique_name`` guards, so their parameter
    names are the canonical ``<layer>.w_0`` spellings), seeds the cache
    vars with zeros directly in the scope (no startup run — the scope's
    model params are someone else's and must not be re-initialized), and
    exposes ``prefill`` / ``decode_step``. Thread-compatible, not
    thread-safe: one driver at a time (the engine's loop thread, or the
    caller of ``greedy_generate``)."""

    def __init__(self, cfg, place=None, scope=None, slots=None,
                 max_len=None, prefill_buckets=None):
        self.cfg = copy.copy(cfg)
        self.cfg.is_test = True
        self.slots = int(_flag("decode_slots", slots))
        max_len = int(_flag("decode_max_len", max_len))
        if max_len <= 0:
            max_len = int(cfg.max_position_embeddings)
        if max_len > cfg.max_position_embeddings:
            raise ValueError(
                "decode max_len %d exceeds max_position_embeddings %d"
                % (max_len, cfg.max_position_embeddings)
            )
        if self.slots < 1 or max_len < 2:
            raise ValueError(
                "need slots >= 1 and max_len >= 2, got %d / %d"
                % (self.slots, max_len)
            )
        self.max_len = max_len
        self.buckets = prefill_ladder(
            max_len, _flag("decode_prefill_buckets", prefill_buckets) or None
        )
        self.place = place if place is not None else fluid.CPUPlace()
        self.scope = scope if scope is not None else fluid.core.Scope()
        # own executor: the session's program/plan caches never contend
        # with (or evict) a caller's LRU entries
        self.exe = fluid.Executor(self.place)
        # session-local activity tallies (the process-global profiler
        # counters aggregate every session in the process; per-engine
        # stats need the unshared view)
        self.prefills = 0
        self.steps = 0
        # one driver at a time: the engine's loop thread is naturally
        # exclusive, but greedy_generate funnels arbitrary caller
        # threads into one CACHED session per (scope, geometry) — they
        # serialize on this lock so interleaved prefill/decode_step
        # calls can never cross-contaminate the slot-0 cache
        self.lock = threading.RLock()
        self._prefill = {}
        for seq_len in self.buckets:
            with fluid.unique_name.guard():
                main, _startup, _feeds, next_logits = _gpt.build_gpt_prefill(
                    self.cfg, self.slots, seq_len, max_len
                )
            self._prefill[seq_len] = (main, next_logits.name)
        with fluid.unique_name.guard():
            main, _startup, _feeds, step_logits = _gpt.build_gpt_decode_step(
                self.cfg, self.slots, max_len
            )
        self._decode = (main, step_logits.name)
        self._cols = np.arange(max_len)
        self._pos_cache = {
            T: np.arange(T).reshape(1, T, 1).astype("int64")
            for T in self.buckets
        }
        self.reset_caches()

    # -- state ---------------------------------------------------------------
    def reset_caches(self):
        """Zero every cache var in the scope (host-side: no program, no
        param re-init). Correctness never depends on this — prefill
        replaces a slot's whole row — but fresh buffers make warmup and
        tests deterministic."""
        shape = _gpt.decode_cache_shape(self.cfg, self.slots, self.max_len)
        for k_name, v_name in _gpt.decode_cache_names(
            self.cfg, self.slots, self.max_len
        ):
            self.scope.set(k_name, np.zeros(shape, "float32"))
            self.scope.set(v_name, np.zeros(shape, "float32"))

    def bind_params(self, program):
        """Alias ``program``'s parameters onto this session's canonical
        names. A program built OUTSIDE a fresh ``unique_name.guard()``
        carries shifted numeric suffixes (``gpt_0_att_q.w_3``); the
        session's programs always say ``.w_0``. Aliasing the scope entry
        (same array object — params are read-only here) lets the decode
        runtime attach to any trained/initialized scope. Cheap;
        re-invoked per generate call so retrained params stay current.

        Contract: ``program`` is THE model of this scope — the alias
        targets the canonical name, so a scope deliberately holding two
        same-architecture models (one guard-built, one not) would see
        the guard-built one's params replaced by this program's. Give
        each model its own scope (the repo-wide convention) if both
        must stay live."""
        for v in program.list_vars():
            if not getattr(v, "is_parameter", False):
                continue
            canon = re.sub(r"_(\d+)$", "_0", v.name)
            if canon == v.name:
                continue
            val = self.scope.get(v.name)
            if val is not None:
                self.scope.set(canon, val)

    def bucket_for(self, prompt_len):
        for b in self.buckets:
            if b >= prompt_len:
                return b
        raise ValueError(
            "prompt of %d tokens exceeds the prefill ladder (max %d)"
            % (prompt_len, self.buckets[-1])
        )

    # -- device steps --------------------------------------------------------
    def prefill(self, slot, prompt_ids):
        """Run the prompt through the bucketed prefill program, writing
        slot ``slot``'s cache row; returns the next-token logits
        [vocab] at the last real prompt position."""
        P = len(prompt_ids)
        if not 0 <= slot < self.slots:
            raise ValueError("slot %d out of range" % slot)
        if P < 1:
            raise ValueError("empty prompt")
        T = self.bucket_for(P)
        main, fetch_name = self._prefill[T]
        ids = np.zeros((1, T, 1), "int64")
        ids[0, :P, 0] = prompt_ids
        mask = (np.arange(T) < P).astype("float32").reshape(1, T, 1)
        last_onehot = np.zeros((1, T, 1), "float32")
        last_onehot[0, P - 1, 0] = 1.0
        feed = {
            "ids": ids,
            "pos_ids": self._pos_cache[T],
            "input_mask": mask,
            "slot_idx": np.array([[slot]], "int64"),
            "last_onehot": last_onehot,
        }
        t0 = time.perf_counter()
        with _trace.span("decode_prefill", cat="serving", bucket=T, rows=P):
            (lv,) = self.exe.run(
                main, feed=feed, fetch_list=[fetch_name], scope=self.scope
            )
        _profiler.bump_counter("decode_prefills")
        self.prefills += 1
        _profiler.bump_histogram(
            "decode_prefill_ms", (time.perf_counter() - t0) * 1e3
        )
        return np.asarray(lv)[0]

    def decode_step(self, tokens, positions, active):
        """ONE fused step over all slots: slot i's ``tokens[i]`` lands at
        cache position ``positions[i]`` and its next-token logits come
        back; slots with ``active[i]`` False feed inert zeros (a free
        slot's dead cache row takes a masked position-0 write; its
        output is ignored and admission rewrites the row anyway).
        Returns logits [slots, vocab]."""
        act = np.asarray(active, bool)
        pos = np.where(act, np.asarray(positions, "int64"), 0)
        tok = np.where(act, np.asarray(tokens, "int64"), 0)
        key_bias = (
            ((self._cols[None, :] > pos[:, None]) | ~act[:, None])
            .astype("float32") * -1e4
        )
        main, fetch_name = self._decode
        feed = {
            "step_ids": tok.reshape(self.slots, 1, 1),
            "step_pos": pos.reshape(self.slots, 1, 1),
            "key_bias": key_bias,
        }
        t0 = time.perf_counter()
        with _trace.span(
            "decode_step", cat="serving", active=int(act.sum())
        ):
            (lv,) = self.exe.run(
                main, feed=feed, fetch_list=[fetch_name], scope=self.scope
            )
        _profiler.bump_counter("decode_steps")
        self.steps += 1
        _profiler.bump_histogram(
            "decode_step_ms", (time.perf_counter() - t0) * 1e3
        )
        return np.asarray(lv)


# -- greedy_generate's session cache ----------------------------------------
# stored ON the scope object (not in a module registry): a session holds
# a strong reference to its scope, so any global map — even weak-keyed —
# would pin every scope it ever saw (WeakKeyDictionary values that
# reference their key are never collected). As a scope attribute, the
# scope→session→scope cycle is ordinary garbage for the cycle collector
# and sessions really do die with the scope. Keyed by model geometry +
# flash policy so distinct configs in one scope never share programs.
_GEN_LOCK = threading.Lock()


def session_for_generate(exe, cfg, scope, max_len, param_program):
    scope_obj = scope if scope is not None else fluid.core.global_scope()
    key = (
        cfg.vocab_size, cfg.hidden_size, cfg.num_layers, cfg.num_heads,
        cfg.intermediate_size, cfg.max_position_embeddings,
        repr(getattr(cfg, "use_flash_attention", False)),
        bool(getattr(cfg, "flash_interpret", False)),
        int(max_len), type(exe.place).__name__,
    )
    with _GEN_LOCK:
        cache = getattr(scope_obj, "_decode_gen_sessions", None)
        if cache is None:
            cache = {"lock": threading.Lock(), "sessions": {}}
            scope_obj._decode_gen_sessions = cache
    # session construction (len(buckets)+1 graph builds) happens under
    # the PER-SCOPE lock only: first-time callers on unrelated scopes
    # build in parallel; same-scope callers serialize
    with cache["lock"]:
        sess = cache["sessions"].get(key)
        if sess is None:
            sess = DecodeSession(
                cfg, place=exe.place, scope=scope_obj, slots=1,
                max_len=max_len,
            )
            cache["sessions"][key] = sess
    sess.bind_params(param_program)
    return sess


# ---------------------------------------------------------------------------
# sampling — host-side, over the decode step's FETCHED logits
# ---------------------------------------------------------------------------


def sample_token(logits, temperature=0.0, top_k=0, top_p=0.0, rng=None):
    """Pick one token id from a ``[vocab]`` logits row.

    Host-side by design: the compiled prefill/decode programs already
    fetch the logits, so sampling over them adds zero graph surface — no
    new compiled program, no shape change, the strict-compile gate never
    sees it. ``temperature <= 0`` is GREEDY (argmax), the default
    everywhere, which keeps every token-exact parity contract intact;
    ``top_k``/``top_p`` only apply when temperature sampling is on.
    ``rng`` is a ``np.random.RandomState`` (seeded per request by the
    engine) so a given (prompt, knobs, seed) replays the same completion.
    Filtering order matches the common serving convention: temperature
    scale -> top-k cut -> softmax -> nucleus (top-p) cut -> renormalize.
    """
    z = np.asarray(logits, np.float64).ravel()
    if temperature is None or temperature <= 0.0:
        return int(z.argmax())
    z = z / float(temperature)
    if top_k and 0 < int(top_k) < z.size:
        kth = np.partition(z, -int(top_k))[-int(top_k)]
        z = np.where(z < kth, -np.inf, z)
    z = z - z.max()
    probs = np.exp(z)
    probs /= probs.sum()
    if top_p and 0.0 < float(top_p) < 1.0:
        order = np.argsort(-probs)
        csum = np.cumsum(probs[order])
        # keep the minimal prefix whose mass reaches top_p: a token stays
        # if the mass BEFORE it is still short of top_p (the first token
        # always stays, so the cut can never empty the distribution)
        drop = order[(csum - probs[order]) >= float(top_p)]
        probs[drop] = 0.0
        probs /= probs.sum()
    if not np.isfinite(probs).all():
        # a denormal temperature (1e-308) overflows the scaled logits to
        # inf and the softmax to NaN; fail THIS request loudly instead
        # of handing np.random.choice a poisoned distribution
        raise ValueError(
            "sampling produced non-finite probabilities "
            "(temperature %r too extreme for the logits)" % (temperature,)
        )
    r = rng if rng is not None else np.random
    return int(r.choice(probs.size, p=probs))


# ---------------------------------------------------------------------------
# streaming handle
# ---------------------------------------------------------------------------

_SENTINEL = object()


class GenerationStream(object):
    """Per-request streaming handle. The engine pushes tokens as they are
    generated; the caller iterates (``for tok in stream``) for live
    streaming, or blocks on ``tokens()`` / ``result()`` for the whole
    completion. Single consumer. ``finish_reason`` is ``"eos"`` /
    ``"length"`` once done."""

    def __init__(self, prompt_ids, max_new_tokens=None, eos_id=None,
                 temperature=0.0, top_k=0, top_p=0.0, seed=None):
        self.prompt_ids = [int(t) for t in prompt_ids]
        self.max_new_tokens = max_new_tokens
        self.eos_id = eos_id
        # sampling knobs (host-side over fetched logits — sample_token):
        # temperature <= 0 keeps the request greedy/argmax regardless of
        # top_k/top_p, so the token-exact default path is untouched. The
        # per-request RandomState makes a seeded request replay exactly
        # whatever other streams share its decode batch.
        self.temperature = float(temperature or 0.0)
        self.top_k = int(top_k or 0)
        self.top_p = float(top_p or 0.0)
        self.seed = seed
        self._rng = (
            np.random.RandomState(seed) if self.temperature > 0.0 else None
        )
        self.finish_reason = None
        # engine tick bookkeeping (scheduler tests / fairness probes):
        # the tick a slot was admitted on and the last tick it decoded on
        self.first_tick = None
        self.last_tick = None
        self._q = queue.Queue()
        self._tokens = []
        self._done = threading.Event()
        self._error = None
        self._cancelled = False

    def cancel(self):
        """Abandon the request: the engine retires its slot at the next
        tick boundary (finish_reason ``"cancelled"``) instead of
        decoding tokens nobody will read — a transport whose client
        timed out or disconnected MUST call this, or dead requests keep
        occupying decode slots to completion. Safe from any thread,
        idempotent, a no-op once the stream already finished."""
        self._cancelled = True

    # engine side
    def pick(self, logits):
        """Select this request's next token from a ``[vocab]`` logits
        row: greedy argmax unless the request armed temperature
        sampling (then ``sample_token`` with the per-request RNG)."""
        if self._rng is None:
            return int(np.asarray(logits).ravel().argmax())
        return sample_token(logits, temperature=self.temperature,
                            top_k=self.top_k, top_p=self.top_p,
                            rng=self._rng)

    def _push(self, tok):
        self._tokens.append(int(tok))
        self._q.put(int(tok))

    def _finish(self, reason):
        self.finish_reason = reason
        self._done.set()
        self._q.put(_SENTINEL)

    def _fail(self, exc):
        self._error = exc
        self._done.set()
        self._q.put(_SENTINEL)

    # consumer side
    @property
    def done(self):
        return self._done.is_set()

    def __iter__(self):
        return self.stream_tokens(timeout=None)

    def stream_tokens(self, timeout=None):
        """Like iteration, but the WHOLE stream must finish within
        ``timeout`` seconds (None = unbounded): raises ``TimeoutError``
        mid-iteration when the budget runs out, so a transport (the HTTP
        gateway's SSE writer) can bound a wedged stream instead of
        holding its connection open forever. Single consumer — don't mix
        with ``__iter__`` on the same stream."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            remaining = None
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError("generation still in flight")
            try:
                item = self._q.get(timeout=remaining)
            except queue.Empty:
                raise TimeoutError("generation still in flight")
            if item is _SENTINEL:
                if self._error is not None:
                    raise self._error
                return
            yield item

    def tokens(self, timeout=None):
        """Block until the request finishes; returns the GENERATED tokens
        (prompt excluded)."""
        if not self._done.wait(timeout):
            raise TimeoutError("generation still in flight")
        if self._error is not None:
            raise self._error
        return list(self._tokens)

    def result(self, timeout=None):
        """prompt + generated tokens — ``greedy_generate``'s contract."""
        return self.prompt_ids + self.tokens(timeout)


class _Slot(object):
    __slots__ = ("stream", "pending_token", "next_pos", "generated")

    def __init__(self, stream, pending_token, next_pos):
        self.stream = stream
        self.pending_token = pending_token  # emitted, not yet cached
        self.next_pos = next_pos            # cache position it writes next
        self.generated = 1                  # prefill already emitted one


# ---------------------------------------------------------------------------
# continuous-batching engine
# ---------------------------------------------------------------------------


class DecodeEngine(object):
    """Continuous batching over a ``DecodeSession`` slot pool.

    One loop thread ticks: admit queued requests into free slots via
    prefill (mid-flight — active streams keep decoding across
    admissions), then run ONE fused decode step for every active slot,
    stream each new token out, and retire slots on EOS / max-tokens /
    max-length. Greedy (argmax) decoding — token-exact with
    ``gpt._reference_generate``.

    ``start()`` eagerly compiles every prefill bucket and the decode
    step inside a warmup window, then arms the PR 7 counted strict
    serving gate: with ``FLAGS_serving_strict_compiles`` any later
    request-path XLA compile raises ``SteadyStateRecompileError`` with
    the sentinel's attribution. Admission/retirement churn cannot trip
    it — no compiled shape depends on which slots are live."""

    def __init__(self, cfg, place=None, scope=None, slots=None,
                 max_len=None, prefill_buckets=None, queue_depth=None,
                 param_program=None):
        self._cfg = cfg
        self._place = place
        self._scope = scope
        self._slots_arg = slots
        self._max_len_arg = max_len
        self._buckets_arg = prefill_buckets
        self.queue_depth = int(_flag("decode_queue_depth", queue_depth))
        self._param_program = param_program
        self.session = None
        self.started = False
        self.tick = 0
        self._pending = deque()
        self._active = {}
        self._free = []
        self._cond = threading.Condition()
        self._stop = False
        self._thread = None
        # engine-local tallies: stats() must report THIS engine, not the
        # process-global counters shared with sibling sessions/engines
        self._counts = {"requests": 0, "admissions": 0,
                        "retirements": 0, "tokens": 0}
        self._armed = False
        self._occ_gauge = None
        self._queue_gauge = None

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        if self.started:
            raise RuntimeError("decode engine already started")
        if self._thread is not None and self._thread.is_alive():
            # a previous stop()'s thread-join timed out (loop wedged in a
            # device call): refuse to spawn a second driver for the
            # (thread-unsafe) session — _stop stays latched, so the old
            # thread exits at its next loop-top check and a later start
            # succeeds
            raise RuntimeError(
                "previous decode-engine loop thread has not exited yet"
            )
        self.session = DecodeSession(
            self._cfg, place=self._place, scope=self._scope,
            slots=self._slots_arg, max_len=self._max_len_arg,
            prefill_buckets=self._buckets_arg,
        )
        if self._param_program is not None:
            self.session.bind_params(self._param_program)
        self._warmup()
        self._free = list(range(self.session.slots))
        self._stop = False
        try:
            # telemetry mirrors InferenceServer: exporter lights up from
            # flags, occupancy/queue depth publish as scrape-time gauges,
            # and the steady-compile gate arms COUNTED (ownership-scoped)
            _obs_exporter.maybe_start_from_flags()
            self._occ_gauge = lambda e=self: len(e._active)
            _obs_registry.register_gauge(
                "serving_slot_occupancy", self._occ_gauge
            )
            self._queue_gauge = lambda e=self: len(e._pending)
            _obs_registry.register_gauge(
                "decode_queue_depth", self._queue_gauge
            )
            _xla_stats.arm_serving_steady()
            self._armed = True
            self._thread = threading.Thread(
                target=self._loop, name="decode-engine", daemon=True
            )
            self._thread.start()
            # LAST: a half-started engine must never look started — a
            # failure above (thread exhaustion, gauge clash) would
            # otherwise leave submits feeding a queue nothing drains
            self.started = True
        except Exception:
            if self._armed:
                _xla_stats.disarm_serving_steady()
                self._armed = False
            if self._occ_gauge is not None:
                _obs_registry.unregister_gauge(
                    "serving_slot_occupancy", self._occ_gauge
                )
                self._occ_gauge = None
            if self._queue_gauge is not None:
                _obs_registry.unregister_gauge(
                    "decode_queue_depth", self._queue_gauge
                )
                self._queue_gauge = None
            raise
        return self

    def _warmup(self):
        """Compile every shape the steady state can touch: each prefill
        bucket once, the decode step once (its compiled shape is
        independent of WHICH slots are active, so one all-inactive step
        covers every future mix). Cache state is reset afterwards."""
        sess = self.session
        with _xla_stats.warmup_window(), _trace.span(
            "decode_warmup", cat="serving"
        ):
            for T in sess.buckets:
                P = min(T, sess.max_len - 1)
                sess.prefill(0, [0] * P)
            sess.decode_step(
                [0] * sess.slots, [0] * sess.slots, [False] * sess.slots
            )
            sess.reset_caches()

    def stop(self):
        if not self.started:
            return
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30)
            # a still-wedged loop thread keeps its handle: start()
            # refuses to run a second driver beside it (see start())
            if not self._thread.is_alive():
                self._thread = None
        if self._armed:
            _xla_stats.disarm_serving_steady()
            self._armed = False
        if self._occ_gauge is not None:
            _obs_registry.unregister_gauge(
                "serving_slot_occupancy", self._occ_gauge
            )
            self._occ_gauge = None
        if self._queue_gauge is not None:
            _obs_registry.unregister_gauge(
                "decode_queue_depth", self._queue_gauge
            )
            self._queue_gauge = None
        # drain under the SAME lock submit() enqueues under, and flip
        # started inside it: a submit racing this stop either lands
        # before the drain (failed here) or observes stopped and raises —
        # it can never strand an unserved stream in a dead queue
        with self._cond:
            failed = list(self._active.values())
            self._active.clear()
            pending = list(self._pending)
            self._pending.clear()
            self.started = False
        err = ServingError("decode engine stopped")
        for slot in failed:
            slot.stream._fail(err)
        for stream in pending:
            stream._fail(err)

    def __enter__(self):
        return self if self.started else self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- request path --------------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens=None, eos_id=None,
               temperature=0.0, top_k=0, top_p=0.0, seed=None):
        """Non-blocking admission; returns a ``GenerationStream``.
        Bounded queue: beyond ``queue_depth`` waiting requests, sheds
        with ``ServerOverloadedError`` (same backpressure contract as
        the micro-batcher). Sampling knobs are per-request and host-side
        (``sample_token``): greedy (``temperature=0``) is the default,
        and a seeded sampling request replays deterministically."""
        prompt = [int(t) for t in prompt_ids]
        if not prompt:
            raise ValueError("empty prompt")
        if not self.started or self.session is None:
            raise ServingError("decode engine not started")
        if len(prompt) >= self.session.max_len:
            raise ValueError(
                "prompt of %d tokens leaves no room to generate "
                "(max_len %d)" % (len(prompt), self.session.max_len)
            )
        self.session.bucket_for(len(prompt))  # validates against the ladder
        if max_new_tokens is not None and max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        stream = GenerationStream(prompt, max_new_tokens=max_new_tokens,
                                  eos_id=eos_id, temperature=temperature,
                                  top_k=top_k, top_p=top_p, seed=seed)
        with self._cond:
            # re-checked under the lock stop() drains under: after the
            # drain, started is already False here and the stream can
            # never be stranded in a dead queue
            if not self.started or self._stop:
                raise ServingError("decode engine stopped")
            if len(self._pending) >= self.queue_depth:
                raise ServerOverloadedError(
                    "decode admission queue full (%d pending)"
                    % len(self._pending),
                    retry_after_ms=50,
                )
            self._pending.append(stream)
            # inside the lock: _counts is read-modify-write from
            # arbitrary caller threads here (everything else touching it
            # is the loop thread)
            self._counts["requests"] += 1
            self._cond.notify_all()
        _profiler.bump_counter("decode_requests")
        return stream

    def generate(self, prompt_ids, max_new_tokens=None, eos_id=None,
                 temperature=0.0, top_k=0, top_p=0.0, seed=None):
        """Submit and return the streaming handle (iterate for tokens as
        they land; ``.tokens()`` / ``.result()`` to block)."""
        return self.submit(prompt_ids, max_new_tokens=max_new_tokens,
                           eos_id=eos_id, temperature=temperature,
                           top_k=top_k, top_p=top_p, seed=seed)

    def stats(self):
        """THIS engine's counters + live occupancy snapshot (the
        process-global profiler counters additionally aggregate every
        other decode session in the process — e.g. greedy_generate's
        cached 1-slot sessions)."""
        return {
            "slots": self.session.slots if self.session else 0,
            "active": len(self._active),
            "queued": len(self._pending),
            "ticks": self.tick,
            "requests": self._counts["requests"],
            "prefills": self.session.prefills if self.session else 0,
            "steps": self.session.steps if self.session else 0,
            "tokens": self._counts["tokens"],
            "admissions": self._counts["admissions"],
            "retirements": self._counts["retirements"],
        }

    # -- engine loop ---------------------------------------------------------
    def _loop(self):
        while True:
            with self._cond:
                while (not self._stop and not self._pending
                       and not self._active):
                    self._cond.wait()
                if self._stop:
                    return
            try:
                self._reap_cancelled()
                self._admit()
                if self._active:
                    self._step()
            except Exception as e:  # noqa: BLE001 - fail the live streams
                # a failed device step (incl. SteadyStateRecompileError
                # from the strict gate) fails the requests it was serving;
                # the engine itself stays up for the next submission. The
                # freed slots COUNT as retirements so the documented
                # admissions == retirements + occupancy invariant holds
                # across recovered failures
                for slot in list(self._active.values()):
                    slot.stream._fail(e)
                    _profiler.bump_counter("serving_slot_retirements")
                    self._counts["retirements"] += 1
                self._free.extend(self._active.keys())
                self._active.clear()

    def _reap_cancelled(self):
        """Retire slots whose consumer abandoned the stream (transport
        timeout / client disconnect) — BEFORE spending a prefill or a
        decode step on them. Freed slots count as retirements so the
        admissions == retirements + occupancy invariant holds. The
        PENDING queue is swept too: a request cancelled while queued
        must release its bounded-admission-queue entry immediately, not
        sit shedding live traffic with 429s until a slot frees."""
        for idx, slot in list(self._active.items()):
            if slot.stream._cancelled:
                self._active.pop(idx, None)
                self._free.append(idx)
                _profiler.bump_counter("serving_slot_retirements")
                self._counts["retirements"] += 1
                slot.stream._finish("cancelled")
        with self._cond:
            if any(s._cancelled for s in self._pending):
                live = deque()
                for s in self._pending:
                    if s._cancelled:
                        s._finish("cancelled")
                    else:
                        live.append(s)
                self._pending = live

    def _admit(self):
        """Prefill queued requests into free slots — mid-flight, between
        decode steps, never evicting an active stream."""
        while self._free:
            with self._cond:
                if not self._pending:
                    return
                stream = self._pending.popleft()
            if stream._cancelled:
                # cancelled while queued: never admitted, so no slot,
                # no retirement tally — just finish the dead handle
                stream._finish("cancelled")
                continue
            slot_idx = self._free.pop()
            try:
                with _xla_stats.serving_request_window():
                    logits = self.session.prefill(
                        slot_idx, stream.prompt_ids
                    )
                # pick() INSIDE the per-request guard: a poisoned
                # sampling request (e.g. a denormal temperature) must
                # fail alone, not escape to the loop's handler and take
                # every co-batched stream down with it
                tok = stream.pick(logits)
            except Exception as e:  # noqa: BLE001 - per-request failure
                self._free.append(slot_idx)
                stream._fail(e)
                continue
            slot = _Slot(stream, tok, next_pos=len(stream.prompt_ids))
            with self._cond:
                # stop() drains under this lock and flips started inside
                # it: if the drain happened while the prefill above was
                # in flight (stop's thread-join timed out), inserting
                # now would strand the stream in a dead engine — fail it
                # here instead
                if self._stop or not self.started:
                    self._free.append(slot_idx)
                    stream._fail(ServingError("decode engine stopped"))
                    continue
                self._active[slot_idx] = slot
            _profiler.bump_counter("serving_slot_admissions")
            self._counts["admissions"] += 1
            stream.first_tick = self.tick
            self._emit(slot_idx, slot, tok)

    def _emit(self, slot_idx, slot, tok):
        """Stream one generated token and retire the slot if finished."""
        stream = slot.stream
        stream._push(tok)
        stream.last_tick = self.tick
        _profiler.bump_counter("decode_tokens")
        self._counts["tokens"] += 1
        reason = None
        if stream.eos_id is not None and tok == stream.eos_id:
            reason = "eos"
        elif (stream.max_new_tokens is not None
              and slot.generated >= stream.max_new_tokens):
            reason = "length"
        elif len(stream.prompt_ids) + slot.generated >= self.session.max_len:
            reason = "length"
        if reason is not None:
            # pop, not del: a stop() whose thread-join timed out may have
            # drained _active concurrently
            self._active.pop(slot_idx, None)
            self._free.append(slot_idx)
            _profiler.bump_counter("serving_slot_retirements")
            self._counts["retirements"] += 1
            stream._finish(reason)

    def _step(self):
        """One fused decode step over every active slot."""
        sess = self.session
        tokens = [0] * sess.slots
        positions = [0] * sess.slots
        active = [False] * sess.slots
        for idx, slot in self._active.items():
            tokens[idx] = slot.pending_token
            positions[idx] = slot.next_pos
            active[idx] = True
        with _xla_stats.serving_request_window():
            logits = sess.decode_step(tokens, positions, active)
        self.tick += 1
        for idx in list(self._active.keys()):
            slot = self._active[idx]
            try:
                tok = slot.stream.pick(logits[idx])
            except Exception as e:  # noqa: BLE001 - fail THIS stream only
                self._active.pop(idx, None)
                self._free.append(idx)
                _profiler.bump_counter("serving_slot_retirements")
                self._counts["retirements"] += 1
                slot.stream._fail(e)
                continue
            slot.next_pos += 1
            slot.generated += 1
            slot.pending_token = tok
            self._emit(idx, slot, tok)
