"""Padding-bucket shape planner.

Every novel input shape reaching XLA costs a compile on the request
critical path — the dominant cost for small inference graphs ("Operator
Fusion in XLA: Analysis and Evaluation", PAPERS.md). The planner rounds a
coalesced micro-batch's (batch, seq) up to a small fixed ladder of
buckets, so every steady-state request hits an executable that was
already compiled (the server warms the whole ladder eagerly at start),
and records exactly what was padded so the rows/tokens can be stripped
before results return to callers.

Batch padding replicates the last valid row (edge padding): replicated
rows travel through ANY model without numeric hazards (no zero rows
hitting a layer_norm denominator or an embedding lookup with id 0
semantics) and are sliced off before anyone sees them. Sequence-axis
padding is opt-in (`seq_buckets`) because it is only sound for models
that mask padded positions; integer feeds pad with `seq_pad_value`
(e.g. a pad token id) and float feeds (masks) pad with zeros, which is
precisely the masked-position convention of the repo's BERT/GPT models.
"""

from __future__ import annotations

import numpy as np

__all__ = ["BucketLadder", "BatchPlan"]


def _default_batch_buckets(max_batch):
    """1, 2, 4, ... up to and including max_batch (always included, so the
    coalescer's fullest batch maps onto a bucket)."""
    out = []
    b = 1
    while b < max_batch:
        out.append(b)
        b *= 2
    out.append(max_batch)
    return out


class BatchPlan(object):
    """What one padded dispatch looked like — consumed by unpad_outputs
    and by the fill-ratio metrics."""

    __slots__ = ("rows", "padded_rows", "seq", "padded_seq", "seq_axis")

    def __init__(self, rows, padded_rows, seq=None, padded_seq=None,
                 seq_axis=1):
        self.rows = rows
        self.padded_rows = padded_rows
        self.seq = seq
        self.padded_seq = padded_seq
        self.seq_axis = seq_axis


class BucketLadder(object):
    """Rounds (batch, seq) up to fixed buckets; pads and unpads feeds.

    ``batch_buckets``: ascending batch sizes (default powers of two up to
    ``max_batch``). ``seq_buckets``: optional ascending sequence lengths;
    when given, feeds of rank >= 2 are padded along ``seq_axis``.
    """

    def __init__(self, max_batch=8, batch_buckets=None, seq_buckets=None,
                 seq_axis=1, seq_pad_value=0, trim_seq_outputs=True):
        if batch_buckets is None:
            batch_buckets = _default_batch_buckets(int(max_batch))
        self.batch_buckets = sorted(set(int(b) for b in batch_buckets))
        if not self.batch_buckets or self.batch_buckets[0] < 1:
            raise ValueError("batch_buckets must be positive: %r"
                             % (batch_buckets,))
        self.seq_buckets = (
            sorted(set(int(s) for s in seq_buckets)) if seq_buckets else None
        )
        self.seq_axis = int(seq_axis)
        self.seq_pad_value = seq_pad_value
        self.trim_seq_outputs = bool(trim_seq_outputs)

    @property
    def max_batch(self):
        return self.batch_buckets[-1]

    def batch_bucket(self, rows):
        """Smallest bucket >= rows. rows beyond the ladder is an admission
        error (the coalescer caps batches at max_batch)."""
        for b in self.batch_buckets:
            if b >= rows:
                return b
        raise ValueError(
            "batch of %d rows exceeds the bucket ladder (max %d)"
            % (rows, self.max_batch)
        )

    def seq_bucket(self, seq):
        for s in self.seq_buckets:
            if s >= seq:
                return s
        raise ValueError(
            "sequence length %d exceeds the bucket ladder (max %d)"
            % (seq, self.seq_buckets[-1])
        )

    def shapes(self):
        """Every (padded_rows, padded_seq) combination on the ladder —
        the eager-warmup set. padded_seq is None without seq bucketing."""
        if self.seq_buckets is None:
            return [(b, None) for b in self.batch_buckets]
        return [(b, s) for b in self.batch_buckets
                for s in self.seq_buckets]

    # -- pad / unpad ---------------------------------------------------------
    def plan(self, feeds):
        """BatchPlan for a list of stacked per-feed arrays (row-major on
        axis 0; all feeds carry the same row count)."""
        rows = int(np.shape(feeds[0])[0])
        seq = padded_seq = None
        if self.seq_buckets is not None:
            lens = [int(a.shape[self.seq_axis]) for a in feeds
                    if np.ndim(a) > self.seq_axis]
            if lens:
                seq = max(lens)
                padded_seq = self.seq_bucket(seq)
        return BatchPlan(rows, self.batch_bucket(rows), seq, padded_seq,
                         self.seq_axis)

    def pad_feeds(self, feeds, plan=None):
        """(padded_feeds, plan). Rows pad by edge replication; the seq
        axis (when bucketed) pads ints with seq_pad_value and floats with
        zeros."""
        feeds = [np.asarray(a) for a in feeds]
        if plan is None:
            plan = self.plan(feeds)
        out = []
        for a in feeds:
            if (plan.padded_seq is not None and np.ndim(a) > self.seq_axis
                    and a.shape[self.seq_axis] < plan.padded_seq):
                width = [(0, 0)] * a.ndim
                width[self.seq_axis] = (
                    0, plan.padded_seq - a.shape[self.seq_axis]
                )
                fill = (self.seq_pad_value
                        if np.issubdtype(a.dtype, np.integer) else 0)
                a = np.pad(a, width, mode="constant", constant_values=fill)
            if a.shape[0] < plan.padded_rows:
                width = [(0, 0)] * a.ndim
                width[0] = (0, plan.padded_rows - a.shape[0])
                a = np.pad(a, width, mode="edge")
            out.append(a)
        return out, plan

    def unpad_outputs(self, outputs, plan):
        """Strip the padding the plan added: outputs whose axis 0 equals
        the padded row count lose the replica rows; outputs carrying the
        padded seq length on seq_axis lose the padded positions. Outputs
        with neither (scalars, reductions) pass through — but a scalar
        from a row-padded batch AGGREGATED the replica rows, which cannot
        be undone here; serve per-row outputs.

        Seq trimming is by SHAPE MATCH on seq_axis: a non-sequence output
        dimension that happens to equal the padded seq length (e.g.
        num_classes == a seq bucket) would be trimmed too. Models with
        such colliding output shapes must build the ladder with
        ``trim_seq_outputs=False`` and strip seq padding themselves."""
        out = []
        for a in outputs:
            a = np.asarray(a)
            if (plan.padded_rows != plan.rows and a.ndim >= 1
                    and a.shape[0] == plan.padded_rows):
                a = a[: plan.rows]
            if (self.trim_seq_outputs
                    and plan.padded_seq is not None
                    and plan.padded_seq != plan.seq
                    and a.ndim > self.seq_axis
                    and a.shape[self.seq_axis] == plan.padded_seq):
                idx = [slice(None)] * a.ndim
                idx[self.seq_axis] = slice(0, plan.seq)
                a = a[tuple(idx)]
            out.append(a)
        return out
