"""HTTP serving gateway — the multi-tenant network front door.

Everything below this module is in-process: the micro-batcher, the
bucket ladder, the KV-cache decode engine, the strict compile gate. The
gateway is the integration layer that turns them into a *service* — the
shape the reference era shipped as Paddle Serving fronting the
AnalysisPredictor C-API surface this repo reproduces:

  HTTP client --> [admission control] --> InferenceServer.infer()
                    |                      (micro-batcher + buckets)
                    +-----------------> InferenceServer.generate()
                                           (DecodeEngine, SSE stream)

Endpoints (stdlib ``http.server`` threaded listener, one handler thread
per in-flight request):

- ``POST /v1/infer`` — JSON tensors in, JSON tensors out, through the
  dynamic batcher (concurrent HTTP clients coalesce into device
  batches exactly like in-process callers);
- ``POST /v1/generate`` — prompt ids in; chunked **SSE** token stream
  out (one ``data:`` event per generated token riding the engine's
  ``GenerationStream``), or a single JSON body with ``"stream": false``;
- ``GET /healthz`` — liveness (always 200 while the process runs);
- ``GET /readyz`` — readiness; flips 503 the moment the PR 3 preemption
  latch is set (``checkpoint.preempt``) or a drain begins, so a load
  balancer stops routing BEFORE the listener closes — the same latch
  the observability exporter's ``/healthz`` reads.

Admission control sits in FRONT of the engine, per tenant
(``X-Tenant-Id`` header, "anon" when absent):

- token-bucket rate limit (``FLAGS_gateway_rate_limit_rps`` refill,
  ``FLAGS_gateway_rate_burst`` capacity) — over it, 429 + Retry-After;
- max-inflight quota (``FLAGS_gateway_tenant_max_inflight``) — a
  flooding tenant 429s at its own quota instead of starving the rest;
- a global cap (``FLAGS_gateway_max_inflight``): beyond it requests
  WAIT in priority order — ``X-Priority: interactive`` (default) is
  granted freed slots before ``batch`` — up to
  ``FLAGS_gateway_admit_timeout_ms``, then shed.

Engine backpressure maps faithfully: ``ServerOverloadedError`` (shed at
admission by the batcher/engine) -> 429 with the engine's own
retry-after hint; ``DeadlineExceededError`` (shed at dispatch) -> 504.
The two shed points stay distinguishable in metrics
(``gateway_shed_admission`` vs ``gateway_shed_dispatch``).

Every request gets an id (``X-Request-Id`` or generated), one JSONL
access-log line (``FLAGS_gateway_access_log``), a ``gateway_request``
span on the handler thread (it time-contains the batcher's
``serving_dispatch``/``predictor_run`` spans, which run on their worker
threads — Perfetto lines them up by containment), and ``gateway_*``
counters/histograms on the PR 5 registry, so the existing ``/metrics``
exporter publishes per-tenant request/shed/latency with no extra
wiring.

Graceful drain: ``stop()`` (or SIGTERM via ``install_sigterm()``, which
sets the shared preemption latch) flips ``/readyz`` to 503, rejects new
work with 503, waits for every in-flight request — including mid-flight
SSE streams — to complete (bounded by ``FLAGS_gateway_drain_timeout_s``),
and only then closes the listener.
"""

from __future__ import annotations

import http.client
import inspect
import itertools
import json
import os
import signal
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from ..checkpoint import preempt as _preempt
from ..fluid import flags as _flags
from ..fluid import profiler as _profiler
from ..testing import chaos as _chaos
from ..observability import exporter as _obs_exporter
from ..observability import flight as _flight
from ..observability import registry as _obs_registry
from ..observability import trace as _trace
from . import kv_tier as _kv_tier
from .access_log import AccessLog
from .batcher import (
    DeadlineExceededError,
    ServerOverloadedError,
    ServingError,
)

__all__ = ["Gateway", "encode_tensor", "decode_tensor"]


def _flag(name, override):
    return override if override is not None else _flags.get_flag(name)


# -- JSON tensor wire format -------------------------------------------------
# {"data": <nested lists>, "dtype": "float32", "shape": [2, 3]} — shape
# optional (inferred from nesting), dtype defaults to float32. Exact for
# float32: every float32 is exactly a double, json round-trips the
# double, and the cast back recovers the original bits.


def decode_tensor(obj):
    if not isinstance(obj, dict) or "data" not in obj:
        raise ValueError(
            "tensor must be {'data': ..., 'dtype': ..., 'shape': ...}"
        )
    try:
        # `or`: a JSON null dtype means "default" (float32), it must
        # not fall through to np.dtype(None) == float64
        dt = np.dtype(obj.get("dtype") or "float32")
    except TypeError:
        # np.dtype raises TypeError for unknown names; a malformed
        # client body must map to 400, not the generic 500 path
        raise ValueError("unknown dtype %r" % (obj.get("dtype"),))
    try:
        arr = np.asarray(obj["data"], dtype=dt)
    except (TypeError, ValueError):
        raise ValueError("tensor data does not parse as %s" % dt)
    if obj.get("shape") is not None:
        arr = arr.reshape([int(d) for d in obj["shape"]])
    return arr


def encode_tensor(arr):
    arr = np.asarray(arr)
    return {"data": arr.tolist(), "shape": list(arr.shape),
            "dtype": str(arr.dtype)}


_SCHED_KW_CACHE = {}


def _accepts_sched_kwargs(fn):
    """True when ``fn`` (a server's generate) can take the scheduling
    identity kwargs (priority/tenant) — explicitly or via **kwargs.
    Cached by the bound method's underlying function."""
    key = getattr(fn, "__func__", fn)
    hit = _SCHED_KW_CACHE.get(key)
    if hit is None:
        try:
            sig = inspect.signature(fn)
            params = sig.parameters.values()
            hit = any(p.kind is inspect.Parameter.VAR_KEYWORD
                      for p in params) or (
                "priority" in sig.parameters
                and "tenant" in sig.parameters)
        except (TypeError, ValueError):
            hit = False
        _SCHED_KW_CACHE[key] = hit
        if len(_SCHED_KW_CACHE) > 256:  # bespoke-fake churn bound
            _SCHED_KW_CACHE.clear()
            _SCHED_KW_CACHE[key] = hit
    return hit


# -- admission control -------------------------------------------------------


# request bodies are buffered in the handler thread: bound them so a
# client-supplied Content-Length cannot OOM the process (same
# client-controlled-resource class as the tenant-table cap)
_MAX_BODY_BYTES = 64 * 1024 * 1024


class _PayloadTooLarge(ValueError):
    """Request body over _MAX_BODY_BYTES — mapped to HTTP 413."""


class _AdmissionDenied(ServingError):
    """Internal: request shed at GATEWAY admission (never dispatched).
    ``reason`` in {"ratelimit", "quota", "overload"}."""

    def __init__(self, reason, msg, retry_after_ms=1000):
        super().__init__(msg)
        self.reason = reason
        self.retry_after_ms = max(1, int(retry_after_ms))


class _TokenBucket(object):
    """Classic token bucket: ``rate`` tokens/sec refill into ``burst``
    capacity; one token per request. Not thread-safe on its own — the
    controller's lock serializes access. ``clock`` is injectable (the
    fleet simulator feeds its virtual clock; default wall monotonic)."""

    __slots__ = ("rate", "burst", "tokens", "t", "_clock")

    def __init__(self, rate, burst, clock=None):
        self.rate = float(rate)
        self.burst = float(max(1, burst))
        self.tokens = self.burst
        self._clock = clock or time.monotonic
        self.t = self._clock()

    def try_take(self):
        """None on success, else seconds until a token is available."""
        now = self._clock()
        self.tokens = min(self.burst,
                          self.tokens + (now - self.t) * self.rate)
        self.t = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return None
        return (1.0 - self.tokens) / self.rate


# shared rate bucket for the >_MAX_TRACKED_TENANTS long tail — a
# sentinel key no client-supplied tenant string can equal
_OVERFLOW_BUCKET = object()


class _Admission(object):
    """Per-tenant rate limit + inflight quota + global cap with
    priority-ordered waiting. ``admit()`` either returns (after
    reserving an inflight slot) or raises ``_AdmissionDenied``;
    ``release()`` frees the slot and wakes waiters — interactive
    waiters are granted freed capacity before batch waiters.

    The decision chain lives in small ``*_locked`` primitives so two
    callers share ONE policy: the gateway's blocking ``admit()`` and
    the fleet simulator's event-driven ``try_admit``/``try_grant``
    (which parks virtually instead of on the condition). ``clock`` is
    injectable for the same reason — the simulator feeds its virtual
    clock and the rate buckets/deadlines follow it."""

    def __init__(self, rate_rps, burst, tenant_max_inflight, max_inflight,
                 admit_timeout_ms, clock=None):
        self.rate_rps = float(rate_rps)
        self.burst = int(burst)
        self.tenant_max = int(tenant_max_inflight)
        self.global_max = int(max_inflight)
        self.admit_timeout_s = float(admit_timeout_ms) / 1e3
        self._clock = clock or time.monotonic
        self._buckets = {}
        self._inflight = {}
        self._total = 0
        self._waiting = {"interactive": 0, "batch": 0}
        self._cond = threading.Condition()

    @property
    def total_inflight(self):
        with self._cond:
            return self._total

    def waiting_by_class(self):
        """{priority_class: parked-waiter count}: the QUEUED (not yet
        admitted) pressure — what the ``gateway_admit_waiting`` gauges
        export and the SLO policy / simulator read. Grant-time ordering
        alone made this invisible: a batch flood parked on the cap
        looked identical to an idle gateway."""
        with self._cond:
            return dict(self._waiting)

    def _check_rate_locked(self, tenant):
        # rate limit: cheapest check first, fail fast with the
        # bucket's own refill estimate as the retry hint. Buckets
        # key on the RAW tenant name but bounded (the header is
        # client data): past _MAX_TRACKED_TENANTS distinct
        # tenants the long tail shares one sentinel-keyed
        # overflow bucket — a sentinel, not a name, so no real
        # tenant (not even one literally called "overflow") can
        # collide into it, and sanitization collisions ("a-b" vs
        # "a.b") can't couple two tenants' rates
        if self.rate_rps <= 0:
            return
        key = tenant
        if (key not in self._buckets
                and len(self._buckets) >= _MAX_TRACKED_TENANTS):
            key = _OVERFLOW_BUCKET
        bucket = self._buckets.get(key)
        if bucket is None:
            bucket = self._buckets[key] = _TokenBucket(
                self.rate_rps, self.burst, clock=self._clock
            )
        wait_s = bucket.try_take()
        if wait_s is not None:
            raise _AdmissionDenied(
                "ratelimit",
                "tenant %r over %.3g req/s rate limit" %
                (tenant, self.rate_rps),
                retry_after_ms=wait_s * 1e3,
            )

    def _check_quota_locked(self, tenant):
        # tenant quota: the isolation knob — one tenant's flood caps at
        # its own share, the others' headroom survives
        if (self.tenant_max > 0
                and self._inflight.get(tenant, 0) >= self.tenant_max):
            raise _AdmissionDenied(
                "quota",
                "tenant %r at max inflight %d" %
                (tenant, self.tenant_max),
                # a slot frees when one of the tenant's own requests
                # completes; no better estimate than "soon"
                retry_after_ms=50,
            )

    def _cap_blocked_locked(self, cls):
        # global cap, interactive ahead of batch — a batch request only
        # takes capacity while no interactive request is waiting
        return self._total >= self.global_max or (
            cls == "batch" and self._waiting["interactive"] > 0
        )

    def _grant_locked(self, tenant):
        self._inflight[tenant] = self._inflight.get(tenant, 0) + 1
        self._total += 1

    def _try_admit_locked(self, tenant, priority, first=True):
        """One admission attempt (caller holds the lock): the full
        rate→quota→cap chain on the ``first`` attempt; on a wake-up
        retry (``first=False``) the cap plus the post-wait quota
        re-check — several same-tenant requests can pass the pre-wait
        check with 0 inflight, park on the cap, then all wake; without
        the re-check they would all admit and exceed the tenant's
        share. Returns None on grant (slot reserved) or "wait" when
        the request must park; raises _AdmissionDenied otherwise."""
        cls = "batch" if priority == "batch" else "interactive"
        if first:
            self._check_rate_locked(tenant)
            self._check_quota_locked(tenant)
        if self._cap_blocked_locked(cls):
            return "wait"
        if not first:
            self._check_quota_locked(tenant)
        self._grant_locked(tenant)
        return None

    # -- event-driven drivers (the fleet simulator) ---------------------
    def try_admit(self, tenant, priority):
        """Non-blocking first attempt: None on grant, "wait" when the
        caller should park (track the park via note_wait_start/_end and
        retry with try_grant on release/deadline events); raises like
        ``admit()``."""
        with self._cond:
            return self._try_admit_locked(tenant, priority, first=True)

    def try_grant(self, tenant, priority):
        """Wake-up retry for a parked caller (post-wait semantics)."""
        with self._cond:
            return self._try_admit_locked(tenant, priority, first=False)

    def note_wait_start(self, priority):
        cls = "batch" if priority == "batch" else "interactive"
        with self._cond:
            self._waiting[cls] += 1

    def note_wait_end(self, priority):
        cls = "batch" if priority == "batch" else "interactive"
        with self._cond:
            self._waiting[cls] = max(0, self._waiting[cls] - 1)
            if cls == "interactive" and self._waiting["interactive"] == 0:
                # unblock batch waiters parked on the priority predicate
                self._cond.notify_all()

    def admit(self, tenant, priority):
        cls = "batch" if priority == "batch" else "interactive"
        with self._cond:
            if self._try_admit_locked(tenant, priority, first=True) is None:
                return
            # blocked on the global cap (or the interactive-first
            # predicate): WAIT, bounded by the admit timeout
            t_wait = self._clock()
            deadline = t_wait + self.admit_timeout_s
            self._waiting[cls] += 1
            try:
                while True:
                    remaining = deadline - self._clock()
                    if remaining <= 0:
                        raise _AdmissionDenied(
                            "overload",
                            "gateway at max inflight %d (%s waited %.0fms)"
                            % (self.global_max, priority,
                               self.admit_timeout_s * 1e3),
                            retry_after_ms=self.admit_timeout_s * 1e3,
                        )
                    self._cond.wait(remaining)
                    if not self._cap_blocked_locked(cls):
                        break
            finally:
                self._waiting[cls] -= 1
                if cls == "interactive" and self._waiting["interactive"] == 0:
                    # unblock batch waiters parked on the
                    # interactive-priority predicate
                    self._cond.notify_all()
            _profiler.bump_histogram(
                "gateway_admit_wait_ms",
                (self._clock() - t_wait) * 1e3,
            )
            self._check_quota_locked(tenant)  # post-wait re-check
            self._grant_locked(tenant)

    def release(self, tenant):
        with self._cond:
            n = self._inflight.get(tenant, 0) - 1
            if n > 0:
                self._inflight[tenant] = n
            else:
                self._inflight.pop(tenant, None)
            self._total -= 1
            self._cond.notify_all()


# the JSONL access-log writer (with size-based rotation) moved to
# serving/access_log.py — one helper shared with the router's front
# door, so the two logs can never drift apart in format or bounding

_request_ids = itertools.count(1)  # .__next__ atomic under the GIL

# X-Tenant-Id is CLIENT-CONTROLLED: per-tenant metric names and rate
# buckets must not let an attacker grow process memory / Prometheus
# cardinality without bound. The first _MAX_TRACKED_TENANTS distinct
# tenants get their own slug (and so their own metric series and token
# bucket); everyone after that shares the "overflow" slug+bucket. The
# inflight-quota map needs no bound — entries pop at zero.
_MAX_TRACKED_TENANTS = 256


class _TenantTable(object):
    """Bounded tenant -> prometheus-safe slug map (process-wide: the
    metric registry the slugs land in is process-global too)."""

    def __init__(self, cap=_MAX_TRACKED_TENANTS):
        self.cap = int(cap)
        self._map = {}
        self._lock = threading.Lock()

    def slug(self, tenant):
        with self._lock:
            s = self._map.get(tenant)
            if s is None:
                if len(self._map) >= self.cap:
                    return "overflow"
                s = _obs_registry.prom_name(tenant).lower()
                self._map[tenant] = s
            return s


_tenants = _TenantTable()


def _tenant_slug(tenant):
    """Prometheus-safe tenant fragment for per-tenant metric families
    (bounded — see _TenantTable)."""
    return _tenants.slug(tenant)


# -- the gateway -------------------------------------------------------------


class Gateway(object):
    """HTTP front door over an ``InferenceServer`` (whose attached
    ``DecodeEngine``, if any, serves ``/v1/generate``). ``None``
    parameters resolve from the ``FLAGS_gateway_*`` knobs.

    Usage::

        server = serving.InferenceServer(pred, decode_engine=engine)
        server.start(warmup_inputs=[x])
        gw = serving.Gateway(server, port=8500).start()
        gw.install_sigterm()       # SIGTERM -> drain -> close listener
        ...
        gw.stop()                  # graceful: drains in-flight first
    """

    def __init__(self, server, port=None, host="127.0.0.1",
                 rate_limit_rps=None, rate_burst=None,
                 tenant_max_inflight=None, max_inflight=None,
                 admit_timeout_ms=None, drain_timeout_s=None,
                 access_log=None, access_log_max_mb=None,
                 extra_headers=None, role=None):
        self.server = server
        self.host = host
        # fleet KV-tier role: "prefill" replicas compute + publish
        # chain blocks over /v1/kv/prefill; "decode" replicas own
        # slots and pull published blocks on admission miss; "mixed"
        # (default, and the only pre-role behavior) does both locally.
        # Advertised on /readyz so the router and operators see it.
        self.role = str(role or "mixed")
        if self.role not in ("prefill", "decode", "mixed"):
            raise ValueError("role must be prefill|decode|mixed, got %r"
                             % (role,))
        self.kv_peers_file = str(_flags.get_flag("kv_tier_peers_file"))
        self.kv_pull_min_tokens = int(
            _flags.get_flag("kv_tier_pull_min_tokens")
        )
        self.kv_pull_timeout_s = float(
            _flags.get_flag("kv_tier_pull_timeout_s")
        )
        # static response headers stamped on every reply (fleet
        # replicas tag X-Replica-Id / X-Model-Version so the router and
        # rollout audits can attribute each answer)
        self.extra_headers = dict(extra_headers or {})
        self.port_requested = int(_flag("gateway_port", port))
        self.drain_timeout_s = float(
            _flag("gateway_drain_timeout_s", drain_timeout_s)
        )
        self.admission = _Admission(
            _flag("gateway_rate_limit_rps", rate_limit_rps),
            _flag("gateway_rate_burst", rate_burst),
            _flag("gateway_tenant_max_inflight", tenant_max_inflight),
            _flag("gateway_max_inflight", max_inflight),
            _flag("gateway_admit_timeout_ms", admit_timeout_ms),
        )
        self.access_log = AccessLog(
            _flag("gateway_access_log", access_log),
            max_mb=_flag("gateway_access_log_max_mb", access_log_max_mb),
        )
        self._httpd = None
        self._http_thread = None
        self._started = False
        self._draining = False
        self._drain_cond = threading.Condition()
        self._inflight = 0
        self._inflight_gauge = None
        self._draining_gauge = None
        self._waiting_gauges = {}
        self._prev_sigterm = None
        self._sig_installed = False
        self._drain_watch = None
        self._stop_watch = threading.Event()
        self._stopped = threading.Event()

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        if self._started:
            raise RuntimeError("gateway already started")
        handler = _make_handler(self)
        self._httpd = ThreadingHTTPServer(
            (self.host, self.port_requested), handler
        )
        self._httpd.daemon_threads = True
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, name="gateway_http",
            daemon=True,
        )
        self._http_thread.start()
        self._draining = False
        self._started = True
        # telemetry: the obs exporter lights up /metrics etc. from
        # FLAGS_obs_* (no-op when disarmed) — gateway metrics ride the
        # same registry, so one scrape covers engine + gateway
        _obs_exporter.maybe_start_from_flags()
        self._inflight_gauge = lambda g=self: g._inflight
        _obs_registry.register_gauge("gateway_inflight",
                                     self._inflight_gauge)
        self._draining_gauge = lambda g=self: 1.0 if g._draining else 0.0
        _obs_registry.register_gauge("gateway_draining",
                                     self._draining_gauge)
        # queued (not yet admitted) pressure per priority class — the
        # signal the SLO policy and the fleet simulator read; renders
        # as labeled series gateway_admit_waiting{class="..."}
        self._waiting_gauges = {}
        for _cls in ("interactive", "batch"):
            fn = (lambda g=self, c=_cls:
                  g.admission.waiting_by_class().get(c, 0))
            gname = 'gateway_admit_waiting{class="%s"}' % _cls
            self._waiting_gauges[gname] = fn
            _obs_registry.register_gauge(gname, fn)
        # watch the shared preemption latch: a SIGTERM seen by ANY
        # installed handler (ours via install_sigterm, or a trainer's
        # PreemptionHandler in the same process) drains this gateway
        self._stop_watch.clear()
        self._stopped.clear()
        self._drain_watch = threading.Thread(
            target=self._watch_preemption, name="gateway_drain_watch",
            daemon=True,
        )
        self._drain_watch.start()
        return self

    @property
    def port(self):
        """The BOUND port (differs from 0-requested ephemeral binds);
        None once the listener is closed."""
        return self._httpd.server_address[1] if self._httpd else None

    def url(self, path="/healthz"):
        if self._httpd is None:
            raise RuntimeError("gateway is not listening")
        return "http://%s:%d%s" % (self.host, self.port, path)

    def install_sigterm(self):
        """Route SIGTERM into the graceful-drain path: the handler sets
        the shared preemption latch (``checkpoint.preempt``), which
        flips ``/readyz`` AND the exporter's ``/healthz`` to draining;
        the watch thread then drains in-flight streams and closes the
        listener. A previously installed Python handler (a colocated
        trainer's ``PreemptionHandler`` final save) is CHAINED after the
        latch — its state must not be lost because a gateway installed
        later. Caveat: a chained handler that exits the process
        (``exit_after=True``) will cut the drain short; colocated
        trainers that want the drain should install with
        ``save_in_handler``/``exit_after`` off and poll the latch.
        Main-thread only (signal API constraint) — a gateway driven from
        a worker thread relies on the process's own PreemptionHandler
        setting the same latch."""
        if threading.current_thread() is not threading.main_thread():
            return self
        if self._sig_installed:
            # idempotent: a second install would capture OUR handler as
            # _prev_sigterm and the chain would recurse on SIGTERM
            return self
        self._prev_sigterm = signal.signal(
            signal.SIGTERM, self._on_sigterm
        )
        self._sig_installed = True
        return self

    def _on_sigterm(self, signum, frame):
        # minimal handler: latch, then chain. The drain itself (bounded,
        # seconds) must not run between arbitrary bytecodes on the main
        # thread — the watch thread does it. Once the gateway has
        # stopped the handler degrades to a pure pass-through: a stop()
        # that ran on the watch thread cannot signal.signal() the old
        # handler back (main-thread-only API), so this stays installed
        # but transparent.
        if self._started:
            _preempt.request_preemption()
        prev = self._prev_sigterm
        if callable(prev):  # SIG_DFL / SIG_IGN / None are not
            prev(signum, frame)

    def _watch_preemption(self):
        while not self._stop_watch.wait(0.05):
            if _preempt.preemption_requested():
                self.stop()
                return

    def draining(self):
        return (self._draining or not self._started
                or _preempt.preemption_requested())

    def kv_advert(self):
        """The /readyz KV-tier advertisement: this replica's role plus
        (when a paged prefix index is live) its block size and hot
        chain-head keys — what the router's affinity scorer matches an
        incoming prompt's chain against. Cheap and lock-free; an engine
        without an index advertises role only."""
        out = {"role": self.role}
        eng = getattr(self.server, "_decode_engine", None)
        try:
            if eng is not None and getattr(eng, "pindex", None) is not None:
                out["block"] = eng.block_size
                out["heads"] = eng.prefix_heads()
        except Exception:  # noqa: BLE001 - advert is best-effort
            pass
        return out

    def stop(self, drain_timeout_s=None):
        """Graceful stop: flip NOT-READY, reject new work with 503, wait
        (bounded) for every in-flight request — including mid-stream SSE
        responses — then close the listener. Idempotent; concurrent
        callers (SIGTERM watch + an explicit stop) drain once, and the
        late caller BLOCKS until that drain completes — the documented
        ``gw.stop(); server.stop()`` teardown must not rip the engine
        out from under requests another thread is still draining."""
        timeout = (self.drain_timeout_s if drain_timeout_s is None
                   else float(drain_timeout_s))
        with self._drain_cond:
            in_progress = self._draining
            if not in_progress and not self._started:
                self._restore_sigterm()
                return
            self._draining = True  # /readyz 503 + new requests 503
        if in_progress:
            # another thread owns the drain: wait it out (bounded)
            self._stopped.wait(timeout + 10.0)
            # a watch-thread stop couldn't restore the signal handler
            # (main-thread-only API); finish the job if we can
            self._restore_sigterm()
            return
        self._stop_watch.set()
        deadline = time.monotonic() + timeout
        with self._drain_cond:
            while self._inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    _profiler.bump_counter("gateway_drain_timeouts")
                    break
                self._drain_cond.wait(remaining)
        if self._httpd is not None:
            try:
                self._httpd.shutdown()
                self._httpd.server_close()
            except Exception:
                pass
        if self._http_thread is not None:
            self._http_thread.join(timeout=5.0)
        self._httpd = None
        # the drain is a terminal moment for this process's serving
        # life: leave the flight-recorder/trace black box on disk (no-op
        # when FLAGS_obs_dir is unarmed)
        _obs_exporter.dump_blackbox()
        if self._inflight_gauge is not None:
            _obs_registry.unregister_gauge("gateway_inflight",
                                           self._inflight_gauge)
            self._inflight_gauge = None
        if self._draining_gauge is not None:
            _obs_registry.unregister_gauge("gateway_draining",
                                           self._draining_gauge)
            self._draining_gauge = None
        for gname, fn in self._waiting_gauges.items():
            _obs_registry.unregister_gauge(gname, fn)
        self._waiting_gauges = {}
        self._restore_sigterm()
        self._started = False
        self._stopped.set()  # unblock concurrent stop() callers

    def _restore_sigterm(self):
        """Put the previous SIGTERM handler back — only possible from
        the main thread (signal API); a stop() driven by the watch
        thread leaves ours installed as a pass-through (_on_sigterm
        checks _started) until a main-thread stop() lands here."""
        if (self._sig_installed
                and threading.current_thread() is threading.main_thread()):
            try:
                signal.signal(signal.SIGTERM, self._prev_sigterm)
                self._sig_installed = False
            except (ValueError, TypeError):
                pass

    def __enter__(self):
        return self if self._started else self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- per-request bookkeeping (called from handler threads) ---------------
    def _enter_request(self):
        with self._drain_cond:
            if self._draining or not self._started:
                return False
            self._inflight += 1
            return True

    def _exit_request(self):
        with self._drain_cond:
            self._inflight -= 1
            self._drain_cond.notify_all()


# -- HTTP handler ------------------------------------------------------------


def _make_handler(gw):
    class _Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        server_version = "paddle-tpu-gateway/1"
        # socket timeout: a client that trickles its body (or stalls a
        # read) is disconnected instead of pinning a handler thread
        timeout = 60.0

        def log_message(self, *args):  # access log is ours, not stderr's
            pass

        # -- plumbing --------------------------------------------------------
        def _send_json(self, code, obj, headers=(), close=False):
            """``close=True`` on any response sent WITHOUT having read
            the request body (early 429/404/503) or after a partial
            read: protocol_version is HTTP/1.1, so a kept-alive client
            would otherwise see the unread body bytes parsed as its
            next request line and desync."""
            data = json.dumps(obj, sort_keys=True).encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            if close:
                self.send_header("Connection", "close")
                self.close_connection = True
            # every response names its distributed trace: the client
            # (or the router relaying this) correlates the answer with
            # the merged fleet trace by this one header
            if getattr(self, "_trace_id", None):
                self.send_header("X-Trace-Id", self._trace_id)
            for k, v in gw.extra_headers.items():
                self.send_header(k, v)
            for k, v in headers:
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(data)

        def _read_body(self):
            try:
                n = int(self.headers.get("Content-Length", "0"))
            except ValueError:
                raise ValueError("bad Content-Length")
            if n <= 0:
                raise ValueError("missing request body")
            if n > _MAX_BODY_BYTES:
                raise _PayloadTooLarge(
                    "request body of %d bytes exceeds the %d-byte cap"
                    % (n, _MAX_BODY_BYTES)
                )
            body = self.rfile.read(n)
            try:
                obj = json.loads(body.decode("utf-8"))
            except (UnicodeDecodeError, ValueError):
                raise ValueError("request body is not valid JSON")
            if not isinstance(obj, dict):
                raise ValueError("request body must be a JSON object")
            return obj

        @staticmethod
        def _opt_number(body, key):
            """Optional numeric field -> float|None; a non-numeric value
            is a 400 (ValueError), not a 500 from a downstream compare."""
            v = body.get(key)
            if v is None:
                return None
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                raise ValueError("%r must be a number" % key)
            return float(v)

        def _send_shed_429(self, tenant, rid, reason, retry_after_ms,
                           msg, close=False):
            """The one 429 contract (admission sheds of every kind):
            Retry-After header in ceil'd seconds, machine-readable body,
            admission-shed + per-tenant counters."""
            _profiler.bump_counter("gateway_shed_admission")
            _profiler.bump_counter("gateway_tenant_shed_"
                                   + _tenant_slug(tenant))
            retry_after_ms = max(1, int(retry_after_ms))
            self._send_json(
                429,
                {"error": msg, "reason": reason,
                 "retry_after_ms": retry_after_ms, "request_id": rid},
                headers=(("Retry-After",
                          str(max(1, (retry_after_ms + 999) // 1000))),),
                close=close,
            )

        def _request_meta(self):
            # strip BEFORE the fallback: a whitespace-only header must
            # land in "anon", not mint an empty-string tenant with its
            # own bucket and a malformed metric slug
            tenant = (self.headers.get("X-Tenant-Id") or "").strip() \
                or "anon"
            priority = (self.headers.get("X-Priority") or
                        "interactive").strip().lower()
            if priority not in ("interactive", "batch"):
                priority = "interactive"
            rid = (self.headers.get("X-Request-Id")
                   or "req-%d-%d" % (os.getpid(), next(_request_ids)))
            return tenant, priority, rid

        # -- GET: health/readiness ------------------------------------------
        def do_GET(self):
            # the handler object persists across a kept-alive
            # connection: a previous POST's trace id must not leak onto
            # a health probe's response
            self._trace_id = None
            path = self.path.split("?", 1)[0]
            if path == "/healthz":
                # liveness: the process is up and handling sockets —
                # plus the clock-anchor pair (ts wall / ts_mono span
                # clock) fleet_trace.py aligns this process's spans with
                self._send_json(200, dict(
                    {"status": "alive", "pid": os.getpid()},
                    **_trace.clock_anchor()))
            elif path == "/readyz":
                if gw.draining():
                    self._send_json(503, {"status": "draining"})
                else:
                    # the KV-tier advertisement rides the readiness
                    # poll the router already makes: hot prefix-chain
                    # heads + block size + role, for affinity scoring
                    # the lease stamp doubles as the router's liveness
                    # signal for ADOPTED backends (pid + wall-clock ts,
                    # same shape as the endpoint-file lease)
                    self._send_json(
                        200,
                        {"status": "ready",
                         "inflight": gw.admission.total_inflight,
                         "kv": gw.kv_advert(),
                         "lease": {"pid": os.getpid(),
                                   "ts": time.time()}},
                    )
            else:
                self._send_json(404, {"error": "not found"})

        # -- POST: the serving endpoints ------------------------------------
        def do_POST(self):
            # same kept-alive hygiene as do_GET: a previous request's
            # trace id must not stamp an unmatched route's 404
            self._trace_id = None
            path = self.path.split("?", 1)[0]
            if path == "/v1/infer":
                self._serve(path, self._infer)
            elif path == "/v1/generate":
                self._serve(path, self._generate)
            elif path == "/v1/kv/prefill":
                # internal fleet endpoint (prefill-role replicas):
                # bypasses tenant admission — peers are fleet traffic,
                # not tenants; the engine's own queue bound still sheds
                self._kv_prefill()
            else:
                # body unread -> close, or a kept-alive client desyncs
                self._send_json(404, {"error": "not found"}, close=True)

        def _serve(self, endpoint, fn):
            """Shared request wrapper: drain gate, body read (BEFORE
            admission — an admitted inflight slot must never wait on a
            trickling client body), admission control, span, metrics,
            access log, error->status mapping.

            Distributed trace: an incoming W3C ``traceparent`` (the
            router's, or any foreign caller's) is ADOPTED — this hop's
            ``gateway_request`` span becomes a child of the remote span
            and every engine-side span opened under the scope inherits
            the trace — and a gateway fronted directly mints its own.
            The id goes back out on ``X-Trace-Id``, the SSE terminal
            events, the access-log line, and the flight record."""
            tenant, priority, rid = self._request_meta()
            # stashed for handlers that thread scheduling identity into
            # the engine (_generate) — fn() only receives (tenant, rid,
            # body)
            self._priority = priority
            tp = _trace.parse_traceparent(self.headers.get("traceparent"))
            trace_id, parent_span = tp if tp else (_trace.new_trace_id(),
                                                  None)
            self._trace_id = trace_id
            self._parent_span = parent_span
            self._span_id = None
            t0 = time.monotonic()
            # reset BEFORE any _log call (including the draining-reject
            # below): the handler object is reused across a kept-alive
            # connection, and a stale stash from the previous request
            # must never leak into this request's access-log line
            self._log_extra = None
            self._flight_extra = None
            _profiler.bump_counter("gateway_requests")
            _profiler.bump_counter("gateway_tenant_requests_"
                                   + _tenant_slug(tenant))
            if not gw._enter_request():
                self._send_json(
                    503, {"error": "draining", "request_id": rid},
                    close=True,
                )
                self._log(rid, tenant, priority, endpoint, 503, t0,
                          reason="draining")
                return
            status, reason, tokens = 500, None, None
            try:
                with _trace.trace_scope(trace_id, parent_span), \
                        _trace.span("gateway_request", cat="gateway",
                                    endpoint=endpoint, tenant=tenant,
                                    request_id=rid,
                                    priority=priority) as sp:
                    self._span_id = sp.span_id
                    try:
                        body = self._read_body()
                    except _PayloadTooLarge as e:
                        # refused unread -> must close the connection
                        status, reason = 413, "too_large"
                        self._send_json(413, {"error": str(e),
                                              "request_id": rid},
                                        close=True)
                        return
                    except ValueError as e:
                        # ambiguous read state (bad/missing length,
                        # undecodable body) -> close conservatively
                        status, reason = 400, "bad_request"
                        self._send_json(400, {"error": str(e),
                                              "request_id": rid},
                                        close=True)
                        return
                    # journey facts for the flight record: queue depth
                    # as seen AT entry and how long admission held us
                    inflight_at_entry = gw.admission.total_inflight
                    t_adm = time.monotonic()
                    try:
                        gw.admission.admit(tenant, priority)
                    except _AdmissionDenied as e:
                        status, reason = 429, e.reason
                        # body consumed above: keep-alive stays safe
                        self._send_shed_429(tenant, rid, e.reason,
                                            e.retry_after_ms, str(e))
                        return
                    finally:
                        self._flight_extra = {
                            "admit_wait_ms": round(
                                (time.monotonic() - t_adm) * 1e3, 3),
                            "inflight_at_entry": inflight_at_entry,
                        }
                    try:
                        status, reason, tokens = fn(tenant, rid, body)
                    finally:
                        gw.admission.release(tenant)
                    if sp.args is not None:
                        # the span records its kwargs dict by reference,
                        # so the status lands in the exported trace args
                        sp.args["status"] = status
            except ConnectionError:
                # BrokenPipe AND ConnectionReset/Aborted: the client
                # went away — not a server error, don't write to the
                # dead socket or pollute 5xx monitoring
                status, reason = 499, "client_disconnected"
            except Exception as e:  # handler must never kill the thread
                status, reason = 500, repr(e)
                try:
                    # body state unknown here -> close the connection
                    self._send_json(500, {"error": repr(e),
                                          "request_id": rid}, close=True)
                except Exception:
                    pass
            finally:
                gw._exit_request()
                ms = (time.monotonic() - t0) * 1e3
                if status < 400:
                    _profiler.bump_histogram("gateway_latency_ms", ms)
                    _profiler.bump_histogram(
                        "gateway_tenant_latency_ms_" + _tenant_slug(tenant),
                        ms,
                    )
                self._log(rid, tenant, priority, endpoint, status, t0,
                          reason=reason, tokens=tokens)

        def _log(self, rid, tenant, priority, endpoint, status, t0,
                 reason=None, tokens=None):
            rec = {
                "ts": time.time(),
                "request_id": rid,
                "tenant": tenant,
                "priority": priority,
                "endpoint": endpoint,
                "status": int(status),
                "ms": round((time.monotonic() - t0) * 1e3, 3),
            }
            if getattr(self, "_trace_id", None):
                rec["trace_id"] = self._trace_id
                if self._span_id:
                    rec["span_id"] = self._span_id
                if self._parent_span:
                    rec["parent_span_id"] = self._parent_span
            if reason:
                rec["reason"] = reason
            if tokens is not None:
                rec["tokens"] = int(tokens)
            extra = getattr(self, "_log_extra", None)
            if extra:
                rec.update(extra)
            gw.access_log.write(rec)
            # the same record is this request's flight-recorder entry
            # (plus the admission journey facts) — one shape, two
            # sinks, so the black box and the log can never disagree
            fx = getattr(self, "_flight_extra", None)
            _flight.note(dict(rec, **fx) if fx else rec)
            if status >= 500:
                _flight.dump_on_error()

        # -- /v1/infer -------------------------------------------------------
        def _infer(self, tenant, rid, body):
            """Returns (status, reason, tokens) after writing the
            response. Body: {"inputs": [tensor...], "deadline_ms": f}."""
            try:
                raw = body.get("inputs")
                if not isinstance(raw, list) or not raw:
                    raise ValueError("'inputs' must be a non-empty list "
                                     "of tensors")
                feeds = [decode_tensor(t) for t in raw]
                deadline_ms = self._opt_number(body, "deadline_ms")
            except ValueError as e:
                # body fully consumed by _serve: keep-alive stays safe
                self._send_json(400, {"error": str(e),
                                      "request_id": rid})
                return 400, "bad_request", None
            try:
                outs = gw.server.infer(feeds, deadline_ms=deadline_ms)
            except ServerOverloadedError as e:
                # shed at the ENGINE's admission queue: same 429 +
                # Retry-After contract as the gateway's own sheds
                self._send_shed_429(tenant, rid, "overload",
                                    e.retry_after_ms, str(e))
                return 429, "overload", None
            except DeadlineExceededError as e:
                # shed at DISPATCH: the deadline passed in the queue
                _profiler.bump_counter("gateway_shed_dispatch")
                _profiler.bump_counter("gateway_tenant_shed_"
                                       + _tenant_slug(tenant))
                self._send_json(504, {"error": str(e),
                                      "reason": "deadline",
                                      "request_id": rid})
                return 504, "deadline", None
            except ServingError as e:
                self._send_json(500, {"error": str(e),
                                      "request_id": rid})
                return 500, "serving_error", None
            self._send_json(200, {
                "request_id": rid,
                "outputs": [encode_tensor(o) for o in outs],
            })
            return 200, None, None

        # -- /v1/generate ----------------------------------------------------
        def _generate(self, tenant, rid, body):
            """Body: {"prompt_ids": [...], "max_new_tokens", "eos_id",
            "temperature", "top_k", "top_p", "seed", "stream" (default
            true), "deadline_ms", "resume_tokens"}. Streaming responses
            are chunked SSE: one ``data: {"token": t}`` event per
            generated token, then ``data: {"done": true, ...}``.

            ``resume_tokens`` is the durable-generation resume form:
            the suffix an interrupted run of this exact request already
            emitted (the router builds it from the tokens it relayed
            before a replica died). The stream then emits only the
            token-exact continuation; the done/error events carry
            ``emitted_count`` + seed/knobs so ANY caller can
            reconstruct the next resume request. A temperature-sampled
            resume without its seed is a 400 (the engine's
            seed-required rule — the replayed picks would be
            unreproducible)."""
            try:
                prompt = body.get("prompt_ids")
                if (not isinstance(prompt, list) or not prompt
                        or not all(isinstance(t, int) for t in prompt)):
                    raise ValueError(
                        "'prompt_ids' must be a non-empty list of ints"
                    )
                resume = body.get("resume_tokens")
                if resume is not None:
                    if (not isinstance(resume, list)
                            or not all(isinstance(t, int)
                                       and not isinstance(t, bool)
                                       for t in resume)):
                        raise ValueError(
                            "'resume_tokens' must be a list of ints"
                        )
                stream_mode = bool(body.get("stream", True))
                deadline_ms = self._opt_number(body, "deadline_ms")
                kw = dict(
                    max_new_tokens=body.get("max_new_tokens"),
                    eos_id=body.get("eos_id"),
                    temperature=self._opt_number(body, "temperature"),
                    top_k=body.get("top_k", 0),
                    top_p=self._opt_number(body, "top_p"),
                    seed=body.get("seed"),
                    resume_tokens=resume or None,
                )
            except ValueError as e:
                self._send_json(400, {"error": str(e),
                                      "request_id": rid})
                return 400, "bad_request", None
            timeout = (deadline_ms / 1e3
                       if deadline_ms and deadline_ms > 0 else None)
            # decode-role pull: a cold prompt chain (below the pull
            # threshold) fetches published blocks from a prefill-role
            # peer BEFORE admission, so the local prefill shrinks to
            # the unpulled suffix; any failure degrades to plain local
            # prefill — the pull is never on the correctness path
            self._kv_pull_if_cold(prompt)
            # scheduling identity for the engine's weighted-fair /
            # preemption scheduler; guarded so bespoke server fakes
            # with a positional-only generate() keep working
            if _accepts_sched_kwargs(gw.server.generate):
                kw["priority"] = getattr(self, "_priority", "interactive")
                kw["tenant"] = tenant
            try:
                stream = gw.server.generate(prompt, **kw)
            except ServerOverloadedError as e:
                self._send_shed_429(tenant, rid, "overload",
                                    e.retry_after_ms, str(e))
                return 429, "overload", None
            except (ValueError, TypeError, ServingError) as e:
                code = 500 if isinstance(e, ServingError) else 400
                self._send_json(code, {"error": str(e),
                                       "request_id": rid})
                return code, "bad_request" if code == 400 else "error", None
            if not stream_mode:
                try:
                    toks = stream.tokens(timeout=timeout)
                except TimeoutError as e:
                    # the client's answer is gone: CANCEL so the engine
                    # retires the slot instead of decoding to max_new
                    stream.cancel()
                    _profiler.bump_counter("gateway_shed_dispatch")
                    _profiler.bump_counter("gateway_tenant_shed_"
                                           + _tenant_slug(tenant))
                    self._send_json(504, {"error": str(e),
                                          "reason": "deadline",
                                          "request_id": rid})
                    return 504, "deadline", None
                facts = self._stash_gen_facts(stream)
                self._send_json(200, dict({
                    "request_id": rid,
                    "tokens": toks,
                    "finish_reason": stream.finish_reason,
                }, **facts, **self._resume_state(stream, len(toks))))
                return 200, None, len(toks)
            return self._stream_sse(stream, tenant, rid, timeout)

        def _kv_pull_if_cold(self, prompt):
            """Fleet KV pull (decode-role path): when the local tier
            would cache fewer than ``FLAGS_kv_tier_pull_min_tokens`` of
            this prompt, fetch the chain's published blocks from a
            prefill-role peer (controller-maintained peers file) and
            drop them into the host store — the admission that follows
            re-admits them H2D through the standard spilled-block path.
            Wholly best-effort: any failure (no peers, timeout, dead
            peer, mismatched geometry) counts ``kv_tier_pull_failures``
            and the request prefills locally, token-exact either way."""
            if gw.kv_pull_min_tokens <= 0 or not gw.kv_peers_file:
                return
            eng = getattr(gw.server, "_decode_engine", None)
            if eng is None or getattr(eng, "host_store", None) is None:
                return
            try:
                bs = eng.block_size
                if len(prompt) <= bs:
                    return  # nothing a peer could hand us
                if (eng.estimate_cached_tokens(prompt)
                        >= gw.kv_pull_min_tokens):
                    return
                peers = _kv_tier.read_peers(gw.kv_peers_file)
                if not peers:
                    return
                # chain-root key spreads prompts across peers
                # deterministically: the same prefix always asks the
                # same peer, so peer-side caches stay hot too
                keys = _kv_tier.chain_keys(prompt, bs)
                peer = peers[int(keys[0][:8], 16) % len(peers)]
                conn = http.client.HTTPConnection(
                    str(peer.get("host", "127.0.0.1")),
                    int(peer["port"]), timeout=gw.kv_pull_timeout_s,
                )
                try:
                    conn.request(
                        "POST", "/v1/kv/prefill",
                        json.dumps({"prompt_ids": list(prompt)}),
                        {"Content-Type": "application/json"},
                    )
                    resp = conn.getresponse()
                    raw = resp.read()
                finally:
                    conn.close()
                if resp.status != 200:
                    raise ServingError(
                        "kv pull got HTTP %d" % resp.status
                    )
                doc = json.loads(raw.decode("utf-8"))
                if int(doc.get("block") or 0) != bs:
                    raise ServingError("kv pull block-size mismatch")
                cfg = eng.session.cfg
                row_shape = [cfg.num_heads, bs,
                             cfg.hidden_size // cfg.num_heads]
                entries = _kv_tier.decode_entries(
                    doc.get("blocks") or [], row_shape
                )
                n = eng.offer_blocks(entries)
                _profiler.bump_counter("kv_tier_pulls")
                _profiler.bump_counter("kv_tier_pull_tokens", n * bs)
            except Exception:  # noqa: BLE001 - degrade to local prefill
                _profiler.bump_counter("kv_tier_pull_failures")

        def _kv_prefill(self):
            """POST /v1/kv/prefill (internal fleet endpoint): compute
            and serialize the prompt's chain blocks. If the chain is
            not fully published yet, one 1-token generation drives the
            chunked prefill + publish, then the loop thread exports the
            blocks (host-store blocks serve straight from the tier).
            Returns base64 float32 payloads in chain order."""
            t0 = time.monotonic()
            rid = self.headers.get("X-Request-Id") or "-"
            try:
                body = self._read_body()
            except ValueError as e:
                self._send_json(400, {"error": str(e)}, close=True)
                return
            eng = getattr(gw.server, "_decode_engine", None)
            try:
                prompt = body.get("prompt_ids") \
                    if isinstance(body, dict) else None
                if (not isinstance(prompt, list) or not prompt
                        or not all(isinstance(t, int) for t in prompt)):
                    raise ValueError(
                        "'prompt_ids' must be a non-empty list of ints"
                    )
                if eng is None or getattr(eng, "pindex", None) is None:
                    self._send_json(503, {
                        "error": "no paged prefix index on this replica",
                        "request_id": rid,
                    })
                    return
                bs = eng.block_size
                want = len(prompt) // bs
                if want < 1:
                    raise ValueError(
                        "prompt shorter than one block (%d)" % bs
                    )
                entries = eng.request_export(prompt, timeout=5.0) or []
                if len(entries) < want:
                    # cold chain: one 1-token generation prefills and
                    # publishes it (counts as normal engine traffic)
                    stream = gw.server.generate(prompt, max_new_tokens=1)
                    stream.tokens(timeout=60)
                    entries = eng.request_export(prompt, timeout=5.0) or []
                self._send_json(200, {
                    "block": bs,
                    "count": len(entries),
                    "served_ms": round((time.monotonic() - t0) * 1e3, 3),
                    "blocks": _kv_tier.encode_entries(entries),
                })
            except ValueError as e:
                self._send_json(400, {"error": str(e),
                                      "request_id": rid})
            except ServerOverloadedError as e:
                self._send_json(429, {"error": str(e),
                                      "request_id": rid})
            except Exception as e:  # noqa: BLE001 - internal endpoint
                self._send_json(500, {"error": str(e),
                                      "request_id": rid})

        def _resume_state(self, stream, sent):
            """The reconstruction state every generate done/error event
            carries: how many tokens of the LOGICAL generation are out
            (the resumed suffix plus this stream's emissions) and the
            determinism knobs — enough for any caller (the router's
            failover path, or an end client) to build the next resume
            request without having tracked anything but the tokens.
            ``trace_id`` rides along so the terminal event correlates
            with the merged fleet trace even when the headers are long
            gone (a buffered SSE consumer)."""
            # getattr like _stash_gen_facts: duck-typed stream fakes
            # (tests, bespoke servers) must not break the error path
            state = {
                "emitted_count": (
                    len(getattr(stream, "resume_tokens", ()) or ())
                    + int(sent)
                ),
                "seed": getattr(stream, "seed", None),
                "temperature": getattr(stream, "temperature", 0.0),
                "top_k": getattr(stream, "top_k", 0),
                "top_p": getattr(stream, "top_p", 0.0),
            }
            if getattr(self, "_trace_id", None):
                state["trace_id"] = self._trace_id
            return state

        def _stash_gen_facts(self, stream, fallback_ttft_ms=None):
            """Engine-stamped latency + prefix-cache facts, derived ONCE
            per request: stashed for the access-log line and returned
            for the response payload (JSON body or SSE done event), so
            the two surfaces can never disagree. ``fallback_ttft_ms``
            covers a stream the engine didn't stamp (the SSE writer's
            gateway-side first-chunk wall)."""
            ttft = getattr(stream, "ttft_ms", None)
            if ttft is None:
                ttft = fallback_ttft_ms
            facts = {
                "ttft_ms": round(ttft, 3) if ttft is not None else None,
                "cached_prefix_tokens": int(getattr(
                    stream, "cached_prefix_tokens", 0) or 0),
                # windowed-admission fact (1 = monolithic prefill):
                # with resumed_tokens > 0 this is the proof a resume's
                # re-prefill rode the chunked/prefix path
                "admit_windows": int(getattr(
                    stream, "admit_windows", 0) or 0),
                "resumed_tokens": len(getattr(
                    stream, "resume_tokens", ()) or ()),
            }
            # speculative-decoding facts (paged engine v2): drafted /
            # accepted counts plus the per-request acceptance rate —
            # only when the engine actually drafted, so legacy engines'
            # payloads and log lines stay byte-identical
            drafted = int(getattr(stream, "spec_drafted", 0) or 0)
            if drafted:
                accepted = int(getattr(stream, "spec_accepted", 0) or 0)
                facts["spec_drafted"] = drafted
                facts["spec_accepted"] = accepted
                facts["spec_acceptance"] = round(accepted / drafted, 4)
            # scheduler journey fact: how many times this stream was
            # preemption-evicted and token-exactly re-admitted — only
            # when it happened, so untouched payloads stay identical
            preempted = int(getattr(stream, "preemptions", 0) or 0)
            if preempted:
                facts["preemptions"] = preempted
            # engine-tick journey fact for the flight record: how many
            # fused decode ticks this generation spanned
            ft = getattr(stream, "first_tick", None)
            lt = getattr(stream, "last_tick", None)
            if ft is not None and lt is not None:
                facts["ticks_spanned"] = int(lt) - int(ft) + 1
            self._log_extra = facts
            return facts

        def _stream_sse(self, stream, tenant, rid, timeout):
            """Chunked SSE: headers now, one data event per token as the
            engine emits it, a final done event carrying finish_reason.
            Errors after headers ride an in-band ``{"error": ...}``
            event (the 200 is already on the wire)."""
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.send_header("Transfer-Encoding", "chunked")
            self.send_header("X-Request-Id", rid)
            if getattr(self, "_trace_id", None):
                self.send_header("X-Trace-Id", self._trace_id)
            for k, v in gw.extra_headers.items():
                self.send_header(k, v)
            self.end_headers()
            sent = 0
            first_tok_ms = None
            t0 = time.monotonic()
            # ENGINE exceptions (deadline, stream failure) and CLIENT
            # write exceptions must be told apart by SOURCE, not type:
            # on py3.10+ socket.timeout IS TimeoutError, so a write to
            # a stalled client that times out is type-identical to the
            # generation deadline — only next(it) can raise the
            # deadline, only _chunk can raise the socket
            it = iter(stream.stream_tokens(timeout=timeout))
            while True:
                try:
                    tok = next(it)
                except StopIteration:
                    break
                except TimeoutError:
                    stream.cancel()  # free the decode slot — see above
                    _profiler.bump_counter("gateway_shed_dispatch")
                    _profiler.bump_counter("gateway_tenant_shed_"
                                           + _tenant_slug(tenant))
                    try:
                        # carries the reconstruction state (emitted
                        # count, seed, knobs) like every terminal
                        # generate event — a caller can resume even a
                        # deadline-cut stream with a fresh budget
                        self._chunk('data: %s\n\n' % json.dumps(
                            dict({"error": "deadline",
                                  "request_id": rid},
                                 **self._resume_state(stream, sent))
                        ))
                        self._chunk_end()
                    except OSError:
                        return 499, "client_stalled", sent
                    return 504, "deadline", sent
                except Exception as e:  # noqa: BLE001
                    # the 200 + chunked framing is already on the
                    # wire: ANY stream failure (the engine fails
                    # streams with the original exception type, not
                    # just ServingError) must ride an in-band error
                    # event — a late _send_json(500) would inject a
                    # raw status line into the chunked body
                    try:
                        self._chunk('data: %s\n\n' % json.dumps(
                            dict({"error": str(e) or repr(e),
                                  "request_id": rid},
                                 **self._resume_state(stream, sent))
                        ))
                        self._chunk_end()
                    except OSError:
                        stream.cancel()
                        return 499, "client_stalled", sent
                    return 500, "stream_error", sent
                if first_tok_ms is None:
                    first_tok_ms = (time.monotonic() - t0) * 1e3
                    _profiler.bump_histogram("gateway_ttft_ms",
                                             first_tok_ms)
                try:
                    self._chunk('data: {"token": %d}\n\n' % tok)
                except OSError as e:
                    # client went away (reset/pipe) or STALLED (write
                    # timeout) mid-stream: nothing left to write to,
                    # and nobody left to decode for. A ConnectionError
                    # re-raises into _serve's 499 mapping; a write
                    # timeout must NOT re-raise — the generic handler
                    # would _send_json(500) into the open chunked body
                    stream.cancel()
                    if isinstance(e, ConnectionError):
                        raise
                    return 499, "client_stalled", sent
                sent += 1
                _profiler.bump_counter("gateway_stream_tokens")
                # chaos seam (no-op unless FLAGS_chaos_die_after_tokens
                # is armed): the process dies AFTER this token hit the
                # wire, pinning replica-death trials to an exact token
                # boundary
                _chaos.on_stream_token()
            # the done event carries the engine-stamped TTFT (falling
            # back to the gateway-side first-chunk wall) and the
            # prefix-cache reuse fact, so a streaming client sees its
            # amortization — same dict the access log records
            facts = self._stash_gen_facts(stream,
                                          fallback_ttft_ms=first_tok_ms)
            try:
                self._chunk('data: %s\n\n' % json.dumps(
                    dict({"done": True,
                          "finish_reason": stream.finish_reason,
                          "tokens": sent, "request_id": rid}, **facts,
                         **self._resume_state(stream, sent)),
                    sort_keys=True,
                ))
                self._chunk_end()
            except OSError as e:
                if isinstance(e, ConnectionError):
                    raise
                return 499, "client_stalled", sent
            return 200, None, sent

        def _chunk(self, text):
            data = text.encode("utf-8")
            self.wfile.write(b"%x\r\n" % len(data))
            self.wfile.write(data)
            self.wfile.write(b"\r\n")
            self.wfile.flush()

        def _chunk_end(self):
            self.wfile.write(b"0\r\n\r\n")
            self.wfile.flush()

    return _Handler
