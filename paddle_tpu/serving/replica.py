"""One serving replica: the process the FleetController spawns.

``python -m paddle_tpu.serving.replica --model-dir D --endpoint-file F``
builds the full single-process serving stack over a saved inference
model — AnalysisPredictor -> InferenceServer (micro-batcher + bucket
ladder, eagerly warmed) -> Gateway (HTTP front door) — then reports its
ephemeral ports back to the controller through an atomically written
*endpoint file* and heartbeats through the supervisor's worker protocol
(``PADDLE_TPU_HEARTBEAT_FILE``) until a SIGTERM drains it.

Contract with the controller:

- warmup happens BEFORE the gateway starts listening, so the first
  ``/readyz`` 200 already implies a fully warmed bucket ladder (and,
  under ``FLAGS_serving_strict_compiles``, an armed compile gate) —
  the controller can shift rollout traffic on readiness alone;
- ``warmup.npz`` beside the model (one array per feed, ``arr_0..``
  order) provides the warmup example; without it the replica serves
  unwarmed (strict mode would then fail its first request by design);
- every ``/v1/infer`` response carries ``X-Replica-Id`` and
  ``X-Model-Version`` headers (the router relays them), so rollout
  audits can attribute each answer to the exact replica and version
  that produced it;
- SIGTERM (the controller's drain) rides the gateway's graceful path:
  ``/readyz`` flips 503, every in-flight request completes, the
  listener closes, the process exits 0. Only a crash exits nonzero.

Scope: this stock replica serves ``/v1/infer`` over any
``save_inference_model`` export. ``/v1/generate`` needs a
``DecodeEngine`` (a GPT-config decode session, not an arbitrary saved
model) — generation fleets supply a custom ``replica_cmd`` whose
process attaches one (``InferenceServer(pred, decode_engine=...)`` +
``Gateway``, exactly as in tools/gateway_probe.py) or register such
gateways on the Router directly; the router's SSE pin/relay path works
against any gateway backend and is tested against streaming backends.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

__all__ = ["main"]


def _write_endpoint(path, payload):
    """Atomic tmp+replace: the controller must never read a torn file."""
    tmp = "%s.tmp.%d" % (path, os.getpid())
    with open(tmp, "w") as f:
        json.dump(payload, f, sort_keys=True)
    os.replace(tmp, path)


def _load_warmup(model_dir, warmup_path):
    import numpy as np

    path = warmup_path or os.path.join(model_dir, "warmup.npz")
    if not os.path.isfile(path):
        return None
    with np.load(path) as f:
        return [f["arr_%d" % i] for i in range(len(f.files))]


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model-dir", required=True,
                    help="saved inference model (save_inference_model)")
    ap.add_argument("--endpoint-file", required=True,
                    help="where to report the bound ports (atomic JSON)")
    ap.add_argument("--replica-id", default="0")
    ap.add_argument("--version", type=int, default=0,
                    help="model version tag (rollout audit header)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--warmup-npz", default="",
                    help="override the warmup example "
                         "(default: <model-dir>/warmup.npz)")
    args = ap.parse_args(argv)

    # heavy imports AFTER argparse: --help must not pay for jax
    from paddle_tpu import inference, serving
    from paddle_tpu.distributed import supervisor as _supervisor
    from paddle_tpu.observability import exporter as _obs_exporter

    pred = inference.create_paddle_predictor(
        inference.AnalysisConfig(args.model_dir)
    )
    warmup = _load_warmup(args.model_dir, args.warmup_npz)
    server = serving.InferenceServer(pred).start(warmup_inputs=warmup)
    gw = serving.Gateway(
        server, port=0, host=args.host,
        extra_headers={
            "X-Replica-Id": str(args.replica_id),
            "X-Model-Version": str(args.version),
        },
    ).start()
    gw.install_sigterm()

    exp = _obs_exporter.global_exporter()
    _write_endpoint(args.endpoint_file, {
        "pid": os.getpid(),
        "replica_id": str(args.replica_id),
        "version": int(args.version),
        "model_dir": args.model_dir,
        "gateway_port": gw.port,
        "metrics_port": exp.port if exp is not None else None,
        "warmed": warmup is not None,
        "ts": time.time(),
    })

    hb = _supervisor.worker_heartbeat()
    step = 0
    try:
        # serve until the gateway's drain closes the listener (SIGTERM
        # -> /readyz 503 -> in-flight completes -> port is None)
        while gw.port is not None:
            if hb is not None:
                hb.beat(step, status="serve")
            step += 1
            time.sleep(0.2)
    finally:
        gw.stop()
        server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
