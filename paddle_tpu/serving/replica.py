"""One serving replica: the process the FleetController spawns.

``python -m paddle_tpu.serving.replica --model-dir D --endpoint-file F``
builds the full single-process serving stack over a saved inference
model — AnalysisPredictor -> InferenceServer (micro-batcher + bucket
ladder, eagerly warmed) -> Gateway (HTTP front door) — then reports its
ephemeral ports back to the controller through an atomically written
*endpoint file* and heartbeats through the supervisor's worker protocol
(``PADDLE_TPU_HEARTBEAT_FILE``) until a SIGTERM drains it.

Contract with the controller:

- warmup happens BEFORE the gateway starts listening, so the first
  ``/readyz`` 200 already implies a fully warmed bucket ladder (and,
  under ``FLAGS_serving_strict_compiles``, an armed compile gate) —
  the controller can shift rollout traffic on readiness alone;
- ``warmup.npz`` beside the model (one array per feed, ``arr_0..``
  order) provides the warmup example; without it the replica serves
  unwarmed (strict mode would then fail its first request by design);
- every ``/v1/infer`` response carries ``X-Replica-Id`` and
  ``X-Model-Version`` headers (the router relays them), so rollout
  audits can attribute each answer to the exact replica and version
  that produced it;
- SIGTERM (the controller's drain) rides the gateway's graceful path:
  ``/readyz`` flips 503, every in-flight request completes, the
  listener closes, the process exits 0. Only a crash exits nonzero.

Scope: this stock replica serves ``/v1/infer`` over any
``save_inference_model`` export. ``/v1/generate`` needs a
``DecodeEngine``: pass ``--gpt-decode '<json spec>'`` and the replica
builds a GPT decode session beside the predictor — the spec carries the
GPTConfig geometry plus ``{"seed", "max_len", "slots",
"prefill_buckets"}``, and the params initialize from a SEEDED startup
program, so every replica spawned with the same spec holds bit-identical
weights (the property that makes a mid-stream failover token-exact: the
resumed replica's logits equal the dead one's). Engine knobs
(``FLAGS_decode_prefix_cache_mb``, ``FLAGS_decode_prefill_chunk``, ...)
ride the environment like everything else. Fleets with bespoke engines
still supply a custom ``replica_cmd``; the router's SSE pin/relay path
works against any gateway backend.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

__all__ = ["build_gpt_decode_engine", "main"]


def _write_endpoint(path, payload):
    """Atomic tmp+replace (the shared ``modeldir.commit_json``
    discipline): the controller must never read a torn file."""
    from paddle_tpu.checkpoint import modeldir as _modeldir

    _modeldir.commit_json(path, payload)


def _load_warmup(model_dir, warmup_path):
    import numpy as np

    path = warmup_path or os.path.join(model_dir, "warmup.npz")
    if not os.path.isfile(path):
        return None
    with np.load(path) as f:
        return [f["arr_%d" % i] for i in range(len(f.files))]


def build_gpt_decode_engine(spec):
    """A ``DecodeEngine`` from a ``--gpt-decode`` spec dict: tiny-based
    GPTConfig overrides plus ``seed`` (params initialize from a seeded
    startup program — bit-identical across every process given the same
    spec, the replica-interchangeability contract failover rests on),
    ``max_len``, ``slots`` and ``prefill_buckets``. Shared with the
    failover probe, which builds ITS oracle engine from the same spec."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.models import gpt as _gpt
    from paddle_tpu.serving.decode import DecodeEngine

    spec = dict(spec)
    seed = int(spec.pop("seed", 0))
    max_len = int(spec.pop("max_len", 64))
    slots = int(spec.pop("slots", 8))
    buckets = spec.pop("prefill_buckets", None)
    spec.setdefault("hidden_dropout", 0.0)
    spec.setdefault("attention_dropout", 0.0)
    cfg = _gpt.GPTConfig.tiny(**spec)
    cfg.max_position_embeddings = max_len
    with fluid.unique_name.guard():
        infer_prog, startup, _names, _logits = _gpt.build_gpt_infer(
            cfg, max_len
        )
    startup.random_seed = seed
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    with fluid.executor.scope_guard(scope):
        exe.run(startup)
    return DecodeEngine(cfg, scope=scope, slots=slots, max_len=max_len,
                        prefill_buckets=buckets,
                        param_program=infer_prog)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model-dir", required=True,
                    help="saved inference model (save_inference_model)")
    ap.add_argument("--endpoint-file", required=True,
                    help="where to report the bound ports (atomic JSON)")
    ap.add_argument("--replica-id", default="0")
    ap.add_argument("--version", type=int, default=0,
                    help="model version tag (rollout audit header)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--warmup-npz", default="",
                    help="override the warmup example "
                         "(default: <model-dir>/warmup.npz)")
    ap.add_argument("--gpt-decode", default="",
                    help="JSON spec: attach a seeded GPT DecodeEngine "
                         "so this replica serves /v1/generate "
                         "(see build_gpt_decode_engine)")
    ap.add_argument("--role", default="mixed",
                    choices=("prefill", "decode", "mixed"),
                    help="fleet KV-tier role: prefill replicas compute "
                         "+ publish chain blocks over /v1/kv/prefill; "
                         "decode replicas own slots and pull published "
                         "blocks on admission miss; mixed does both")
    args = ap.parse_args(argv)

    # heavy imports AFTER argparse: --help must not pay for jax
    from paddle_tpu import inference, serving
    from paddle_tpu.distributed import supervisor as _supervisor
    from paddle_tpu.observability import exporter as _obs_exporter

    pred = inference.create_paddle_predictor(
        inference.AnalysisConfig(args.model_dir)
    )
    engine = None
    if args.gpt_decode:
        engine = build_gpt_decode_engine(json.loads(args.gpt_decode))
    warmup = _load_warmup(args.model_dir, args.warmup_npz)
    server = serving.InferenceServer(
        pred, decode_engine=engine
    ).start(warmup_inputs=warmup)
    gw = serving.Gateway(
        server, port=0, host=args.host, role=args.role,
        extra_headers={
            "X-Replica-Id": str(args.replica_id),
            "X-Model-Version": str(args.version),
        },
    ).start()
    gw.install_sigterm()

    from paddle_tpu.observability import trace as _trace

    exp = _obs_exporter.global_exporter()
    # the clock-anchor pair (ts wall / ts_mono span clock) rides the
    # endpoint file so the controller can align this replica's trace
    # timeline even before (or without) pulling its /healthz
    anchor = _trace.clock_anchor()
    endpoint = {
        "pid": os.getpid(),
        "replica_id": str(args.replica_id),
        "version": int(args.version),
        "model_dir": args.model_dir,
        "gateway_port": gw.port,
        "metrics_port": exp.port if exp is not None else None,
        "role": args.role,
        "warmed": warmup is not None,
        "ts": anchor["ts"],
        "ts_mono": anchor["ts_mono"],
        "lease_ts": time.time(),
    }
    _write_endpoint(args.endpoint_file, endpoint)

    from paddle_tpu.fluid import flags as _flags

    lease_interval = float(_flags.get_flag("fleet_lease_interval_s"))
    hb = _supervisor.worker_heartbeat()
    step = 0
    last_lease = time.time()
    try:
        # serve until the gateway's drain closes the listener (SIGTERM
        # -> /readyz 503 -> in-flight completes -> port is None)
        while gw.port is not None:
            if hb is not None:
                hb.beat(step, status="serve")
            # re-stamp the endpoint lease: proof this loop is turning,
            # which outlives the controller (adoption trusts the stamp
            # before any controller is back to probe us)
            if lease_interval > 0 and \
                    time.time() - last_lease >= lease_interval:
                endpoint["lease_ts"] = last_lease = time.time()
                try:
                    _write_endpoint(args.endpoint_file, endpoint)
                except OSError:
                    pass
            step += 1
            time.sleep(0.2)
    finally:
        gw.stop()
        server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
