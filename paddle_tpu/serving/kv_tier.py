"""Fleet KV tier: content-addressed prefix blocks across device, host,
and replicas.

PR 12/16 made prefix K/V reuse cheap *inside* one replica — the paged
pool plus ``PagedPrefixIndex`` turn a shared system prompt into
zero-copy block references. But the index is per-replica and bounded by
device memory: at fleet scale the same prefix re-prefills once per
replica, and an LRU-evicted block is recomputed from scratch. This
module is the tier that fixes both, built on one observation: a paged
KV block is now a plain refcounted array addressed by a content hash
(the chain digest), so it can move across the device/host boundary and
between replicas without any replica-local naming — bytes-moved vs
tokens-recomputed becomes a measurable crossover instead of a guess
(PAPERS: portable array redistribution).

Three pieces, smallest first:

``block_hash`` / ``chain_keys``
    THE canonical chain digest — ``decode.py`` aliases it (so a test
    that monkeypatches ``decode._block_hash`` still works) and the
    router computes the same keys for affinity scoring. One definition
    means a replica's advertisement and the router's expectation can
    never drift.

``HostBlockStore``
    The host-spill tier behind ``PagedPrefixIndex``: when the device
    index LRU-evicts an entry, the engine spills the block's K/V rows
    D2H into this store (async, off the tick thread — see
    ``SpillWorker``) instead of letting the bytes vanish. A later
    admission whose chain walks past the device index re-admits the
    spilled payload H2D into freshly allocated blocks — O(bytes copied)
    against O(tokens^2) re-prefill, which wins past a measured
    crossover length (banked in PERF.md). Capacity-bounded by
    ``FLAGS_kv_tier_host_mb`` with its own LRU; thread-safe (the spill
    worker puts, the engine loop gets).

``encode_entries`` / ``decode_entries``
    The wire form for the role-split fleet: a prefill-role replica
    serializes its chain blocks (base64 float32 rows) over the internal
    ``/v1/kv/prefill`` endpoint; a decode-role replica pulls and admits
    them into its own pool. The decoder re-verifies every chain link —
    a payload is data, never trusted naming.

The tier is an optimization layered on an unchanged correctness story:
every spilled / re-admitted / pulled block holds the exact float32 rows
the local prefill would have computed (same seeded params fleet-wide),
so every stream stays token-exact vs ``_reference_generate``.
"""

from __future__ import annotations

import base64
import threading
import time
from collections import OrderedDict, deque

import numpy as np

from ..fluid import profiler as _profiler

__all__ = [
    "HostBlockStore",
    "SpillWorker",
    "block_hash",
    "chain_keys",
    "decode_entries",
    "encode_entries",
    "read_peers",
]


def block_hash(prev_key, tokens):
    """Chain digest for one prompt block: block i's key folds in block
    i-1's, so equal keys mean equal WHOLE prefixes. A real digest
    (sha256 over prev_digest || token bytes), NOT ``hash()`` — the
    gateway hands this map client-controlled token ids, and a
    birthday-searchable 61-bit key would let a tenant engineer
    cross-request K/V reuse. Shared by the engine's index, the host
    store, and the router's affinity scorer — one definition, zero
    drift. No consumer trusts the key alone: every match re-compares
    the stored (prev, tokens) link."""
    import hashlib

    h = hashlib.sha256()
    h.update(repr(prev_key).encode())
    h.update(np.asarray(tokens, np.int64).tobytes())
    return h.hexdigest()


def chain_keys(prompt, block):
    """The prompt's full-block chain keys, root first: key i covers
    tokens [0, (i+1)*block). The router scores a backend by the deepest
    of these keys the backend advertises — chain keys name whole
    prefixes, so depth alone gives expected cached tokens."""
    out = []
    prev = 0
    for b in range(len(prompt) // int(block)):
        toks = tuple(prompt[b * block:(b + 1) * block])
        prev = block_hash(prev, toks)
        out.append(prev)
    return out


class _HostEntry(object):
    __slots__ = ("key", "prev", "tokens", "payload", "nbytes")

    def __init__(self, key, prev, tokens, payload):
        self.key = key
        self.prev = prev
        self.tokens = tuple(int(t) for t in tokens)
        # payload: [(k_row, v_row)] per layer, each a float32
        # [heads, block, d_head] HOST array — the exact bytes the pool
        # row held on device
        self.payload = payload
        self.nbytes = sum(k.nbytes + v.nbytes for k, v in payload)


class HostBlockStore(object):
    """Host-RAM LRU of spilled prefix blocks, keyed by chain digest.

    The device index's eviction shadow: ``put`` is called by the spill
    worker with the evicted block's K/V rows; ``get`` is called by the
    engine loop at admission when the chain walk outruns the device
    index. Thread-safe under one lock — both sides are rare relative to
    decode ticks, and the payloads themselves are immutable once
    stored. Capacity is bytes (``FLAGS_kv_tier_host_mb``); inserting
    past it evicts the host-LRU tail (``kv_tier_host_evictions``) —
    a block falling off BOTH tiers is finally recomputed, which is the
    pre-PR-17 behavior for every block."""

    def __init__(self, capacity_bytes):
        self.capacity_bytes = int(capacity_bytes)
        if self.capacity_bytes < 1:
            raise ValueError(
                "host store needs capacity_bytes >= 1, got %d"
                % self.capacity_bytes
            )
        self._lock = threading.Lock()
        self._entries = OrderedDict()  # key -> _HostEntry, LRU order
        self._bytes = 0
        self.spills = 0          # accepted puts
        self.readmits = 0        # hits the engine re-admitted
        self.host_evictions = 0  # entries the byte cap pushed out

    def __len__(self):
        with self._lock:
            return len(self._entries)

    @property
    def bytes_used(self):
        with self._lock:
            return self._bytes

    def put(self, key, prev, tokens, payload, tally=True):
        """Store one spilled block (idempotent: a key already resident
        just refreshes its LRU position — re-spilling the same content
        moves no new bytes). Returns True when the payload was
        accepted; an over-capacity single block is refused rather than
        flushing the whole store for one entry. ``tally=False`` skips
        the spill counters — a block PULLED from a peer is not a D2H
        spill (the pull path keeps its own kv_tier_pull_* tallies)."""
        e = _HostEntry(key, prev, tokens, payload)
        if e.nbytes > self.capacity_bytes:
            return False
        with self._lock:
            old = self._entries.get(key)
            if old is not None:
                self._entries.move_to_end(key)
                return True
            while self._bytes + e.nbytes > self.capacity_bytes:
                _k, victim = self._entries.popitem(last=False)
                self._bytes -= victim.nbytes
                self.host_evictions += 1
                _profiler.bump_counter("kv_tier_host_evictions")
            self._entries[key] = e
            self._bytes += e.nbytes
            if tally:
                self.spills += 1
        if tally:
            _profiler.bump_counter("kv_tier_spills")
            _profiler.bump_counter("kv_tier_bytes_d2h", e.nbytes)
        return True

    def get(self, key, prev, tokens):
        """The entry under ``key`` — chain-verified against the
        caller's (prev, tokens) link, LRU-refreshed. None on miss or
        link mismatch (a colliding key must fall through to prefill,
        same rule as the device index)."""
        tokens = tuple(int(t) for t in tokens)
        with self._lock:
            e = self._entries.get(key)
            if e is None or e.tokens != tokens or e.prev != prev:
                return None
            self._entries.move_to_end(key)
            return e

    def note_readmit(self, entry):
        """Tally one H2D re-admission of ``entry`` (the engine owns the
        actual pool write; the store owns the counters so unit tests
        can audit traffic without an engine)."""
        self.readmits += 1
        _profiler.bump_counter("kv_tier_readmits")
        _profiler.bump_counter("kv_tier_bytes_h2d", entry.nbytes)

    def stats(self):
        with self._lock:
            return {
                "host_blocks": len(self._entries),
                "host_bytes": self._bytes,
                "capacity_bytes": self.capacity_bytes,
                "spills": self.spills,
                "readmits": self.readmits,
                "host_evictions": self.host_evictions,
            }


class SpillWorker(object):
    """One daemon thread draining spill jobs off the engine tick.

    The engine loop must never pay a D2H read mid-tick, but eviction
    happens mid-tick (inside the admission path's allocation pressure).
    Protocol: the loop thread pins the evicted block (one extra
    allocator ref) and ``submit``s a job; this thread batches every
    queued job into ONE ``batch_fn(jobs)`` call (the engine's reader
    snapshots each per-layer pool once per batch, not once per block)
    and the engine's batch_fn hands the freed block ids back through
    its done-queue for the loop thread to decref. ``drain`` bounds the
    allocator-pressure path: when the free list is empty and blocks
    are pinned awaiting spill, the engine may wait (bounded) for this
    thread to finish the in-flight batch."""

    def __init__(self, batch_fn, name="kv-spill"):
        self._batch_fn = batch_fn
        self._jobs = deque()
        self._cond = threading.Condition()
        self._stop = False
        self._busy = 0  # jobs taken but not yet completed
        self._thread = threading.Thread(
            target=self._run, name=name, daemon=True
        )
        self._thread.start()

    def submit(self, job):
        with self._cond:
            if self._stop:
                raise RuntimeError("spill worker stopped")
            self._jobs.append(job)
            self._cond.notify_all()

    @property
    def pending(self):
        with self._cond:
            return len(self._jobs) + self._busy

    def drain(self, timeout=1.0):
        """Block (bounded) until every submitted job has completed.
        Returns True when fully drained."""
        deadline = time.monotonic() + float(timeout)
        with self._cond:
            while self._jobs or self._busy:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cond.wait(left)
        return True

    def stop(self, timeout=5.0):
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        self._thread.join(timeout=timeout)

    def _run(self):
        while True:
            with self._cond:
                while not self._jobs and not self._stop:
                    self._cond.wait()
                if not self._jobs and self._stop:
                    return
                batch = list(self._jobs)
                self._jobs.clear()
                self._busy = len(batch)
            try:
                self._batch_fn(batch)
            except Exception:  # noqa: BLE001 - spill is best-effort
                # a failed spill loses an optimization, never bytes a
                # request depends on; the engine's done-queue still gets
                # the block ids back (batch_fn guarantees it in its own
                # finally), so no block leaks pinned
                pass
            finally:
                with self._cond:
                    self._busy = 0
                    self._cond.notify_all()


# ---------------------------------------------------------------------------
# wire form: serialized chain blocks for the prefill -> decode pull path
# ---------------------------------------------------------------------------
def encode_entries(entries):
    """JSON-safe form of exported chain blocks: ``entries`` is
    [(key, prev, tokens, payload)] in CHAIN ORDER (root first), payload
    as in ``_HostEntry``. Arrays ride base64 float32 — bit-exact, and
    the decoder rebuilds shapes from the advertised geometry."""
    out = []
    for key, prev, tokens, payload in entries:
        out.append({
            "key": key,
            "prev": prev,
            "tokens": [int(t) for t in tokens],
            "layers": [
                [base64.b64encode(np.ascontiguousarray(
                    k, dtype=np.float32).tobytes()).decode("ascii"),
                 base64.b64encode(np.ascontiguousarray(
                     v, dtype=np.float32).tobytes()).decode("ascii")]
                for k, v in payload
            ],
        })
    return out


def decode_entries(blob, row_shape):
    """Inverse of ``encode_entries``: returns [(key, prev, tokens,
    payload)] with every array reshaped to ``row_shape``
    ([heads, block, d_head]) and every chain link RE-VERIFIED — an
    entry whose key does not hash from its own (prev, tokens) is
    dropped along with everything chained after it (a decode replica
    must never admit a block under a name its content doesn't earn)."""
    n = 1
    for d in row_shape:
        n *= int(d)
    out = []
    expect_prev = 0
    for d in blob:
        key, prev, tokens = d["key"], d["prev"], [int(t) for t in
                                                  d["tokens"]]
        if prev != expect_prev or block_hash(prev, tokens) != key:
            break
        payload = []
        ok = True
        for kb, vb in d["layers"]:
            k = np.frombuffer(base64.b64decode(kb), np.float32)
            v = np.frombuffer(base64.b64decode(vb), np.float32)
            if k.size != n or v.size != n:
                ok = False
                break
            payload.append((k.reshape(row_shape).copy(),
                            v.reshape(row_shape).copy()))
        if not ok:
            break
        out.append((key, prev, tuple(tokens), payload))
        expect_prev = key
    return out


def read_peers(path):
    """The controller-maintained peers file (atomic JSON): the prefill
    replicas a decode replica may pull published blocks from. Returns
    [] on any read problem — a torn or missing file degrades to local
    prefill, never an error."""
    import json
    import os

    if not path or not os.path.isfile(path):
        return []
    try:
        with open(path) as f:
            doc = json.load(f)
        peers = doc.get("peers") or []
        return [p for p in peers
                if isinstance(p, dict) and p.get("port")]
    except (OSError, ValueError):
        return []
