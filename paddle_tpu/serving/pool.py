"""Predictor pool: N AnalysisPredictor clones sharing compiled plans.

``AnalysisPredictor.clone()`` gives each worker thread its own scope and
input/output staging (the mutable per-request state), while the compiled
block and its jit executable cache ride the shared plan holder — so the
pool compiles each bucket shape exactly once, and the eager warmup run on
one member warms every member (reference: analysis_predictor.cc Clone,
which shares the optimized program between per-thread predictors).
"""

from __future__ import annotations

import contextlib
import queue

__all__ = ["PredictorPool"]


class PredictorPool(object):
    def __init__(self, predictor, size=2):
        if size < 1:
            raise ValueError("pool size must be >= 1")
        self.primary = predictor
        self._all = [predictor]
        for _ in range(int(size) - 1):
            self._all.append(predictor.clone())  # share_plans=True default
        self._free = queue.Queue()
        for p in self._all:
            self._free.put(p)

    @property
    def size(self):
        return len(self._all)

    @property
    def free_count(self):
        """Currently checked-in predictors (approximate under races —
        queue length is a snapshot). Published as the
        ``serving_pool_free`` gauge so a scrape shows pool saturation
        next to queue depth."""
        return self._free.qsize()

    @contextlib.contextmanager
    def acquire(self, timeout=None):
        """Check a predictor out for one batch; always returned."""
        try:
            p = self._free.get(timeout=timeout)
        except queue.Empty:
            raise RuntimeError(
                "no free predictor within %.1fs (pool size %d)"
                % (timeout or 0.0, len(self._all))
            )
        try:
            yield p
        finally:
            self._free.put(p)
