"""InferenceServer — the dynamic-batching serving runtime.

Ties the subsystem together on top of AnalysisPredictor:

  client threads --submit--> [admission queue | MicroBatcher]
        --coalesced batch--> BucketLadder.pad_feeds (round to bucket)
        --padded batch-----> PredictorPool predictor.run (compiled plan)
        --outputs----------> unpad_outputs --split--> per-request results

Defaults come from the serving_* flags (fluid/flags.py) so deployments
tune the policy via FLAGS_ env vars without code changes. ``start()``
eagerly warms every bucket-ladder shape through the pool's shared
compiled plans, so steady-state traffic never sees an XLA compile on the
request path; ``stats()`` returns the ServingStats snapshot.

This is the in-process runtime (the piece worth building on TPU); a
transport (HTTP/gRPC) would sit in front of ``infer()`` unchanged.
"""

from __future__ import annotations

import threading

import numpy as np

from ..fluid import flags as _flags
from ..fluid import profiler as _profiler
from ..observability import exporter as _obs_exporter
from ..observability import registry as _obs_registry
from ..observability import trace as _trace
from ..observability import xla_stats as _xla_stats
from .batcher import (
    DeadlineExceededError,
    MicroBatcher,
    ServerOverloadedError,
    ServingError,
)
from .buckets import BucketLadder
from .metrics import snapshot_stats
from .pool import PredictorPool

__all__ = ["InferenceServer"]


def _flag(name, override):
    return override if override is not None else _flags.get_flag(name)


class InferenceServer(object):
    """Dynamic-batching server over an AnalysisPredictor (or anything
    with ``run(list_of_arrays) -> list_of_arrays`` and ``clone()``).

    Parameters default to the serving_* flags; ``ladder`` overrides the
    default power-of-two batch-bucket ladder (e.g. to add seq buckets).
    """

    def __init__(self, predictor, max_batch_size=None, batch_timeout_ms=None,
                 queue_depth=None, num_workers=None, default_deadline_ms=None,
                 ladder=None, decode_engine=None):
        self.max_batch_size = int(_flag("serving_max_batch_size",
                                        max_batch_size))
        self.batch_timeout_ms = float(_flag("serving_batch_timeout_ms",
                                            batch_timeout_ms))
        self.queue_depth = int(_flag("serving_queue_depth", queue_depth))
        self.num_workers = int(_flag("serving_workers", num_workers))
        self.default_deadline_ms = float(_flag("serving_default_deadline_ms",
                                               default_deadline_ms))
        self.ladder = ladder or BucketLadder(max_batch=self.max_batch_size)
        if self.ladder.max_batch < self.max_batch_size:
            raise ValueError(
                "bucket ladder tops out at %d rows but max_batch_size is %d"
                % (self.ladder.max_batch, self.max_batch_size)
            )
        self._predictor = predictor
        self._pool = None
        self._batcher = None
        self._warm_sigs = set()
        self._warm_lock = threading.Lock()
        self._baseline = {}
        self._lat_base = 0
        self._pool_gauge = None
        self._steady_armed = False
        self._started = False
        # autoregressive generation rides a DecodeEngine (serving/decode.py
        # KV-cache slot pool + continuous batching); classification-style
        # whole-forward traffic keeps the micro-batcher path
        self._decode_engine = decode_engine
        self._engine_started_here = False

    # -- lifecycle -----------------------------------------------------------
    def start(self, warmup_inputs=None):
        """Build the pool and dispatch workers. ``warmup_inputs`` (one
        example request: list of arrays, axis 0 = rows) eagerly compiles
        every bucket-ladder shape BEFORE traffic arrives, so no
        steady-state request ever waits on XLA."""
        if self._started:
            raise RuntimeError("server already started")
        self._pool = PredictorPool(self._predictor, size=self.num_workers)
        if warmup_inputs is not None:
            self.warmup(warmup_inputs)
        # baseline AFTER warmup: stats() reports steady-state traffic only
        self._baseline = _profiler.get_counters()
        self._lat_base = len(_profiler.get_histogram("serving_latency_ms"))
        self._batcher = MicroBatcher(
            self._run_batch,
            max_batch_size=self.max_batch_size,
            batch_timeout_ms=self.batch_timeout_ms,
            queue_depth=self.queue_depth,
            num_workers=self.num_workers,
            default_deadline_ms=self.default_deadline_ms,
        )
        self._started = True
        # telemetry: FLAGS_obs_* light up /metrics /healthz /trace and
        # JSONL snapshots with no code changes (no-op when disarmed).
        # The admission-queue depth gauge (serving_queue_depth) is owned
        # by the MicroBatcher itself; pool occupancy publishes here.
        _obs_exporter.maybe_start_from_flags()
        self._pool_gauge = lambda p=self._pool: p.free_count
        _obs_registry.register_gauge("serving_pool_free", self._pool_gauge)
        # warmup is over: from here every XLA compile is a steady-state
        # recompile — counted, and (FLAGS_serving_strict_compiles) fatal
        # to the offending request. NOTE: strict mode presumes
        # warmup_inputs warmed the ladder; an unwarmed strict server
        # fails its first request by design. Arm is COUNTED (ownership-
        # scoped like the gauges): stopping an older server must not
        # disarm the gate under a live successor in the same process.
        _xla_stats.arm_serving_steady()
        self._steady_armed = True
        if self._decode_engine is not None and not self._decode_engine.started:
            # engine warmup also runs pre-arm windows of its own; a server
            # that starts its engine also stops it. A FAILED engine start
            # must unwind the whole server (batcher threads, gauges, the
            # counted strict gate armed just above) — the caller of
            # `InferenceServer(...).start()` has no handle to stop with
            try:
                self._decode_engine.start()
                self._engine_started_here = True
            except Exception:
                self.stop()
                raise
        return self

    def warmup(self, example_inputs):
        """Run every bucket shape once through the pool's shared plans.
        Callable before start() traffic or any time the ladder grows; on
        a live server a predictor is checked OUT of the pool PER SHAPE
        (never raced with a dispatch worker's staging, and released
        between compiles so live traffic interleaves instead of starving
        through the whole ladder — a full-ladder hold on a size-1 pool
        would stall every batch for minutes of TPU compile time)."""
        example = [np.asarray(a) for a in example_inputs]
        c_before = _profiler.get_counters()
        with _xla_stats.warmup_window(), _trace.span(
            "serving_warmup", cat="serving"
        ):
            self._warm_ladder(example)
        if self._started:
            # post-start warmup (ladder growth on a live server): fold the
            # warmup-attributable plan-cache activity into the baseline so
            # stats() keeps reporting request-path compiles only ('zero
            # miss delta == zero steady-state compiles')
            c_after = _profiler.get_counters()
            for k in ("predictor_plan_cache_misses",
                      "predictor_plan_cache_hits"):
                self._baseline[k] = self._baseline.get(k, 0) + (
                    c_after.get(k, 0) - c_before.get(k, 0)
                )

    def _warm_ladder(self, example):
        for rows, seq in self.ladder.shapes():
            feeds = []
            for a in example:
                one = a[:1] if a.ndim else a.reshape(1)
                if (seq is not None and one.ndim > self.ladder.seq_axis
                        and one.shape[self.ladder.seq_axis] > seq):
                    idx = [slice(None)] * one.ndim
                    idx[self.ladder.seq_axis] = slice(0, seq)
                    one = one[tuple(idx)]
                feeds.append(one)
            plan = self.ladder.plan(feeds)
            plan.padded_rows, plan.padded_seq = rows, seq
            padded, _ = self.ladder.pad_feeds(feeds, plan)
            self._record_bucket(padded, warm=True)
            if self._pool is not None:
                with self._pool.acquire() as pred:
                    pred.run(padded)
            else:
                self._predictor.run(padded)

    def stop(self):
        # mirror the trainer's finally: a serving process with
        # FLAGS_obs_dir armed must leave its per-rank snapshot even with
        # snapshot_interval 0 ("one final snapshot" contract)
        _obs_exporter.final_snapshot()
        # disarm THIS server's steady-state compile gate (a stopped or
        # restarting server's compiles are lifecycle, not violations);
        # counted, so another live server's gate stays armed, and
        # idempotent across repeated stop() calls
        if getattr(self, "_steady_armed", False):
            _xla_stats.disarm_serving_steady()
            self._steady_armed = False
        if getattr(self, "_pool_gauge", None) is not None:
            # ownership-scoped: a second server that re-registered the
            # gauge keeps it when this (older) one stops; the queue
            # gauge travels with the batcher (stopped below)
            _obs_registry.unregister_gauge(
                "serving_pool_free", self._pool_gauge
            )
            self._pool_gauge = None
        if self._batcher is not None:
            self._batcher.stop()
        if self._decode_engine is not None and self._engine_started_here:
            self._decode_engine.stop()
            self._engine_started_here = False
        self._started = False

    def __enter__(self):
        return self if self._started else self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- request path --------------------------------------------------------
    def infer(self, inputs, deadline_ms=None, timeout=None):
        """Blocking request: list of arrays (axis 0 = rows, usually 1).
        Returns the per-request output list. Raises
        ServerOverloadedError (shed at admission, carries retry_after_ms)
        or DeadlineExceededError (shed at dispatch) — both retriable —
        or ServingError for execution failures."""
        return self.result(self.submit(inputs, deadline_ms=deadline_ms),
                           timeout=timeout)

    def submit(self, inputs, deadline_ms=None):
        """Non-blocking admission; pair with ``result()``."""
        if not self._started:
            raise ServingError("server not started")
        aligned, seq_plan = self._seq_align(inputs)
        req = self._batcher.submit(aligned, deadline_ms=deadline_ms)
        req.seq_plan = seq_plan
        return req

    def result(self, req, timeout=None):
        outs = self._batcher.result(req, timeout=timeout)
        if req.seq_plan is not None:
            # strip the admission-time seq padding (row axis untouched:
            # seq_plan carries padded_rows == rows)
            outs = self.ladder.unpad_outputs(outs, req.seq_plan)
        return outs

    def generate(self, prompt_ids, max_new_tokens=None, eos_id=None,
                 temperature=0.0, top_k=0, top_p=0.0, seed=None,
                 resume_tokens=None, priority=None, tenant=None):
        """Autoregressive completion through the attached DecodeEngine:
        returns a ``GenerationStream`` — iterate it for tokens as they
        are generated, or block on ``.tokens()`` / ``.result()``. The
        request joins the engine's continuous decode batch (admitted via
        prefill into a KV-cache slot mid-flight; never recompiles).
        Sampling knobs are per-request, host-side over the fetched
        logits (``decode.sample_token``): greedy is the default, a
        seeded sampling request replays deterministically.
        ``resume_tokens`` is the durable-generation resume form (the
        suffix an interrupted run already emitted — see
        ``DecodeEngine.submit``); the stream then emits only the
        token-exact continuation."""
        if self._decode_engine is None:
            raise ServingError(
                "no decode engine attached: construct the server with "
                "decode_engine=DecodeEngine(cfg, ...) to serve generation"
            )
        return self._decode_engine.generate(
            prompt_ids, max_new_tokens=max_new_tokens, eos_id=eos_id,
            temperature=temperature, top_k=top_k, top_p=top_p, seed=seed,
            resume_tokens=resume_tokens, priority=priority, tenant=tenant,
        )

    def _seq_align(self, inputs):
        """(aligned_inputs, request_plan|None). With seq buckets enabled
        each request's seq axis pads to its bucket AT ADMISSION, so
        bucket-equivalent requests share one coalescing signature — on
        raw lengths, mixed-seq traffic would never coalesce (every
        request a distinct sig) and fill would collapse to 1/max_batch
        for exactly the traffic seq buckets exist for. Rows stay
        untouched; the batch-level row pad happens per coalesced batch."""
        if self.ladder.seq_buckets is None:
            return inputs, None
        feeds = [np.asarray(a) for a in inputs]
        plan = self.ladder.plan(feeds)
        if plan.padded_seq is None:
            return feeds, None
        plan.padded_rows = plan.rows  # seq-only pad at admission
        padded, plan = self.ladder.pad_feeds(feeds, plan)
        return padded, plan

    # -- internals -----------------------------------------------------------
    def _record_bucket(self, padded_feeds, warm=False):
        sig = tuple((a.shape, a.dtype.str) for a in padded_feeds)
        with self._warm_lock:  # dispatch workers record concurrently
            hit = sig in self._warm_sigs
            if not hit:
                self._warm_sigs.add(sig)
        if not warm:
            _profiler.bump_counter(
                "serving_bucket_hits" if hit else "serving_bucket_misses"
            )

    def _run_batch(self, stacked, rows):
        padded, plan = self.ladder.pad_feeds(stacked)
        _profiler.bump_counter("serving_pad_rows",
                               plan.padded_rows - plan.rows)
        self._record_bucket(padded)
        # nests inside the batcher's serving_dispatch span (same worker
        # thread): pool wait + device time vs stacking/padding overhead.
        # The request window scopes the steady-state compile gate to
        # THIS thread's compiles — a colocated trainer never trips it.
        with _xla_stats.serving_request_window(), _trace.span(
                "predictor_run", cat="serving",
                rows=rows, padded_rows=plan.padded_rows):
            # blocking acquire: when warmup (or a slow batch) holds the
            # pool, batches WAIT rather than failing their clients;
            # per-request deadlines bound the caller-visible latency
            with self._pool.acquire() as pred:
                outs = pred.run(padded)
        return self.ladder.unpad_outputs(outs, plan)

    def stats(self):
        """ServingStats snapshot (deltas since start; latency percentiles
        over the histogram window)."""
        return snapshot_stats(
            baseline=self._baseline,
            queue_depth=self._batcher.queue_len if self._batcher else 0,
            max_batch_size=self.max_batch_size,
            latency_baseline_count=self._lat_base,
        )
