"""Shared JSONL access-log writer (gateway + router).

One locked single-write appends each record as a whole line —
concurrent handler threads at worst interleave whole lines, the same
contract as ``registry.write_snapshot``. Disabled when pathless; a
full disk must not fail requests.

Size bounding: with ``max_mb > 0`` the log rotates the moment an
append pushes it past the cap — the current file renames to
``<path>.1`` (replacing the previous rollover: keep-1) and appends
continue into a fresh file, so a long-lived front door holds at most
~2x the cap on disk. Rotation happens under the same lock as the
write, so no line is ever torn across the boundary; rotations are
counted (``access_log_rotations``).

Several PROCESSES may share one path (a replica pool appending to one
gateway log): appends stay line-atomic via O_APPEND, and rotation is
guarded against the cross-process race — an fcntl flock (where
available) serializes writers, and the rotor re-checks that its fd
still IS the live file (inode match) before renaming, so a peer that
rotated first can't have its freshly-preserved ``.1`` history
clobbered by the near-empty successor.
"""

from __future__ import annotations

import json
import os
import threading

from ..fluid import profiler as _profiler

__all__ = ["AccessLog"]

try:
    from fcntl import LOCK_EX as _LOCK_EX
    from fcntl import flock as _flock
except ImportError:  # non-POSIX: in-process lock + inode check only
    _flock = None


class AccessLog(object):
    def __init__(self, path, max_mb=0.0):
        self.path = str(path) if path else None
        try:
            self.max_bytes = int(float(max_mb or 0.0) * 1024 * 1024)
        except (TypeError, ValueError):
            self.max_bytes = 0
        self._lock = threading.Lock()

    def write(self, record):
        if not self.path:
            return
        line = json.dumps(record, sort_keys=True) + "\n"
        try:
            with self._lock:
                with open(self.path, "a") as f:
                    if self.max_bytes > 0 and _flock is not None:
                        _flock(f, _LOCK_EX)  # released when f closes
                    f.write(line)
                    size = f.tell()
                    if self.max_bytes > 0 and size >= self.max_bytes:
                        # a peer process may have rotated between our
                        # open and here (its full file is now .1, the
                        # path is a fresh near-empty file): only rotate
                        # while this fd still IS the live file
                        try:
                            live = os.stat(self.path).st_ino == os.fstat(
                                f.fileno()).st_ino
                        except OSError:
                            live = False
                        if live:
                            # keep-1 rollover: the previous .1 (one full
                            # cap of history) is the price of a bounded
                            # disk footprint
                            os.replace(self.path, self.path + ".1")
                            _profiler.bump_counter("access_log_rotations")
        except OSError:
            pass
