"""Simulated replica: empirical service-time model + slot occupancy.

``ServiceModel.fit`` distils recorded journeys into per-priority-class
empirical pools of TTFT and inter-token latencies; the simulator then
*resamples* those pools (bootstrap-style) instead of assuming a
parametric distribution — the simulated day inherits the real day's
tail shape.  ``SimReplica`` is the queueing model the virtual clock
drives: a fixed slot pool with interactive-first, preempted-first
dequeue order mirroring the real engine's scheduler, plus the rolling
SLI windows that feed ``scrape_sample`` — the SAME dict shape
``FleetController._scrape_samples`` produces, so the real
AutoscalerPolicy / SLOPolicy run against it unmodified.
"""

from __future__ import annotations

import collections

import numpy as np

from ...observability import registry as _registry

__all__ = ["ServiceModel", "SimReplica"]

_CLASSES = ("interactive", "batch")

# conservative fixed defaults when no recording (or an empty class pool)
# is available to fit against: ~60ms to first token, ~25ms/token after.
_DEFAULT_TTFT_MS = 60.0
_DEFAULT_INTERTOKEN_MS = 25.0


class ServiceModel(object):
    """Per-class empirical (TTFT, inter-token) latency pools."""

    def __init__(self, ttft_ms=None, intertoken_ms=None):
        # {cls: [samples...]} — missing/empty classes fall back to the
        # pooled samples, then to the fixed defaults.
        self.ttft_ms = dict(ttft_ms or {})
        self.intertoken_ms = dict(intertoken_ms or {})

    @classmethod
    def fit(cls, journeys):
        """Fit from journey records: ``ttft_ms`` is recorded directly;
        inter-token is ``(ms - ttft_ms) / max(tokens - 1, 1)`` — the
        stream's mean decode cadence.  A journey with a duration but no
        ``ttft_ms`` (a non-streaming /v1/infer request) is a single-shot
        service: its whole ``ms`` joins the TTFT pool — replayed as
        one token, its service time is exactly the recorded one."""
        ttft = {c: [] for c in _CLASSES}
        inter = {c: [] for c in _CLASSES}
        for j in journeys or []:
            c = "batch" if j.get("priority") == "batch" else "interactive"
            try:
                t = j.get("ttft_ms")
                ms = j.get("ms")
                toks = j.get("tokens")
            except AttributeError:
                continue
            if t is not None and float(t) > 0:
                ttft[c].append(float(t))
                if ms is not None and toks and float(ms) >= float(t):
                    inter[c].append(
                        (float(ms) - float(t)) / max(float(toks) - 1.0, 1.0)
                    )
            elif ms is not None and float(ms) > 0 and not toks:
                ttft[c].append(float(ms))
        return cls(ttft, inter)

    def _pool(self, table, cls):
        pool = table.get(cls)
        if pool:
            return pool
        merged = [v for vs in table.values() for v in vs]
        return merged or None

    def sample_ttft_ms(self, cls, rng):
        pool = self._pool(self.ttft_ms, cls)
        if pool is None:
            return _DEFAULT_TTFT_MS
        return float(pool[int(rng.randint(0, len(pool)))])

    def sample_intertoken_ms(self, cls, rng):
        pool = self._pool(self.intertoken_ms, cls)
        if pool is None:
            return _DEFAULT_INTERTOKEN_MS
        return float(pool[int(rng.randint(0, len(pool)))])

    def as_dict(self):
        out = {}
        for label, table in (("ttft_ms", self.ttft_ms),
                             ("intertoken_ms", self.intertoken_ms)):
            for c in _CLASSES:
                out["%s_%s" % (label, c)] = _registry.percentiles(
                    table.get(c) or [])
        return out


class _SimJob(object):
    __slots__ = ("req", "remaining", "preempted", "enq_t", "start_t",
                 "first_token_t", "intertoken_s")

    def __init__(self, req, now):
        self.req = req
        self.remaining = int(req["max_new_tokens"])
        self.preempted = False
        self.enq_t = float(now)
        self.start_t = None
        self.first_token_t = None
        self.intertoken_s = None


class SimReplica(object):
    """One simulated replica: slot pool + pending queue + SLI windows.

    The simulator owns the clock; the replica only answers "which job
    runs next" and "when does this slot produce its next token", and
    accumulates the rolling windows ``scrape_sample`` summarises.
    """

    def __init__(self, replica_id, model, slots=4, queue_depth=64,
                 window=256):
        self.id = str(replica_id)
        self.model = model
        self.slots = int(slots)
        self.queue_depth = int(queue_depth)
        self.pending = []          # [_SimJob] — dequeue via _dequeue()
        self.active = {}           # slot_idx -> _SimJob
        self.free = list(range(self.slots))
        self.shed_total = 0
        self.completed = 0
        self.preemptions = 0
        self._ttft_win = collections.deque(maxlen=int(window))
        self._inter_win = collections.deque(maxlen=int(window))
        self._lat_win = collections.deque(maxlen=int(window))

    # -- queueing ----------------------------------------------------

    def enqueue(self, req, now):
        """Admit a request to the pending queue; False = shed (full)."""
        if len(self.pending) >= self.queue_depth:
            self.shed_total += 1
            return None
        job = _SimJob(req, now)
        self.pending.append(job)
        return job

    def _dequeue(self):
        """Interactive before batch, preempted replays first within a
        class, FIFO within a tenant — the real engine's dequeue order."""
        if not self.pending:
            return None
        best_i = 0
        best_key = None
        for i, job in enumerate(self.pending):
            key = (0 if job.req["priority"] != "batch" else 1,
                   0 if job.preempted else 1, i)
            if best_key is None or key < best_key:
                best_key, best_i = key, i
        return self.pending.pop(best_i)

    def start_next(self, now, rng):
        """Bind the next pending job to a free slot; returns
        ``(slot_idx, job, first_event_dt_s)`` or None."""
        if not self.free or not self.pending:
            return None
        job = self._dequeue()
        if job is None:
            return None
        slot = self.free.pop()
        self.active[slot] = job
        job.start_t = now
        cls = job.req["priority"]
        if job.preempted:
            # re-prefill of prompt+emitted: charge a fresh TTFT-shaped
            # delay but do NOT re-stamp first_token_t (SLI stays honest,
            # same as the real engine's ttft_ms guard).
            dt = self.model.sample_ttft_ms(cls, rng) / 1e3
        else:
            dt = self.model.sample_ttft_ms(cls, rng) / 1e3
        job.intertoken_s = max(
            1e-4, self.model.sample_intertoken_ms(cls, rng) / 1e3)
        return slot, job, max(1e-4, dt)

    def preempt_for_interactive(self, now):
        """If interactive waits with no free slot, evict the cheapest
        active batch job back to pending; returns the evicted slot."""
        if self.free:
            return None
        if not any(j.req["priority"] != "batch" for j in self.pending):
            return None
        victims = [(j.remaining, s) for s, j in self.active.items()
                   if j.req["priority"] == "batch"]
        if not victims:
            return None
        _, slot = min(victims)
        job = self.active.pop(slot)
        job.preempted = True
        self.preemptions += 1
        self.free.append(slot)
        self.pending.insert(0, job)
        return slot

    def on_token(self, slot, now):
        """Advance the job in ``slot`` by one emitted token; returns
        ``('token', dt)`` or ``('done', None)``."""
        job = self.active.get(slot)
        if job is None:
            return None
        if job.first_token_t is None:
            job.first_token_t = now
            self._ttft_win.append((now - job.enq_t) * 1e3)
        else:
            self._inter_win.append(job.intertoken_s * 1e3)
        job.remaining -= 1
        if job.remaining <= 0:
            self.active.pop(slot)
            self.free.append(slot)
            self.completed += 1
            self._lat_win.append((now - job.enq_t) * 1e3)
            return ("done", None)
        return ("token", job.intertoken_s)

    # -- SLI scrape --------------------------------------------------

    def queue_len(self):
        return len(self.pending)

    def scrape_sample(self, shed_seen):
        """The dict shape FleetController._scrape_samples emits — the
        real policies consume this unmodified.  ``shed_seen`` is the
        caller-held previous shed total (delta semantics preserved);
        returns ``(sample, new_shed_seen)``."""
        shed_delta = max(0, self.shed_total - int(shed_seen))
        ttft = _registry.percentiles(list(self._ttft_win))
        inter = _registry.percentiles(list(self._inter_win))
        lat = _registry.percentiles(list(self._lat_win))
        sample = {
            "replica": self.id,
            "queue_depth": float(len(self.pending)),
            "shed_delta": float(shed_delta),
            "p95_ms": lat.get("p95"),
            "ttft_p95_ms": ttft.get("p95"),
            "intertoken_p95_ms": inter.get("p95"),
        }
        return sample, self.shed_total
