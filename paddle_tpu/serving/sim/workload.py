"""Workload sources for the fleet simulator.

Two ways to produce the request tape the simulator replays:

- ``from_journeys`` turns recorded flight-recorder journeys (the JSONL
  schema from ``observability.flight.to_journey``) back into arrival
  events, optionally scale-replicated (10x/100x the recorded day) with
  seeded arrival jitter so the copies don't land on one virtual instant.
- ``synthetic_workload`` fabricates a day from shape parameters:
  a flat/diurnal/flash-crowd rate curve, tenant skew, and an
  interactive/batch mix — for what-if trials no recording covers.

Every request is a plain dict (a "request record"), sortable by arrival
time; all randomness flows through one ``numpy.random.RandomState`` so
a fed seed makes the whole tape — and therefore the whole simulation —
deterministic.
"""

from __future__ import annotations

import math

import numpy as np

__all__ = ["from_journeys", "synthetic_workload"]


def _request(arrival_s, tenant, priority, prompt_tokens, max_new_tokens,
             request_id):
    return {
        "arrival_s": float(max(0.0, arrival_s)),
        "tenant": str(tenant or "anon"),
        "priority": "batch" if priority == "batch" else "interactive",
        "prompt_tokens": int(max(1, prompt_tokens)),
        "max_new_tokens": int(max(1, max_new_tokens)),
        "request_id": str(request_id),
    }


def from_journeys(journeys, scale=1.0, seed=0):
    """Convert journey records into a sorted request tape.

    Arrival time is reconstructed as ``ts - ms/1e3`` (the journey stamps
    completion) and normalised so the earliest arrival is t=0.  With
    ``scale`` > 1 each journey is replicated ``round(scale)`` times with
    seeded jitter of up to one recorded-span second, modelling "the same
    day at Nx volume" without N identical simultaneous arrivals.
    """
    rng = np.random.RandomState(int(seed))
    copies = max(1, int(round(float(scale))))
    raw = []
    for j in journeys:
        try:
            ts = float(j.get("ts") or 0.0)
            ms = float(j.get("ms") or 0.0)
        except (TypeError, ValueError):
            continue
        tokens = j.get("tokens")
        raw.append((ts - ms / 1e3, j, tokens))
    if not raw:
        return []
    t0 = min(r[0] for r in raw)
    span = max(1.0, max(r[0] for r in raw) - t0)
    out = []
    for arrival, j, tokens in raw:
        base = arrival - t0
        # a journey that never counted tokens is a single-response
        # request (/v1/infer): one "token" whose service time is the
        # recorded duration — NOT a default-length generation
        n_new = int(tokens) if tokens else 1
        n_prompt = int(j.get("prompt_tokens") or 0) or max(
            1, int(j.get("cached_prefix_tokens") or 0)) or 8
        for c in range(copies):
            jitter = 0.0 if c == 0 else float(rng.uniform(0.0, span))
            out.append(_request(
                base + jitter, j.get("tenant"), j.get("priority"),
                n_prompt, n_new,
                "%s/%d" % (j.get("request_id") or "rec", c)))
    out.sort(key=lambda r: (r["arrival_s"], r["request_id"]))
    return out


def _rate_at(kind, t, duration_s, rps):
    """Requests/second of the shaped curve at virtual time ``t``."""
    if kind == "diurnal":
        # one full day-shaped sine over the duration: trough at the
        # edges, peak in the middle, never below 10% of nominal.
        phase = math.sin(math.pi * (t / max(1.0, duration_s)))
        return rps * max(0.1, phase)
    if kind == "flash":
        # flat baseline with a 10x flash crowd for the middle tenth.
        lo, hi = 0.45 * duration_s, 0.55 * duration_s
        return rps * (10.0 if lo <= t < hi else 1.0)
    # "skew" and "flat" keep a constant rate; skew shapes tenants below.
    return rps


def synthetic_workload(kind="flat", duration_s=600.0, rps=2.0, seed=0,
                       tenants=("tenant-a", "tenant-b", "tenant-c"),
                       batch_fraction=0.3, prompt_tokens=8,
                       max_new_tokens=12):
    """Fabricate a request tape: ``kind`` in flat|diurnal|skew|flash.

    Arrivals are a thinned Poisson process against the shaped rate
    curve; ``skew`` sends 80% of traffic to the first tenant (Zipf-ish
    hot tenant) while the others split the rest uniformly.
    """
    kind = str(kind or "flat")
    if kind not in ("flat", "diurnal", "skew", "flash"):
        raise ValueError("unknown synthetic workload kind: %r" % kind)
    rng = np.random.RandomState(int(seed))
    duration_s = float(duration_s)
    rps = float(rps)
    tenants = list(tenants) or ["anon"]
    if kind == "skew" and len(tenants) > 1:
        hot = [0.8] + [0.2 / (len(tenants) - 1)] * (len(tenants) - 1)
    else:
        hot = [1.0 / len(tenants)] * len(tenants)
    peak = rps * (10.0 if kind == "flash" else 1.0)
    out = []
    t = 0.0
    i = 0
    while True:
        # thinning: candidate arrivals at the peak rate, accepted with
        # probability rate(t)/peak — an exact non-homogeneous Poisson.
        t += float(rng.exponential(1.0 / max(1e-9, peak)))
        if t >= duration_s:
            break
        if rng.uniform() * peak > _rate_at(kind, t, duration_s, rps):
            continue
        tenant = tenants[int(rng.choice(len(tenants), p=hot))]
        prio = "batch" if rng.uniform() < batch_fraction else "interactive"
        n_p = max(1, int(rng.poisson(prompt_tokens)))
        n_new = max(1, int(rng.poisson(max_new_tokens)))
        out.append(_request(t, tenant, prio, n_p, n_new, "syn-%06d" % i))
        i += 1
    return out
