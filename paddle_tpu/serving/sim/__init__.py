"""paddle_tpu.serving.sim — trace-driven fleet simulator.

Replays recorded flight-recorder journeys (or synthetic what-if
variants) through the REAL fleet control-plane classes — the
autoscaler policies, the gateway's admission controller, the router's
pick/breaker logic — on a virtual clock, against replicas whose
service-time model is fit from the same recordings.  A whole recorded
day replays in seconds, deterministically under a fed seed; the CLI
front end is ``tools/fleet_sim.py``.

Quickstart::

    from paddle_tpu.serving import sim

    journeys = sim.load_journeys("flight_controller.jsonl")
    report = sim.FleetSim(
        sim.from_journeys(journeys, scale=10),
        model=sim.ServiceModel.fit(journeys),
        policy=sim.make_policy("slo"),
        seed=42,
    ).run()
    print(report["requests"], report["classes"]["interactive"])
"""

from ...observability.flight import load_journeys, to_journey  # noqa: F401
from ..fleet import make_policy  # noqa: F401
from .core import FleetSim  # noqa: F401
from .replica import ServiceModel, SimReplica  # noqa: F401
from .workload import from_journeys, synthetic_workload  # noqa: F401

__all__ = [
    "FleetSim",
    "ServiceModel",
    "SimReplica",
    "from_journeys",
    "synthetic_workload",
    "make_policy",
    "load_journeys",
    "to_journey",
]
