"""FleetSim — discrete-event replay of a fleet day on a virtual clock.

The simulator is the promotion gate for control-plane changes: it feeds
a recorded (or synthetic) request tape through the REAL policy classes
— ``AutoscalerPolicy`` / ``SLOPolicy`` via ``make_policy``, the
gateway's ``_Admission`` (rate limits, quotas, priority-ordered
waiting), and the ``Router``'s least-inflight/breaker ``_pick`` — all
constructed with the sim's virtual clock injected, against
``SimReplica`` service models fit from the same flight recordings.  No
subprocesses, no sockets, no wall-clock reads: a whole recorded day
replays in seconds and two runs with the same seed produce identical
reports byte-for-byte.

Event loop: a single heap of ``(time, seq, kind, payload)`` tuples —
``seq`` breaks ties deterministically.  Kinds:

- ``arrival``  a request enters: route (Router._pick) → admission
  (try_admit) → replica queue, or shed / virtual park.
- ``kick``     try binding pending jobs to free slots (with batch
  preemption when interactive waits).
- ``token``    a slot emits one token (TTFT on the first).
- ``deadline`` a parked admission times out → shed "overload".
- ``policy``   one autoscaler tick over scraped samples.
- ``ready``    a scaled-up replica turns ready and joins the router.
"""

from __future__ import annotations

import heapq

import numpy as np

from ...fluid import flags as _flags
from ...observability import registry as _registry
from ..fleet import make_policy
from ..gateway import _Admission, _AdmissionDenied
from ..router import Router
from .replica import ServiceModel, SimReplica

__all__ = ["FleetSim"]

REPORT_SCHEMA_VERSION = 1


class _SimReplicaHandle(object):
    """One replica's sim-side bundle: the queueing model, its own
    admission controller (per-replica gateway front door), the parked
    virtual waiters, and scrape bookkeeping."""

    __slots__ = ("id", "replica", "admission", "waiters", "shed_seen",
                 "draining", "backend", "granting")

    def __init__(self, rid, replica, admission):
        self.id = str(rid)
        self.replica = replica
        self.admission = admission
        self.waiters = []   # [(deadline_t, seq, req, backend)] parked
        self.shed_seen = 0
        self.draining = False
        self.backend = None
        self.granting = False   # _grant_waiters reentrancy guard


class FleetSim(object):
    def __init__(self, workload, model=None, policy=None, seed=0,
                 slots=4, queue_depth=64, min_replicas=None,
                 max_replicas=None, scale_interval_s=None,
                 rate_rps=0.0, burst=1, tenant_max_inflight=0,
                 max_inflight=None, admit_timeout_ms=2000.0,
                 replica_ready_s=None):
        self.workload = sorted(workload or [],
                               key=lambda r: (r["arrival_s"],
                                              r["request_id"]))
        self.model = model or ServiceModel()
        self.policy = policy or make_policy(min_replicas=min_replicas,
                                            max_replicas=max_replicas)
        self.seed = int(seed)
        self.rng = np.random.RandomState(self.seed)
        self.slots = int(slots)
        self.queue_depth = int(queue_depth)
        self.scale_interval_s = float(
            scale_interval_s
            if scale_interval_s is not None
            else _flags.get_flag("fleet_scale_interval_s", 2.0))
        self.replica_ready_s = float(
            replica_ready_s
            if replica_ready_s is not None
            else _flags.get_flag("sim_replica_ready_s", 5.0))
        # per-replica admission knobs (a replica gateway's front door);
        # max_inflight defaults to slots + queue_depth — the engine can
        # actually hold that many
        self._admit_args = (float(rate_rps), int(burst),
                            int(tenant_max_inflight),
                            int(max_inflight if max_inflight is not None
                                else self.slots + self.queue_depth),
                            float(admit_timeout_ms))
        # virtual clock — everything (router breakers, admission
        # deadlines/buckets, service events) reads THIS
        self.now = 0.0
        self._clock = lambda: self.now
        self.router = Router(port=0, clock=self._clock)
        self._heap = []
        self._seq = 0
        self._handles = {}
        self._next_rid = 0
        self._target = self.policy.min_replicas
        self._pending_ready = 0    # replicas scaled up but not ready yet
        # accounting
        self.injected = 0
        self.completed = 0
        self.shed = {}
        self._arrivals = []        # run() fills this from the workload
        self._inflight = {}        # request_id -> (handle, backend, req)
        self._done_rows = []       # per-request completion facts
        self.replica_trajectory = []   # [(t, ready_count)]
        self.target_trajectory = []    # [(t, target, reason)]

    # -- event plumbing ----------------------------------------------

    def _push(self, t, kind, payload):
        self._seq += 1
        heapq.heappush(self._heap, (float(t), self._seq, kind, payload))

    def _shed(self, reason):
        self.shed[reason] = self.shed.get(reason, 0) + 1

    # -- replica lifecycle -------------------------------------------

    def _spawn_replica(self, ready_at):
        rid = "sim-%d" % self._next_rid
        self._next_rid += 1
        h = _SimReplicaHandle(
            rid,
            SimReplica(rid, self.model, slots=self.slots,
                       queue_depth=self.queue_depth),
            _Admission(*self._admit_args, clock=self._clock),
        )
        self._handles[rid] = h
        self._push(ready_at, "ready", rid)
        return h

    def _ready_count(self):
        return sum(1 for h in self._handles.values() if not h.draining
                   and h.backend is not None)

    def _on_ready(self, rid):
        h = self._handles.get(rid)
        if h is None or h.draining:
            return
        self._pending_ready = max(0, self._pending_ready - 1)
        h.backend = self.router.add_backend(rid, "sim", 0, ready=True)

    def _drain_replica(self):
        """Scale-down: newest ready replica stops taking new work; it
        disappears once its queue and slots empty."""
        ready = [h for h in self._handles.values()
                 if h.backend is not None and not h.draining]
        if not ready:
            return
        h = max(ready, key=lambda x: int(x.id.rsplit("-", 1)[1]))
        h.draining = True
        self.router.remove_backend(h.id)
        self._maybe_reap(h)

    def _maybe_reap(self, h):
        if (h.draining and not h.replica.active and not h.replica.pending
                and not h.waiters):
            self._handles.pop(h.id, None)

    # -- request flow ------------------------------------------------

    def _on_arrival(self, req):
        self.injected += 1
        b = self.router._pick()
        if b is None:
            self._shed("no_backend")
            return
        h = self._handles.get(b.id)
        if h is None or h.draining:
            self.router._release(b)
            self._shed("no_backend")
            return
        try:
            verdict = h.admission.try_admit(req["tenant"], req["priority"])
        except _AdmissionDenied as e:
            self.router._note_success(b)   # the replica answered (429)
            self.router._release(b)
            self._shed(e.reason)
            # the live gateway's 429 counter feeds THIS replica's scrape
            # (shed_delta is what arms the autoscaler) — mirror it
            h.replica.shed_total += 1
            return
        if verdict == "wait":
            h.admission.note_wait_start(req["priority"])
            deadline = self.now + h.admission.admit_timeout_s
            self._seq += 1
            h.waiters.append((deadline, self._seq, req, b))
            self._push(deadline, "deadline", (h.id, req["request_id"]))
            return
        self._admitted(h, b, req)

    def _admitted(self, h, b, req):
        job = h.replica.enqueue(req, self.now)
        if job is None:             # engine queue full → shed at entry
            h.admission.release(req["tenant"])
            self.router._note_success(b)
            self.router._release(b)
            self._shed("overload")
            self._grant_waiters(h)
            return
        self._inflight[req["request_id"]] = (h, b, req)
        self._push(self.now, "kick", h.id)

    def _grant_waiters(self, h):
        """Capacity freed on ``h``: retry parked admissions, interactive
        class first, FIFO within a class — the class ordering the real
        ``_Admission`` wake path enforces (its cap check parks batch
        while any interactive waiter exists, so the first "wait" verdict
        means every later waiter would wait too)."""
        if h.granting:
            return          # _admitted below can recurse via a shed
        h.granting = True
        try:
            while h.waiters:
                h.waiters.sort(key=lambda w: (
                    0 if w[2]["priority"] != "batch" else 1, w[1]))
                _deadline, _seq, req, b = h.waiters[0]
                try:
                    verdict = h.admission.try_grant(req["tenant"],
                                                    req["priority"])
                except _AdmissionDenied as e:
                    h.waiters.pop(0)
                    h.admission.note_wait_end(req["priority"])
                    self.router._note_success(b)
                    self.router._release(b)
                    self._shed(e.reason)
                    h.replica.shed_total += 1
                    continue
                if verdict == "wait":
                    break
                h.waiters.pop(0)
                h.admission.note_wait_end(req["priority"])
                self._admitted(h, b, req)
        finally:
            h.granting = False

    def _on_deadline(self, hid, request_id):
        h = self._handles.get(hid)
        if h is None:
            return
        for i, (deadline, _seq, req, b) in enumerate(h.waiters):
            if req["request_id"] == request_id:
                if deadline > self.now + 1e-9:
                    return          # was re-parked later (not possible
                                    # today, but keep the guard cheap)
                del h.waiters[i]
                h.admission.note_wait_end(req["priority"])
                self.router._note_success(b)
                self.router._release(b)
                self._shed("overload")
                h.replica.shed_total += 1
                self._maybe_reap(h)
                return

    def _on_kick(self, hid):
        h = self._handles.get(hid)
        if h is None:
            return
        r = h.replica
        # priority preemption, mirroring the engine: interactive parked
        # in the replica queue with no free slot evicts a batch slot
        if _flags.get_flag("sched_preempt", True):
            r.preempt_for_interactive(self.now)
        while True:
            bound = r.start_next(self.now, self.rng)
            if bound is None:
                break
            slot, job, dt = bound
            self._push(self.now + dt, "token", (hid, slot, id(job)))

    def _on_token(self, hid, slot, job_tag):
        h = self._handles.get(hid)
        if h is None:
            return
        job = h.replica.active.get(slot)
        if job is None or id(job) != job_tag:
            return                  # slot was preempted/rebound — the
                                    # new binding scheduled its own event
        out = h.replica.on_token(slot, self.now)
        if out is None:
            return
        kind, dt = out
        if kind == "token":
            self._push(self.now + dt, "token", (hid, slot, job_tag))
            return
        # done: full completion accounting + capacity handback
        req = job.req
        row = self._inflight.pop(req["request_id"], None)
        if row is not None:
            rh, b, _ = row
            rh.admission.release(req["tenant"])
            self.router._note_success(b)
            self.router._release(b)
        self.completed += 1
        self._done_rows.append({
            "priority": req["priority"],
            "tenant": req["tenant"],
            "ttft_ms": ((job.first_token_t - job.enq_t) * 1e3
                        if job.first_token_t is not None else None),
            "ms": (self.now - job.enq_t) * 1e3,
            "preempted": bool(job.preempted),
        })
        self._grant_waiters(h)
        self._push(self.now, "kick", hid)
        self._maybe_reap(h)

    # -- autoscaling -------------------------------------------------

    def policy_tick(self, samples):
        """One policy round over ``samples`` — the SINGLE code path the
        parity tests drive directly: observe → clamp → apply."""
        target, reason = self.policy.observe(samples, self._target)
        if target != self._target or reason is not None:
            self.target_trajectory.append(
                (round(self.now, 6), int(target), reason))
        self._target = target
        live = self._ready_count() + self._pending_ready
        while live < self._target:
            self._spawn_replica(self.now + self.replica_ready_s)
            self._pending_ready += 1
            live += 1
        while live > self._target and self._ready_count() > 0:
            self._drain_replica()
            live -= 1
        return target, reason

    def _on_policy(self):
        samples = []
        for h in sorted(self._handles.values(), key=lambda x: x.id):
            if h.backend is None or h.draining:
                continue
            sample, h.shed_seen = h.replica.scrape_sample(h.shed_seen)
            samples.append(sample)
        self.policy_tick(samples)
        self.replica_trajectory.append(
            (round(self.now, 6), self._ready_count()))
        if self._work_remains():
            self._push(self.now + self.scale_interval_s, "policy", None)

    def _work_remains(self):
        if self._inflight or self._arrivals:
            return True
        return any(h.waiters or h.replica.pending or h.replica.active
                   for h in self._handles.values())

    # -- run ---------------------------------------------------------

    def run(self, max_events=2_000_000):
        """Replay the tape to completion; returns the report dict."""
        self._arrivals = list(self.workload)
        self._arrivals.reverse()    # pop() from the front, cheaply
        for _ in range(self.policy.min_replicas):
            self._spawn_replica(self.now)   # initial pool: ready at t=0
            self._pending_ready += 1
        self._push(0.0, "policy", None)
        events = 0
        while self._heap or self._arrivals:
            # feed arrivals into the heap lazily so a 100x tape does
            # not balloon the heap up front
            while self._arrivals and (
                    not self._heap
                    or self._arrivals[-1]["arrival_s"] <= self._heap[0][0]):
                req = self._arrivals.pop()
                self._push(req["arrival_s"], "arrival", req)
            if not self._heap:
                break
            t, _seq, kind, payload = heapq.heappop(self._heap)
            self.now = max(self.now, t)
            if kind == "arrival":
                self._on_arrival(payload)
            elif kind == "kick":
                self._on_kick(payload)
            elif kind == "token":
                self._on_token(*payload)
            elif kind == "deadline":
                self._on_deadline(*payload)
            elif kind == "ready":
                self._on_ready(payload)
                self._push(self.now, "kick", payload)
            elif kind == "policy":
                self._on_policy()
            events += 1
            if events >= int(max_events):
                break
        return self.report()

    # -- report ------------------------------------------------------

    def report(self):
        shed_total = sum(self.shed.values())
        by_class = {"interactive": {"ttft_ms": [], "ms": []},
                    "batch": {"ttft_ms": [], "ms": []}}
        preempted_done = 0
        for row in self._done_rows:
            c = by_class[row["priority"]]
            if row["ttft_ms"] is not None:
                c["ttft_ms"].append(row["ttft_ms"])
            c["ms"].append(row["ms"])
            preempted_done += 1 if row["preempted"] else 0
        classes = {}
        for cls, pools in sorted(by_class.items()):
            classes[cls] = {
                "ttft_ms": _registry.percentiles(pools["ttft_ms"]),
                "latency_ms": _registry.percentiles(pools["ms"]),
            }
        return {
            "schema_version": REPORT_SCHEMA_VERSION,
            "seed": self.seed,
            "policy": type(self.policy).__name__,
            "virtual_s": round(self.now, 6),
            "requests": {
                "injected": self.injected,
                "completed": self.completed,
                "shed": shed_total,
                "shed_by_reason": dict(sorted(self.shed.items())),
                "incomplete": self.injected - self.completed - shed_total,
            },
            "preemptions": sum(h.replica.preemptions
                               for h in self._handles.values()),
            "completed_after_preemption": preempted_done,
            "classes": classes,
            "replica_trajectory": list(self.replica_trajectory),
            "target_trajectory": list(self.target_trajectory),
            "final_target": int(self._target),
        }
