"""ServingStats — the observability snapshot of a running server.

All raw signals ride the always-on ``fluid.profiler`` counters and
sliding-window histograms (the same surface the bench/probe tooling —
and now the ``observability`` registry's Prometheus/JSONL renderers —
reads), so one snapshot call assembles: queue depth, batch-fill
ratio, bucket-plan hit rate, latency percentiles, and shed counts.
Percentile math is delegated to ``observability.registry.percentiles``
so serving keeps no private windowing/summary code.
Counter fields are deltas since the server's ``start()`` (the baseline
snapshot), and the latency percentiles exclude samples recorded before
it (via the histogram sample count at start) — so a fresh server's
stats start at zero even when other serving activity preceded it in the
process. Percentiles are over the histogram's bounded sliding window
(the most recent samples, which is what a dashboard wants from a
long-lived server).

Known tradeoff: the counters are process-global (that is what makes one
probe/bench surface work for the executor, predictor, and server alike),
so the baseline-delta isolation is exact for SEQUENTIAL servers only —
two servers serving concurrently in one process see each other's
serving_* bumps and latency samples mixed into their snapshots.
"""

from __future__ import annotations

from ..fluid import profiler as _profiler
from ..observability import registry as _registry

__all__ = ["ServingStats", "snapshot_stats"]

_COUNTERS = (
    "serving_requests",
    "serving_completed",
    "serving_shed_overload",
    "serving_shed_deadline",
    "serving_batches",
    "serving_batched_rows",
    "serving_pad_rows",
    "serving_bucket_hits",
    "serving_bucket_misses",
    "predictor_plan_cache_hits",
    "predictor_plan_cache_misses",
)


class ServingStats(object):
    """Immutable snapshot; ``as_dict()`` for logging/JSON."""

    __slots__ = (
        "queue_depth", "requests", "completed", "shed_overload",
        "shed_deadline", "batches", "batched_rows", "pad_rows",
        "batch_fill_ratio", "bucket_hits", "bucket_misses",
        "bucket_hit_rate", "plan_cache_hits", "plan_cache_misses",
        "latency_ms",
    )

    def __init__(self, **kw):
        for k in self.__slots__:
            setattr(self, k, kw.get(k))

    def as_dict(self):
        return {k: getattr(self, k) for k in self.__slots__}

    def __repr__(self):
        return "ServingStats(%s)" % ", ".join(
            "%s=%r" % (k, getattr(self, k)) for k in self.__slots__
        )


def snapshot_stats(baseline=None, queue_depth=0, max_batch_size=1,
                   latency_baseline_count=0):
    """Assemble a ServingStats from the live profiler counters minus the
    ``baseline`` snapshot (dict from profiler.get_counters()).
    ``latency_baseline_count`` (the histogram's sample count at server
    start) excludes a PREVIOUS server's samples from the percentiles;
    once the sliding window has wrapped the slice turns conservative
    (oldest in-window samples dropped), which is exact whenever fewer
    than the window's 65536 samples have ever been recorded."""
    c = _profiler.get_counters()
    base = baseline or {}
    # clamped at zero: a profiler.reset_counters()/reset_profiler() call
    # mid-serving zeroes the live counters under the baseline — report
    # from-zero figures rather than negative ones
    d = {k: max(c.get(k, 0) - base.get(k, 0), 0) for k in _COUNTERS}
    batches = d["serving_batches"]
    rows = d["serving_batched_rows"]
    fill = (
        round(rows / float(batches * max_batch_size), 4) if batches else None
    )
    bh, bm = d["serving_bucket_hits"], d["serving_bucket_misses"]
    hit_rate = round(bh / float(bh + bm), 4) if (bh + bm) else None
    lat = _profiler.get_histogram("serving_latency_ms")
    if latency_baseline_count and len(lat) >= latency_baseline_count:
        lat = lat[latency_baseline_count:]
    # else: a mid-serving histogram reset left fewer samples than the
    # baseline — everything present is post-reset, keep it all
    return ServingStats(
        queue_depth=queue_depth,
        requests=d["serving_requests"],
        completed=d["serving_completed"],
        shed_overload=d["serving_shed_overload"],
        shed_deadline=d["serving_shed_deadline"],
        batches=batches,
        batched_rows=rows,
        pad_rows=d["serving_pad_rows"],
        batch_fill_ratio=fill,
        bucket_hits=bh,
        bucket_misses=bm,
        bucket_hit_rate=hit_rate,
        plan_cache_hits=d["predictor_plan_cache_hits"],
        plan_cache_misses=d["predictor_plan_cache_misses"],
        # percentile math lives in the observability registry now — one
        # formula shared with snapshots and the gang aggregator (same
        # numpy linear-interpolation semantics this module always had)
        latency_ms=_registry.percentiles(lat),
    )
