"""Unified metrics registry: one API over the always-on profiler state.

Before this module, three dialects coexisted: ``fluid.profiler``
counters/histograms, ``serving.metrics.ServingStats``'s own percentile
math, and the supervisor's ad-hoc JSONL log. The registry absorbs them:
the BACKING STORE stays the profiler's locked counters and bounded
sliding-window histograms (so every existing ``bump_counter`` call site
is already publishing here, and one reset discipline governs all), and
this module owns the read side — Prometheus text rendering for scrape
endpoints, JSONL snapshots for per-rank files the supervisor merges
(``aggregate.py``), and the shared percentile math ``ServingStats`` now
delegates to instead of duplicating.

Gauges are the one signal counters can't carry (current queue depth,
pool occupancy): they register as callables sampled at render time, so
a dead gauge (its owner stopped) is skipped rather than poisoning the
scrape.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time

import numpy as np

from ..fluid import profiler as _profiler
from . import trace as _trace

__all__ = [
    "SCHEMA_VERSION",
    "Counter",
    "Histogram",
    "counter",
    "histogram",
    "register_gauge",
    "unregister_gauge",
    "gauge_values",
    "percentiles",
    "render_prometheus",
    "parse_prometheus",
    "snapshot",
    "write_snapshot",
    "snapshot_path",
]

# versions every machine-readable artifact this layer emits (JSONL
# snapshots; aggregate.py stamps its gang report with the same number):
# consumers can dispatch on it instead of sniffing fields
SCHEMA_VERSION = 1

_gauges = {}  # name -> callable() -> number
_gauges_lock = threading.Lock()


class Counter(object):
    """Handle over one always-on profiler counter (monotonic)."""

    __slots__ = ("name",)

    def __init__(self, name):
        self.name = name

    def inc(self, n=1):
        _profiler.bump_counter(self.name, n)

    def value(self):
        return _profiler.get_counter(self.name)


class Histogram(object):
    """Handle over one sliding-window profiler histogram."""

    __slots__ = ("name",)

    def __init__(self, name):
        self.name = name

    def observe(self, value):
        _profiler.bump_histogram(self.name, value)

    def summary(self):
        return _profiler.summarize_histogram(self.name)


def counter(name):
    return Counter(name)


def histogram(name):
    return Histogram(name)


def register_gauge(name, fn):
    """Register ``fn() -> number`` sampled at every render/snapshot.
    Re-registering a name replaces it (a restarted server re-owns its
    gauge)."""
    with _gauges_lock:
        _gauges[name] = fn


def unregister_gauge(name, fn=None):
    """Remove a gauge. With ``fn`` given, removal happens only while it
    is still the registered callable — a stopping owner must not tear
    down a successor's re-registration of the same name."""
    with _gauges_lock:
        if fn is None or _gauges.get(name) is fn:
            _gauges.pop(name, None)


def gauge_values():
    """{name: float} for every registered gauge whose callable still
    works; erroring gauges are skipped (a stopped owner must not poison
    the scrape)."""
    with _gauges_lock:
        items = list(_gauges.items())
    out = {}
    for name, fn in items:
        try:
            out[name] = float(fn())
        except Exception:
            continue
    return out


def percentiles(samples, points=(50, 95, 99)):
    """{count, mean, p<point>...} with linear-interpolation percentiles
    (numpy semantics) rounded to 3 decimals, Nones when empty — the
    exact contract ServingStats.latency_ms always had; it now lives here
    so serving, probes, and the gang aggregator share one formula."""
    if samples is None or len(samples) == 0:
        return {"count": 0, "mean": None,
                **{"p%d" % p: None for p in points}}
    arr = np.asarray(samples, dtype=np.float64)
    out = {"count": int(arr.size), "mean": round(float(arr.mean()), 3)}
    for p in points:
        out["p%d" % p] = round(float(np.percentile(arr, p)), 3)
    return out


# Prometheus metric names: [a-zA-Z_:][a-zA-Z0-9_:]*
_SANITIZE = re.compile(r"[^a-zA-Z0-9_:]")


def prom_name(name):
    n = _SANITIZE.sub("_", str(name))
    if not n or n[0].isdigit():
        n = "_" + n
    return n


def render_prometheus():
    """The registry as Prometheus text exposition (version 0.0.4):
    counters as ``counter``, gauges as ``gauge``, histograms as
    ``summary`` (quantile series + _sum/_count over the bounded window).
    Every registered counter round-trips: ``parse_prometheus`` of this
    text recovers exact values (the obs_probe acceptance check)."""
    lines = []
    for name, val in sorted(_profiler.get_counters().items()):
        pn = prom_name(name)
        lines.append("# TYPE %s counter" % pn)
        lines.append("%s %d" % (pn, val))
    gauge_typed = set()
    for name, val in sorted(gauge_values().items()):
        # a gauge registered as 'name{label="v"}' renders as a labeled
        # series under the base family (one TYPE line per family) —
        # how the per-class admission-wait gauges expose their class
        base, _, labels = str(name).partition("{")
        pn = prom_name(base)
        if pn not in gauge_typed:
            gauge_typed.add(pn)
            lines.append("# TYPE %s gauge" % pn)
        series = pn + ("{" + labels if labels else "")
        lines.append("%s %.17g" % (series, val))
    for name, samples in sorted(_profiler.get_histograms().items()):
        pn = prom_name(name)
        s = percentiles(samples, points=(50, 95, 99))
        lines.append("# TYPE %s summary" % pn)
        for q, key in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
            lines.append('%s{quantile="%g"} %.17g' % (pn, q, s[key]))
        lines.append("%s_sum %.17g" % (pn, float(np.sum(samples))))
        lines.append("%s_count %d" % (pn, len(samples)))
    return "\n".join(lines) + "\n"


def parse_prometheus(text):
    """Inverse of ``render_prometheus`` for round-trip checks:
    {(name, labels_str): float} — labels_str is "" for plain series."""
    out = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        metric, _, val = line.rpartition(" ")
        if "{" in metric:
            name, _, rest = metric.partition("{")
            labels = rest.rstrip("}")
        else:
            name, labels = metric, ""
        try:
            out[(name, labels)] = float(val)
        except ValueError:
            continue
    return out


def snapshot(rank=None):
    """One JSON-able snapshot of everything registered: schema_version,
    wall-clock ``ts`` (for humans) AND monotonic ``ts_mono`` (orders
    events across NTP steps on one host), rank/pid, counters, gauges,
    and per-histogram summaries. This is the per-rank record
    ``aggregate.py`` merges into the gang report."""
    from . import xla_stats as _xla_stats

    rank = _trace.gang_rank(rank)
    hists = {
        name: percentiles(samples, points=(50, 95, 99))
        for name, samples in _profiler.get_histograms().items()
    }
    return {
        "schema_version": SCHEMA_VERSION,
        "ts": time.time(),
        "ts_mono": time.monotonic(),
        "rank": int(rank),
        "pid": os.getpid(),
        "counters": _profiler.get_counters(),
        "gauges": gauge_values(),
        "histograms": hists,
        # device-plane roll-up: per-rank compile counts by trigger +
        # the newest records' fingerprints, so the gang aggregator can
        # surface a restart's recompile storm without the full ring
        "compiles": _xla_stats.summary(),
    }


def snapshot_path(dirname, rank=None):
    return os.path.join(
        str(dirname), "rank_%d.jsonl" % _trace.gang_rank(rank)
    )


def write_snapshot(dirname, rank=None):
    """Append one snapshot line to ``dirname/rank_<rank>.jsonl``
    (O_APPEND single write: concurrent writers at worst interleave whole
    lines, and the aggregator skips torn ones). Returns the path."""
    snap = snapshot(rank=rank)
    path = snapshot_path(dirname, rank=snap["rank"])
    os.makedirs(str(dirname), exist_ok=True)
    with open(path, "a") as f:
        f.write(json.dumps(snap, sort_keys=True) + "\n")
    return path
