"""Thread-safe span tracer with Chrome trace-event export.

Reference lineage: the Fluid stack's ``platform/profiler.h`` RecordEvent
+ ``platform/device_tracer.h`` timeline, whose proto ``tools/timeline.py``
converted to chrome://tracing JSON. This is the host half of that design
rebuilt as one spine: every subsystem (executor step loop, DeviceFeeder,
checkpoint snapshot + writer thread, serving batcher/pool dispatch,
pserver RPC client, legacy ``fluid.profiler.RecordEvent``) opens spans
here, and one export answers "where did this step's milliseconds go".
Device-side timelines still come from ``jax.profiler`` (xprof); the two
complement each other — this trace carries the host orchestration XLA
cannot see.

Design constraints, in order:

- **Always-on cheap**: recording is gated by ``FLAGS_obs_trace``
  (default on) behind a flags-version-cached check, and a completed span
  costs two ``perf_counter`` reads, a tuple, and one locked deque append
  (bounded: ``FLAGS_obs_trace_buffer`` newest spans survive — a
  long-lived server must not grow host memory without bound).
  ``tools/obs_probe.py`` measures the enabled-vs-disabled step-path
  overhead and gates it <2%.
- **Thread-safe with explicit nesting**: each thread keeps its own span
  stack (``threading.local``), so parent/child edges are exact even with
  the checkpoint writer, serving batcher workers, and the feeder all
  tracing concurrently. ``tid`` in the export is the OS thread ident,
  ``pid`` is the gang rank (``PADDLE_TRAINER_ID``), so a multi-rank
  job's merged traces line up side by side in Perfetto.
- **Standard format**: ``chrome_trace()`` emits trace-event JSON
  (``ph: "X"`` complete events + thread-name metadata) that loads in
  Perfetto / chrome://tracing unchanged.
"""

from __future__ import annotations

import functools
import itertools
import json
import os
import threading
import time
from collections import deque

from ..fluid import flags as _flags

__all__ = [
    "span",
    "traced",
    "enabled",
    "force_enable",
    "gang_rank",
    "get_spans",
    "reset",
    "chrome_trace",
    "save_chrome_trace",
]

# record layout (tuple for append cheapness):
# (name, cat, start_s, end_s, tid, depth, parent_name, span_id, args|None)
_lock = threading.Lock()
_buf = deque(maxlen=65536)
_ids = itertools.count(1)  # .__next__ is atomic under the GIL
_tls = threading.local()
_thread_names = {}  # tid -> thread name, for trace metadata
# (flags.version(), enabled) — the disarmed/armed check must cost one
# integer compare on hot paths, same idiom as testing/chaos.py
_enabled_cache = (None, True)
# ref-count of force_enable holders (an explicit profiling session must
# record spans even when the always-on tracer is flagged off)
_force_on = 0


def enabled():
    """Is span recording armed (FLAGS_obs_trace, or a force_enable
    holder)? Cached per flags-version so per-span cost stays at one
    integer compare. The same once-per-flags-change branch applies
    FLAGS_obs_trace_buffer, so the bound takes effect on live paths
    (trainer, server) that never call reset()."""
    global _enabled_cache
    ver = _flags.version()
    cached_ver, cached = _enabled_cache
    if cached_ver != ver:
        cached = bool(_flags.get_flag("obs_trace", True))
        _enabled_cache = (ver, cached)
        _apply_buffer_bound()
    return cached or _force_on > 0


def _buffer_bound():
    try:
        return max(int(_flags.get_flag("obs_trace_buffer", 65536)), 1)
    except (TypeError, ValueError):
        return 65536


def _apply_buffer_bound():
    """Re-size the ring buffer to FLAGS_obs_trace_buffer, keeping the
    newest spans."""
    global _buf
    n = _buffer_bound()
    if _buf.maxlen != n:
        with _lock:
            _buf = deque(_buf, maxlen=n)


def force_enable(on):
    """Arm (``True``) / disarm (``False``) recording regardless of
    FLAGS_obs_trace. Ref-counted: ``fluid.profiler.start_profiler``
    holds this for the session so the legacy API keeps producing a
    timeline when the always-on tracer was turned off for overhead."""
    global _force_on
    _force_on = max(0, _force_on + (1 if on else -1))


class span(object):
    """Context manager recording one timed span.

    ``with span("ckpt_snapshot", cat="ckpt", step=7): ...`` — kwargs
    land in the Chrome event's ``args``. Nesting is tracked per thread:
    a span opened inside another becomes its child (``parent``/``depth``
    in the record, time containment in Perfetto). Disabled tracing makes
    enter/exit a near-no-op."""

    __slots__ = ("name", "cat", "args", "_t0", "_armed", "_parent")

    def __init__(self, name, cat="host", **args):
        self.name = name
        self.cat = cat
        self.args = args or None
        self._armed = False

    def __enter__(self):
        if not enabled():
            return self
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        self._parent = stack[-1] if stack else None
        stack.append(self.name)
        self._armed = True
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if not self._armed:
            return False
        t1 = time.perf_counter()
        self._armed = False
        stack = _tls.stack
        if stack:
            stack.pop()
        tid = threading.get_ident()
        rec = (
            self.name, self.cat, self._t0, t1, tid, len(stack),
            self._parent, next(_ids), self.args,
        )
        with _lock:
            if tid not in _thread_names:  # once per thread, not per span
                _thread_names[tid] = threading.current_thread().name
            _buf.append(rec)
        return False


def traced(name=None, cat="host"):
    """Decorator form: ``@traced`` / ``@traced("label", cat="serving")``
    wraps the call in a span (label defaults to the qualified name)."""
    if callable(name):  # bare @traced
        return traced(None)(name)

    def deco(fn):
        label = name or fn.__qualname__

        @functools.wraps(fn)
        def wrapper(*a, **kw):
            with span(label, cat=cat):
                return fn(*a, **kw)

        return wrapper

    return deco


def get_spans():
    """Snapshot of the ring buffer as dicts (oldest first); list and
    dicts are copies — same isolation contract as profiler counters."""
    with _lock:
        recs = list(_buf)
    return [
        {
            "name": r[0], "cat": r[1], "start": r[2], "end": r[3],
            "tid": r[4], "depth": r[5], "parent": r[6], "id": r[7],
            "args": dict(r[8]) if r[8] else {},
        }
        for r in recs
    ]


def reset():
    """Drop every retained span and re-read the buffer bound from
    FLAGS_obs_trace_buffer (so tests can shrink it)."""
    global _buf
    with _lock:
        _buf = deque(maxlen=_buffer_bound())


def gang_rank(rank=None):
    """The gang rank labeling every per-rank artifact (trace ``pid``,
    snapshot filename, exporter identity): an explicit value wins, else
    PADDLE_TRAINER_ID, else 0 (non-numeric counts as unset). One
    resolver so a change to rank discovery can't skew artifacts apart."""
    if rank is not None:
        return int(rank)
    try:
        return int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    except ValueError:
        return 0


def chrome_trace():
    """The retained spans as a Chrome trace-event dict: ``ph: "X"``
    complete events with ``ts``/``dur`` in microseconds, ``pid`` = gang
    rank, ``tid`` = thread, nesting by containment (exact, because spans
    close LIFO per thread), plus process/thread-name metadata. Loads in
    Perfetto / chrome://tracing as-is."""
    spans = get_spans()
    rank = gang_rank()
    t0 = min((s["start"] for s in spans), default=0.0)
    events = [
        {
            "name": "process_name", "ph": "M", "pid": rank, "tid": 0,
            "args": {"name": "rank %d" % rank},
        }
    ]
    with _lock:  # span exits insert names concurrently
        names = list(_thread_names.items())
    # OS thread idents are pthread addresses — huge and collision-prone
    # under any modulus — so the export aliases each distinct ident to a
    # small stable row id (collision-free by construction)
    alias = {
        t: i + 1
        for i, t in enumerate(sorted(
            {t for t, _ in names} | {s["tid"] for s in spans}
        ))
    }
    for tid, tname in sorted(names):
        events.append({
            "name": "thread_name", "ph": "M", "pid": rank,
            "tid": alias[tid], "args": {"name": tname},
        })
    for s in spans:
        args = dict(s["args"])
        args["depth"] = s["depth"]
        if s["parent"]:
            args["parent"] = s["parent"]
        events.append({
            "name": s["name"], "cat": s["cat"], "ph": "X",
            "ts": (s["start"] - t0) * 1e6,
            "dur": (s["end"] - s["start"]) * 1e6,
            "pid": rank, "tid": alias[s["tid"]], "args": args,
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def save_chrome_trace(path):
    """Write ``chrome_trace()`` to ``path`` (atomic tmp+rename so a
    half-written export never loads as torn JSON). Returns the path."""
    tmp = "%s.tmp.%d" % (path, os.getpid())
    with open(tmp, "w") as f:
        json.dump(chrome_trace(), f)
    os.replace(tmp, path)
    return path
