"""Thread-safe span tracer with Chrome trace-event export.

Reference lineage: the Fluid stack's ``platform/profiler.h`` RecordEvent
+ ``platform/device_tracer.h`` timeline, whose proto ``tools/timeline.py``
converted to chrome://tracing JSON. This is the host half of that design
rebuilt as one spine: every subsystem (executor step loop, DeviceFeeder,
checkpoint snapshot + writer thread, serving batcher/pool dispatch,
pserver RPC client, legacy ``fluid.profiler.RecordEvent``) opens spans
here, and one export answers "where did this step's milliseconds go".
Device-side timelines still come from ``jax.profiler`` (xprof); the two
complement each other — this trace carries the host orchestration XLA
cannot see.

Design constraints, in order:

- **Always-on cheap**: recording is gated by ``FLAGS_obs_trace``
  (default on) behind a flags-version-cached check, and a completed span
  costs two ``perf_counter`` reads, a tuple, and one locked deque append
  (bounded: ``FLAGS_obs_trace_buffer`` newest spans survive — a
  long-lived server must not grow host memory without bound).
  ``tools/obs_probe.py`` measures the enabled-vs-disabled step-path
  overhead and gates it <2%.
- **Thread-safe with explicit nesting**: each thread keeps its own span
  stack (``threading.local``), so parent/child edges are exact even with
  the checkpoint writer, serving batcher workers, and the feeder all
  tracing concurrently. ``tid`` in the export is the OS thread ident,
  ``pid`` is the gang rank (``PADDLE_TRAINER_ID``), so a multi-rank
  job's merged traces line up side by side in Perfetto.
- **Standard format**: ``chrome_trace()`` emits trace-event JSON
  (``ph: "X"`` complete events + thread-name metadata) that loads in
  Perfetto / chrome://tracing unchanged.
"""

from __future__ import annotations

import functools
import itertools
import json
import os
import re
import threading
import time
from collections import deque

from ..fluid import flags as _flags

__all__ = [
    "span",
    "traced",
    "instant",
    "enabled",
    "force_enable",
    "gang_rank",
    "get_spans",
    "reset",
    "chrome_trace",
    "save_chrome_trace",
    "new_trace_id",
    "parse_traceparent",
    "format_traceparent",
    "trace_scope",
    "current_context",
    "clock_anchor",
    "TRACE_SCHEMA_VERSION",
]

# /trace payload schema: bumped to 2 when the export grew the
# distributed-tracing envelope (schema_version, clock_anchor, ts_base,
# per-event trace_id/span_id/parent_span_id args) — fleet_trace.py and
# foreign consumers version-negotiate on it
TRACE_SCHEMA_VERSION = 2

# record layout (tuple for append cheapness):
# (name, cat, start_s, end_s, tid, depth, parent_name, span_id, args|None,
#  trace_id|None, span_hex|None, parent_hex|None, is_instant)
# The last four are the DISTRIBUTED identity: trace_id is the W3C
# 32-hex request id minted at the fleet's front door and carried across
# processes via `traceparent`; span_hex/parent_hex are this span's and
# its parent's 16-hex W3C span ids (chained through trace_scope + span
# nesting, so a child on another THREAD or PROCESS still names its real
# parent). All None outside a trace_scope — the always-on in-process
# tracer pays nothing for the fleet machinery.
_lock = threading.Lock()
_buf = deque(maxlen=65536)
_ids = itertools.count(1)  # .__next__ is atomic under the GIL
_tls = threading.local()
_thread_names = {}  # tid -> thread name, for trace metadata
# (flags.version(), enabled) — the disarmed/armed check must cost one
# integer compare on hot paths, same idiom as testing/chaos.py
_enabled_cache = (None, True)
# ref-count of force_enable holders (an explicit profiling session must
# record spans even when the always-on tracer is flagged off)
_force_on = 0


def enabled():
    """Is span recording armed (FLAGS_obs_trace, or a force_enable
    holder)? Cached per flags-version so per-span cost stays at one
    integer compare. The same once-per-flags-change branch applies
    FLAGS_obs_trace_buffer, so the bound takes effect on live paths
    (trainer, server) that never call reset()."""
    global _enabled_cache
    ver = _flags.version()
    cached_ver, cached = _enabled_cache
    if cached_ver != ver:
        cached = bool(_flags.get_flag("obs_trace", True))
        _enabled_cache = (ver, cached)
        _apply_buffer_bound()
    return cached or _force_on > 0


def _buffer_bound():
    try:
        return max(int(_flags.get_flag("obs_trace_buffer", 65536)), 1)
    except (TypeError, ValueError):
        return 65536


def _apply_buffer_bound():
    """Re-size the ring buffer to FLAGS_obs_trace_buffer, keeping the
    newest spans."""
    global _buf
    n = _buffer_bound()
    if _buf.maxlen != n:
        with _lock:
            _buf = deque(_buf, maxlen=n)


def force_enable(on):
    """Arm (``True``) / disarm (``False``) recording regardless of
    FLAGS_obs_trace. Ref-counted: ``fluid.profiler.start_profiler``
    holds this for the session so the legacy API keeps producing a
    timeline when the always-on tracer was turned off for overhead."""
    global _force_on
    _force_on = max(0, _force_on + (1 if on else -1))


# -- distributed trace context ----------------------------------------------
# W3C trace-context shapes: trace_id is 32 lowercase hex, span ids are
# 16. Span ids are DERIVED, not drawn from urandom per span: a random
# per-process seed XOR a Weyl-sequence hash of the process-local span
# counter is unique within the process by construction, collision-odds
# ~2^-64 across processes, and costs one multiply — span enter/exit
# stays on the <2% overhead budget even inside a scope.
_PROC_SEED = int.from_bytes(os.urandom(8), "big")
_SPAN_MASK = (1 << 64) - 1
_TRACEPARENT = re.compile(
    r"^[0-9a-f]{2}-([0-9a-f]{32})-([0-9a-f]{16})-[0-9a-f]{2}$"
)


def _span_hex(local_id):
    return "%016x" % (
        (_PROC_SEED ^ (local_id * 0x9E3779B97F4A7C15)) & _SPAN_MASK
    )


def new_trace_id():
    """A fresh W3C trace id (32 hex chars) — minted once per request at
    the fleet's front door (router, or a directly-fronted gateway)."""
    return os.urandom(16).hex()


def parse_traceparent(value):
    """``(trace_id, parent_span_id)`` from a W3C ``traceparent`` header,
    or None for absent/malformed values (a bad header means "mint your
    own", never an error — foreign clients send arbitrary bytes)."""
    if not value:
        return None
    m = _TRACEPARENT.match(str(value).strip().lower())
    if m is None:
        return None
    tid = m.group(1)
    if tid == "0" * 32 or m.group(2) == "0" * 16:
        return None  # the spec's all-zero ids are invalid
    return tid, m.group(2)


def format_traceparent(trace_id, span_id):
    """The ``traceparent`` header value naming ``span_id`` as the
    remote parent of whatever the receiving hop opens."""
    return "00-%s-%s-01" % (trace_id, span_id)


class trace_scope(object):
    """Thread-local ambient trace context: every span opened inside the
    scope records ``trace_id`` and chains ``parent_span_id`` from the
    nearest enclosing span (or the scope's remote parent — the
    traceparent a hop received). ``trace_id=None`` makes the scope a
    no-op, so call sites pass whatever context they captured without
    branching. Scopes nest; each thread owns its own stack."""

    __slots__ = ("_entry", "_pushed")

    def __init__(self, trace_id, parent_span_id=None):
        self._entry = (trace_id, parent_span_id) if trace_id else None
        self._pushed = False

    def __enter__(self):
        if self._entry is not None:
            stack = getattr(_tls, "ctx", None)
            if stack is None:
                stack = _tls.ctx = []
            stack.append(self._entry)
            self._pushed = True
        return self

    def __exit__(self, *exc):
        if self._pushed:
            _tls.ctx.pop()
            self._pushed = False
        return False


def current_context():
    """``(trace_id, parent_span_id)`` of the innermost ambient scope on
    THIS thread (the parent is the nearest enclosing span's id), or
    None. Capture it where a request is accepted and re-enter it via
    ``trace_scope(*ctx)`` on whatever thread later works for that
    request — that hand-off is how the batcher worker's and decode
    loop's spans join the request's tree."""
    stack = getattr(_tls, "ctx", None)
    return stack[-1] if stack else None


def clock_anchor():
    """The ``(ts, ts_mono)`` pair that lets a merger map THIS process's
    span timestamps onto a wall clock: ``ts_mono`` is sampled from the
    SAME clock spans record (``perf_counter``), so
    ``wall = ts + (span_t - ts_mono)`` exactly. Exposed by the
    exporter's ``/healthz``, the replica endpoint file, and the
    ``/trace`` payload itself — fleet_trace.py aligns per-process
    clocks against the controller's anchor."""
    return {"ts": time.time(), "ts_mono": time.perf_counter()}


class span(object):
    """Context manager recording one timed span.

    ``with span("ckpt_snapshot", cat="ckpt", step=7): ...`` — kwargs
    land in the Chrome event's ``args``. Nesting is tracked per thread:
    a span opened inside another becomes its child (``parent``/``depth``
    in the record, time containment in Perfetto). Disabled tracing makes
    enter/exit a near-no-op."""

    __slots__ = ("name", "cat", "args", "_t0", "_armed", "_parent",
                 "trace_id", "span_id", "_parent_hex", "_ctx_pushed")

    def __init__(self, name, cat="host", **args):
        self.name = name
        self.cat = cat
        self.args = args or None
        self._armed = False
        # distributed identity, populated at __enter__ when an ambient
        # trace_scope is active on this thread (None otherwise). span_id
        # is readable the moment the span opens — a hop forwards it in
        # `traceparent` BEFORE its children exist.
        self.trace_id = None
        self.span_id = None

    def __enter__(self):
        if not enabled():
            return self
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        self._parent = stack[-1] if stack else None
        stack.append(self.name)
        self._armed = True
        self._ctx_pushed = False
        ctx = getattr(_tls, "ctx", None)
        if ctx:
            # inside a trace_scope: mint this span's W3C id, remember
            # the enclosing id as parent, and become the ambient parent
            # for anything opened (or captured) underneath
            trace_id, parent = ctx[-1]
            self.trace_id = trace_id
            self._parent_hex = parent
            self.span_id = _span_hex(next(_ids))
            ctx.append((trace_id, self.span_id))
            self._ctx_pushed = True
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if not self._armed:
            return False
        t1 = time.perf_counter()
        self._armed = False
        stack = _tls.stack
        if stack:
            stack.pop()
        if self._ctx_pushed:
            _tls.ctx.pop()
            self._ctx_pushed = False
        tid = threading.get_ident()
        rec = (
            self.name, self.cat, self._t0, t1, tid, len(stack),
            self._parent, next(_ids), self.args,
            self.trace_id, self.span_id,
            self._parent_hex if self.trace_id else None, False,
        )
        with _lock:
            if tid not in _thread_names:  # once per thread, not per span
                _thread_names[tid] = threading.current_thread().name
            _buf.append(rec)
        return False


def instant(name, cat="host", **args):
    """Record a zero-duration INSTANT event (Perfetto ``ph: "i"``) —
    the attributable mark for moments that have no extent, like the
    router's failover splice between two replicas' stream segments.
    Carries the ambient trace context like a span (so the mark lands
    inside the request's tree), costs one append, no-op when tracing
    is off."""
    if not enabled():
        return
    t = time.perf_counter()
    tid = threading.get_ident()
    ctx = getattr(_tls, "ctx", None)
    trace_id = span_hex = parent = None
    if ctx:
        trace_id, parent = ctx[-1]
        span_hex = _span_hex(next(_ids))
    stack = getattr(_tls, "stack", None)
    rec = (
        name, cat, t, t, tid, len(stack) if stack else 0,
        stack[-1] if stack else None, next(_ids), args or None,
        trace_id, span_hex, parent, True,
    )
    with _lock:
        if tid not in _thread_names:
            _thread_names[tid] = threading.current_thread().name
        _buf.append(rec)


def traced(name=None, cat="host"):
    """Decorator form: ``@traced`` / ``@traced("label", cat="serving")``
    wraps the call in a span (label defaults to the qualified name)."""
    if callable(name):  # bare @traced
        return traced(None)(name)

    def deco(fn):
        label = name or fn.__qualname__

        @functools.wraps(fn)
        def wrapper(*a, **kw):
            with span(label, cat=cat):
                return fn(*a, **kw)

        return wrapper

    return deco


def get_spans(newest=None):
    """Snapshot of the ring buffer as dicts (oldest first); list and
    dicts are copies — same isolation contract as profiler counters.
    ``trace_id``/``span_id``/``parent_span_id`` are the distributed
    identity (None outside a trace_scope); ``instant`` marks
    zero-duration events. ``newest=`` bounds the snapshot to the newest
    N records BEFORE dict conversion — the periodic black-box dump must
    not pay a full-ring copy to keep 1/16th of it."""
    with _lock:
        recs = list(_buf)
    if newest is not None:
        n = int(newest)
        recs = recs[-n:] if n > 0 else []  # -0 would slice the WHOLE ring
    return [
        {
            "name": r[0], "cat": r[1], "start": r[2], "end": r[3],
            "tid": r[4], "depth": r[5], "parent": r[6], "id": r[7],
            "args": dict(r[8]) if r[8] else {},
            "trace_id": r[9], "span_id": r[10],
            "parent_span_id": r[11], "instant": r[12],
        }
        for r in recs
    ]


def reset():
    """Drop every retained span and re-read the buffer bound from
    FLAGS_obs_trace_buffer (so tests can shrink it)."""
    global _buf
    with _lock:
        _buf = deque(maxlen=_buffer_bound())


def gang_rank(rank=None):
    """The gang rank labeling every per-rank artifact (trace ``pid``,
    snapshot filename, exporter identity): an explicit value wins, else
    PADDLE_TRAINER_ID, else 0 (non-numeric counts as unset). One
    resolver so a change to rank discovery can't skew artifacts apart."""
    if rank is not None:
        return int(rank)
    try:
        return int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    except ValueError:
        return 0


def _span_matches(s, trace_id):
    """Does this span belong to ``trace_id``? Either its own distributed
    identity matches, or it is a shared-work span (a batched dispatch /
    fused decode tick) whose ``trace_ids`` args list names the trace."""
    if s["trace_id"] == trace_id:
        return True
    tids = s["args"].get("trace_ids")
    return isinstance(tids, (list, tuple)) and trace_id in tids


def chrome_trace(trace_id=None, newest=None):
    """The retained spans as a Chrome trace-event dict: ``ph: "X"``
    complete events (``ph: "i"`` for instants) with ``ts``/``dur`` in
    microseconds, ``pid`` = gang rank, ``tid`` = thread, nesting by
    containment (exact, because spans close LIFO per thread), plus
    process/thread-name metadata. Loads in Perfetto / chrome://tracing
    as-is. The distributed envelope rides as EXTRA top-level keys
    (Perfetto ignores them): ``schema_version``, ``clock_anchor`` (the
    wall/mono pair a merger aligns on), ``ts_base`` (the mono origin
    subtracted from every ``ts``, so absolute times reconstruct), and
    process identity; per-event ``trace_id``/``span_id``/
    ``parent_span_id`` land in ``args``. ``trace_id=`` filters to one
    request's spans (shared-work spans whose ``trace_ids`` list names
    it included); ``newest=`` keeps only the newest N spans (bounded
    periodic dumps)."""
    # the newest bound applies pre-conversion when it can (no filter);
    # with a trace_id filter it must run AFTER, on the matching spans
    spans = get_spans(newest=None if trace_id is not None else newest)
    if trace_id is not None:
        spans = [s for s in spans if _span_matches(s, trace_id)]
        if newest is not None:
            n = int(newest)
            spans = spans[-n:] if n > 0 else []
    rank = gang_rank()
    t0 = min((s["start"] for s in spans), default=0.0)
    events = [
        {
            "name": "process_name", "ph": "M", "pid": rank, "tid": 0,
            "args": {"name": "rank %d" % rank},
        }
    ]
    with _lock:  # span exits insert names concurrently
        names = list(_thread_names.items())
    # OS thread idents are pthread addresses — huge and collision-prone
    # under any modulus — so the export aliases each distinct ident to a
    # small stable row id (collision-free by construction)
    alias = {
        t: i + 1
        for i, t in enumerate(sorted(
            {t for t, _ in names} | {s["tid"] for s in spans}
        ))
    }
    for tid, tname in sorted(names):
        events.append({
            "name": "thread_name", "ph": "M", "pid": rank,
            "tid": alias[tid], "args": {"name": tname},
        })
    for s in spans:
        args = dict(s["args"])
        args["depth"] = s["depth"]
        if s["parent"]:
            args["parent"] = s["parent"]
        if s["trace_id"]:
            args["trace_id"] = s["trace_id"]
            args["span_id"] = s["span_id"]
            if s["parent_span_id"]:
                args["parent_span_id"] = s["parent_span_id"]
        ev = {
            "name": s["name"], "cat": s["cat"],
            "ts": (s["start"] - t0) * 1e6,
            "pid": rank, "tid": alias[s["tid"]], "args": args,
        }
        if s["instant"]:
            ev["ph"] = "i"
            ev["s"] = "p"  # process-scoped instant mark
        else:
            ev["ph"] = "X"
            ev["dur"] = (s["end"] - s["start"]) * 1e6
        events.append(ev)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "schema_version": TRACE_SCHEMA_VERSION,
        "clock_anchor": clock_anchor(),
        "ts_base": t0,
        "rank": rank,
        "pid_os": os.getpid(),
    }


def save_chrome_trace(path):
    """Write ``chrome_trace()`` to ``path`` (atomic tmp+rename so a
    half-written export never loads as torn JSON). Returns the path."""
    tmp = "%s.tmp.%d" % (path, os.getpid())
    with open(tmp, "w") as f:
        json.dump(chrome_trace(), f)
    os.replace(tmp, path)
    return path
