"""Fleet trace merge: N processes' /trace pulls -> ONE aligned timeline.

The reference's ``device_tracer`` correlated host and device events
inside one process via correlation ids; this module is that idea at
fleet scale. Each process (router/controller, every replica gateway +
engine) exports spans stamped with W3C ``trace_id``/``span_id``/
``parent_span_id`` (observability/trace.py) on its OWN monotonic clock.
The merge:

1. **pulls** every process's ``/trace`` payload (schema_version >= 2:
   carries a ``clock_anchor`` ``(ts, ts_mono)`` pair and the ``ts_base``
   its event timestamps are relative to) — over HTTP for live
   processes, from the on-disk black-box dump (``trace_rank_<r>.json``,
   written by the exporter's snapshot loop and teardown paths) for
   processes that died;
2. **aligns** clocks: each process's span times map through its anchor
   onto its wall clock, an NTP-style skew estimate (the process's
   reported wall time against the puller's request midpoint) corrects
   genuinely skewed wall clocks, and everything lands on the reference
   (controller) process's timeline;
3. **merges** into one Perfetto-loadable trace — one ``pid`` row per
   process, instants (the failover seam) preserved — and
4. **links** each trace_id's spans into a single tree: children chain
   to parents by span id ACROSS processes; spans whose parent never
   recorded (evicted from the bounded ring, or died with a SIGKILLed
   process mid-request) attach to a synthetic per-process root that
   itself hangs off the tree — orphans are marked and counted
   (``trace_orphan_spans``), never dropped. Shared-work spans (a
   batched dispatch / fused decode tick carrying a ``trace_ids`` list)
   join every tree they served. Requests whose tree connects spans
   from 2+ processes count ``trace_requests_linked``.

CLI::

    python -m paddle_tpu.observability.fleet_trace \
        --endpoint controller=http://127.0.0.1:9100 \
        --endpoint replica0=http://127.0.0.1:9101 \
        --out fleet_trace.json

Load ``fleet_trace.json`` in https://ui.perfetto.dev — a request's
router span time-contains its gateway and engine spans across process
rows.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import time
import urllib.error
import urllib.request

from ..fluid import profiler as _profiler
from . import trace as _trace

__all__ = [
    "ProcessClock",
    "pull_trace",
    "load_trace_dump",
    "spans_of",
    "merge",
    "span_trees",
    "containment_violations",
    "write_merged",
]

_TRACE_DUMP = re.compile(r"^trace_rank_(\d+)\.json$")

# wall-clock skew below this is indistinguishable from pull latency on
# one host — applying it would ADD noise, not remove skew; above it the
# clock is genuinely off and the estimate wins
SKEW_TOLERANCE_S = 0.25


class ProcessClock(object):
    """Maps one process's span timestamps (its ``perf_counter`` clock)
    onto a shared wall timeline.

    ``anchor`` is the process's ``(ts, ts_mono)`` pair; ``skew_s`` is
    its wall clock's measured offset from the reference clock (0 for a
    same-host process). A MONO-ONLY process (anchor without ``ts`` —
    a foreign exporter that can't sample wall time) degrades to
    identity mapping against the reference anchor: correct exactly when
    the two processes share a monotonic epoch (same host), which is the
    only case a mono-only anchor can support at all."""

    def __init__(self, anchor, skew_s=0.0, reference=None):
        anchor = anchor or {}
        self.ts = anchor.get("ts")
        self.ts_mono = anchor.get("ts_mono")
        self.skew_s = float(skew_s or 0.0)
        self._ref = reference or {}

    def to_wall(self, mono):
        """Reference wall time of one span timestamp."""
        if self.ts is None or self.ts_mono is None:
            ref_ts = self._ref.get("ts")
            ref_mono = self._ref.get("ts_mono")
            if ref_ts is None or ref_mono is None:
                return float(mono)  # nothing to align against
            return ref_ts + (float(mono) - ref_mono)
        return self.ts + (float(mono) - self.ts_mono) - self.skew_s

    @staticmethod
    def estimate_skew(reported_ts, t_request_0, t_request_1,
                      tolerance_s=SKEW_TOLERANCE_S):
        """NTP-style one-shot skew estimate: the process reported its
        wall time ``reported_ts`` somewhere inside the puller's
        [t0, t1] request window, so ``reported - midpoint`` bounds the
        clock offset to within half the round trip. Below
        ``tolerance_s`` the estimate is indistinguishable from pull
        latency and is ignored (same-host clocks are identical; noise
        must not smear an already-aligned timeline)."""
        if reported_ts is None:
            return 0.0
        skew = float(reported_ts) - (float(t_request_0)
                                     + float(t_request_1)) / 2.0
        return skew if abs(skew) > float(tolerance_s) else 0.0


def _http_json(url, timeout):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read().decode("utf-8"))


def pull_trace(base_url, label=None, trace_id=None, timeout=5.0):
    """Pull one live process: ``{label, trace, anchor, skew_s}`` from
    its ``/trace`` (+ optional ``?trace_id=`` narrowing) and
    ``/healthz`` (the anchor + the skew sample). Raises on an
    unreachable process — the caller decides whether a black-box dump
    can stand in."""
    url = base_url.rstrip("/")
    q = "?trace_id=%s" % trace_id if trace_id else ""
    trace = _http_json(url + "/trace" + q, timeout)
    t0 = time.time()
    try:
        health = _http_json(url + "/healthz", timeout)
    except urllib.error.HTTPError as e:  # draining answers 503 + body
        health = json.loads(e.read().decode("utf-8"))
    t1 = time.time()
    anchor = trace.get("clock_anchor") or {
        "ts": health.get("ts"), "ts_mono": health.get("ts_mono"),
    }
    skew = ProcessClock.estimate_skew(health.get("ts"), t0, t1)
    return {
        "label": label or url,
        "trace": trace,
        "anchor": anchor,
        "skew_s": skew,
    }


def load_trace_dump(path, label=None):
    """A dead process's black-box span dump as a pull-shaped dict (its
    anchor rides inside the payload; skew is unknowable post-mortem —
    same-host 0 is the only defensible estimate)."""
    with open(path) as f:
        trace = json.load(f)
    return {
        "label": label or os.path.basename(path),
        "trace": trace,
        "anchor": trace.get("clock_anchor"),
        "skew_s": 0.0,
    }


def find_trace_dumps(obs_root):
    """[(label, path)] for every ``trace_rank_*.json`` black box under
    ``obs_root`` (one level of subdirs + the root itself — the fleet
    layout, via the walker shared with the flight-record reader)."""
    from . import aggregate as _aggregate

    return [
        ("%s/%s" % (subdir, fn) if subdir else fn, path)
        for subdir, fn, path in _aggregate.iter_obs_dumps(
            obs_root, _TRACE_DUMP)
    ]


def _dedup_pulls(pulls):
    """Drop later pulls that are the SAME process as an earlier one
    (payload ``(rank, pid_os)`` identity): a live process's snapshot
    loop also writes its black box to disk, so ``--endpoint`` +
    ``--obs-root`` would otherwise merge each survivor twice — a
    duplicate pid row, and single-process traces miscounted as
    cross-process. First pull wins (live endpoints are pulled before
    dumps, and a merge-time pull is fresher than any snapshot).
    Payloads without both identity fields (foreign exporters, synthetic
    fixtures) are never deduped. Returns (kept, dropped_labels)."""
    seen = set()
    kept, dropped = [], []
    for pull in pulls:
        trace = pull.get("trace") or {}
        rank, pid_os = trace.get("rank"), trace.get("pid_os")
        if rank is not None and pid_os is not None:
            key = (rank, pid_os)
            if key in seen:
                dropped.append(str(pull.get("label")))
                continue
            seen.add(key)
        kept.append(pull)
    return kept, dropped


def spans_of(pull):
    """Span dicts reconstructed from one pull's trace events, with
    ABSOLUTE mono times (``ts_base`` re-added) and the distributed ids
    lifted out of args. Metadata events are skipped; instants keep
    ``instant: True``."""
    trace = pull["trace"]
    base = float(trace.get("ts_base") or 0.0)
    out = []
    for ev in trace.get("traceEvents", ()):
        ph = ev.get("ph")
        if ph not in ("X", "i"):
            continue
        args = ev.get("args") or {}
        start = base + float(ev.get("ts", 0.0)) / 1e6
        dur = float(ev.get("dur", 0.0)) / 1e6 if ph == "X" else 0.0
        out.append({
            "name": ev.get("name"),
            "cat": ev.get("cat"),
            "start": start,
            "end": start + dur,
            "tid": ev.get("tid"),
            "instant": ph == "i",
            "trace_id": args.get("trace_id"),
            "span_id": args.get("span_id"),
            "parent_span_id": args.get("parent_span_id"),
            "trace_ids": args.get("trace_ids"),
            "args": args,
            "process": pull["label"],
        })
    return out


def merge(pulls, reference=None):
    """Merge N pulls into one report dict:

    - ``trace``: a single Perfetto-loadable chrome trace — one ``pid``
      per process (named rows), every event's ``ts`` on the reference
      wall timeline;
    - ``spans``: the aligned span dicts (``start``/``end`` now wall
      seconds on the reference clock);
    - ``trees``: per-trace_id span trees (see ``span_trees``);
    - counters: ``requests_linked`` (trees connecting 2+ processes,
      also bumped onto the metrics registry as
      ``trace_requests_linked``) and ``orphan_spans``
      (``trace_orphan_spans``).

    ``reference`` defaults to the FIRST pull's anchor — pull the
    controller first and the merged timeline is the controller's.
    """
    if not pulls:
        return {"trace": {"traceEvents": []}, "spans": [], "trees": {},
                "requests_linked": 0, "orphan_spans": 0,
                "duplicate_pulls": []}
    pulls, dropped = _dedup_pulls(pulls)
    reference = reference or pulls[0].get("anchor") or {}
    events = []
    all_spans = []
    t0 = None
    per_pull = []
    for i, pull in enumerate(pulls):
        clock = ProcessClock(pull.get("anchor"),
                             skew_s=pull.get("skew_s", 0.0),
                             reference=reference)
        spans = spans_of(pull)
        for s in spans:
            s["start"] = clock.to_wall(s["start"])
            s["end"] = clock.to_wall(s["end"])
            if t0 is None or s["start"] < t0:
                t0 = s["start"]
        per_pull.append((i, pull, spans))
        all_spans.extend(spans)
    t0 = t0 or 0.0
    for i, pull, spans in per_pull:
        events.append({
            "name": "process_name", "ph": "M", "pid": i, "tid": 0,
            "args": {"name": str(pull["label"])},
        })
        for s in spans:
            ev = {
                "name": s["name"], "cat": s["cat"],
                "ts": (s["start"] - t0) * 1e6,
                "pid": i, "tid": s["tid"] or 0, "args": s["args"],
            }
            if s["instant"]:
                ev["ph"] = "i"
                ev["s"] = "p"
            else:
                ev["ph"] = "X"
                ev["dur"] = (s["end"] - s["start"]) * 1e6
            events.append(ev)
    trees = span_trees(all_spans)
    linked = sum(1 for t in trees.values()
                 if t["connected"] and len(t["processes"]) >= 2)
    orphans = sum(t["orphans"] for t in trees.values())
    if linked:
        _profiler.bump_counter("trace_requests_linked", linked)
    if orphans:
        _profiler.bump_counter("trace_orphan_spans", orphans)
    return {
        "trace": {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "schema_version": _trace.TRACE_SCHEMA_VERSION,
            "merged_processes": [str(p["label"]) for p in pulls],
            "t0_wall": t0,
        },
        "spans": all_spans,
        "trees": trees,
        "requests_linked": linked,
        "orphan_spans": orphans,
        "duplicate_pulls": dropped,
    }


def span_trees(spans):
    """{trace_id: tree} over aligned span dicts.

    Each tree: ``nodes`` ({span_id: span}), ``children``
    ({span_id: [span_id]}), ``root`` (the unique parentless span's id,
    or None), ``connected`` (exactly one real root and every node
    reachable from it), ``orphans`` (spans whose named parent never
    recorded — ring eviction, or a process that died mid-request: they
    attach under a synthetic ``synthetic:<process>`` node that hangs
    off the root, marked, NEVER dropped), ``instants``, ``ticks``
    (shared-work spans listing this trace in ``trace_ids``), and
    ``processes`` (every process contributing a span)."""
    by_trace = {}
    shared_by_trace = {}
    for s in spans:
        if s.get("trace_id") and s.get("span_id"):
            by_trace.setdefault(s["trace_id"], []).append(s)
        tids = s.get("trace_ids")
        if isinstance(tids, (list, tuple)):
            for t in tids:
                by_trace.setdefault(t, [])
                shared_by_trace.setdefault(t, []).append(s)
    trees = {}
    for trace_id, members in by_trace.items():
        nodes = {s["span_id"]: s for s in members if not s["instant"]}
        instants = [s for s in members if s["instant"]]
        shared = shared_by_trace.get(trace_id, [])
        children = {}
        roots, orphan_spans = [], []
        for sid, s in nodes.items():
            parent = s.get("parent_span_id")
            if parent is None:
                roots.append(sid)
            elif parent in nodes:
                children.setdefault(parent, []).append(sid)
            else:
                orphan_spans.append(s)
        root = roots[0] if len(roots) == 1 else None
        if root is None and not roots and orphan_spans:
            # a trace ADOPTED from a client's traceparent has no local
            # root: the fleet's topmost span (router_request) chains to
            # the client's remote span, which no pull can ever contain.
            # Promote the earliest such span — it IS the fleet-side
            # root; its remote parentage stays visible on the span —
            # so "send your own traceparent" still yields one
            # connected tree.
            top = min(orphan_spans, key=lambda s: s["start"])
            orphan_spans.remove(top)
            top["remote_parent"] = True
            root = top["span_id"]
        processes = {s["process"] for s in members} | {
            s["process"] for s in shared
        }
        # orphans hang from a synthetic per-process node under the root
        # (or stand alone when the trace has no root at all): the tree
        # stays connected and the orphan is visibly marked synthetic
        synth = {}
        for s in orphan_spans:
            key = "synthetic:%s" % s["process"]
            if key not in synth:
                synth[key] = {
                    "name": key, "span_id": key, "synthetic": True,
                    "process": s["process"], "instant": False,
                    "trace_id": trace_id,
                }
                nodes[key] = synth[key]
                if root is not None:
                    children.setdefault(root, []).append(key)
            s["orphan"] = True
            children.setdefault(key, []).append(s["span_id"])
        # connectivity: every non-synthetic node reachable from the root
        connected = root is not None
        if connected:
            seen = set()
            stack = [root]
            while stack:
                cur = stack.pop()
                if cur in seen:
                    continue
                seen.add(cur)
                stack.extend(children.get(cur, ()))
            connected = all(sid in seen for sid in nodes)
        trees[trace_id] = {
            "nodes": nodes,
            "children": children,
            "root": root,
            "connected": connected,
            "orphans": len(orphan_spans),
            "instants": instants,
            "ticks": shared,
            "processes": processes,
        }
    return trees


def containment_violations(tree, slack_s=0.05):
    """Parent/child time-containment violations in one aligned tree:
    [(parent_name, child_name, overhang_s)] where a REAL child starts
    before or ends after its REAL parent by more than ``slack_s``.
    Zero violations is the cross-process alignment bar: the router
    span contains the gateway span contains the engine spans, on wall
    time, across processes. Synthetic edges (orphan attachment) carry
    no timing claim and are skipped."""
    out = []
    nodes, children = tree["nodes"], tree["children"]
    for pid, kids in children.items():
        p = nodes.get(pid)
        if p is None or p.get("synthetic"):
            continue
        for cid in kids:
            c = nodes.get(cid)
            if c is None or c.get("synthetic"):
                continue
            over = max(p["start"] - c["start"], c["end"] - p["end"])
            if over > slack_s:
                out.append((p["name"], c["name"], round(over, 6)))
    return out


def write_merged(path, merged):
    """Write the merged Perfetto trace (atomic tmp+rename)."""
    tmp = "%s.tmp.%d" % (path, os.getpid())
    with open(tmp, "w") as f:
        json.dump(merged["trace"], f)
    os.replace(tmp, path)
    return path


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="merge a serving fleet's /trace exports into one "
                    "Perfetto timeline"
    )
    ap.add_argument("--endpoint", action="append", default=[],
                    metavar="LABEL=URL",
                    help="live process to pull (repeatable); the FIRST "
                         "one is the reference clock")
    ap.add_argument("--dump", action="append", default=[],
                    metavar="LABEL=PATH",
                    help="black-box trace_rank_*.json of a dead process")
    ap.add_argument("--obs-root", default="",
                    help="fleet obs/ dir: every trace_rank_*.json "
                         "below it merges as a dump")
    ap.add_argument("--trace-id", default="",
                    help="narrow live pulls to one request")
    ap.add_argument("--out", default="fleet_trace.json")
    args = ap.parse_args(argv)

    pulls = []
    for spec in args.endpoint:
        label, _, url = spec.partition("=")
        if not url:
            label, url = url or spec, spec
        pulls.append(pull_trace(url, label=label or None,
                                trace_id=args.trace_id or None))
    for spec in args.dump:
        label, _, path = spec.partition("=")
        if not path:
            label, path = "", spec
        pulls.append(load_trace_dump(path, label=label or None))
    if args.obs_root:
        for label, path in find_trace_dumps(args.obs_root):
            pulls.append(load_trace_dump(path, label=label))
    merged = merge(pulls)
    write_merged(args.out, merged)
    linked = merged["requests_linked"]
    dropped = merged["duplicate_pulls"]
    print(
        "fleet_trace: %d processes, %d spans, %d traces "
        "(%d cross-process, %d orphan spans) -> %s"
        % (len(pulls) - len(dropped), len(merged["spans"]),
           len(merged["trees"]), linked, merged["orphan_spans"],
           args.out)
    )
    if dropped:
        print("fleet_trace: skipped %d duplicate pull(s) of already-"
              "merged processes: %s" % (len(dropped), ", ".join(dropped)))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main(sys.argv[1:]))
