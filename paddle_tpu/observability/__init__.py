"""paddle_tpu.observability — unified tracing, metrics, and gang telemetry.

The reference Fluid stack shipped a dedicated observability layer
(``platform/profiler.h``, ``platform/device_tracer.h`` + the
chrome-trace ``tools/timeline.py``); this package is that layer rebuilt
as one spine over the whole reproduction:

- ``trace``     — thread-safe span tracer (ring buffer, Perfetto export)
- ``registry``  — metrics API over the always-on profiler counters /
  histograms: Prometheus text + JSONL snapshot renderers
- ``exporter``  — stdlib HTTP ``/metrics`` ``/healthz`` ``/trace`` +
  per-rank JSONL snapshot files, armed by ``FLAGS_obs_*``
- ``aggregate`` — supervisor-side merge of per-rank snapshots +
  supervisor.log into ``gang_report.json``
- ``xla_stats`` — device-plane telemetry: compile spans + recompile
  sentinel with cache-key attribution, per-program-key FLOP/HBM-byte
  census, device-memory gauges, strict serving compile gate
- ``flight``    — per-request flight recorder: bounded journey-record
  ring dumped to disk on drain/error (the telemetry that survives a
  dead replica)
- ``fleet_trace`` — merge N processes' ``/trace`` pulls into ONE
  clock-aligned Perfetto timeline with cross-process span trees

Submodules load lazily (PEP 562): ``trace`` sits on hot paths inside
``fluid`` itself, so this package must import without dragging the rest
of the stack in (and without import cycles through ``fluid.profiler``).
"""

import importlib

_SUBMODULES = ("trace", "registry", "exporter", "aggregate", "xla_stats",
               "flight", "fleet_trace")

__all__ = list(_SUBMODULES)


def __getattr__(name):
    if name in _SUBMODULES:
        mod = importlib.import_module("." + name, __name__)
        globals()[name] = mod
        return mod
    raise AttributeError("module %r has no attribute %r" % (__name__, name))


def __dir__():
    return sorted(list(globals()) + list(_SUBMODULES))
