"""Device-plane telemetry: compile spans, recompile sentinel, cost census.

PR 5's tracer/registry see every host-side layer; everything below
``jax.jit`` was a black box — compiles, per-executable FLOP/HBM-byte
costs, device memory — visible only through one-off ``tools/hlo_scan.py``
runs. This module is the device-plane counterpart of ``trace.py`` /
``registry.py``: the executor's lower-and-compile path reports here, and
three always-on signals come out:

- **Compile telemetry**: every ``_CompiledBlock`` build and every XLA
  executable compile emits a span plus a structured record — program
  cache key, wall ms, and a trigger classification (``cold`` /
  ``shape_change`` / ``program_mutation`` / ``feed_order_change`` /
  ``lru_eviction`` / ``uncached_rebuild``). The **recompile sentinel**
  diffs the new cache key against the nearest prior key of the same
  program, so a record says *which component changed* (version, feed
  set/order, fetch list, a feed's shape), not just "it recompiled".
- **Cost census**: the executor compiles ahead-of-time per feed-shape
  signature, so the compiled executable is in hand at record time and
  XLA cost analysis + the optimized-HLO op census are FREE (no second
  compile). Per-program-key gauges (``xla_flops_<key>``,
  ``xla_bytes_accessed_<key>``, ``xla_out_bytes_<key>``) publish through
  the registry; live/peak device-memory gauges register where the
  backend exposes ``memory_stats()`` (TPU/GPU — the CPU backend
  doesn't). ``tools/hlo_scan.py`` shares the census functions below, so
  the one-off scan and the always-on plane can never disagree.
- **Strict serving gate**: ``serving.InferenceServer`` arms the gate
  (``arm_serving_steady()``, counted per live server) once warmup
  finished; an executable compile on a serving-request thread (inside a
  ``serving_request_window()``, as the dispatch workers are) and outside
  a ``warmup_window()`` then bumps ``serving_steady_recompiles`` and —
  under ``FLAGS_serving_strict_compiles`` — raises
  ``SteadyStateRecompileError`` with the attribution attached, turning
  the "0 recompiles after warmup" claim into an enforced invariant. A
  colocated trainer's compiles never touch the gate.

Everything is bounded (``FLAGS_obs_compile_records`` records, capped
key history and census map) and lock-guarded; the steady-state step path
touches none of it.
"""

from __future__ import annotations

import collections
import itertools
import os
import re
import threading
import time
import weakref
import zlib
from collections import OrderedDict, deque

from ..fluid import flags as _flags
from ..fluid import profiler as _profiler

__all__ = [
    "INTERESTING_OPS",
    "SteadyStateRecompileError",
    "op_census",
    "interesting_ops",
    "cost_summary",
    "executable_census",
    "program_label",
    "make_key",
    "fingerprint",
    "key_slug",
    "on_build",
    "on_dispatch_rebind",
    "on_xla_compile",
    "note_eviction",
    "serving_steady",
    "arm_serving_steady",
    "disarm_serving_steady",
    "serving_request_window",
    "warmup_window",
    "get_records",
    "summary",
    "compiles_endpoint",
    "census_by_key",
    "headline_census",
    "attach_headline_census",
    "reset",
]


# ---------------------------------------------------------------------------
# Shared HLO census library (extracted from tools/hlo_scan.py — the scan
# now imports THESE, so scan output and the always-on census share one
# implementation)
# ---------------------------------------------------------------------------

# the op families PERF.md's fusion-hygiene methodology watches
INTERESTING_OPS = (
    "transpose", "convert", "copy", "fusion", "dot", "convolution",
    "all-reduce", "custom-call",
)

# `%name = <type> opcode(...)`; the type may be a tuple `(f32[..], ..)`
# for multi-output fusions, so the type part must admit parentheses
_HLO_OP_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*[\w\[\],{}()\s/]*\s"
    r"([a-z][a-z\-]*)\(",
    re.M,
)


def op_census(hlo_text):
    """{opcode: count} over one optimized-HLO module's instruction list."""
    hist = collections.Counter()
    for m in _HLO_OP_RE.finditer(hlo_text):
        hist[m.group(1)] += 1
    return dict(hist)


def interesting_ops(hist):
    """The fixed fusion-hygiene subset (zero-filled) of an op census."""
    return {k: hist.get(k, 0) for k in INTERESTING_OPS}


def cost_summary(raw_cost):
    """{"flops", "bytes_accessed", "out_bytes"} from a
    ``Compiled.cost_analysis()`` result (list-of-dict or dict across jax
    versions; missing keys surface as None)."""
    if isinstance(raw_cost, (list, tuple)):
        cost = raw_cost[0] if raw_cost else {}
    else:
        cost = raw_cost or {}
    return {
        "flops": cost.get("flops"),
        "bytes_accessed": cost.get("bytes accessed"),
        "out_bytes": cost.get("bytes accessedout{}"),
    }


def executable_census(compiled):
    """Full census of one compiled executable: cost analysis + optimized
    HLO op histogram. ``hlo_ops`` is the complete {opcode: count} map
    (callers wanting the fusion-hygiene subset apply
    ``interesting_ops``)."""
    out = cost_summary(compiled.cost_analysis())
    if out["out_bytes"] is None:
        try:  # backends without the per-operand cost keys still know sizes
            out["out_bytes"] = float(
                compiled.memory_analysis().output_size_in_bytes
            )
        except Exception:
            pass
    hist = op_census(compiled.as_text())
    out["hlo_ops"] = hist
    out["total_hlo_ops"] = sum(hist.values())
    return out


# ---------------------------------------------------------------------------
# Program identity + cache keys
# ---------------------------------------------------------------------------

# program object -> stable per-process label. Weakly keyed: telemetry
# must never pin a Program (the executor LRU test relies on dead clones
# collecting), and a recycled id() can't alias two programs to one label.
_prog_ids = weakref.WeakKeyDictionary()
_prog_seq = itertools.count(1)

_lock = threading.Lock()


def program_label(program):
    with _lock:
        label = _prog_ids.get(program)
        if label is None:
            label = "P%d" % next(_prog_seq)
            _prog_ids[program] = label
        return label


def make_key(program, feed_names, fetch_names, mesh=None, block_idx=0,
             spmd=None):
    """The serializable image of the executor's program cache key:
    program label + version + sorted feed names + ordered fetch names
    (+ SPMD mesh shape / sharding-policy summary / non-zero block index
    when applicable)."""
    extra = []
    if block_idx:
        extra.append(("block", int(block_idx)))
    if mesh is not None:
        extra.append((
            "spmd",
            tuple(zip(list(mesh.axis_names), list(mesh.devices.shape))),
        ))
    if spmd:
        extra.append(
            ("spmd_policy", tuple(sorted(spmd.items())))
        )
    return {
        "program": program_label(program),
        "version": int(getattr(program, "_version", 0)),
        "feeds": tuple(sorted(feed_names)),
        "fetches": tuple(fetch_names),
        "extra": tuple(extra),
    }


def fingerprint(key):
    return "%s|v%d|f=%s|o=%s|x=%s" % (
        key["program"], key["version"], ",".join(key["feeds"]),
        ",".join(key["fetches"]), repr(key["extra"]),
    )


def key_slug(key):
    """Prometheus-safe short name for per-key gauge families:
    ``P3_v2_1a2b3c4d`` (the hash disambiguates feed/fetch variants of
    one program version)."""
    return "%s_v%d_%08x" % (
        key["program"], key["version"],
        zlib.crc32(fingerprint(key).encode()) & 0xFFFFFFFF,
    )


# ---------------------------------------------------------------------------
# Record store + recompile sentinel
# ---------------------------------------------------------------------------

_KEY_HISTORY_CAP = 16      # prior keys remembered per program
_PROGRAMS_CAP = 64         # program labels carrying key history
_TRIGGER_CAP = 256         # build-trigger fingerprints remembered
_EXEC_SEEN_CAP = 1024      # (fingerprint, segment) shape signatures
_EVICTED_CAP = 256         # evicted-key fingerprints remembered
_CENSUS_CAP = 64           # program keys carrying census gauges

_records = deque(maxlen=1024)
_records_flag_ver = None
_key_history = OrderedDict()   # program label -> [key, ...] newest last
_evicted = OrderedDict()   # fingerprint -> eviction wall-clock ts
_build_trigger = OrderedDict()  # fingerprint -> newest (trigger, diff)
_exec_seen = OrderedDict()  # (fingerprint, segment) -> last feed_shapes
_census = OrderedDict()    # fingerprint -> accumulated census totals
# monotonic process-lifetime totals (NOT derived from the bounded record
# ring: a recompile storm larger than the ring must still be fully
# counted in snapshots / the gang report)
_totals = {"builds": 0, "compiles": 0, "dispatch_rebinds": 0,
           "compile_ms": 0.0}
_trigger_totals = collections.Counter()
_steady_count = 0         # armed steady-state gates (one per live server)
_tls = threading.local()  # per-thread request-window + warmup depths
_mem_gauges_done = False


class SteadyStateRecompileError(RuntimeError):
    """A steady-state serving compile under FLAGS_serving_strict_compiles.
    Carries the structured record so the shedding layer / client can see
    the attribution."""

    def __init__(self, record):
        self.record = record
        super().__init__(
            "steady-state XLA recompile in serving (strict mode): "
            "trigger=%s key=%s diff=%r"
            % (record["trigger"], record["fingerprint"], record["diff"])
        )


def _apply_record_bound():
    """Resize the record ring to FLAGS_obs_compile_records on any flags
    change (same once-per-version idiom as trace.enabled)."""
    global _records, _records_flag_ver
    ver = _flags.version()
    if _records_flag_ver == ver:
        return
    _records_flag_ver = ver
    try:
        n = max(int(_flags.get_flag("obs_compile_records", 1024)), 1)
    except (TypeError, ValueError):
        n = 1024
    if _records.maxlen != n:
        _records = deque(_records, maxlen=n)


def _phase():
    if getattr(_tls, "warmup", 0) > 0:
        return "warmup"
    if _steady_count > 0:
        return "steady"
    return ""


def _key_diff(new, prior):
    """(changed_components, detail) between two cache keys of the same
    program — the attribution payload of the sentinel."""
    changed, detail = [], {}
    if new["version"] != prior["version"]:
        changed.append("version")
        detail["version"] = [prior["version"], new["version"]]
    if new["feeds"] != prior["feeds"]:
        changed.append("feeds")
        detail["feeds_added"] = sorted(set(new["feeds"]) - set(prior["feeds"]))
        detail["feeds_removed"] = sorted(
            set(prior["feeds"]) - set(new["feeds"])
        )
    if new["fetches"] != prior["fetches"]:
        changed.append("fetches")
        detail["fetches"] = [list(prior["fetches"]), list(new["fetches"])]
    if new["extra"] != prior["extra"]:
        changed.append("extra")
        detail["extra"] = [repr(prior["extra"]), repr(new["extra"])]
    return changed, detail


def _classify_build(key):
    """Trigger + diff for a new _CompiledBlock build, against the nearest
    prior key of the same program (fewest changed components wins, newest
    breaks ties) and the evicted-key memory. Caller holds _lock."""
    fp = fingerprint(key)
    if fp in _evicted:
        return "lru_eviction", {
            "prior": fp, "changed": ["evicted"],
            "evicted_ts": _evicted[fp],
        }
    hist = _key_history.get(key["program"], [])
    if not hist:
        return "cold", {}
    best = None
    for prior in reversed(hist):  # newest first
        changed, detail = _key_diff(key, prior)
        if best is None or len(changed) < len(best[1]):
            best = (prior, changed, detail)
        if not changed:
            break
    prior, changed, detail = best
    diff = {"prior": fingerprint(prior), "changed": changed,
            "detail": detail}
    if not changed:
        # identical key rebuilt while still remembered and never evicted:
        # the caller bypassed the program cache (use_program_cache=False)
        return "uncached_rebuild", diff
    if "version" in changed:
        return "program_mutation", diff
    return "feed_order_change", diff


def _append(record):
    _apply_record_bound()
    from . import trace as _trace

    record.setdefault("ts", time.time())
    record.setdefault("rank", _trace.gang_rank())
    _records.append(record)
    return record


def on_build(key, wall_ms, n_xla_segments=0):
    """One ``_CompiledBlock`` construction (trace + segment lowering).
    Classifies the trigger via the sentinel and remembers the key as the
    program's newest. Returns the record."""
    _maybe_register_device_memory_gauges()
    with _lock:
        trigger, diff = _classify_build(key)
        fp = fingerprint(key)
        _evicted.pop(fp, None)
        hist = _key_history.setdefault(key["program"], [])
        hist[:] = [k for k in hist if fingerprint(k) != fp]
        hist.append(dict(key))
        del hist[:-_KEY_HISTORY_CAP]
        _key_history.move_to_end(key["program"])
        while len(_key_history) > _PROGRAMS_CAP:
            _key_history.popitem(last=False)
        _build_trigger[fp] = (trigger, diff)
        _build_trigger.move_to_end(fp)
        while len(_build_trigger) > _TRIGGER_CAP:
            _build_trigger.popitem(last=False)
        # a rebuild replaces the block's executables wholesale: its
        # fresh compiles must inherit THIS build's trigger (eviction,
        # mutation, ...), not read as shape changes against executables
        # that no longer exist
        for seen_key in [k for k in _exec_seen if k[0] == fp]:
            del _exec_seen[seen_key]
        record = _append({
            "kind": "build", "key": dict(key), "fingerprint": fp,
            "slug": key_slug(key), "trigger": trigger, "diff": diff,
            "wall_ms": round(float(wall_ms), 3),
            "segments": int(n_xla_segments), "phase": _phase(),
        })
        _totals["builds"] += 1
    _profiler.bump_counter("xla_builds")
    _profiler.bump_histogram("xla_build_ms", wall_ms)
    return record


def on_dispatch_rebind(key, ordered_feeds):
    """The executor's dispatch-plan cache missed but the canonical cache
    hit: same compiled block, new feed ORDER. No XLA work happened — the
    record (trigger ``feed_order_change``, ``recompiled: false``) exists
    so ``/compiles`` proves the cache absorbed it."""
    with _lock:
        record = _append({
            "kind": "dispatch", "key": dict(key),
            "fingerprint": fingerprint(key), "slug": key_slug(key),
            "trigger": "feed_order_change",
            "diff": {"changed": ["feed_order"],
                     "detail": {"feed_order": list(ordered_feeds)}},
            "recompiled": False, "wall_ms": 0.0, "phase": _phase(),
        })
        _totals["dispatch_rebinds"] += 1
    _profiler.bump_counter("xla_dispatch_rebinds")
    return record


def on_xla_compile(key, segment, feed_shapes, wall_ms, compiled=None):
    """One real XLA executable compile (the executor's AOT
    lower-and-compile of one segment at one feed-shape signature).
    Runs the cost census on the in-hand executable (free — no second
    compile), registers the per-key gauges, and applies the strict
    serving gate. Raises SteadyStateRecompileError AFTER recording when
    the gate is armed and tripped."""
    census = None
    if compiled is not None and bool(
        _flags.get_flag("obs_compile_census", True)
    ):
        try:
            census = executable_census(compiled)
        except Exception:  # census must never break execution
            census = None
    with _lock:
        fp = fingerprint(key)
        seen_key = (fp, int(segment))
        prev_shapes = _exec_seen.get(seen_key)
        if prev_shapes is None:
            trigger, diff = _build_trigger.get(fp, ("cold", {}))
        else:
            changed = {
                n: [prev_shapes.get(n), feed_shapes.get(n)]
                for n in set(prev_shapes) | set(feed_shapes)
                if prev_shapes.get(n) != feed_shapes.get(n)
            }
            trigger = "shape_change"
            diff = {"changed": ["feed_shapes"],
                    "detail": {"feed_shapes": changed} if changed
                    else {"state_or_const": True}}
        _exec_seen[seen_key] = dict(feed_shapes)
        _exec_seen.move_to_end(seen_key)
        while len(_exec_seen) > _EXEC_SEEN_CAP:
            _exec_seen.popitem(last=False)
        record = _append({
            "kind": "compile", "key": dict(key), "fingerprint": fp,
            "slug": key_slug(key), "segment": int(segment),
            "trigger": trigger, "diff": diff,
            "feed_shapes": dict(feed_shapes),
            "wall_ms": round(float(wall_ms), 3),
            "census": census, "phase": _phase(),
        })
        if census is not None:
            _accumulate_census(key, fp, segment, census)
        _totals["compiles"] += 1
        _totals["compile_ms"] += float(wall_ms)
        _trigger_totals[trigger] += 1
        # only a compile on a serving-request thread can violate the
        # gate: a colocated trainer's legitimate new-shape compile in
        # the same process is neither a serving recompile nor a reason
        # to crash the training step under strict mode. The warmup
        # exemption is per-thread too — one server's live ladder growth
        # must not mask a sibling server's steady recompile
        steady_violation = (
            _steady_count > 0
            and getattr(_tls, "warmup", 0) == 0
            and getattr(_tls, "depth", 0) > 0
        )
    _profiler.bump_counter("xla_compiles")
    _profiler.bump_histogram("xla_compile_ms", wall_ms)
    if trigger != "cold":
        _profiler.bump_counter("xla_recompiles")
    if steady_violation:
        _profiler.bump_counter("serving_steady_recompiles")
        if bool(_flags.get_flag("serving_strict_compiles", False)):
            raise SteadyStateRecompileError(record)
    return record


def note_eviction(key):
    """The executor's bounded LRU dropped a compiled block: remember the
    fingerprint so the sentinel can label its re-build ``lru_eviction``
    instead of a puzzling re-``cold``. The eviction counter covers every
    drop — including keyless entries (pipeline programs) that carry no
    fingerprint to remember."""
    _profiler.bump_counter("executor_compiled_block_evictions")
    if key is None:
        return
    with _lock:
        fp = fingerprint(key)
        _evicted[fp] = time.time()
        _evicted.move_to_end(fp)
        while len(_evicted) > _EVICTED_CAP:
            _evicted.popitem(last=False)


# ---------------------------------------------------------------------------
# Census accumulation + gauges
# ---------------------------------------------------------------------------

def _accumulate_census(key, fp, segment, census):
    """Fold one executable's census into the per-program-key totals and
    (re-)register the registry gauges. Caller holds _lock."""
    entry = _census.get(fp)
    if entry is None:
        entry = _census[fp] = {
            "slug": key_slug(key), "key": dict(key), "segments": {},
        }
    entry["segments"][int(segment)] = {
        "flops": census.get("flops"),
        "bytes_accessed": census.get("bytes_accessed"),
        "out_bytes": census.get("out_bytes"),
        "hlo_ops": interesting_ops(census.get("hlo_ops") or {}),
        "total_hlo_ops": census.get("total_hlo_ops"),
    }
    for field in ("flops", "bytes_accessed", "out_bytes"):
        # a backend whose cost analysis lacks a key must total None, not
        # 0.0 — a false zero would render as a real gauge and let bench
        # bank a zeroed baseline over the true one
        vals = [
            s[field] for s in entry["segments"].values()
            if s[field] is not None
        ]
        entry[field] = sum(vals) if vals else None
    _census.move_to_end(fp)
    from . import registry as _registry

    slug = entry["slug"]
    _registry.register_gauge("xla_flops_" + slug,
                             lambda e=entry: e["flops"])
    _registry.register_gauge("xla_bytes_accessed_" + slug,
                             lambda e=entry: e["bytes_accessed"])
    _registry.register_gauge("xla_out_bytes_" + slug,
                             lambda e=entry: e["out_bytes"])
    while len(_census) > _CENSUS_CAP:
        _fp, dropped = _census.popitem(last=False)
        for prefix in ("xla_flops_", "xla_bytes_accessed_",
                       "xla_out_bytes_"):
            _registry.unregister_gauge(prefix + dropped["slug"])


def census_by_key():
    """{fingerprint: totals} snapshot of every program key censused so
    far (totals summed over that key's compiled segments)."""
    with _lock:
        return {
            fp: {
                "slug": e["slug"], "key": dict(e["key"]),
                "flops": e.get("flops"),
                "bytes_accessed": e.get("bytes_accessed"),
                "out_bytes": e.get("out_bytes"),
                "segments": {str(i): dict(s)
                             for i, s in e["segments"].items()},
            }
            for fp, e in _census.items()
        }


def headline_census():
    """The census totals of the heaviest program key compiled in this
    process (max flops) — what a bench rung banks as its flops/bytes
    budget. None when nothing was censused."""
    cens = census_by_key()
    if not cens:
        return None
    fp, best = max(
        cens.items(), key=lambda kv: kv[1].get("flops") or 0.0
    )
    return {
        "fingerprint": fp, "slug": best["slug"],
        "flops": best["flops"], "bytes_accessed": best["bytes_accessed"],
        "out_bytes": best["out_bytes"], "census_keys": len(cens),
    }


def attach_headline_census(result):
    """Copy the headline census totals (flops / bytes_accessed /
    out_bytes) into a bench RESULT dict — the single definition of the
    banked field set, shared by every bench child. No-op (and returns
    the dict unchanged) when nothing was censused."""
    census = headline_census()
    if census is not None:
        for k in ("flops", "bytes_accessed", "out_bytes"):
            # never emit a None/zeroed field: bank_write only protects
            # the banked baseline when the key is ABSENT
            if census[k] is not None:
                result[k] = census[k]
    return result


def _maybe_register_device_memory_gauges():
    """Register live/peak device-memory gauges once, where the backend
    exposes ``Device.memory_stats()`` (TPU/GPU; the CPU backend returns
    None — nothing registers, nothing poisons a scrape)."""
    global _mem_gauges_done
    if _mem_gauges_done:
        return
    _mem_gauges_done = True
    try:
        import jax

        devices = [
            d for d in jax.local_devices() if d.memory_stats() is not None
        ]
    except Exception:
        return
    if not devices:
        return
    from . import registry as _registry

    def _sum_stat(stat):
        total = 0
        for d in devices:
            stats = d.memory_stats() or {}
            total += stats.get(stat, 0)
        return total

    _registry.register_gauge(
        "xla_mem_bytes_in_use", lambda: _sum_stat("bytes_in_use")
    )
    _registry.register_gauge(
        "xla_mem_peak_bytes_in_use",
        lambda: _sum_stat("peak_bytes_in_use"),
    )


# ---------------------------------------------------------------------------
# Serving steady-state gate
# ---------------------------------------------------------------------------

def serving_steady(on):
    """Force the steady-state recompile gate to an absolute state
    (tests / probes). Servers use the counted ``arm_serving_steady`` /
    ``disarm_serving_steady`` pair instead, so stopping an old server
    never disarms the gate out from under a live successor."""
    global _steady_count
    with _lock:
        _steady_count = 1 if on else 0


def arm_serving_steady():
    """One server finished warmup: count its gate in (ownership-scoped —
    each live server arms once, disarms once at stop)."""
    global _steady_count
    with _lock:
        _steady_count += 1


def disarm_serving_steady():
    """One server stopped: count its gate out; the gate stays armed
    while any other server in the process is still live."""
    global _steady_count
    with _lock:
        _steady_count = max(0, _steady_count - 1)


class serving_request_window(object):
    """Marks the current thread as executing a serving request (the
    dispatch workers wrap ``_run_batch`` in one): only compiles inside
    a request window can violate the armed steady-state gate. Scoping
    the gate to request threads keeps a colocated trainer's (or a
    second, still-warming workload's) legitimate compiles from bumping
    ``serving_steady_recompiles`` or strict-raising into code that never
    touched serving. Thread-local and re-entrant."""

    def __enter__(self):
        _tls.depth = getattr(_tls, "depth", 0) + 1
        return self

    def __exit__(self, *exc):
        _tls.depth = max(0, getattr(_tls, "depth", 0) - 1)
        return False


class warmup_window(object):
    """Context manager marking deliberate compile activity (server
    warmup, ladder growth on a live server): compiles inside the window
    record with phase ``warmup`` and never trip the strict gate.
    Thread-local and re-entrant — warmup compiles run on the warming
    caller's thread, and a global exemption would let one server's live
    ladder growth mask a SIBLING server's steady recompile."""

    def __enter__(self):
        _tls.warmup = getattr(_tls, "warmup", 0) + 1
        return self

    def __exit__(self, *exc):
        _tls.warmup = max(0, getattr(_tls, "warmup", 0) - 1)
        return False


# ---------------------------------------------------------------------------
# Read side
# ---------------------------------------------------------------------------

def get_records():
    """Snapshot copy of the retained records, oldest first."""
    with _lock:
        return [dict(r) for r in _records]


def summary():
    """Compact roll-up for snapshots / the gang report: totals by kind
    and trigger, steady-state violations, compile wall-clock, and the
    newest few records' fingerprints. Totals are monotonic
    process-lifetime counters, NOT ring-derived — a recompile storm
    larger than ``FLAGS_obs_compile_records`` still counts in full in
    the gang report; only ``recent`` reads the (bounded) ring."""
    with _lock:
        totals = dict(_totals)
        by_trigger = dict(_trigger_totals)
        recent = [
            {"kind": r["kind"], "fingerprint": r["fingerprint"],
             "trigger": r["trigger"], "wall_ms": r["wall_ms"],
             "phase": r["phase"]}
            for r in list(_records)[-8:]
        ]
    return {
        "builds": totals["builds"],
        "compiles": totals["compiles"],
        "dispatch_rebinds": totals["dispatch_rebinds"],
        "by_trigger": by_trigger,
        "steady_recompiles": _profiler.get_counter(
            "serving_steady_recompiles"
        ),
        "compile_ms_total": round(totals["compile_ms"], 3),
        "recent": recent,
    }


# newest SPMD plan summary (set by parallel.spmd.lower via
# set_active_spmd — a setter hook so spmd.py never imports this module
# at its own import time and vice versa). Rides /compiles so the
# exporter shows which mesh/policy the live compiles were built under.
_active_spmd = None


def set_active_spmd(summary_dict):
    global _active_spmd
    with _lock:
        _active_spmd = dict(summary_dict) if summary_dict else None


def active_spmd():
    with _lock:
        return dict(_active_spmd) if _active_spmd else None


def compiles_endpoint():
    """The ``/compiles`` document: summary + full records + per-key
    census (the whole device plane in one JSON GET)."""
    from . import trace as _trace

    return {
        "schema_version": 1,
        "ts": time.time(),
        "rank": _trace.gang_rank(),
        "pid": os.getpid(),
        "serving_steady": _steady_count > 0,
        "spmd": active_spmd(),
        "summary": summary(),
        "records": get_records(),
        "census": census_by_key(),
    }


def reset():
    """Drop records, key history, census, and gate state (tests). Gauges
    for dropped census keys unregister so a later scrape isn't poisoned
    by stale closures."""
    global _steady_count
    from . import registry as _registry

    with _lock:
        dropped = [e["slug"] for e in _census.values()]
        _records.clear()
        _key_history.clear()
        _evicted.clear()
        _build_trigger.clear()
        _exec_seen.clear()
        _census.clear()
        _totals.update(builds=0, compiles=0, dispatch_rebinds=0,
                       compile_ms=0.0)
        _trigger_totals.clear()
        _steady_count = 0
        _tls.depth = 0
        _tls.warmup = 0
    for slug in dropped:
        for prefix in ("xla_flops_", "xla_bytes_accessed_",
                       "xla_out_bytes_"):
            _registry.unregister_gauge(prefix + slug)
