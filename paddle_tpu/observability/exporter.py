"""Lightweight telemetry exporter: stdlib HTTP + per-rank JSONL files.

One ``Exporter`` serves three read-only endpoints from a daemon thread
(no dependency beyond ``http.server``):

- ``/metrics`` — the registry in Prometheus text format;
- ``/healthz`` — ``{"status": "ok"|"draining", ...}``; flips to
  ``draining`` (HTTP 503) the moment the PR 3 preemption path has seen
  SIGTERM (``checkpoint.preempt.preemption_requested()``) or the owner
  calls ``set_health(False)`` — so a load balancer or the gang
  supervisor stops routing to a worker that is wrapping up;
- ``/trace`` — the tracer ring buffer as Chrome trace-event JSON
  (open the URL, save, load in Perfetto);
- ``/compiles`` — the device-plane compile telemetry (``xla_stats``):
  every build/compile record with trigger + cache-key diff, plus the
  per-program-key FLOP/HBM-byte census.

Port policy (``FLAGS_obs_http_port``): -1 disables HTTP entirely, 0
binds an ephemeral port (tests, single-host probes), >0 binds that port
or WALKS UP through ``FLAGS_obs_http_port_retries`` successors when
it's taken — on a multi-rank host every rank calls the same entry point
with the same flag env, and rank k landing on port+k beats rank k
crashing (``obs_port_fallbacks`` counts the walks).

Independent of HTTP, ``FLAGS_obs_dir`` arms per-rank JSONL snapshot
files (``rank_<r>.jsonl``): periodic at ``FLAGS_obs_snapshot_interval_s``
plus one final snapshot at ``stop()``/``final_snapshot()``. The gang
supervisor injects ``FLAGS_obs_dir`` into worker environments and merges
the files into a gang report (``aggregate.py``) — snapshots are the
telemetry that SURVIVES a worker, which is what post-mortem merge needs.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..fluid import flags as _flags
from ..fluid import profiler as _profiler
from . import flight as _flight
from . import registry as _registry
from . import trace as _trace

__all__ = [
    "Exporter",
    "maybe_start_from_flags",
    "global_exporter",
    "stop_global",
    "final_snapshot",
    "dump_blackbox",
]


def _preempting():
    try:
        from ..checkpoint import preempt as _preempt

        return _preempt.preemption_requested()
    except Exception:
        return False


def _make_handler(exporter):
    class _Handler(BaseHTTPRequestHandler):
        def log_message(self, *args):  # scrapes must not spam stderr
            pass

        def _send(self, code, body, ctype):
            data = body.encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            path = self.path.split("?", 1)[0]
            try:
                if path == "/metrics":
                    self._send(
                        200, _registry.render_prometheus(),
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                elif path == "/healthz":
                    health = exporter.healthz()
                    code = 200 if health["status"] == "ok" else 503
                    self._send(
                        code, json.dumps(health, sort_keys=True),
                        "application/json",
                    )
                elif path == "/trace":
                    # ?trace_id= narrows the pull to one request's spans
                    # (fleet_trace.py and foreign consumers negotiate on
                    # the payload's schema_version stamp)
                    qs = urllib.parse.parse_qs(
                        self.path.partition("?")[2]
                    )
                    tid = (qs.get("trace_id") or [None])[0]
                    self._send(
                        200, json.dumps(_trace.chrome_trace(trace_id=tid)),
                        "application/json",
                    )
                elif path == "/compiles":
                    from . import xla_stats as _xla_stats

                    self._send(
                        200,
                        json.dumps(_xla_stats.compiles_endpoint(),
                                   sort_keys=True),
                        "application/json",
                    )
                else:
                    self._send(404, '{"error": "not found"}',
                               "application/json")
            except Exception as e:  # a broken render must not kill the server
                try:
                    self._send(500, json.dumps({"error": repr(e)}),
                               "application/json")
                except Exception:
                    pass

    return _Handler


class Exporter(object):
    """HTTP endpoint + snapshot writer for one process. ``None``
    parameters resolve from the ``FLAGS_obs_*`` knobs at start()."""

    def __init__(self, port=None, port_retries=None, snapshot_dir=None,
                 snapshot_interval_s=None, rank=None, host="127.0.0.1"):
        self.port_requested = int(
            _flags.get_flag("obs_http_port", -1) if port is None else port
        )
        self.port_retries = int(
            _flags.get_flag("obs_http_port_retries", 8)
            if port_retries is None else port_retries
        )
        self.snapshot_dir = (
            str(_flags.get_flag("obs_dir", "") or "")
            if snapshot_dir is None else str(snapshot_dir)
        ) or None
        self.snapshot_interval_s = float(
            _flags.get_flag("obs_snapshot_interval_s", 0.0)
            if snapshot_interval_s is None else snapshot_interval_s
        )
        self.rank = _trace.gang_rank(rank)
        self.host = host
        self._httpd = None
        self._http_thread = None
        self._snap_thread = None
        self._stop = threading.Event()
        self._healthy = True
        self._started = False

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        if self._started:
            return self
        self._stop.clear()  # a stop()ed exporter can start() again
        if self.port_requested >= 0:
            self._bind()
            self._http_thread = threading.Thread(
                target=self._httpd.serve_forever, name="obs_exporter_http",
                daemon=True,
            )
            self._http_thread.start()
        if self.snapshot_dir and self.snapshot_interval_s > 0:
            self._snap_thread = threading.Thread(
                target=self._snapshot_loop, name="obs_exporter_snap",
                daemon=True,
            )
            self._snap_thread.start()
        self._started = True
        return self

    def _bind(self):
        handler = _make_handler(self)
        # port 0 is ephemeral — the OS can't collide, so no walk needed
        candidates = (
            [0] if self.port_requested == 0
            else range(self.port_requested,
                       self.port_requested + self.port_retries + 1)
        )
        last_err = None
        for p in candidates:
            try:
                self._httpd = ThreadingHTTPServer((self.host, p), handler)
                self._httpd.daemon_threads = True
                if p not in (0, self.port_requested):
                    _profiler.bump_counter("obs_port_fallbacks")
                return
            except OSError as e:
                last_err = e
                continue
        raise OSError(
            "obs exporter: no free port in [%d, %d]: %s"
            % (self.port_requested,
               self.port_requested + self.port_retries, last_err)
        )

    def stop(self, join_timeout=5.0):
        """Idempotent: final snapshot (when armed), HTTP shutdown, thread
        joins. Safe to call from a SIGTERM-driven teardown — everything
        here is bounded."""
        if not self._started:
            return
        self._started = False
        self._stop.set()
        if self.snapshot_dir:
            try:
                self.write_snapshot()
            except OSError:
                pass
        if self._httpd is not None:
            try:
                self._httpd.shutdown()
                self._httpd.server_close()
            except Exception:
                pass
        for t in (self._http_thread, self._snap_thread):
            if t is not None and t.is_alive():
                t.join(timeout=join_timeout)
        self._httpd = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- endpoints' state ----------------------------------------------------
    @property
    def port(self):
        """The BOUND port (differs from port_requested after an
        ephemeral bind or a port-in-use walk); None when HTTP is off."""
        return self._httpd.server_address[1] if self._httpd else None

    def url(self, path="/metrics"):
        if self._httpd is None:
            raise RuntimeError("exporter has no HTTP endpoint")
        return "http://%s:%d%s" % (self.host, self.port, path)

    def set_health(self, ok):
        """Manual health override (a server draining its queue flips this
        before stop); preemption flips /healthz regardless."""
        self._healthy = bool(ok)

    def healthz(self):
        draining = (not self._healthy) or self._stop.is_set() or _preempting()
        # the clock-anchor pair rides every health answer: ts is wall,
        # ts_mono the SAME clock spans record — what fleet_trace.py
        # aligns per-process trace timelines with (and the NTP-style
        # skew estimate reads ts against the puller's own clock)
        anchor = _trace.clock_anchor()
        return {
            "status": "draining" if draining else "ok",
            "rank": self.rank,
            "pid": os.getpid(),
            "ts": anchor["ts"],
            "ts_mono": anchor["ts_mono"],
        }

    # -- snapshots -----------------------------------------------------------
    def write_snapshot(self):
        if not self.snapshot_dir:
            raise RuntimeError("exporter has no snapshot dir")
        # the black box rides the snapshot cadence: a replica that is
        # later SIGKILLed leaves at most one interval's worth of spans
        # and flight records unrecorded on disk. Dumped BEFORE the
        # registry snapshot so the dump's own counter bumps are inside
        # it — a quiescent process's snapshot must equal its live
        # counters exactly (the obs probe's round-trip bar).
        dump_blackbox(self.snapshot_dir, rank=self.rank)
        return _registry.write_snapshot(self.snapshot_dir, rank=self.rank)

    def _snapshot_loop(self):
        while not self._stop.wait(self.snapshot_interval_s):
            try:
                self.write_snapshot()
            except OSError:
                continue  # a full/unmounted disk must not kill telemetry


# -- process-global convenience entry points --------------------------------
_global = None
_global_lock = threading.Lock()


def maybe_start_from_flags():
    """Start (once) the process-global exporter when the FLAGS_obs_*
    knobs ask for anything — called from both ``InferenceServer.start()``
    and the trainer loop, so EITHER workload lights up telemetry with
    env flags alone. Returns the exporter or None when nothing is
    enabled. Never raises: a telemetry bind failure must not take down
    training or serving."""
    global _global
    with _global_lock:
        if _global is not None:
            return _global
        port = int(_flags.get_flag("obs_http_port", -1))
        snap_dir = str(_flags.get_flag("obs_dir", "") or "")
        if port < 0 and not snap_dir:
            return None
        try:
            _global = Exporter().start()
        except OSError:
            # HTTP bind exhausted its port walk — but the JSONL snapshot
            # side needs no port, and the gang report needs the
            # snapshots: degrade to a port-less exporter when armed
            if not snap_dir:
                return None
            try:
                _global = Exporter(port=-1).start()
            except OSError:
                return None
        return _global


def global_exporter():
    return _global


def stop_global():
    global _global
    with _global_lock:
        exp, _global = _global, None
    if exp is not None:
        exp.stop()


def final_snapshot():
    """Write one registry snapshot for this rank if FLAGS_obs_dir is set
    — works with or without a running exporter (the trainer calls this
    in its ``finally`` so even a worker that never started HTTP leaves
    the per-rank record the gang aggregator merges). The flight-recorder
    and span-dump black boxes ride along: drain/SIGTERM teardowns all
    funnel through here, which is exactly when the post-mortem record
    must hit disk."""
    snap_dir = str(_flags.get_flag("obs_dir", "") or "")
    if not snap_dir:
        return None
    # black box FIRST, same ordering invariant as the snapshot loop:
    # the dump's own counter bumps must land inside the snapshot, so a
    # quiescent process's final snapshot equals its live counters
    dump_blackbox(snap_dir)
    try:
        path = _registry.write_snapshot(snap_dir)
    except OSError:
        path = None
    return path


def trace_dump_path(dirname, rank=None):
    return os.path.join(
        str(dirname), "trace_rank_%d.json" % _trace.gang_rank(rank)
    )


def dump_blackbox(dirname=None, rank=None):
    """Persist the post-mortem pair for this process into ``dirname``
    (default FLAGS_obs_dir): the flight-recorder ring
    (``flight_rank_<r>.json``) and a bounded span dump
    (``trace_rank_<r>.json``, the newest ``FLAGS_trace_dump_spans``
    spans as a standard /trace payload). Atomic whole-file replaces —
    newest state wins — so fleet_trace.py can merge a process that can
    no longer be pulled over HTTP. Never raises."""
    dirname = dirname or str(_flags.get_flag("obs_dir", "") or "")
    if not dirname:
        return None
    _flight.dump(dirname, rank=rank)
    try:
        cap = max(int(_flags.get_flag("trace_dump_spans", 4096)), 1)
    except (TypeError, ValueError):
        cap = 4096
    try:
        os.makedirs(str(dirname), exist_ok=True)
        path = trace_dump_path(dirname, rank=rank)
        tmp = "%s.tmp.%d" % (path, os.getpid())
        with open(tmp, "w") as f:
            json.dump(_trace.chrome_trace(newest=cap), f)
        os.replace(tmp, path)
        return path
    except OSError:
        return None
