"""Supervisor-side telemetry merge: per-rank snapshots -> gang report.

A supervised gang's telemetry is scattered by design — each rank's
process owns its counters/histograms and leaves ``rank_<r>.jsonl``
snapshot files (exporter.py), and the supervisor's ``supervisor.log``
carries the restart narrative. This module joins them into ONE
``gang_report.json`` an operator (or the crash probe) reads after the
fact: how many restarts and why, downtime per restart, and per-rank
step-time percentiles + progress counters from each rank's NEWEST
snapshot. The supervisor writes it on every restart event and again on
exit, so even a gang that dies mid-flight leaves a merged record.

Snapshots are merged last-line-wins per rank: a restarted worker appends
to the same file, and its newest snapshot reflects the life that
mattered (counters are process-local, so they restart from zero with the
process — the report keeps each life's final word, not a fake sum across
lives).
"""

from __future__ import annotations

import json
import os
import re
import time

from . import registry as _registry

__all__ = [
    "GANG_REPORT",
    "read_rank_snapshots",
    "gang_report",
    "write_gang_report",
]

GANG_REPORT = "gang_report.json"
_RANK_FILE = re.compile(r"^rank_(\d+)\.jsonl$")

# the counters/histograms worth surfacing per rank without dumping the
# whole registry into the report (the full detail stays in the JSONL)
_RANK_COUNTERS = (
    "train_steps",
    "dist_degraded_steps",
    "ckpt_saves_committed",
    "ckpt_restore_fallbacks",
    "ckpt_resharded_restores",
    "executor_plan_cache_hits",
    "executor_plan_cache_misses",
    "pserver_rpc_conn_retries",
)
_RANK_HISTOGRAMS = ("train_step_ms", "ckpt_save_ms", "ckpt_snapshot_ms")


def read_rank_snapshots(obs_dir):
    """{rank: newest snapshot dict} from ``rank_*.jsonl`` under
    ``obs_dir``. Torn/garbage lines are skipped (the writer appends
    whole lines, but a crash can still truncate the last one)."""
    out = {}
    try:
        names = os.listdir(obs_dir)
    except OSError:
        return out
    for fn in names:
        m = _RANK_FILE.match(fn)
        if not m:
            continue
        rank = int(m.group(1))
        newest = None
        try:
            with open(os.path.join(obs_dir, fn)) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        newest = json.loads(line)
                    except ValueError:
                        continue
        except OSError:
            continue
        if newest is not None:
            out[rank] = newest
    return out


def _downtimes_ms(events):
    """[(failure detection -> next restarted gang_start) ms] from
    supervisor events, on the monotonic timestamps when present
    (schema_version >= 1), falling back to wall-clock for older logs.

    supervisor.log is append-only across supervisor RUNS (a reused
    workdir accumulates them), and each run's monotonic clock has its
    own epoch — so a detection may only pair with a gang_start from the
    SAME run. A fresh run's first gang_start carries ``restart == 0``
    and clears any detection a dead previous run left dangling; terminal
    events end a run's pairing too, and negative deltas (mixed clock
    epochs in malformed logs) are dropped rather than poisoning the
    percentiles."""
    key = "ts_mono" if any("ts_mono" in e for e in events) else "ts"
    downtimes = []
    detect = None
    for e in events:
        ev = e.get("event")
        if ev in ("crash_detected", "hang_detected", "worker_preempted"):
            detect = e.get(key)
        elif ev in ("gang_done", "giveup", "preempted"):
            detect = None
        elif ev == "gang_start":
            if e.get("restart", 0) and detect is not None \
                    and e.get(key) is not None:
                delta_ms = (e[key] - detect) * 1000.0
                if delta_ms >= 0:
                    downtimes.append(delta_ms)
            detect = None
    return downtimes


def _rank_summary(snap):
    counters = snap.get("counters", {})
    hists = snap.get("histograms", {})
    return {
        "snapshot_ts": snap.get("ts"),
        "pid": snap.get("pid"),
        "counters": {
            k: counters[k] for k in _RANK_COUNTERS if k in counters
        },
        "step_time_ms": hists.get("train_step_ms"),
        "histograms": {
            k: hists[k] for k in _RANK_HISTOGRAMS if k in hists
        },
        "compiles": snap.get("compiles"),
    }


def _gang_compiles(snaps):
    """Cross-rank roll-up of the per-rank compile summaries: totals by
    trigger plus steady-state violations — one place to see a gang
    restart's recompile storm."""
    total, steady = 0, 0
    by_trigger = {}
    for snap in snaps.values():
        c = snap.get("compiles") or {}
        total += int(c.get("compiles", 0))
        steady += int(c.get("steady_recompiles", 0))
        for trig, n in (c.get("by_trigger") or {}).items():
            by_trigger[trig] = by_trigger.get(trig, 0) + int(n)
    return {
        "compiles_total": total,
        "by_trigger": by_trigger,
        "steady_recompiles": steady,
    }


def _last_run(events):
    """The event slice belonging to the NEWEST supervisor run: the log
    appends across runs in a reused workdir, and the report must
    describe the current gang, not a sum over dead ones. A run begins
    at a ``supervisor_boot`` event; logs predating it fall back to the
    newest ``gang_start`` with ``restart == 0`` (which misses a
    pre-first-start ``gang_resize``/``giveup`` — exactly why the boot
    event exists)."""
    start = 0
    booted = any(e.get("event") == "supervisor_boot" for e in events)
    for i, e in enumerate(events):
        if booted:
            if e.get("event") == "supervisor_boot":
                start = i
        elif e.get("event") == "gang_start" and not e.get("restart", 0):
            start = i
    return events[start:]


def gang_report(workdir, obs_dir=None):
    """Merge ``workdir``'s supervisor.log + per-rank snapshots (default
    ``workdir/obs``) into one report dict. Counters, outcome, and
    downtime all describe the newest supervisor run in the log."""
    from ..distributed import supervisor as _sup

    events = _last_run(_sup.load_events(str(workdir)))
    obs_dir = obs_dir or os.path.join(str(workdir), "obs")
    snaps = read_rank_snapshots(obs_dir)
    downtimes = _downtimes_ms(events)
    terminal = None
    for e in events:  # last terminal event wins
        if e.get("event") in ("gang_done", "giveup", "preempted"):
            terminal = e["event"]
    # elastic-resize audit trail: one record per gang attempt (the
    # gang_start events carry the attempt's world size and rank->pid
    # map since ISSUE 6), so a resized run is reconstructible post-hoc
    attempts = [
        {
            "restart": e.get("restart", 0),
            "world_size": e.get("world_size"),
            "slots": e.get("slots"),
            "rank_pids": e.get("rank_pids"),
        }
        for e in events if e.get("event") == "gang_start"
    ]
    return {
        "schema_version": _registry.SCHEMA_VERSION,
        "ts": time.time(),
        "ts_mono": time.monotonic(),
        "workdir": str(workdir),
        "outcome": terminal,  # None while the gang is still running
        "restarts": sum(1 for e in events if e.get("event") == "restart"),
        "crashes": sum(
            1 for e in events if e.get("event") == "crash_detected"
        ),
        "hang_kills": sum(
            1 for e in events if e.get("event") == "hang_detected"
        ),
        "preemptions": sum(
            1 for e in events if e.get("event") == "worker_preempted"
        ),
        "resizes": sum(
            1 for e in events if e.get("event") == "gang_resize"
        ),
        "attempts": attempts,
        "world_size_final": (
            attempts[-1]["world_size"] if attempts else None
        ),
        "downtime_ms": _registry.percentiles(downtimes, points=(50, 99)),
        "compiles": _gang_compiles(snaps),
        "ranks_reporting": sorted(snaps),
        "per_rank": {str(r): _rank_summary(s) for r, s in snaps.items()},
    }


def write_gang_report(workdir, obs_dir=None, path=None):
    """Emit ``gang_report.json`` under ``workdir`` (atomic tmp+rename:
    an operator tailing the file never reads a torn report). Returns the
    path."""
    report = gang_report(workdir, obs_dir=obs_dir)
    path = path or os.path.join(str(workdir), GANG_REPORT)
    tmp = "%s.tmp.%d" % (path, os.getpid())
    with open(tmp, "w") as f:
        json.dump(report, f, sort_keys=True, indent=1)
    os.replace(tmp, path)
    return path
