"""Supervisor-side telemetry merge: per-rank snapshots -> gang report.

A supervised gang's telemetry is scattered by design — each rank's
process owns its counters/histograms and leaves ``rank_<r>.jsonl``
snapshot files (exporter.py), and the supervisor's ``supervisor.log``
carries the restart narrative. This module joins them into ONE
``gang_report.json`` an operator (or the crash probe) reads after the
fact: how many restarts and why, downtime per restart, and per-rank
step-time percentiles + progress counters from each rank's NEWEST
snapshot. The supervisor writes it on every restart event and again on
exit, so even a gang that dies mid-flight leaves a merged record.

Snapshots are merged last-line-wins per rank: a restarted worker appends
to the same file, and its newest snapshot reflects the life that
mattered (counters are process-local, so they restart from zero with the
process — the report keeps each life's final word, not a fake sum across
lives).

The serving fleet gets the same treatment (``fleet_report``): the
controller's ``fleet.log`` narrative (scale/rollout/crash events, the
ready-replica count over time) merges with each replica process's
snapshot dir (``obs/replica_<id>/rank_0.jsonl``) into
``fleet_report.json`` — per-replica request tallies beside the
control-plane story.
"""

from __future__ import annotations

import json
import os
import re
import time

from . import registry as _registry

__all__ = [
    "GANG_REPORT",
    "FLEET_REPORT",
    "iter_obs_dumps",
    "read_flight_records",
    "slowest_requests",
    "read_rank_snapshots",
    "read_replica_snapshots",
    "gang_report",
    "write_gang_report",
    "fleet_report",
    "write_fleet_report",
]

GANG_REPORT = "gang_report.json"
FLEET_REPORT = "fleet_report.json"
_RANK_FILE = re.compile(r"^rank_(\d+)\.jsonl$")
_REPLICA_DIR = re.compile(r"^replica_(\d+)$")

# the counters/histograms worth surfacing per rank without dumping the
# whole registry into the report (the full detail stays in the JSONL)
_RANK_COUNTERS = (
    "train_steps",
    "dist_degraded_steps",
    "ckpt_saves_committed",
    "ckpt_restore_fallbacks",
    "ckpt_resharded_restores",
    "executor_plan_cache_hits",
    "executor_plan_cache_misses",
    "pserver_rpc_conn_retries",
)
_RANK_HISTOGRAMS = ("train_step_ms", "ckpt_save_ms", "ckpt_snapshot_ms")


def read_rank_snapshots(obs_dir):
    """{rank: newest snapshot dict} from ``rank_*.jsonl`` under
    ``obs_dir``. Torn/garbage lines are skipped (the writer appends
    whole lines, but a crash can still truncate the last one)."""
    out = {}
    try:
        names = os.listdir(obs_dir)
    except OSError:
        return out
    for fn in names:
        m = _RANK_FILE.match(fn)
        if not m:
            continue
        rank = int(m.group(1))
        newest = None
        try:
            with open(os.path.join(obs_dir, fn)) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        newest = json.loads(line)
                    except ValueError:
                        continue
        except OSError:
            continue
        if newest is not None:
            out[rank] = newest
    return out


def _downtimes_ms(events):
    """[(failure detection -> next restarted gang_start) ms] from
    supervisor events, on the monotonic timestamps when present
    (schema_version >= 1), falling back to wall-clock for older logs.

    supervisor.log is append-only across supervisor RUNS (a reused
    workdir accumulates them), and each run's monotonic clock has its
    own epoch — so a detection may only pair with a gang_start from the
    SAME run. A fresh run's first gang_start carries ``restart == 0``
    and clears any detection a dead previous run left dangling; terminal
    events end a run's pairing too, and negative deltas (mixed clock
    epochs in malformed logs) are dropped rather than poisoning the
    percentiles."""
    key = "ts_mono" if any("ts_mono" in e for e in events) else "ts"
    downtimes = []
    detect = None
    for e in events:
        ev = e.get("event")
        if ev in ("crash_detected", "hang_detected", "worker_preempted"):
            detect = e.get(key)
        elif ev in ("gang_done", "giveup", "preempted"):
            detect = None
        elif ev == "gang_start":
            if e.get("restart", 0) and detect is not None \
                    and e.get(key) is not None:
                delta_ms = (e[key] - detect) * 1000.0
                if delta_ms >= 0:
                    downtimes.append(delta_ms)
            detect = None
    return downtimes


def _rank_summary(snap):
    counters = snap.get("counters", {})
    hists = snap.get("histograms", {})
    return {
        "snapshot_ts": snap.get("ts"),
        "pid": snap.get("pid"),
        "counters": {
            k: counters[k] for k in _RANK_COUNTERS if k in counters
        },
        "step_time_ms": hists.get("train_step_ms"),
        "histograms": {
            k: hists[k] for k in _RANK_HISTOGRAMS if k in hists
        },
        "compiles": snap.get("compiles"),
    }


def _gang_compiles(snaps):
    """Cross-rank roll-up of the per-rank compile summaries: totals by
    trigger plus steady-state violations — one place to see a gang
    restart's recompile storm."""
    total, steady = 0, 0
    by_trigger = {}
    for snap in snaps.values():
        c = snap.get("compiles") or {}
        total += int(c.get("compiles", 0))
        steady += int(c.get("steady_recompiles", 0))
        for trig, n in (c.get("by_trigger") or {}).items():
            by_trigger[trig] = by_trigger.get(trig, 0) + int(n)
    return {
        "compiles_total": total,
        "by_trigger": by_trigger,
        "steady_recompiles": steady,
    }


def _last_run(events):
    """The event slice belonging to the NEWEST supervisor run: the log
    appends across runs in a reused workdir, and the report must
    describe the current gang, not a sum over dead ones. A run begins
    at a ``supervisor_boot`` event; logs predating it fall back to the
    newest ``gang_start`` with ``restart == 0`` (which misses a
    pre-first-start ``gang_resize``/``giveup`` — exactly why the boot
    event exists)."""
    start = 0
    booted = any(e.get("event") == "supervisor_boot" for e in events)
    for i, e in enumerate(events):
        if booted:
            if e.get("event") == "supervisor_boot":
                start = i
        elif e.get("event") == "gang_start" and not e.get("restart", 0):
            start = i
    return events[start:]


def gang_report(workdir, obs_dir=None):
    """Merge ``workdir``'s supervisor.log + per-rank snapshots (default
    ``workdir/obs``) into one report dict. Counters, outcome, and
    downtime all describe the newest supervisor run in the log."""
    from ..distributed import supervisor as _sup

    events = _last_run(_sup.load_events(str(workdir)))
    obs_dir = obs_dir or os.path.join(str(workdir), "obs")
    snaps = read_rank_snapshots(obs_dir)
    downtimes = _downtimes_ms(events)
    terminal = None
    for e in events:  # last terminal event wins
        if e.get("event") in ("gang_done", "giveup", "preempted"):
            terminal = e["event"]
    # elastic-resize audit trail: one record per gang attempt (the
    # gang_start events carry the attempt's world size and rank->pid
    # map since ISSUE 6), so a resized run is reconstructible post-hoc
    attempts = [
        {
            "restart": e.get("restart", 0),
            "world_size": e.get("world_size"),
            "slots": e.get("slots"),
            "rank_pids": e.get("rank_pids"),
        }
        for e in events if e.get("event") == "gang_start"
    ]
    return {
        "schema_version": _registry.SCHEMA_VERSION,
        "ts": time.time(),
        "ts_mono": time.monotonic(),
        "workdir": str(workdir),
        "outcome": terminal,  # None while the gang is still running
        "restarts": sum(1 for e in events if e.get("event") == "restart"),
        "crashes": sum(
            1 for e in events if e.get("event") == "crash_detected"
        ),
        "hang_kills": sum(
            1 for e in events if e.get("event") == "hang_detected"
        ),
        "preemptions": sum(
            1 for e in events if e.get("event") == "worker_preempted"
        ),
        "sdc_quarantines": sum(
            1 for e in events if e.get("event") == "replica_quarantined"
        ),
        "resizes": sum(
            1 for e in events if e.get("event") == "gang_resize"
        ),
        "attempts": attempts,
        "world_size_final": (
            attempts[-1]["world_size"] if attempts else None
        ),
        "downtime_ms": _registry.percentiles(downtimes, points=(50, 99)),
        "compiles": _gang_compiles(snaps),
        "ranks_reporting": sorted(snaps),
        "per_rank": {str(r): _rank_summary(s) for r, s in snaps.items()},
    }


def write_gang_report(workdir, obs_dir=None, path=None):
    """Emit ``gang_report.json`` under ``workdir`` (atomic tmp+rename:
    an operator tailing the file never reads a torn report). Returns the
    path."""
    report = gang_report(workdir, obs_dir=obs_dir)
    path = path or os.path.join(str(workdir), GANG_REPORT)
    return _write_json(report, path)


def _write_json(report, path):
    # the fleet's shared atomic-commit discipline (tmp.<pid> +
    # os.replace) lives in checkpoint.modeldir; imported lazily so a
    # report-only consumer doesn't pay for it at module import
    from ..checkpoint import modeldir as _modeldir

    return _modeldir.commit_json(path, report, indent=1)


# ---------------------------------------------------------------------------
# serving-fleet merge: replica snapshots + fleet.log -> fleet_report.json
# ---------------------------------------------------------------------------

# the per-replica counters worth surfacing in the fleet roll-up (the
# request-path tallies an operator reads first; full detail stays in
# each replica's JSONL snapshots)
_REPLICA_COUNTERS = (
    "gateway_requests",
    "serving_requests",
    "serving_completed",
    "serving_batches",
    "serving_shed_overload",
    "serving_shed_deadline",
    "gateway_shed_admission",
    "gateway_shed_dispatch",
    # prefix-cache / KV-tier effectiveness (the fleet_report roll-up
    # computes hit rates and byte totals from these)
    "decode_prefix_hits",
    "decode_prefix_misses",
    "decode_prefix_cached_tokens",
    "decode_prompt_tokens",
    "kv_tier_spills",
    "kv_tier_readmits",
    "kv_tier_bytes_d2h",
    "kv_tier_bytes_h2d",
    "kv_tier_pulls",
    "kv_tier_pull_tokens",
)


def _prefix_cache_rollup(summaries):
    """Fleet-wide prefix-cache effectiveness from the per-replica
    counter summaries: hit rate over admissions, the fraction of all
    prompt tokens served from cache, and the KV-tier spill/re-admit
    byte flow. Per-replica rows keep the same shape so an operator can
    spot the one cold replica dragging the fleet rate down."""
    def one(counters):
        hits = int(counters.get("decode_prefix_hits", 0))
        misses = int(counters.get("decode_prefix_misses", 0))
        cached = int(counters.get("decode_prefix_cached_tokens", 0))
        prompt = int(counters.get("decode_prompt_tokens", 0))
        return {
            "hits": hits,
            "misses": misses,
            "hit_rate": round(hits / (hits + misses), 4)
            if hits + misses else None,
            "cached_tokens": cached,
            "prompt_tokens": prompt,
            "cached_token_fraction": round(cached / prompt, 4)
            if prompt else None,
            "spills": int(counters.get("kv_tier_spills", 0)),
            "readmits": int(counters.get("kv_tier_readmits", 0)),
            "bytes_d2h": int(counters.get("kv_tier_bytes_d2h", 0)),
            "bytes_h2d": int(counters.get("kv_tier_bytes_h2d", 0)),
        }

    per_replica = {}
    totals = {}
    for rid, s in summaries.items():
        row = one(s.get("counters", {}))
        per_replica[rid] = row
        for k, v in row.items():
            if isinstance(v, int):
                totals[k] = totals.get(k, 0) + v
    fleet = one(totals and {
        "decode_prefix_hits": totals.get("hits", 0),
        "decode_prefix_misses": totals.get("misses", 0),
        "decode_prefix_cached_tokens": totals.get("cached_tokens", 0),
        "decode_prompt_tokens": totals.get("prompt_tokens", 0),
        "kv_tier_spills": totals.get("spills", 0),
        "kv_tier_readmits": totals.get("readmits", 0),
        "kv_tier_bytes_d2h": totals.get("bytes_d2h", 0),
        "kv_tier_bytes_h2d": totals.get("bytes_h2d", 0),
    } or {})
    return {"fleet": fleet, "per_replica": per_replica}


_FLIGHT_DUMP = re.compile(r"^flight_rank_\d+\.json$")


def iter_obs_dumps(obs_root, pattern):
    """Yield ``(subdir, filename, path)`` for every dump whose filename
    fully matches ``pattern`` (a compiled regex) under ``obs_root``: the
    root itself (``subdir == ""``) plus ONE level of subdirectories —
    the fleet layout (``replica_<id>/`` dirs, and a ``controller/`` dir
    when the router keeps its obs out of the root). The single walker
    both the flight-record reader and ``fleet_trace.find_trace_dumps``
    use, so the layout knowledge cannot drift between them. Unreadable
    or concurrently-removed dirs skip — never raise; a half-dead obs
    tree is this code's NORMAL operating condition."""
    try:
        names = sorted(os.listdir(str(obs_root)))
    except OSError:
        return
    for name in names:
        p = os.path.join(str(obs_root), name)
        if pattern.match(name):
            yield "", name, p
        elif os.path.isdir(p):
            try:
                subs = sorted(os.listdir(p))
            except OSError:
                continue
            for sub in subs:
                if pattern.match(sub):
                    yield name, sub, os.path.join(p, sub)


def read_flight_records(obs_root):
    """[(source_label, record), ...] from every flight-recorder dump
    (``flight_rank_*.json``) under ``obs_root``: the root itself (the
    controller/router process, labelled ``controller``) plus one level
    of subdirectories (``replica_<id>/`` and a controller dir, labelled
    by dir name). Torn or missing dumps read as empty."""
    from . import flight as _flight

    out = []
    for subdir, _fn, path in iter_obs_dumps(obs_root, _FLIGHT_DUMP):
        for rec in _flight.load(path):
            out.append((subdir or "controller", rec))
    return out


def slowest_requests(obs_root, top=10, replicas=None):
    """The fleet's slowest requests across every process's flight
    recorder, slowest first — each row keeps its journey facts
    (trace_id, backend, retries/failovers, admission wait, windows,
    ticks) plus which process recorded it. The table an operator reads
    FIRST in a latency incident: it names the trace_id to pull from
    the merged fleet trace. ``replicas=`` scopes ``replica_<id>``
    sources to those ids (a reused workdir keeps dead runs' replica
    dirs; their dumps must not name trace_ids the current fleet never
    saw); non-replica sources (the controller) always pass. Rows are
    the stable journey schema (``flight.to_journey``) — the same codec
    the fleet simulator replays, so the report and the sim can never
    disagree about a field."""
    from . import flight as _flight

    rows = []
    for label, rec in read_flight_records(obs_root):
        m = _REPLICA_DIR.match(label)
        if replicas is not None and m and int(m.group(1)) not in replicas:
            continue
        row = _flight.to_journey(dict(rec, process=label))
        if not isinstance(row.get("ms"), (int, float)):
            continue
        rows.append(row)
    rows.sort(key=lambda r: -float(r["ms"]))
    return rows[:int(top)]


def read_replica_snapshots(obs_root):
    """{replica_id: newest snapshot dict} from ``replica_<id>/`` dirs
    under ``obs_root`` (each replica process writes the standard
    per-rank JSONL snapshots into its own directory — a replica has no
    gang rank, so its file is ``rank_0.jsonl``)."""
    out = {}
    try:
        names = os.listdir(str(obs_root))
    except OSError:
        return out
    for name in names:
        m = _REPLICA_DIR.match(name)
        if not m:
            continue
        snaps = read_rank_snapshots(os.path.join(str(obs_root), name))
        if snaps:
            # newest across whatever ranks the dir holds (normally
            # exactly rank 0)
            newest = max(snaps.values(),
                         key=lambda s: s.get("ts_mono") or 0)
            out[int(m.group(1))] = newest
    return out


def _last_fleet_run(events):
    """The slice belonging to the newest controller run — anchored on
    its ``fleet_boot`` event (fleet.log appends across runs in a reused
    workdir, like supervisor.log)."""
    start = 0
    for i, e in enumerate(events):
        if e.get("event") == "fleet_boot":
            start = i
    return events[start:]


def _replica_summary(snap):
    counters = snap.get("counters", {})
    compiles = snap.get("compiles") or {}
    hists = snap.get("histograms", {})
    return {
        "snapshot_ts": snap.get("ts"),
        "pid": snap.get("pid"),
        "counters": {
            k: counters[k] for k in _REPLICA_COUNTERS if k in counters
        },
        "latency_ms": hists.get("serving_latency_ms"),
        "steady_recompiles": int(compiles.get("steady_recompiles", 0)),
    }


def fleet_report(workdir, obs_root=None):
    """Merge ``workdir/fleet.log`` + per-replica snapshot dirs (default
    ``workdir/obs``) into one report: the ready-replica count over
    time, every scale/rollout/crash event, and per-replica request
    tallies — the serving-side twin of ``gang_report``."""
    from ..distributed import supervisor as _sup

    # the log filename is serving.fleet.FLEET_LOG; spelled literally so
    # a report-only consumer (post-mortem tooling) never pays the whole
    # serving-package import for one string constant
    all_events = _sup.load_events(str(workdir), filename="fleet.log")
    events = _last_fleet_run(all_events)
    obs_root = obs_root or os.path.join(str(workdir), "obs")
    snaps = read_replica_snapshots(obs_root)
    # scope the snapshots to THIS run, like the events: a reused
    # workdir keeps dead runs' replica_<id> dirs on disk, and replica
    # ids restart per run — without the filter a previous run's
    # replica would inflate per_replica and the fleet-wide
    # steady_recompiles sum the probes gate on. A replica ADOPTED by a
    # restarted controller belongs to this run exactly like a spawned
    # one (its ids don't restart across an adoption — the journal
    # resumes the id sequence), so adoption events join the scope set.
    spawned = {
        e.get("replica") for e in events
        if e.get("event") in ("replica_spawn", "replica_adopt")
    }
    if spawned:
        snaps = {r: s for r, s in snaps.items() if r in spawned}
    # ready-replica count over time: every lifecycle event that moves
    # the count carries ready_replicas, so the timeline is exact
    timeline = [
        {
            "ts": e.get("ts"),
            "ts_mono": e.get("ts_mono"),
            "event": e.get("event"),
            "ready_replicas": e.get("ready_replicas"),
        }
        for e in events if e.get("ready_replicas") is not None
    ]
    scale_events = [
        {
            "event": e["event"],
            "from_replicas": e.get("from_replicas"),
            "to_replicas": e.get("to_replicas"),
            "reason": e.get("reason"),
            "ts": e.get("ts"),
        }
        for e in events if e.get("event") in ("scale_up", "scale_down")
    ]
    rollouts = [
        {k: e.get(k) for k in ("event", "version", "from_version",
                               "model_dir", "ms", "error", "ts")
         if k in e}
        for e in events
        if str(e.get("event", "")).startswith("rollout_")
    ]
    boot = next((e for e in events if e.get("event") == "fleet_boot"), {})
    version = boot.get("version")
    for e in events:
        if e.get("event") == "rollout_done":
            version = e.get("version")
    ready_ms = [
        e["ready_ms"] for e in events
        if e.get("event") == "replica_ready"
        and e.get("ready_ms") is not None
    ]
    summaries = {str(r): _replica_summary(s) for r, s in snaps.items()}
    # control-plane durability audit. Counts are scoped to the newest
    # run like everything else EXCEPT controller_boots: a boot count of
    # one per run is a tautology, so restarts are counted across the
    # whole log — the one fact only the full history holds.
    boots = sum(1 for e in all_events if e.get("event") == "fleet_boot")
    recover = next(
        (e for e in events if e.get("event") == "controller_recover"),
        None,
    )
    adoption = {
        "controller_boots": boots,
        "controller_restarts": max(0, boots - 1),
        "adopted": sum(1 for e in events
                       if e.get("event") == "replica_adopt"),
        "respawned": sum(1 for e in events
                         if e.get("event") == "replica_spawn"
                         and e.get("replacement")),
        "lease_expiries": sum(
            1 for e in events
            if e.get("event") == "replica_lease_expired"
        ),
        # how long the pool served unsupervised before this run's
        # controller recovered it (None: this run adopted nothing)
        "headless_ms": recover.get("headless_ms") if recover else None,
    }
    return {
        "schema_version": _registry.SCHEMA_VERSION,
        "ts": time.time(),
        "ts_mono": time.monotonic(),
        "workdir": str(workdir),
        "version": version,
        "replicas_ready_final": (
            timeline[-1]["ready_replicas"] if timeline else 0
        ),
        "replica_timeline": timeline,
        "scale_events": scale_events,
        "scale_ups": sum(1 for e in scale_events
                         if e["event"] == "scale_up"),
        "scale_downs": sum(1 for e in scale_events
                           if e["event"] == "scale_down"),
        "rollouts": rollouts,
        "crashes": sum(1 for e in events
                       if e.get("event") == "replica_crash"),
        "hangs": sum(1 for e in events
                     if e.get("event") == "replica_hang"),
        "adoption": adoption,
        "replica_ready_ms": _registry.percentiles(ready_ms,
                                                  points=(50, 99)),
        "replicas_reporting": sorted(snaps),
        "per_replica": summaries,
        # fleet-wide prefix-cache / KV-tier effectiveness: hit rate,
        # cached-token fraction, spill/re-admit byte flow — per replica
        # and rolled up (the number the KV tier exists to move)
        "prefix_cache": _prefix_cache_rollup(summaries),
        "steady_recompiles": sum(
            s["steady_recompiles"] for s in summaries.values()
        ),
        # the flight recorders' fleet-wide slowest-requests table (the
        # journey record of each: trace_id, backend, retries, admission
        # wait, windows/ticks) — empty when no process dumped yet;
        # replica sources scoped to THIS run, like the snapshots above
        "slowest_requests": slowest_requests(
            obs_root, replicas=spawned if spawned else None
        ),
    }


def write_fleet_report(workdir, obs_root=None, path=None):
    """Emit ``fleet_report.json`` under ``workdir`` (atomic tmp+rename,
    like the gang report). Returns the path."""
    report = fleet_report(workdir, obs_root=obs_root)
    path = path or os.path.join(str(workdir), FLEET_REPORT)
    return _write_json(report, path)
