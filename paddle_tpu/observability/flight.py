"""Per-request flight recorder: the journey records that SURVIVE a
process.

The span tracer answers "where did the milliseconds go" while the
process is alive to be asked; a crashed or SIGKILLed replica takes its
ring buffer with it. The flight recorder is the black box beside it: a
bounded per-process ring of one JSON record per REQUEST — admission
wait, queue depth at entry, prefill windows, decode ticks spanned,
retries/failovers, terminal status, and the request's trace_id so the
record joins the distributed trace — dumped to ``FLAGS_obs_dir`` on
drain/SIGTERM/final-snapshot, periodically by the exporter's snapshot
loop, and (throttled) the moment a request ends in a server error. The
fleet report merges every process's dump into one slowest-requests
table (``observability.aggregate``).

Writers are the serving front doors (gateway, router): they call
``note(record)`` once per finished request with whatever journey facts
they hold. The ring is bounded by ``FLAGS_trace_flight_records`` —
evictions are counted (``trace_flight_dropped``), never an error.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

from ..fluid import flags as _flags
from ..fluid import profiler as _profiler
from . import trace as _trace

__all__ = ["note", "records", "reset", "dump", "dump_on_error",
           "flight_path", "to_journey", "write_journeys",
           "load_journeys", "JOURNEY_SCHEMA_VERSION"]

# the stable journey-export schema (JSONL, one journey per line). Bumped
# only when a FIELD changes meaning — adding optional fields is not a
# bump; consumers (the simulator, the fleet report) read by name and
# ignore what they don't know.
JOURNEY_SCHEMA_VERSION = 1

_lock = threading.Lock()
_buf = deque(maxlen=256)
_flags_seen = None  # re-apply the ring bound when the flags change
_last_error_dump = 0.0


def _bound():
    try:
        return max(int(_flags.get_flag("trace_flight_records", 256)), 1)
    except (TypeError, ValueError):
        return 256


def _apply_bound_locked():
    global _buf, _flags_seen
    ver = _flags.version()
    if ver == _flags_seen:
        return
    _flags_seen = ver
    n = _bound()
    if _buf.maxlen != n:
        _buf = deque(_buf, maxlen=n)


def note(record):
    """Append one per-request journey record (a flat JSON-serializable
    dict). Cheap: one locked append; the oldest record falls off when
    the ring is full (counted, never raised)."""
    with _lock:
        _apply_bound_locked()
        dropped = len(_buf) == _buf.maxlen
        _buf.append(dict(record))
    _profiler.bump_counter("trace_flight_noted")
    if dropped:
        _profiler.bump_counter("trace_flight_dropped")


def records():
    """Copies of the retained records, oldest first."""
    with _lock:
        return [dict(r) for r in _buf]


def reset():
    global _flags_seen
    with _lock:
        _flags_seen = None
        _buf.clear()


def flight_path(dirname, rank=None):
    return os.path.join(
        str(dirname), "flight_rank_%d.json" % _trace.gang_rank(rank)
    )


def dump(dirname=None, rank=None):
    """Write the current ring to ``dirname`` (default FLAGS_obs_dir) as
    ``flight_rank_<r>.json`` — whole-file atomic replace, newest state
    wins, so repeated dumps (periodic + final) never duplicate records
    downstream. Returns the path, or None when no directory is armed.
    Never raises: the recorder must not take down the path it
    observes."""
    dirname = dirname or str(_flags.get_flag("obs_dir", "") or "")
    if not dirname:
        return None
    try:
        os.makedirs(str(dirname), exist_ok=True)
        path = flight_path(dirname, rank=rank)
        tmp = "%s.tmp.%d" % (path, os.getpid())
        payload = {
            "schema_version": _trace.TRACE_SCHEMA_VERSION,
            "ts": time.time(),
            "pid": os.getpid(),
            "records": records(),
        }
        with open(tmp, "w") as f:
            json.dump(payload, f, sort_keys=True)
        os.replace(tmp, path)
    except OSError:
        return None
    _profiler.bump_counter("trace_flight_dumps")
    return path


def dump_on_error(throttle_s=5.0):
    """Dump after a request ended in a server error — throttled so an
    error storm costs one disk write per window, not one per failure."""
    global _last_error_dump
    now = time.monotonic()
    with _lock:
        if now - _last_error_dump < throttle_s:
            return None
        _last_error_dump = now
    return dump()


def load(path):
    """Parse one dump file back into its record list ([] on any
    problem — merge tooling treats a torn dump as an empty one)."""
    try:
        with open(path) as f:
            payload = json.load(f)
    except (OSError, ValueError):
        return []
    recs = payload.get("records")
    return recs if isinstance(recs, list) else []


# -- journey export/import ---------------------------------------------------
#
# The flight ring's records are whatever the front door stashed that
# day; the JOURNEY is the stable, versioned view of one — the contract
# the simulator replays and the fleet report tabulates, so neither ever
# reaches into ring internals or chases a gateway field rename.

_J_STR = ("request_id", "tenant", "priority", "endpoint", "reason",
          "trace_id", "backend", "process")
_J_NUM = ("ts", "ms", "status", "tokens", "admit_wait_ms",
          "inflight_at_entry", "ttft_ms", "ticks_spanned", "retries",
          "failovers", "cached_prefix_tokens", "admit_windows",
          "resumed_tokens", "preemptions")


def _num(v):
    if isinstance(v, bool):
        return None
    if isinstance(v, str):
        # hand-edited / re-exported JSONL sometimes quotes numbers;
        # accept them, drop anything unparseable
        try:
            return float(v)
        except ValueError:
            return None
    if not isinstance(v, (int, float)):
        return None
    return float(v) if isinstance(v, float) else int(v)


def to_journey(record):
    """Normalize one raw flight record (or an already-exported journey
    line) into the stable journey dict: known string fields coerced to
    str, known numeric fields to int/float (bad types dropped, never
    raised), ``schema_version`` stamped, unknown fields discarded.
    ``priority`` defaults to ``interactive`` and ``tenant`` to ``anon``
    so every journey is replayable as-is."""
    rec = record if isinstance(record, dict) else {}
    j = {"schema_version": JOURNEY_SCHEMA_VERSION}
    for k in _J_STR:
        v = rec.get(k)
        if v is not None and not isinstance(v, (dict, list)):
            j[k] = str(v)
    for k in _J_NUM:
        v = _num(rec.get(k))
        if v is not None:
            j[k] = v
    j.setdefault("tenant", "anon")
    if j.get("priority") not in ("interactive", "batch"):
        j["priority"] = "interactive"
    return j


def write_journeys(path, records_in=None):
    """Export journeys as JSONL (one ``to_journey`` dict per line) to
    ``path``, atomic replace. ``records_in`` defaults to the live ring.
    Returns the number of lines written."""
    recs = records() if records_in is None else list(records_in)
    rows = [to_journey(r) for r in recs]
    tmp = "%s.tmp.%d" % (str(path), os.getpid())
    with open(tmp, "w") as f:
        for row in rows:
            f.write(json.dumps(row, sort_keys=True) + "\n")
    os.replace(tmp, str(path))
    return len(rows)


def load_journeys(path):
    """Parse a journey JSONL file back into journey dicts (each
    re-normalized through ``to_journey`` — a hand-edited or
    future-versioned line still yields the fields this version knows).
    Torn lines are skipped; a missing file reads as []."""
    out = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                if isinstance(row, dict):
                    out.append(to_journey(row))
    except OSError:
        return []
    return out
