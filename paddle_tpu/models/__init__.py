"""Model zoo built on the fluid layers API (used by tests and bench.py)."""
