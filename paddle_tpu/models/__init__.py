"""Model zoo built on the fluid layers API (used by tests and bench.py).

Mirrors the reference's book/dist-test fixture models (SURVEY.md §4, §6
configs): LeNet (MNIST), ResNet-50 (ImageNet), BERT-base, Transformer NMT.
"""

from . import lenet, resnet, bert, transformer  # noqa: F401
