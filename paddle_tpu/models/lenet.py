"""LeNet-5 for MNIST — bring-up config 1 (BASELINE.json "configs";
reference fixture: python/paddle/fluid/tests/book/test_recognize_digits.py
conv_net)."""

import paddle_tpu.fluid as fluid


def lenet(img, label, class_num=10):
    """Build the LeNet forward + loss on the current program.

    ``img``: [N, 1, 28, 28] float32, ``label``: [N, 1] int64.
    Returns (avg_loss, accuracy, logits).
    """
    conv1 = fluid.layers.conv2d(
        input=img, num_filters=6, filter_size=5, padding=2, act="relu"
    )
    pool1 = fluid.layers.pool2d(conv1, pool_size=2, pool_stride=2)
    conv2 = fluid.layers.conv2d(input=pool1, num_filters=16, filter_size=5, act="relu")
    pool2 = fluid.layers.pool2d(conv2, pool_size=2, pool_stride=2)
    fc1 = fluid.layers.fc(input=pool2, size=120, act="relu")
    fc2 = fluid.layers.fc(input=fc1, size=84, act="relu")
    logits = fluid.layers.fc(input=fc2, size=class_num)
    loss = fluid.layers.softmax_with_cross_entropy(logits, label)
    avg_loss = fluid.layers.mean(loss)
    acc = fluid.layers.accuracy(input=fluid.layers.softmax(logits), label=label)
    return avg_loss, acc, logits


def build_lenet_train(batch_size=None, learning_rate=0.01, optimizer="sgd"):
    """Build (main, startup) programs for LeNet training; returns
    (main_prog, startup_prog, feeds, avg_loss, acc)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[1, 28, 28], dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        avg_loss, acc, _ = lenet(img, label)
        if optimizer == "adam":
            opt = fluid.optimizer.Adam(learning_rate=learning_rate)
        else:
            opt = fluid.optimizer.SGD(learning_rate=learning_rate)
        opt.minimize(avg_loss)
    return main, startup, [img, label], avg_loss, acc
