"""Decoder-only causal language model (GPT-style).

The reference era's language model is the PTB LSTM
(reference: python/paddle/fluid/tests/book/test_rnn_encoder_decoder.py,
and the word-language-model configs); a decoder-only transformer LM is
the modern successor built from the SAME fluid pieces this repo already
ships: embedding + the shared ``multi_head_attention`` (models/bert.py,
with its fused flash-attention path) under the kernel's causal flag +
post-LN residual FFN blocks + an (untied) LM softmax head.

TPU-first notes: with ``cfg.use_flash_attention`` the causal mask rides
the Pallas kernel's static flag (no [T, T] bias tensor is built), the
whole step compiles to one XLA computation, and long-context training
composes with the sequence-parallel machinery (parallel/ring_attention
runs the same kernels per ring hop).
"""

import numpy as np

import paddle_tpu.fluid as fluid

from . import bert as _bert


class GPTConfig(object):
    def __init__(self, vocab_size=50257, hidden_size=768, num_layers=12,
                 num_heads=12, intermediate_size=3072,
                 max_position_embeddings=1024, hidden_dropout=0.1,
                 attention_dropout=0.1, is_test=False,
                 use_flash_attention=False):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.intermediate_size = intermediate_size
        self.max_position_embeddings = max_position_embeddings
        self.hidden_dropout = hidden_dropout
        self.attention_dropout = attention_dropout
        self.is_test = is_test
        self.use_flash_attention = use_flash_attention

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault("vocab_size", 211)
        kw.setdefault("hidden_size", 64)
        kw.setdefault("num_layers", 2)
        kw.setdefault("num_heads", 4)
        kw.setdefault("intermediate_size", 128)
        kw.setdefault("max_position_embeddings", 64)
        return cls(**kw)


def _causal_bias(seq_len):
    """[1, T, T] additive bias (0 attendable / -1e4 future) for the dense
    path; the flash path masks inside the kernel instead."""
    tri = np.tril(np.ones((1, seq_len, seq_len), np.float32))
    bias = fluid.layers.assign((tri - 1.0) * 1e4)
    bias = fluid.layers.unsqueeze(bias, axes=[1])  # [1, 1, T, T]
    bias.stop_gradient = True
    return bias


def gpt_decoder(ids, pos_ids, input_mask, cfg, kv_cache=None):
    """Decoder stack on [N, T, 1] int64 ids; returns hidden [N, T, H].

    ``kv_cache`` (None for training/full-forward inference) threads the
    decode runtime's cache plumbing through every layer's attention —
    see ``build_gpt_prefill`` / ``build_gpt_decode_step``. In ``decode``
    mode ``input_mask`` is unused (the per-slot cache key bias carries
    all masking) and T is 1."""
    emb = fluid.layers.embedding(
        input=ids, size=[cfg.vocab_size, cfg.hidden_size],
        param_attr=fluid.ParamAttr(name="tok_embedding"),
    )
    pos = fluid.layers.embedding(
        input=pos_ids, size=[cfg.max_position_embeddings, cfg.hidden_size],
        param_attr=fluid.ParamAttr(name="pos_embedding"),
    )
    h = fluid.layers.elementwise_add(emb, pos)
    h = _bert._dropout(h, cfg.hidden_dropout, cfg.is_test)

    key_bias = None
    attn_bias = None
    mode = kv_cache["mode"] if kv_cache is not None else None
    if mode in ("resume", "paged_window"):
        # resume-prefill window: masking lives entirely in the fed
        # [T, max_len] resume bias (offset-shifted causal + prefix),
        # and attention is dense window×row by design — see
        # multi_head_attention's resume branch. The paged variant is
        # the same regime with the row read through the block table.
        use_flash = False
    elif mode == "paged_step":
        # fused paged step/verify: masking lives in the fed per-slot
        # step bias; flash (the table-chasing decode kernel) engages
        # only on the T=1 single-query form — the T=k verify is the
        # window×row dense regime like resume
        use_flash = _bert.flash_wanted(
            cfg, seq_len=int(kv_cache["max_len"])
        )
    elif mode == "decode":
        # single-query step: masking lives entirely in the fed per-slot
        # cache key bias; the flash policy keys on the CACHE length (the
        # kv extent the kernel actually sweeps), not the length-1 query
        use_flash = _bert.flash_wanted(
            cfg, seq_len=int(kv_cache["max_len"])
        )
    else:
        # resolve the flash policy ONCE and pass the decision down: the
        # attention helper re-deriving it from a possibly-dynamic q_in seq
        # dim could silently take the dense branch with attn_bias=None,
        # dropping causal+padding masking entirely (ADVICE r5)
        _s = ids.shape[1] if len(ids.shape) >= 2 else -1
        use_flash = _bert.flash_wanted(
            cfg, seq_len=None if _s in (-1, None) else int(_s)
        )
        if use_flash:
            # padding as a key-only bias; causality rides the kernel flag
            key_bias = _bert.mask_to_key_bias(input_mask)
        else:
            # dense path: causal [1,1,T,T] + key padding [N,1,1,T]
            # broadcast. Built whenever the shared attention helper would
            # take its dense branch (attention dropout no longer forces
            # it — the kernel drops in-VMEM), which would otherwise run
            # with neither mask
            pad = fluid.layers.scale(
                fluid.layers.reshape(input_mask, shape=[0, 1, 1, -1]),
                scale=1e4, bias=-1e4,
            )
            pad.stop_gradient = True
            attn_bias = fluid.layers.elementwise_add(
                _causal_bias(ids.shape[1]), pad
            )
    for i in range(cfg.num_layers):
        name = "gpt_%d" % i
        cache_i = None
        if kv_cache is not None:
            k_var, v_var = kv_cache["caches"][i]
            cache_i = {"k": k_var, "v": v_var, "mode": mode}
            if mode == "prefill":
                cache_i["slot_idx"] = kv_cache["slot_idx"]
            elif mode == "resume":
                cache_i["slot_off"] = kv_cache["slot_off"]
                cache_i["resume_bias"] = kv_cache["resume_bias"]
            elif mode == "paged_window":
                cache_i["tables"] = kv_cache["tables"]
                cache_i["pos"] = kv_cache["pos"]
                cache_i["resume_bias"] = kv_cache["resume_bias"]
            elif mode == "paged_step":
                cache_i["tables"] = kv_cache["tables"]
                cache_i["pos"] = kv_cache["pos"]
                cache_i["step_bias"] = kv_cache["step_bias"]
            else:
                cache_i["pos"] = kv_cache["pos"]
                cache_i["key_bias"] = kv_cache["key_bias"]
        attn = _bert.multi_head_attention(
            h, h, attn_bias, cfg, name + "_att", key_bias=key_bias,
            causal=True, use_flash=use_flash, cache=cache_i,
        )
        attn = _bert._dropout(attn, cfg.hidden_dropout, cfg.is_test)
        h = fluid.layers.layer_norm(
            fluid.layers.elementwise_add(h, attn), begin_norm_axis=2,
            name=name + "_ln1",
        )
        ff = _bert._dropout(
            _bert._ffn(h, cfg, name + "_ffn"), cfg.hidden_dropout,
            cfg.is_test,
        )
        h = fluid.layers.layer_norm(
            fluid.layers.elementwise_add(h, ff), begin_norm_axis=2,
            name=name + "_ln2",
        )
    return h


def gpt_lm_logits(ids, pos_ids, input_mask, cfg, kv_cache=None):
    """[N, T, vocab] next-token logits."""
    h = gpt_decoder(ids, pos_ids, input_mask, cfg, kv_cache=kv_cache)
    return fluid.layers.fc(
        input=h, size=cfg.vocab_size, num_flatten_dims=2, name="lm_head"
    )


def build_gpt_lm_train(cfg, seq_len, learning_rate=3e-4, use_amp=False):
    """Next-token LM training graph: positions t predict tokens t+1,
    padded positions masked out of the loss.

    Returns (main, startup, feeds, avg_loss)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data(name="ids", shape=[seq_len, 1],
                                dtype="int64")
        pos_ids = fluid.layers.data(name="pos_ids", shape=[seq_len, 1],
                                    dtype="int64")
        input_mask = fluid.layers.data(
            name="input_mask", shape=[seq_len, 1], dtype="float32"
        )
        logits = gpt_lm_logits(ids, pos_ids, input_mask, cfg)
        # shift: logits[:, :-1] predict ids[:, 1:]
        pred = fluid.layers.slice(logits, axes=[1], starts=[0],
                                  ends=[seq_len - 1])
        tgt = fluid.layers.slice(ids, axes=[1], starts=[1], ends=[seq_len])
        loss = fluid.layers.softmax_with_cross_entropy(pred, tgt)
        # mask the loss at padded TARGET positions
        tgt_mask = fluid.layers.slice(input_mask, axes=[1], starts=[1],
                                      ends=[seq_len])
        loss = fluid.layers.elementwise_mul(loss, tgt_mask)
        denom = fluid.layers.reduce_sum(tgt_mask)
        avg_loss = fluid.layers.elementwise_div(
            fluid.layers.reduce_sum(loss), denom
        )
        opt = fluid.optimizer.Adam(learning_rate=learning_rate)
        if use_amp:
            from paddle_tpu.fluid.contrib import mixed_precision as _mp

            opt = _mp.decorate(opt)
        opt.minimize(avg_loss)
    feeds = [ids, pos_ids, input_mask]
    return main, startup, feeds, avg_loss


def build_gpt_infer(cfg, seq_len):
    """Inference graph (is_test semantics): returns (main, startup,
    feed names, logits). The caller's config is not mutated."""
    import copy

    cfg = copy.copy(cfg)
    cfg.is_test = True
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data(name="ids", shape=[seq_len, 1],
                                dtype="int64")
        pos_ids = fluid.layers.data(name="pos_ids", shape=[seq_len, 1],
                                    dtype="int64")
        input_mask = fluid.layers.data(
            name="input_mask", shape=[seq_len, 1], dtype="float32"
        )
        logits = gpt_lm_logits(ids, pos_ids, input_mask, cfg)
    return main, startup, ["ids", "pos_ids", "input_mask"], logits


# ---------------------------------------------------------------------------
# autoregressive decode runtime graphs (KV-cache prefill / single-step decode)
# ---------------------------------------------------------------------------


def decode_cache_names(cfg, slots, max_len):
    """Per-layer (K, V) cache var names — one fixed contract shared by
    the prefill and decode programs (and the host-side cache init). The
    pool geometry is part of the name: two sessions sharing one scope
    (e.g. a 1-slot greedy_generate session next to an 8-slot serving
    engine) must never read each other's differently-shaped buffers."""
    return [
        ("gpt_cache_k_%d_p%dx%d" % (i, slots, max_len),
         "gpt_cache_v_%d_p%dx%d" % (i, slots, max_len))
        for i in range(cfg.num_layers)
    ]


def decode_cache_shape(cfg, slots, max_len):
    return [
        int(slots), cfg.num_heads, int(max_len),
        cfg.hidden_size // cfg.num_heads,
    ]


def _declare_cache_vars(cfg, slots, max_len):
    """Declare the per-layer persistable cache vars in the CURRENT main
    program. No initializer: the host seeds them with zeros directly in
    the scope (running a startup here would also re-init the shared
    model params)."""
    block = fluid.default_main_program().global_block()
    shape = decode_cache_shape(cfg, slots, max_len)
    return [
        tuple(
            block.create_var(
                name=n, shape=shape, dtype="float32", persistable=True
            )
            for n in names
        )
        for names in decode_cache_names(cfg, slots, max_len)
    ]


def build_gpt_prefill(cfg, slots, seq_len, max_len):
    """Prefill graph: ONE prompt (batch 1, padded to the ``seq_len``
    bucket) runs the normal causal forward and, per layer, writes its
    K/V into the cache slot indexed by the fed scalar ``slot_idx``
    (dynamic-update-slice — the index is runtime data, so every slot
    shares this one compiled program). ``last_onehot`` [1, seq_len, 1]
    selects the last real prompt position's logits in-graph, so the
    fetch is [1, vocab] — not [seq_len, vocab].

    Returns (main, startup, feed names, next_logits). The startup is a
    byproduct (param initializers) and is NOT meant to be run by the
    decode runtime — params come from the scope it attaches to."""
    import copy

    cfg = copy.copy(cfg)
    cfg.is_test = True
    main, startup = fluid.Program(), fluid.Program()
    # the cache vars are this program's only mutable state and the
    # session owns them outright: donate, so XLA writes the slot row in
    # the cache's own buffer instead of copying the pool per prefill
    main._donate_mutable = True
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data(name="ids", shape=[seq_len, 1],
                                dtype="int64")
        pos_ids = fluid.layers.data(name="pos_ids", shape=[seq_len, 1],
                                    dtype="int64")
        input_mask = fluid.layers.data(
            name="input_mask", shape=[seq_len, 1], dtype="float32"
        )
        slot_idx = fluid.layers.data(name="slot_idx", shape=[1],
                                     dtype="int64")
        last_onehot = fluid.layers.data(
            name="last_onehot", shape=[seq_len, 1], dtype="float32"
        )
        kv_cache = {
            "mode": "prefill",
            "caches": _declare_cache_vars(cfg, slots, max_len),
            "slot_idx": slot_idx,
            "max_len": max_len,
        }
        logits = gpt_lm_logits(ids, pos_ids, input_mask, cfg,
                               kv_cache=kv_cache)
        next_logits = fluid.layers.reduce_sum(
            fluid.layers.elementwise_mul(logits, last_onehot), dim=1
        )
    feeds = ["ids", "pos_ids", "input_mask", "slot_idx", "last_onehot"]
    return main, startup, feeds, next_logits


def build_gpt_resume_prefill(cfg, slots, seq_len, max_len):
    """Resume-prefill graph: ONE prompt *window* (batch 1, padded to the
    ``seq_len`` bucket) prefills starting at a FED cache position — the
    program-shape family behind prefix-cache hits and chunked prefill.
    Per layer the window's K/V is written at (slot, offset) — both
    runtime data via ``slot_off`` [2], so the whole bucket ladder keeps
    compiling exactly once regardless of where windows land — and the
    window's queries attend DENSE over the slot's full updated row
    (cached prefix + window) under the fed ``resume_bias``
    [seq_len, max_len]: 0 where cache position j <= offset + i for
    window query i, -1e4 beyond. That bias IS the causal mask shifted
    by the runtime offset; feeding it keeps the offset out of the
    compiled shape. ``last_onehot`` selects the last real window
    token's logits (meaningful on a prompt's FINAL window; earlier
    chunks ignore the fetch).

    Returns (main, startup, feed names, next_logits [1, vocab])."""
    import copy

    cfg = copy.copy(cfg)
    cfg.is_test = True
    main, startup = fluid.Program(), fluid.Program()
    # donate: the window write updates the slot row in the cache's own
    # buffer, like the prefill/decode programs
    main._donate_mutable = True
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data(name="ids", shape=[seq_len, 1],
                                dtype="int64")
        pos_ids = fluid.layers.data(name="pos_ids", shape=[seq_len, 1],
                                    dtype="int64")
        slot_off = fluid.layers.data(name="slot_off", shape=[2],
                                     dtype="int64")
        resume_bias = fluid.layers.data(
            name="resume_bias", shape=[seq_len, max_len], dtype="float32"
        )
        last_onehot = fluid.layers.data(
            name="last_onehot", shape=[seq_len, 1], dtype="float32"
        )
        kv_cache = {
            "mode": "resume",
            "caches": _declare_cache_vars(cfg, slots, max_len),
            "slot_off": slot_off,
            "resume_bias": resume_bias,
            "max_len": max_len,
        }
        logits = gpt_lm_logits(ids, pos_ids, None, cfg, kv_cache=kv_cache)
        next_logits = fluid.layers.reduce_sum(
            fluid.layers.elementwise_mul(logits, last_onehot), dim=1
        )
    feeds = ["ids", "pos_ids", "slot_off", "resume_bias", "last_onehot"]
    return main, startup, feeds, next_logits


# -- prefix K/V store (device-resident block pool for prefix-cache reuse) ----


def prefix_store_names(cfg, blocks, block):
    """Per-layer (K, V) prefix-store var names. Pool geometry is part of
    the name for the same reason as ``decode_cache_names``: two stores
    of different shapes sharing one scope must never alias."""
    return [
        ("gpt_prefix_k_%d_n%dx%d" % (i, blocks, block),
         "gpt_prefix_v_%d_n%dx%d" % (i, blocks, block))
        for i in range(cfg.num_layers)
    ]


def prefix_store_shape(cfg, blocks, block):
    return [
        int(blocks), cfg.num_heads, int(block),
        cfg.hidden_size // cfg.num_heads,
    ]


def prefix_block_bytes(cfg, block):
    """Device bytes one cached prefix block costs across all layers
    (K + V, fp32) — what ``FLAGS_decode_prefix_cache_mb`` divides by."""
    d_head = cfg.hidden_size // cfg.num_heads
    return cfg.num_layers * 2 * cfg.num_heads * int(block) * d_head * 4


def _declare_prefix_store_vars(cfg, blocks, block):
    main_block = fluid.default_main_program().global_block()
    shape = prefix_store_shape(cfg, blocks, block)
    return [
        tuple(
            main_block.create_var(
                name=n, shape=shape, dtype="float32", persistable=True
            )
            for n in names
        )
        for names in prefix_store_names(cfg, blocks, block)
    ]


def build_gpt_prefix_copy(cfg, slots, max_len, blocks, block,
                          publish=False):
    """ONE compiled block move between the prefix store and the slot
    cache, across every layer's K and V: ``publish=False`` copies store
    block ``src_loc`` into the slot row at ``dst_loc`` (admitting a
    hit), ``publish=True`` copies a slot-row block into the store
    (publishing a finished prefill). Both 2-element (row, position)
    locations are fed int64 — runtime data, so a prompt's whole cached
    prefix is n runs of this one program, O(copied bytes) each, and the
    strict-compile gate never sees block placement.

    Returns (main, startup, feed names, ok) — ``ok`` is a dummy scalar
    fetch; the real outputs are the persistable pools themselves."""
    main, startup = fluid.Program(), fluid.Program()
    main._donate_mutable = True
    with fluid.program_guard(main, startup):
        dst_loc = fluid.layers.data(name="dst_loc", shape=[2],
                                    dtype="int64")
        src_loc = fluid.layers.data(name="src_loc", shape=[2],
                                    dtype="int64")
        caches = _declare_cache_vars(cfg, slots, max_len)
        stores = _declare_prefix_store_vars(cfg, blocks, block)
        for (ck, cv), (sk, sv) in zip(caches, stores):
            if publish:
                fluid.layers.kv_cache_copy(sk, ck, dst_loc, src_loc, block)
                fluid.layers.kv_cache_copy(sv, cv, dst_loc, src_loc, block)
            else:
                fluid.layers.kv_cache_copy(ck, sk, dst_loc, src_loc, block)
                fluid.layers.kv_cache_copy(cv, sv, dst_loc, src_loc, block)
        ok = fluid.layers.fill_constant(shape=[1], dtype="int32", value=1)
    return main, startup, ["dst_loc", "src_loc"], ok


def build_gpt_decode_step(cfg, slots, max_len):
    """Single-step decode graph: one new token per slot (query length 1)
    against the per-layer KV caches. Feeds — all fixed-shape, so ONE
    compiled program serves every mix of slot lengths / admissions /
    retirements:

    - ``step_ids`` / ``step_pos`` [slots, 1, 1] int64: each slot's newest
      token and its cache position, which is also where its K/V is
      scatter-written (inactive slots feed a zero token at a CALLER-
      CHOSEN position — a free slot's dead row tolerates any landing
      spot, but a mid-chunked-prefill row is live and the engine aims
      the masked write at its next window start);
    - ``key_bias`` [slots, max_len]: additive mask, 0 on live cache
      positions (<= the slot's current position), -1e4 beyond — the only
      mask decode needs, and the causal mask by construction.

    Returns (main, startup, feed names, step_logits [slots, vocab])."""
    import copy

    cfg = copy.copy(cfg)
    cfg.is_test = True
    main, startup = fluid.Program(), fluid.Program()
    # donate the caches: the per-token step updates them in place
    # instead of copying the whole pool every token (decode is
    # bandwidth-bound on exactly this traffic)
    main._donate_mutable = True
    with fluid.program_guard(main, startup):
        step_ids = fluid.layers.data(name="step_ids", shape=[1, 1],
                                     dtype="int64")
        step_pos = fluid.layers.data(name="step_pos", shape=[1, 1],
                                     dtype="int64")
        key_bias = fluid.layers.data(
            name="key_bias", shape=[max_len], dtype="float32"
        )
        kv_cache = {
            "mode": "decode",
            "caches": _declare_cache_vars(cfg, slots, max_len),
            "pos": step_pos,
            "key_bias": key_bias,
            "max_len": max_len,
        }
        logits = gpt_lm_logits(step_ids, step_pos, None, cfg,
                               kv_cache=kv_cache)
        step_logits = fluid.layers.reshape(
            logits, shape=[-1, cfg.vocab_size]
        )
    feeds = ["step_ids", "step_pos", "key_bias"]
    return main, startup, feeds, step_logits


# -- paged KV pool (block-table addressing: ONE shared pool for live slots
# -- AND the prefix cache; a slot's row is whatever its fed table maps to) ---


def paged_pool_names(cfg, blocks, block):
    """Per-layer (K, V) paged-pool var names. Pool geometry is part of
    the name for the same reason as ``decode_cache_names``: two pools of
    different shapes sharing one scope must never alias."""
    return [
        ("gpt_paged_k_%d_n%dx%d" % (i, blocks, block),
         "gpt_paged_v_%d_n%dx%d" % (i, blocks, block))
        for i in range(cfg.num_layers)
    ]


def paged_pool_shape(cfg, blocks, block):
    return [
        int(blocks), cfg.num_heads, int(block),
        cfg.hidden_size // cfg.num_heads,
    ]


def paged_block_bytes(cfg, block):
    """Device bytes one pool block costs across all layers (K + V,
    fp32) — what sizes the allocator and the HBM-footprint accounting
    (a slot costs ``ceil(len/block)`` of these, not ``max_len``)."""
    d_head = cfg.hidden_size // cfg.num_heads
    return cfg.num_layers * 2 * cfg.num_heads * int(block) * d_head * 4


def _declare_paged_pool_vars(cfg, blocks, block):
    main_block = fluid.default_main_program().global_block()
    shape = paged_pool_shape(cfg, blocks, block)
    return [
        tuple(
            main_block.create_var(
                name=n, shape=shape, dtype="float32", persistable=True
            )
            for n in names
        )
        for names in paged_pool_names(cfg, blocks, block)
    ]


def build_gpt_paged_window(cfg, blocks, block, max_blocks, seq_len):
    """Paged prefill-window graph: ONE prompt window (batch 1, padded to
    the ``seq_len`` bucket) lands THROUGH the slot's fed block table —
    the paged runtime's only prefill form (a monolithic prefill is a
    window at position 0). Per layer the window's K/V scatters into the
    pool blocks its ``table`` [max_blocks] maps logical positions
    ``window_pos .. window_pos+T-1`` to, then the window's queries
    attend dense over the gathered logical row under the fed
    ``resume_bias`` [seq_len, max_blocks*block] (offset-shifted causal;
    -1e4 also buries sink-block garbage past the live length). Table,
    position, and bias are all runtime data: one program per bucket, 0
    steady-state recompiles.

    Returns (main, startup, feed names, next_logits [1, vocab])."""
    import copy

    cfg = copy.copy(cfg)
    cfg.is_test = True
    main, startup = fluid.Program(), fluid.Program()
    main._donate_mutable = True
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data(name="ids", shape=[seq_len, 1],
                                dtype="int64")
        pos_ids = fluid.layers.data(name="pos_ids", shape=[seq_len, 1],
                                    dtype="int64")
        table = fluid.layers.data(name="table", shape=[max_blocks],
                                  dtype="int64")
        window_pos = fluid.layers.data(name="window_pos", shape=[1],
                                       dtype="int64")
        resume_bias = fluid.layers.data(
            name="resume_bias", shape=[seq_len, max_blocks * block],
            dtype="float32"
        )
        last_onehot = fluid.layers.data(
            name="last_onehot", shape=[seq_len, 1], dtype="float32"
        )
        kv_cache = {
            "mode": "paged_window",
            "caches": _declare_paged_pool_vars(cfg, blocks, block),
            "tables": table,
            "pos": window_pos,
            "resume_bias": resume_bias,
            "max_len": max_blocks * block,
        }
        logits = gpt_lm_logits(ids, pos_ids, None, cfg, kv_cache=kv_cache)
        next_logits = fluid.layers.reduce_sum(
            fluid.layers.elementwise_mul(logits, last_onehot), dim=1
        )
    feeds = ["ids", "pos_ids", "table", "window_pos", "resume_bias",
             "last_onehot"]
    return main, startup, feeds, next_logits


def build_gpt_paged_step(cfg, slots, blocks, block, max_blocks, step_w=1):
    """Unified paged step/verify graph: every slot advances a
    ``step_w``-token window per tick against the shared paged pool —
    ``step_w=1`` is the fused decode step, ``step_w=k`` the speculative
    VERIFY program that scores all k draft positions in one call. Feeds
    (all fixed-shape; tables/positions/bias are runtime data, so one
    compiled program per window width serves every table layout):

    - ``step_ids`` / ``step_pos`` [slots, step_w, 1] int64: each slot's
      token window and its contiguous cache positions (window start =
      ``step_pos[s, 0]``); inactive slots park their table on the sink
      block and tolerate any position;
    - ``tables`` [slots, max_blocks] int64 block tables;
    - ``step_bias`` [slots, step_w, max_blocks*block]: additive mask, 0
      where cache position j <= step_pos[s, i] for window query i, -1e4
      beyond — per-query causal by construction, and it buries sink /
      stale-tail garbage.

    Returns (main, startup, feeds, step_logits [slots, step_w, vocab]
    reshaped to [slots*step_w, vocab])."""
    import copy

    cfg = copy.copy(cfg)
    cfg.is_test = True
    main, startup = fluid.Program(), fluid.Program()
    main._donate_mutable = True
    with fluid.program_guard(main, startup):
        step_ids = fluid.layers.data(name="step_ids", shape=[step_w, 1],
                                     dtype="int64")
        step_pos = fluid.layers.data(name="step_pos", shape=[step_w, 1],
                                     dtype="int64")
        tables = fluid.layers.data(name="tables", shape=[max_blocks],
                                   dtype="int64")
        step_bias = fluid.layers.data(
            name="step_bias", shape=[step_w, max_blocks * block],
            dtype="float32"
        )
        # write start = each slot's first window position
        write_pos = fluid.layers.reshape(
            fluid.layers.slice(step_pos, axes=[1], starts=[0], ends=[1]),
            shape=[-1],
        )
        kv_cache = {
            "mode": "paged_step",
            "caches": _declare_paged_pool_vars(cfg, blocks, block),
            "tables": tables,
            "pos": write_pos,
            "step_bias": step_bias,
            "max_len": max_blocks * block,
        }
        logits = gpt_lm_logits(step_ids, step_pos, None, cfg,
                               kv_cache=kv_cache)
        step_logits = fluid.layers.reshape(
            logits, shape=[-1, cfg.vocab_size]
        )
    feeds = ["step_ids", "step_pos", "tables", "step_bias"]
    return main, startup, feeds, step_logits


def build_gpt_paged_block_copy(cfg, blocks, block, npairs):
    """ONE compiled pool-internal block copy across every layer's K and
    V: ``cache[dst[i]] = cache[src[i]]`` for each of the ``npairs`` fed
    pairs — the copy-on-write program (duplicate a shared block before
    its new owner writes the partial tail). Pad unused pairs with
    src==dst identity copies to reuse one compiled pair count.

    Returns (main, startup, feed names, ok)."""
    main, startup = fluid.Program(), fluid.Program()
    main._donate_mutable = True
    with fluid.program_guard(main, startup):
        src = fluid.layers.data(name="src", shape=[npairs], dtype="int64")
        dst = fluid.layers.data(name="dst", shape=[npairs], dtype="int64")
        for pk, pv in _declare_paged_pool_vars(cfg, blocks, block):
            fluid.layers.kv_cache_block_copy(pk, src, dst)
            fluid.layers.kv_cache_block_copy(pv, src, dst)
        ok = fluid.layers.fill_constant(shape=[1], dtype="int32", value=1)
    return main, startup, ["src", "dst"], ok


def _reference_generate(exe, infer_prog, logits_var, cfg, prompt_ids,
                        max_len, scope=None):
    """The ORACLE: host-driven greedy decode recomputing the full
    [1, max_len] forward per emitted token. O(T^2) model forwards — kept
    verbatim (minus rebuilding the loop-constant pos_ids / position-index
    arrays every iteration) as the parity reference the decode runtime's
    tests and probe compare token-for-token against."""
    ids = list(prompt_ids)
    pos_ids = np.arange(max_len).reshape(1, max_len, 1).astype("int64")
    positions = np.arange(max_len)
    padded = np.zeros((1, max_len, 1), "int64")
    padded[0, : len(ids), 0] = ids
    for _ in range(max_len - len(prompt_ids)):
        cur = len(ids)
        padded[0, :cur, 0] = ids
        feed = {
            "ids": padded,
            "pos_ids": pos_ids,
            "input_mask": (positions < cur)
            .astype("float32").reshape(1, max_len, 1),
        }
        (lv,) = exe.run(infer_prog, feed=feed, fetch_list=[logits_var],
                        scope=scope)
        nxt = int(np.asarray(lv)[0, cur - 1].argmax())
        ids.append(nxt)
    return ids


def greedy_generate(exe, infer_prog, logits_var, cfg, prompt_ids, max_len,
                    scope=None):
    """Greedy decode through the KV-cache runtime: one prefill over the
    prompt, then O(1)-length incremental steps against the cache — O(T)
    total model work instead of the O(T^2) full-forward-per-token loop
    (kept as ``_reference_generate``, the parity oracle). Output is
    token-exact vs the oracle: the cached K/V are the same projections
    the full forward computes, masked-out positions carry exactly-zero
    softmax weight in fp32, and the argmax sees bitwise-equal logits.

    The single-slot decode session is cached per (scope, model geometry),
    so repeated calls reuse the compiled prefill/decode programs."""
    ids = list(prompt_ids)
    if len(ids) >= max_len:
        return ids
    from paddle_tpu.serving import decode as _decode

    sess = _decode.session_for_generate(exe, cfg, scope, max_len,
                                        infer_prog)
    # the session is cached per (scope, geometry): concurrent callers
    # (the old per-call loop was trivially reentrant) serialize on its
    # lock for the WHOLE generation so interleaved steps can never read
    # each other's slot-0 cache
    with sess.lock:
        logits = sess.prefill(0, ids)
        ids.append(int(np.asarray(logits).ravel().argmax()))
        while len(ids) < max_len:
            step = sess.decode_step([ids[-1]], [len(ids) - 1], [True])
            ids.append(int(np.asarray(step)[0].argmax()))
    return ids
