"""Decoder-only causal language model (GPT-style).

The reference era's language model is the PTB LSTM
(reference: python/paddle/fluid/tests/book/test_rnn_encoder_decoder.py,
and the word-language-model configs); a decoder-only transformer LM is
the modern successor built from the SAME fluid pieces this repo already
ships: embedding + the shared ``multi_head_attention`` (models/bert.py,
with its fused flash-attention path) under the kernel's causal flag +
post-LN residual FFN blocks + an (untied) LM softmax head.

TPU-first notes: with ``cfg.use_flash_attention`` the causal mask rides
the Pallas kernel's static flag (no [T, T] bias tensor is built), the
whole step compiles to one XLA computation, and long-context training
composes with the sequence-parallel machinery (parallel/ring_attention
runs the same kernels per ring hop).
"""

import numpy as np

import paddle_tpu.fluid as fluid

from . import bert as _bert


class GPTConfig(object):
    def __init__(self, vocab_size=50257, hidden_size=768, num_layers=12,
                 num_heads=12, intermediate_size=3072,
                 max_position_embeddings=1024, hidden_dropout=0.1,
                 attention_dropout=0.1, is_test=False,
                 use_flash_attention=False):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.intermediate_size = intermediate_size
        self.max_position_embeddings = max_position_embeddings
        self.hidden_dropout = hidden_dropout
        self.attention_dropout = attention_dropout
        self.is_test = is_test
        self.use_flash_attention = use_flash_attention

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault("vocab_size", 211)
        kw.setdefault("hidden_size", 64)
        kw.setdefault("num_layers", 2)
        kw.setdefault("num_heads", 4)
        kw.setdefault("intermediate_size", 128)
        kw.setdefault("max_position_embeddings", 64)
        return cls(**kw)


def _causal_bias(seq_len):
    """[1, T, T] additive bias (0 attendable / -1e4 future) for the dense
    path; the flash path masks inside the kernel instead."""
    tri = np.tril(np.ones((1, seq_len, seq_len), np.float32))
    bias = fluid.layers.assign((tri - 1.0) * 1e4)
    bias = fluid.layers.unsqueeze(bias, axes=[1])  # [1, 1, T, T]
    bias.stop_gradient = True
    return bias


def gpt_decoder(ids, pos_ids, input_mask, cfg):
    """Decoder stack on [N, T, 1] int64 ids; returns hidden [N, T, H]."""
    emb = fluid.layers.embedding(
        input=ids, size=[cfg.vocab_size, cfg.hidden_size],
        param_attr=fluid.ParamAttr(name="tok_embedding"),
    )
    pos = fluid.layers.embedding(
        input=pos_ids, size=[cfg.max_position_embeddings, cfg.hidden_size],
        param_attr=fluid.ParamAttr(name="pos_embedding"),
    )
    h = fluid.layers.elementwise_add(emb, pos)
    h = _bert._dropout(h, cfg.hidden_dropout, cfg.is_test)

    key_bias = None
    attn_bias = None
    # resolve the flash policy ONCE and pass the decision down: the
    # attention helper re-deriving it from a possibly-dynamic q_in seq dim
    # could silently take the dense branch with attn_bias=None, dropping
    # causal+padding masking entirely (ADVICE r5)
    _s = ids.shape[1] if len(ids.shape) >= 2 else -1
    use_flash = _bert.flash_wanted(
        cfg, seq_len=None if _s in (-1, None) else int(_s)
    )
    if use_flash:
        # padding as a key-only bias; causality rides the kernel flag
        key_bias = _bert.mask_to_key_bias(input_mask)
    else:
        # dense path: causal [1,1,T,T] + key padding [N,1,1,T] broadcast.
        # Built whenever the shared attention helper would take its dense
        # branch (attention dropout no longer forces it — the kernel
        # drops in-VMEM), which would otherwise run with neither mask
        pad = fluid.layers.scale(
            fluid.layers.reshape(input_mask, shape=[0, 1, 1, -1]),
            scale=1e4, bias=-1e4,
        )
        pad.stop_gradient = True
        attn_bias = fluid.layers.elementwise_add(
            _causal_bias(ids.shape[1]), pad
        )
    for i in range(cfg.num_layers):
        name = "gpt_%d" % i
        attn = _bert.multi_head_attention(
            h, h, attn_bias, cfg, name + "_att", key_bias=key_bias,
            causal=True, use_flash=use_flash,
        )
        attn = _bert._dropout(attn, cfg.hidden_dropout, cfg.is_test)
        h = fluid.layers.layer_norm(
            fluid.layers.elementwise_add(h, attn), begin_norm_axis=2,
            name=name + "_ln1",
        )
        ff = _bert._dropout(
            _bert._ffn(h, cfg, name + "_ffn"), cfg.hidden_dropout,
            cfg.is_test,
        )
        h = fluid.layers.layer_norm(
            fluid.layers.elementwise_add(h, ff), begin_norm_axis=2,
            name=name + "_ln2",
        )
    return h


def gpt_lm_logits(ids, pos_ids, input_mask, cfg):
    """[N, T, vocab] next-token logits."""
    h = gpt_decoder(ids, pos_ids, input_mask, cfg)
    return fluid.layers.fc(
        input=h, size=cfg.vocab_size, num_flatten_dims=2, name="lm_head"
    )


def build_gpt_lm_train(cfg, seq_len, learning_rate=3e-4, use_amp=False):
    """Next-token LM training graph: positions t predict tokens t+1,
    padded positions masked out of the loss.

    Returns (main, startup, feeds, avg_loss)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data(name="ids", shape=[seq_len, 1],
                                dtype="int64")
        pos_ids = fluid.layers.data(name="pos_ids", shape=[seq_len, 1],
                                    dtype="int64")
        input_mask = fluid.layers.data(
            name="input_mask", shape=[seq_len, 1], dtype="float32"
        )
        logits = gpt_lm_logits(ids, pos_ids, input_mask, cfg)
        # shift: logits[:, :-1] predict ids[:, 1:]
        pred = fluid.layers.slice(logits, axes=[1], starts=[0],
                                  ends=[seq_len - 1])
        tgt = fluid.layers.slice(ids, axes=[1], starts=[1], ends=[seq_len])
        loss = fluid.layers.softmax_with_cross_entropy(pred, tgt)
        # mask the loss at padded TARGET positions
        tgt_mask = fluid.layers.slice(input_mask, axes=[1], starts=[1],
                                      ends=[seq_len])
        loss = fluid.layers.elementwise_mul(loss, tgt_mask)
        denom = fluid.layers.reduce_sum(tgt_mask)
        avg_loss = fluid.layers.elementwise_div(
            fluid.layers.reduce_sum(loss), denom
        )
        opt = fluid.optimizer.Adam(learning_rate=learning_rate)
        if use_amp:
            from paddle_tpu.fluid.contrib import mixed_precision as _mp

            opt = _mp.decorate(opt)
        opt.minimize(avg_loss)
    feeds = [ids, pos_ids, input_mask]
    return main, startup, feeds, avg_loss


def build_gpt_infer(cfg, seq_len):
    """Inference graph (is_test semantics): returns (main, startup,
    feed names, logits). The caller's config is not mutated."""
    import copy

    cfg = copy.copy(cfg)
    cfg.is_test = True
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data(name="ids", shape=[seq_len, 1],
                                dtype="int64")
        pos_ids = fluid.layers.data(name="pos_ids", shape=[seq_len, 1],
                                    dtype="int64")
        input_mask = fluid.layers.data(
            name="input_mask", shape=[seq_len, 1], dtype="float32"
        )
        logits = gpt_lm_logits(ids, pos_ids, input_mask, cfg)
    return main, startup, ["ids", "pos_ids", "input_mask"], logits


def greedy_generate(exe, infer_prog, logits_var, cfg, prompt_ids, max_len,
                    scope=None):
    """Host-driven greedy decode with a fixed-shape graph: the causal
    mask makes positions >= the current length irrelevant, so one
    compiled [1, max_len] program serves every step (the XLA-friendly
    static-shape idiom; the NMT model's beam search is the batched
    in-graph variant)."""
    ids = list(prompt_ids)
    for _ in range(max_len - len(prompt_ids)):
        cur = len(ids)
        padded = np.zeros((1, max_len, 1), "int64")
        padded[0, :cur, 0] = ids
        feed = {
            "ids": padded,
            "pos_ids": np.arange(max_len).reshape(1, max_len, 1)
            .astype("int64"),
            "input_mask": (np.arange(max_len) < cur)
            .astype("float32").reshape(1, max_len, 1),
        }
        (lv,) = exe.run(infer_prog, feed=feed, fetch_list=[logits_var],
                        scope=scope)
        nxt = int(np.asarray(lv)[0, cur - 1].argmax())
        ids.append(nxt)
    return ids
