"""ResNet family — bring-up config 2/4 (BASELINE.json) and the headline
throughput benchmark model.

Reference fixtures: python/paddle/fluid/tests/unittests/dist_se_resnext.py and
test_parallel_executor_seresnext.py build SE-ResNeXt the same way (conv_bn
helpers over layers.conv2d/batch_norm); this is the plain ResNet-v1.5
variant (stride-2 in the 3x3 of the bottleneck), the standard benchmark
configuration.

TPU notes: convs stay NCHW at the program level (the Fluid contract); the
conv2d lowering hands XLA `NCHW` dimension numbers and XLA picks the optimal
internal layout for the MXU. BatchNorm keeps running stats as persistable
vars mutated via donated buffers.
"""

import paddle_tpu.fluid as fluid

_DEPTH_CFG = {
    18: ("basic", [2, 2, 2, 2]),
    34: ("basic", [3, 4, 6, 3]),
    50: ("bottleneck", [3, 4, 6, 3]),
    101: ("bottleneck", [3, 4, 23, 3]),
    152: ("bottleneck", [3, 8, 36, 3]),
}


def _conv_bn(input, num_filters, filter_size, stride=1, groups=1, act=None,
             is_test=False):
    conv = fluid.layers.conv2d(
        input=input,
        num_filters=num_filters,
        filter_size=filter_size,
        stride=stride,
        padding=(filter_size - 1) // 2,
        groups=groups,
        act=None,
        bias_attr=False,
    )
    return fluid.layers.batch_norm(conv, act=act, is_test=is_test)


def _shortcut(input, ch_out, stride, is_test):
    ch_in = input.shape[1]
    if ch_in != ch_out or stride != 1:
        return _conv_bn(input, ch_out, 1, stride, is_test=is_test)
    return input


def _basic_block(input, num_filters, stride, is_test):
    conv0 = _conv_bn(input, num_filters, 3, stride, act="relu", is_test=is_test)
    conv1 = _conv_bn(conv0, num_filters, 3, 1, is_test=is_test)
    short = _shortcut(input, num_filters, stride, is_test)
    return fluid.layers.relu(fluid.layers.elementwise_add(short, conv1))


def _bottleneck_block(input, num_filters, stride, is_test):
    conv0 = _conv_bn(input, num_filters, 1, act="relu", is_test=is_test)
    conv1 = _conv_bn(conv0, num_filters, 3, stride, act="relu", is_test=is_test)
    conv2 = _conv_bn(conv1, num_filters * 4, 1, is_test=is_test)
    short = _shortcut(input, num_filters * 4, stride, is_test)
    return fluid.layers.relu(fluid.layers.elementwise_add(short, conv2))


def resnet(img, class_num=1000, depth=50, is_test=False, checkpoints=None):
    """ResNet forward; ``img`` [N, 3, H, W] -> logits [N, class_num].

    ``checkpoints``: pass a list to collect each residual-block output var
    — the natural rematerialization cut points (RecomputeOptimizer trades
    the HBM-bandwidth-dominant activation writes for recompute, PERF.md
    "next levers")."""
    block_kind, stages = _DEPTH_CFG[depth]
    block = _basic_block if block_kind == "basic" else _bottleneck_block
    conv = _conv_bn(img, 64, 7, stride=2, act="relu", is_test=is_test)
    pool = fluid.layers.pool2d(
        conv, pool_size=3, pool_stride=2, pool_padding=1, pool_type="max"
    )
    num_filters = [64, 128, 256, 512]
    for stage, count in enumerate(stages):
        for i in range(count):
            stride = 2 if i == 0 and stage > 0 else 1
            pool = block(pool, num_filters[stage], stride, is_test)
            if checkpoints is not None:
                checkpoints.append(pool)
    pool = fluid.layers.pool2d(pool, pool_type="avg", global_pooling=True)
    return fluid.layers.fc(input=pool, size=class_num)


def build_resnet_train(depth=50, class_num=1000, image_size=224,
                       learning_rate=0.1, momentum=0.9, is_test=False,
                       use_amp=False, recompute=False):
    """(main, startup, feeds, avg_loss, acc) for ResNet training.

    ``use_amp``: bf16 mixed precision via the AMP program rewrite
    (contrib/mixed_precision) — matmuls/convs run bf16 on the MXU, master
    weights and the optimizer update stay fp32.

    ``recompute``: rematerialize activations at residual-block boundaries
    (RecomputeOptimizer) — trades recompute FLOPs for the activation HBM
    traffic that dominates the measured step (PERF.md)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(
            name="img", shape=[3, image_size, image_size], dtype="float32"
        )
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        ckpts = [] if recompute else None
        logits = resnet(img, class_num=class_num, depth=depth,
                        is_test=is_test, checkpoints=ckpts)
        loss = fluid.layers.softmax_with_cross_entropy(logits, label)
        avg_loss = fluid.layers.mean(loss)
        acc = fluid.layers.accuracy(
            input=fluid.layers.softmax(logits), label=label
        )
        opt = fluid.optimizer.Momentum(
            learning_rate=learning_rate, momentum=momentum
        )
        if recompute:
            # checkpoint every OTHER block boundary: halves the live
            # activation footprint while bounding replay to two blocks.
            # Recompute sits INSIDE the AMP decorator: AMP's backward
            # rewrites the program then delegates to this backward, which
            # runs the checkpointed append_backward.
            opt = fluid.optimizer.RecomputeOptimizer(opt)
            opt._set_checkpoints(ckpts[1::2])
        if use_amp:
            from paddle_tpu.fluid.contrib import mixed_precision as _mp

            opt = _mp.decorate(opt)
        opt.minimize(avg_loss)
    return main, startup, [img, label], avg_loss, acc
