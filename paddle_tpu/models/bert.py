"""BERT encoder — bring-up config 3 (BASELINE.json "BERT-base blocks") and
the second headline benchmark model.

The reference era's BERT implementations on Fluid (e.g. the
`multihead_matmul_fuse_pass` fusion target, ir/multihead_matmul_fuse_pass.cc)
build attention exactly from this op sequence: fc(Q/K/V) -> reshape ->
transpose -> matmul(QK^T)*scale -> softmax -> dropout -> matmul(V) ->
transpose -> reshape -> fc. On TPU the whole sequence fuses inside one XLA
computation (the fusion pass's job is subsumed by the compiler); matmuls run
on the MXU in bf16 when AMP is on.
"""

import math

import paddle_tpu.fluid as fluid


class BertConfig(object):
    def __init__(self, vocab_size=30522, hidden_size=768, num_layers=12,
                 num_heads=12, intermediate_size=3072,
                 max_position_embeddings=512, type_vocab_size=2,
                 hidden_dropout=0.1, attention_dropout=0.1, is_test=False,
                 use_flash_attention=False):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.intermediate_size = intermediate_size
        self.max_position_embeddings = max_position_embeddings
        self.type_vocab_size = type_vocab_size
        self.hidden_dropout = hidden_dropout
        self.attention_dropout = attention_dropout
        self.is_test = is_test
        self.use_flash_attention = use_flash_attention

    @classmethod
    def base(cls, **kw):
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault("vocab_size", 1024)
        kw.setdefault("hidden_size", 64)
        kw.setdefault("num_layers", 2)
        kw.setdefault("num_heads", 4)
        kw.setdefault("intermediate_size", 128)
        kw.setdefault("max_position_embeddings", 64)
        return cls(**kw)


def _dropout(x, rate, is_test):
    if is_test or rate <= 0.0:
        return x
    return fluid.layers.dropout(x, dropout_prob=rate)


def mask_to_bias(mask_2d):
    """[N, S, S] 0/1 attention mask -> additive bias [N, 1, S, S]
    (0 where attendable, -10000 where masked), broadcast over heads."""
    neg = fluid.layers.elementwise_mul(
        fluid.layers.elementwise_add(
            mask_2d,
            fluid.layers.fill_constant(shape=[1], dtype="float32", value=-1.0),
        ),
        fluid.layers.fill_constant(shape=[1], dtype="float32", value=10000.0),
    )
    bias = fluid.layers.unsqueeze(neg, axes=[1])
    bias.stop_gradient = True
    return bias


def mask_to_key_bias(mask):
    """[N, S, 1] 0/1 token mask -> key-only additive bias [N, S]
    ((m-1)*1e4: 0 where attendable, -1e4 on padded keys) for the fused
    flash-attention path; the query side needs no mask because padded-
    query rows never reach a loss term."""
    b = fluid.layers.scale(
        fluid.layers.reshape(mask, shape=[0, -1]), scale=1e4, bias=-1e4
    )
    b.stop_gradient = True
    return b


# Measured dense/flash crossover on the v5e bench chip (BENCH_BANK.json,
# round 5, post-AMP-harmonization numbers): XLA's fused dense attention
# wins at seq 384 (351 vs 272 seq/s — it runs near the HBM roofline) and
# seq 512 (237 vs 201); GPT-2 at seq 1024 is parity-to-slight-flash-win
# (79.5k vs 78.0k tok/s); at 4096 flash runs +35% over dense's best
# FEASIBLE batch — dense b4 cannot even compile there (the [S, S]
# softmax activations exceed HBM), which is the kernel's real value.
FLASH_AUTO_SEQ_THRESHOLD = 1024


def flash_engages(cfg, key_bias, seq_len=None):
    """True when multi_head_attention will actually run the fused flash
    path (vs the dense fallback). Model builders that skip constructing a
    dense attention bias on the flash path MUST consult this — a silent
    fallback without the dense bias would drop masking entirely.
    Attention dropout no longer forces the fallback: the kernel applies
    it in-VMEM from a stateless per-step hash (kernels/flash_attention.py
    dropout_rate).

    ``cfg.use_flash_attention`` may be True (always fuse), False/None
    (never), or ``"auto"``: fuse when the static query length is at or
    beyond the measured crossover (``FLASH_AUTO_SEQ_THRESHOLD``,
    overridable per-config via ``cfg.flash_auto_threshold``) — below it
    XLA's dense attention is the faster program on TPU."""
    return bool(flash_wanted(cfg, seq_len) and key_bias is not None)


def flash_wanted(cfg, seq_len=None):
    """Resolve ``cfg.use_flash_attention`` (True/False/"auto") to a bool
    without needing the mask — model builders use this to decide WHICH
    mask to construct (key-only for the kernel, dense bias otherwise)."""
    want = getattr(cfg, "use_flash_attention", False)
    if want == "auto":
        thr = getattr(cfg, "flash_auto_threshold", FLASH_AUTO_SEQ_THRESHOLD)
        want = seq_len is not None and seq_len >= thr
    return bool(want)


def _apply_kv_cache(cache, k, v, cfg):
    """Write this call's split-head K/V into the cache described by
    ``cache`` (see ``multi_head_attention``) via the ``kv_cache_write``
    dynamic-update-slice op — O(written bytes), with the write position /
    slot index as runtime DATA, so one compiled program covers every
    admission pattern. Prefill lands the prompt's [1, heads, T, d] K/V
    at the head of slot ``slot_idx``'s row (the stale tail beyond T
    stays key-bias-masked until decode overwrites it position by
    position); decode lands one token per slot at its ``pos``. Returns
    (k, v) for the attention that follows: the LOCAL prompt K/V for
    prefill (attention runs within the prompt), the full UPDATED cache
    for decode (the query attends to everything written so far)."""
    if cache["mode"] == "prefill":
        fluid.layers.kv_cache_write(cache["k"], k, cache["slot_idx"],
                                    slot_mode=True)
        fluid.layers.kv_cache_write(cache["v"], v, cache["slot_idx"],
                                    slot_mode=True)
        return k, v
    if cache["mode"] == "resume":
        # resume-prefill: the window's K/V lands at the fed
        # (slot, offset) — AFTER a cached prefix already copied into the
        # row head — and attention needs the full updated row (prefix +
        # window), so gather the slot back out. Both indices are runtime
        # data: one compiled program per bucket covers every offset.
        k_upd = fluid.layers.kv_cache_write(cache["k"], k,
                                            cache["slot_off"],
                                            slot_mode=True)
        v_upd = fluid.layers.kv_cache_write(cache["v"], v,
                                            cache["slot_off"],
                                            slot_mode=True)
        return (fluid.layers.kv_cache_gather(k_upd, cache["slot_off"]),
                fluid.layers.kv_cache_gather(v_upd, cache["slot_off"]))
    if cache["mode"] == "paged_window":
        # batch-1 window through the slot's block TABLE: the window's
        # K/V lands at logical positions pos..pos+T-1, scattered into
        # whichever physical pool blocks the fed table row maps them
        # to, then the full logical row (every table block, sink
        # garbage included — resume_bias masks it) is gathered back for
        # the window's queries. Covers monolithic prefill (pos 0) and
        # chunked resume alike: offset, table, and positions are all
        # runtime data, so ONE program per bucket serves both.
        k_upd = fluid.layers.kv_cache_write_paged(
            cache["k"], k, cache["tables"], cache["pos"])
        v_upd = fluid.layers.kv_cache_write_paged(
            cache["v"], v, cache["tables"], cache["pos"])
        return (fluid.layers.kv_cache_gather_paged(k_upd, cache["tables"]),
                fluid.layers.kv_cache_gather_paged(v_upd, cache["tables"]))
    if cache["mode"] == "paged_step":
        # fused multi-slot step (T=1 decode / T=k speculative verify):
        # each slot's T-token window scatters through its table row;
        # the attention branch reads the pool back through the tables
        # (paged flash kernel or gather+dense), so just return the
        # updated pool vars.
        k_upd = fluid.layers.kv_cache_write_paged(
            cache["k"], k, cache["tables"], cache["pos"])
        v_upd = fluid.layers.kv_cache_write_paged(
            cache["v"], v, cache["tables"], cache["pos"])
        return k_upd, v_upd
    k_upd = fluid.layers.kv_cache_write(cache["k"], k, cache["pos"])
    v_upd = fluid.layers.kv_cache_write(cache["v"], v, cache["pos"])
    return k_upd, v_upd


def multi_head_attention(q_in, kv_in, attn_bias, cfg, name, key_bias=None,
                         causal=False, use_flash=None, cache=None):
    """Self/cross attention on [N, S, H] inputs.

    With ``cfg.use_flash_attention`` the score/softmax/context chain runs
    as ONE fused flash-attention op — the Pallas kernel keeps the [S, S]
    scores in VMEM, applies attention dropout in-kernel (per-step seed
    from the executor key stream), and ``key_bias`` [N, S] carries the
    padding mask in key-only form.

    ``use_flash``: the builder's RESOLVED policy decision. Model builders
    choose which mask to construct from ``flash_wanted`` and must pass
    that same decision down, so a dynamic query dim here can never
    silently diverge from the mask they built (ADVICE r5). ``None`` keeps
    the legacy behavior of re-resolving from the static query length.

    ``cache``: KV-cache plumbing for autoregressive serving (None for
    training/encoder use). A dict with ``k``/``v`` — persistable
    [slots, heads, max_len, d_head] cache vars — plus ``mode``:

    - ``"prefill"``: attention runs the NORMAL path over the prompt
      (causal + padding masks as usual) and, as a side effect, writes the
      prompt's K/V into the cache slot indexed by the fed scalar
      ``slot_idx``;
    - ``"decode"``: the single-query step. Each slot's new-token K/V
      lands at its fed ``pos`` [slots] cache position (inactive slots
      write wherever the engine aims them — a dead row tolerates any
      spot; a mid-chunked-prefill row gets its next window start, which
      the window rewrites), then the length-1 query attends over the updated
      cache under ``key_bias`` [slots, max_len] (additive, -1e4 beyond
      each slot's live length) — via the decode-mode flash kernel when
      ``use_flash``, dense single-query attention otherwise.
      ``attn_bias``/``causal`` are ignored: the per-slot key mask IS the
      causal mask, since a slot's cache never holds an unmasked future
      token."""
    d_head = cfg.hidden_size // cfg.num_heads

    def _proj(x, suffix):
        return fluid.layers.fc(
            input=x, size=cfg.hidden_size, num_flatten_dims=2,
            name="%s_%s" % (name, suffix),
        )

    def _split_heads(x):
        # [N, S, H] -> [N, heads, S, d_head]
        x = fluid.layers.reshape(x, shape=[0, 0, cfg.num_heads, d_head])
        return fluid.layers.transpose(x, perm=[0, 2, 1, 3])

    q = _split_heads(_proj(q_in, "q"))
    k = _split_heads(_proj(kv_in, "k"))
    v = _split_heads(_proj(kv_in, "v"))
    if cache is not None:
        k, v = _apply_kv_cache(cache, k, v, cfg)
    if cache is not None and cache["mode"] == "paged_step":
        # unified paged step/verify: q [slots, heads, T, d_head] (T=1
        # decode, T=k speculative verify) against each slot's logical
        # row read THROUGH its block table. ``step_bias``
        # [slots, T, max_blocks*block] is the fed offset-shifted causal
        # mask (0 where cache position j <= pos_s + i for window query
        # i, -1e4 beyond — which also buries sink-block garbage), so
        # inactive slots and every live-length mix share one program.
        scale_ = 1.0 / math.sqrt(d_head)
        T_static = q.shape[2]
        if use_flash and T_static == 1:
            # single-query path: the Pallas kernel chases the table via
            # scalar prefetch — the logical rows never materialize.
            kb = fluid.layers.reshape(cache["step_bias"], shape=[0, -1])
            kb.stop_gradient = True
            ctxt = fluid.layers.flash_decode_paged_attention(
                q, cache["k"], cache["v"], cache["tables"], key_bias=kb,
                scale=scale_,
                interpret=getattr(cfg, "flash_interpret", False),
            )
        else:
            rows_k = fluid.layers.kv_cache_gather_paged(
                cache["k"], cache["tables"])
            rows_v = fluid.layers.kv_cache_gather_paged(
                cache["v"], cache["tables"])
            scores = fluid.layers.matmul(
                q, rows_k, transpose_y=True, alpha=scale_
            )
            bias4 = fluid.layers.unsqueeze(cache["step_bias"], axes=[1])
            bias4.stop_gradient = True
            weights = fluid.layers.softmax(
                fluid.layers.elementwise_add(scores, bias4), axis=-1
            )
            ctxt = fluid.layers.matmul(weights, rows_v)
        ctxt = fluid.layers.transpose(ctxt, perm=[0, 2, 1, 3])
        ctxt = fluid.layers.reshape(ctxt, shape=[0, 0, cfg.hidden_size])
        return fluid.layers.fc(
            input=ctxt, size=cfg.hidden_size, num_flatten_dims=2,
            name="%s_out" % name,
        )
    if cache is not None and cache["mode"] in ("resume", "paged_window"):
        # resume-prefill: window queries [1, heads, T, d] against the
        # slot's full updated row [1, heads, max_len, d] under the FED
        # [T, max_len] additive bias (0 on cache position j <= offset+i
        # for window query i, -1e4 beyond) — the causal mask shifted by
        # the runtime offset, which must stay out of the compiled shape.
        # Dense by design even for flash configs: the causal flash
        # kernel assumes an aligned q/k diagonal, and the window×row
        # product is the decode-step regime, not the [T, T] prefill one.
        scale_ = 1.0 / math.sqrt(d_head)
        scores = fluid.layers.matmul(q, k, transpose_y=True, alpha=scale_)
        bias4 = fluid.layers.unsqueeze(cache["resume_bias"], axes=[1])
        bias4.stop_gradient = True
        weights = fluid.layers.softmax(
            fluid.layers.elementwise_add(scores, bias4), axis=-1
        )
        ctxt = fluid.layers.matmul(weights, v)
        ctxt = fluid.layers.transpose(ctxt, perm=[0, 2, 1, 3])
        ctxt = fluid.layers.reshape(ctxt, shape=[0, 0, cfg.hidden_size])
        return fluid.layers.fc(
            input=ctxt, size=cfg.hidden_size, num_flatten_dims=2,
            name="%s_out" % name,
        )
    if cache is not None and cache["mode"] == "decode":
        scale_ = 1.0 / math.sqrt(d_head)
        if use_flash:
            ctxt = fluid.layers.flash_decode_attention(
                q, k, v, key_bias=cache["key_bias"], scale=scale_,
                interpret=getattr(cfg, "flash_interpret", False),
            )
        else:
            scores = fluid.layers.matmul(
                q, k, transpose_y=True, alpha=scale_
            )
            bias4 = fluid.layers.reshape(
                cache["key_bias"], shape=[0, 1, 1, -1]
            )
            bias4.stop_gradient = True
            weights = fluid.layers.softmax(
                fluid.layers.elementwise_add(scores, bias4), axis=-1
            )
            ctxt = fluid.layers.matmul(weights, v)
        ctxt = fluid.layers.transpose(ctxt, perm=[0, 2, 1, 3])
        ctxt = fluid.layers.reshape(ctxt, shape=[0, 0, cfg.hidden_size])
        return fluid.layers.fc(
            input=ctxt, size=cfg.hidden_size, num_flatten_dims=2,
            name="%s_out" % name,
        )
    if use_flash is None:
        _sq = q_in.shape[1] if len(q_in.shape) >= 2 else -1
        use_flash = flash_engages(
            cfg, key_bias, seq_len=None if _sq in (-1, None) else int(_sq)
        )
    else:
        # the kernel still needs the key-side mask to ride along
        use_flash = bool(use_flash) and key_bias is not None
    import warnings

    if (key_bias is not None and not use_flash and attn_bias is None
            and not getattr(cfg, "_warned_flash_mask_drop", False)):
        # the builder prepared ONLY the key-only mask (flash path) but the
        # dense branch is about to run without any attn_bias: causal +
        # padding masking would be silently dropped (ADVICE r5)
        warnings.warn(
            "flash attention resolved off for %r but only a key-only mask "
            "was built: the dense fallback runs UNMASKED. Pass the "
            "builder's resolved use_flash down, or build a dense attn_bias "
            "for the fallback." % name, stacklevel=2)
        cfg._warned_flash_mask_drop = True  # once per config, not per layer
    # warn also for the other mismatch — an EXPLICIT True with no mask to
    # ride the kernel; "auto" choosing dense is working policy
    if (getattr(cfg, "use_flash_attention", False) is True and not use_flash
            and not getattr(cfg, "_warned_flash_fallback", False)):
        warnings.warn(
            "use_flash_attention=True but no key_bias/input_mask was "
            "built: falling back to dense attention", stacklevel=2)
        cfg._warned_flash_fallback = True  # once per config, not per layer
    if use_flash:
        # ``causal`` rides the kernel flag instead of a dense [T, T] bias;
        # attention dropout runs inside the kernel (per-step seed from the
        # executor key stream)
        ctxt = fluid.layers.flash_attention(
            q, k, v, key_bias=key_bias, causal=causal,
            scale=1.0 / math.sqrt(d_head),
            dropout_rate=cfg.attention_dropout, is_test=cfg.is_test,
            # tests force the Pallas kernels off-TPU via this cfg flag
            interpret=getattr(cfg, "flash_interpret", False),
        )
    else:
        scores = fluid.layers.matmul(
            q, k, transpose_y=True, alpha=1.0 / math.sqrt(d_head)
        )
        if attn_bias is not None:
            scores = fluid.layers.elementwise_add(scores, attn_bias)
        weights = fluid.layers.softmax(scores, axis=-1)
        weights = _dropout(weights, cfg.attention_dropout, cfg.is_test)
        ctxt = fluid.layers.matmul(weights, v)  # [N, heads, S, d_head]
    ctxt = fluid.layers.transpose(ctxt, perm=[0, 2, 1, 3])
    ctxt = fluid.layers.reshape(ctxt, shape=[0, 0, cfg.hidden_size])
    return fluid.layers.fc(
        input=ctxt, size=cfg.hidden_size, num_flatten_dims=2,
        name="%s_out" % name,
    )


def _ffn(x, cfg, name):
    h = fluid.layers.fc(
        input=x, size=cfg.intermediate_size, num_flatten_dims=2,
        act="gelu", name="%s_fc0" % name,
    )
    return fluid.layers.fc(
        input=h, size=cfg.hidden_size, num_flatten_dims=2,
        name="%s_fc1" % name,
    )


def encoder_layer(x, attn_bias, cfg, name, key_bias=None, use_flash=None):
    attn = multi_head_attention(x, x, attn_bias, cfg, "%s_att" % name,
                                key_bias=key_bias, use_flash=use_flash)
    attn = _dropout(attn, cfg.hidden_dropout, cfg.is_test)
    x = fluid.layers.layer_norm(
        fluid.layers.elementwise_add(x, attn), begin_norm_axis=2,
        name="%s_ln1" % name,
    )
    ff = _dropout(_ffn(x, cfg, "%s_ffn" % name), cfg.hidden_dropout, cfg.is_test)
    return fluid.layers.layer_norm(
        fluid.layers.elementwise_add(x, ff), begin_norm_axis=2,
        name="%s_ln2" % name,
    )


def bert_encoder(src_ids, pos_ids, sent_ids, input_mask, cfg):
    """Returns (sequence_output [N,S,H], pooled_output [N,H]).

    ``input_mask``: [N, S, 1] float32, 1.0 for real tokens.
    """
    emb = fluid.layers.embedding(
        input=src_ids, size=[cfg.vocab_size, cfg.hidden_size],
        param_attr=fluid.ParamAttr(name="word_embedding"),
    )
    pos = fluid.layers.embedding(
        input=pos_ids, size=[cfg.max_position_embeddings, cfg.hidden_size],
        param_attr=fluid.ParamAttr(name="pos_embedding"),
    )
    sent = fluid.layers.embedding(
        input=sent_ids, size=[cfg.type_vocab_size, cfg.hidden_size],
        param_attr=fluid.ParamAttr(name="sent_embedding"),
    )
    emb = fluid.layers.elementwise_add(
        fluid.layers.elementwise_add(emb, pos), sent
    )
    emb = fluid.layers.layer_norm(emb, begin_norm_axis=2, name="emb_ln")
    emb = _dropout(emb, cfg.hidden_dropout, cfg.is_test)

    mask_t = fluid.layers.transpose(input_mask, perm=[0, 2, 1])
    attn_mask = fluid.layers.matmul(input_mask, mask_t)  # [N, S, S]
    attn_bias = mask_to_bias(attn_mask)
    # resolve the flash policy ONCE here (the dense attn_bias above is
    # always built, so a fallback stays masked either way) and pass the
    # decision down — the attention helper must never re-derive it from a
    # possibly-dynamic query dim (ADVICE r5)
    _s = src_ids.shape[1] if len(src_ids.shape) >= 2 else -1
    use_flash = flash_wanted(
        cfg, seq_len=None if _s in (-1, None) else int(_s)
    )
    key_bias = mask_to_key_bias(input_mask) if use_flash else None

    x = emb
    for i in range(cfg.num_layers):
        x = encoder_layer(x, attn_bias, cfg, "layer_%d" % i,
                          key_bias=key_bias, use_flash=use_flash)

    first_tok = fluid.layers.slice(x, axes=[1], starts=[0], ends=[1])
    first_tok = fluid.layers.reshape(first_tok, shape=[-1, cfg.hidden_size])
    pooled = fluid.layers.fc(
        input=first_tok, size=cfg.hidden_size, act="tanh", name="pooler"
    )
    return x, pooled


def build_bert_classifier(cfg, seq_len, num_classes=2, learning_rate=2e-5,
                          use_amp=False):
    """Sequence-classification fine-tune graph (config 3 / SQuAD-style head).

    ``use_amp``: bf16 mixed precision via the AMP program rewrite — the
    attention/FFN matmuls run bf16 on the MXU, layer-norm statistics and
    the Adam update stay fp32 (gray-list propagation).

    Returns (main, startup, feeds, avg_loss, acc)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        src_ids = fluid.layers.data(name="src_ids", shape=[seq_len, 1], dtype="int64")
        pos_ids = fluid.layers.data(name="pos_ids", shape=[seq_len, 1], dtype="int64")
        sent_ids = fluid.layers.data(name="sent_ids", shape=[seq_len, 1], dtype="int64")
        input_mask = fluid.layers.data(
            name="input_mask", shape=[seq_len, 1], dtype="float32"
        )
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        _, pooled = bert_encoder(src_ids, pos_ids, sent_ids, input_mask, cfg)
        pooled = _dropout(pooled, cfg.hidden_dropout, cfg.is_test)
        logits = fluid.layers.fc(input=pooled, size=num_classes, name="cls")
        loss = fluid.layers.softmax_with_cross_entropy(logits, label)
        avg_loss = fluid.layers.mean(loss)
        acc = fluid.layers.accuracy(
            input=fluid.layers.softmax(logits), label=label
        )
        opt = fluid.optimizer.Adam(learning_rate=learning_rate)
        if use_amp:
            from paddle_tpu.fluid.contrib import mixed_precision as _mp

            opt = _mp.decorate(opt)
        opt.minimize(avg_loss)
    feeds = [src_ids, pos_ids, sent_ids, input_mask, label]
    return main, startup, feeds, avg_loss, acc
