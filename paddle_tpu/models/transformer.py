"""Transformer NMT (encoder-decoder) — bring-up config 5 (BASELINE.json
"Transformer NMT with beam search").

Reference fixture: python/paddle/fluid/tests/unittests/dist_transformer.py
(the same WMT transformer the dist tests train). Same op-level construction
as models/bert.py; adds the causal decoder mask and label smoothing.
"""

import math

import numpy as np

import paddle_tpu.fluid as fluid
from .bert import (multi_head_attention, _ffn, _dropout, mask_to_bias,
                   mask_to_key_bias)


class TransformerConfig(object):
    def __init__(self, src_vocab=30000, tgt_vocab=30000, hidden_size=512,
                 num_heads=8, num_layers=6, intermediate_size=2048,
                 max_len=256, dropout=0.1, label_smooth=0.1, is_test=False,
                 use_flash_attention=False):
        self.src_vocab = src_vocab
        self.tgt_vocab = tgt_vocab
        self.hidden_size = hidden_size
        self.num_heads = num_heads
        self.num_layers = num_layers
        self.intermediate_size = intermediate_size
        self.max_len = max_len
        self.dropout = dropout
        self.label_smooth = label_smooth
        self.is_test = is_test
        self.use_flash_attention = use_flash_attention
        # bert.multi_head_attention reads these names:
        self.hidden_dropout = dropout
        self.attention_dropout = dropout

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault("src_vocab", 1000)
        kw.setdefault("tgt_vocab", 1000)
        kw.setdefault("hidden_size", 64)
        kw.setdefault("num_heads", 4)
        kw.setdefault("num_layers", 2)
        kw.setdefault("intermediate_size", 128)
        kw.setdefault("max_len", 32)
        return cls(**kw)


def _pos_encoding_table(max_len, d_model):
    pos = np.arange(max_len)[:, None].astype("float64")
    dim = np.arange(d_model)[None, :].astype("float64")
    angle = pos / np.power(10000.0, 2 * (dim // 2) / d_model)
    table = np.zeros((max_len, d_model), dtype="float32")
    table[:, 0::2] = np.sin(angle[:, 0::2])
    table[:, 1::2] = np.cos(angle[:, 1::2])
    return table


def _embed(ids, pos_ids, vocab, cfg, name):
    emb = fluid.layers.embedding(
        input=ids, size=[vocab, cfg.hidden_size],
        param_attr=fluid.ParamAttr(name="%s_word_emb" % name),
    )
    emb = fluid.layers.elementwise_mul(
        emb,
        fluid.layers.fill_constant(
            shape=[1], dtype="float32", value=math.sqrt(cfg.hidden_size)
        ),
    )
    pos = fluid.layers.embedding(
        input=pos_ids, size=[cfg.max_len, cfg.hidden_size],
        param_attr=fluid.ParamAttr(
            name="%s_pos_emb" % name,
            initializer=fluid.initializer.NumpyArrayInitializer(
                _pos_encoding_table(cfg.max_len, cfg.hidden_size)
            ),
            trainable=False,
        ),
    )
    pos.stop_gradient = True
    emb = fluid.layers.elementwise_add(emb, pos)
    return _dropout(emb, cfg.dropout, cfg.is_test)


def _residual_ln(x, sub, cfg, name):
    sub = _dropout(sub, cfg.dropout, cfg.is_test)
    return fluid.layers.layer_norm(
        fluid.layers.elementwise_add(x, sub), begin_norm_axis=2, name=name
    )


def transformer(cfg, src_ids, src_pos, src_mask, tgt_ids, tgt_pos, tgt_mask,
                causal_mask):
    """Forward; returns decoder logits [N, T, tgt_vocab].

    masks: src_mask/tgt_mask [N, S, 1] float (1=real token);
    causal_mask [1, T, T] float lower-triangular ones.
    """
    src_self = fluid.layers.matmul(
        src_mask, fluid.layers.transpose(src_mask, perm=[0, 2, 1])
    )
    enc_bias = mask_to_bias(src_self)
    # key-only padding masks for the fused flash path ((m-1)*1e4 per key):
    # encoder/cross keys are SRC positions, decoder-self keys are TGT
    # positions with causality riding the kernel's causal flag
    src_key_bias = tgt_key_bias = None
    if getattr(cfg, "use_flash_attention", False):
        src_key_bias = mask_to_key_bias(src_mask)
        tgt_key_bias = mask_to_key_bias(tgt_mask)
    enc = _embed(src_ids, src_pos, cfg.src_vocab, cfg, "src")
    for i in range(cfg.num_layers):
        name = "enc_%d" % i
        attn = multi_head_attention(enc, enc, enc_bias, cfg, name + "_att",
                                    key_bias=src_key_bias)
        enc = _residual_ln(enc, attn, cfg, name + "_ln1")
        enc = _residual_ln(enc, _ffn(enc, cfg, name + "_ffn"), cfg, name + "_ln2")

    tgt_self = fluid.layers.matmul(
        tgt_mask, fluid.layers.transpose(tgt_mask, perm=[0, 2, 1])
    )
    tgt_self = fluid.layers.elementwise_mul(tgt_self, causal_mask)
    dec_self_bias = mask_to_bias(tgt_self)
    # cross mask: [N, T, 1] x [N, 1, S]
    cross = fluid.layers.matmul(
        tgt_mask, fluid.layers.transpose(src_mask, perm=[0, 2, 1])
    )
    cross_bias = mask_to_bias(cross)

    dec = _embed(tgt_ids, tgt_pos, cfg.tgt_vocab, cfg, "tgt")
    for i in range(cfg.num_layers):
        name = "dec_%d" % i
        attn = multi_head_attention(dec, dec, dec_self_bias, cfg,
                                    name + "_satt", key_bias=tgt_key_bias,
                                    causal=True)
        dec = _residual_ln(dec, attn, cfg, name + "_ln1")
        xatt = multi_head_attention(dec, enc, cross_bias, cfg, name + "_xatt",
                                    key_bias=src_key_bias)
        dec = _residual_ln(dec, xatt, cfg, name + "_ln2")
        dec = _residual_ln(dec, _ffn(dec, cfg, name + "_ffn"), cfg, name + "_ln3")

    return fluid.layers.fc(
        input=dec, size=cfg.tgt_vocab, num_flatten_dims=2, name="dec_proj"
    )


def build_transformer_train(cfg, src_len, tgt_len, learning_rate=2.0,
                            warmup_steps=4000):
    """(main, startup, feeds, avg_loss) — label-smoothed NMT training graph
    with the Noam LR schedule (reference:
    layers/learning_rate_scheduler.py noam_decay)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        src_ids = fluid.layers.data(name="src_ids", shape=[src_len, 1], dtype="int64")
        src_pos = fluid.layers.data(name="src_pos", shape=[src_len, 1], dtype="int64")
        src_mask = fluid.layers.data(name="src_mask", shape=[src_len, 1], dtype="float32")
        tgt_ids = fluid.layers.data(name="tgt_ids", shape=[tgt_len, 1], dtype="int64")
        tgt_pos = fluid.layers.data(name="tgt_pos", shape=[tgt_len, 1], dtype="int64")
        tgt_mask = fluid.layers.data(name="tgt_mask", shape=[tgt_len, 1], dtype="float32")
        labels = fluid.layers.data(name="labels", shape=[tgt_len, 1], dtype="int64")
        causal = _causal_const(tgt_len)
        logits = transformer(
            cfg, src_ids, src_pos, src_mask, tgt_ids, tgt_pos, tgt_mask, causal
        )
        flat = fluid.layers.reshape(logits, shape=[-1, cfg.tgt_vocab])
        lab = fluid.layers.reshape(labels, shape=[-1, 1])
        if cfg.label_smooth > 0:
            one_hot = fluid.layers.one_hot(lab, depth=cfg.tgt_vocab)
            smoothed = fluid.layers.label_smooth(
                label=one_hot, epsilon=cfg.label_smooth
            )
            smoothed.stop_gradient = True
            loss = fluid.layers.softmax_with_cross_entropy(
                flat, smoothed, soft_label=True
            )
        else:
            loss = fluid.layers.softmax_with_cross_entropy(flat, lab)
        # mask out pad positions
        wmask = fluid.layers.reshape(tgt_mask, shape=[-1, 1])
        loss = fluid.layers.elementwise_mul(loss, wmask)
        avg_loss = fluid.layers.elementwise_div(
            fluid.layers.reduce_sum(loss), fluid.layers.reduce_sum(wmask)
        )
        from paddle_tpu.fluid.layers.learning_rate_scheduler import noam_decay

        lr = noam_decay(cfg.hidden_size, warmup_steps)
        lr = fluid.layers.elementwise_mul(
            lr,
            fluid.layers.fill_constant(
                shape=[1], dtype="float32", value=float(learning_rate)
            ),
        )
        opt = fluid.optimizer.Adam(
            learning_rate=lr, beta1=0.9, beta2=0.98, epsilon=1e-9
        )
        opt.minimize(avg_loss)
    feeds = [src_ids, src_pos, src_mask, tgt_ids, tgt_pos, tgt_mask, labels]
    return main, startup, feeds, avg_loss


def _causal_const(tgt_len):
    table = np.tril(np.ones((tgt_len, tgt_len), dtype="float32"))[None]
    v = fluid.layers.assign(table)
    v.stop_gradient = True
    return v


def build_transformer_infer(cfg, src_len, tgt_len):
    """Inference graph (BASELINE config 5): next-token logits for a partial
    target prefix. The causal mask makes position t's logits depend only on
    tgt_ids[:t+1], so one fixed-shape program serves every decode step —
    the TPU-friendly form of the reference's step-wise beam loop (the jit
    cache sees ONE shape instead of T shapes).

    Returns (program, feed names, logits var [N, T, V])."""
    main, startup = fluid.Program(), fluid.Program()
    # fresh name scope: parameter names must match a train program built
    # in its own scope (enc_0_att_q.w_0 etc.), not continue the counters
    with fluid.unique_name.guard(), fluid.program_guard(main, startup):
        src_ids = fluid.layers.data(name="src_ids", shape=[src_len, 1], dtype="int64")
        src_pos = fluid.layers.data(name="src_pos", shape=[src_len, 1], dtype="int64")
        src_mask = fluid.layers.data(name="src_mask", shape=[src_len, 1], dtype="float32")
        tgt_ids = fluid.layers.data(name="tgt_ids", shape=[tgt_len, 1], dtype="int64")
        tgt_pos = fluid.layers.data(name="tgt_pos", shape=[tgt_len, 1], dtype="int64")
        tgt_mask = fluid.layers.data(name="tgt_mask", shape=[tgt_len, 1], dtype="float32")
        causal = _causal_const(tgt_len)
        logits = transformer(
            cfg, src_ids, src_pos, src_mask, tgt_ids, tgt_pos, tgt_mask, causal
        )
    feeds = ["src_ids", "src_pos", "src_mask", "tgt_ids", "tgt_pos", "tgt_mask"]
    return main, feeds, logits


def beam_search_decode(exe, infer_prog, logits, cfg, src, bos_id, eos_id,
                       beam_size=4, max_len=None, scope=None,
                       length_penalty=0.0, src_pad_id=None):
    """Beam-search NMT decoding over the fixed-shape inference program
    (reference: beam_search_op.cc + beam_search_decode_op.cc semantics —
    log-prob accumulated beams, finished-beam freezing, length penalty).

    src: [N, S] int64. Returns (sequences [N, beam, max_len] int64,
    scores [N, beam]) sorted best-first."""
    import numpy as np

    N, S = src.shape
    # the infer program's target length is baked into its shapes
    T = infer_prog.global_block().var("tgt_ids").shape[1]
    if max_len is not None and max_len != T:
        raise ValueError(
            "max_len=%d but the infer program was built with tgt_len=%d"
            % (max_len, T)
        )
    K = beam_size
    V = cfg.tgt_vocab

    src_b = np.repeat(src, K, axis=0)  # [N*K, S]
    src_pos = np.tile(np.arange(S, dtype=np.int64), (N * K, 1))
    if src_pad_id is not None:  # variable-length sources padded with pad_id
        src_mask = (src_b != src_pad_id).astype("float32")
    else:
        src_mask = np.ones((N * K, S), "float32")

    seqs = np.full((N * K, T), eos_id, np.int64)
    seqs[:, 0] = bos_id
    scores = np.full((N, K), -1e9, np.float64)
    scores[:, 0] = 0.0  # first step expands only beam 0 (identical prefixes)
    finished = np.zeros((N, K), bool)

    for t in range(T - 1):
        feed = {
            "src_ids": src_b[..., None],
            "src_pos": src_pos[..., None],
            "src_mask": src_mask[..., None],
            "tgt_ids": seqs[..., None],
            "tgt_pos": np.tile(np.arange(T, dtype=np.int64), (N * K, 1))[..., None],
            "tgt_mask": (np.arange(T) <= t)[None, :].repeat(N * K, 0).astype(
                "float32"
            )[..., None],
        }
        (lg,) = exe.run(infer_prog, feed=feed, fetch_list=[logits], scope=scope)
        lg = np.asarray(lg).reshape(N, K, T, V)[:, :, t, :]  # [N, K, V]
        logp = lg - np.log(np.exp(lg - lg.max(-1, keepdims=True)).sum(-1, keepdims=True)) - lg.max(-1, keepdims=True)
        # frozen beams only extend with eos at no cost
        logp = np.where(
            finished[..., None],
            np.where(np.arange(V)[None, None, :] == eos_id, 0.0, -1e9),
            logp,
        )
        total = scores[..., None] + logp  # [N, K, V]
        flat = total.reshape(N, K * V)
        top = np.argsort(-flat, axis=1)[:, :K]  # [N, K]
        new_scores = np.take_along_axis(flat, top, axis=1)
        beam_idx = top // V
        tok = top % V
        new_seqs = np.empty_like(seqs.reshape(N, K, T))
        for n in range(N):
            new_seqs[n] = seqs.reshape(N, K, T)[n, beam_idx[n]]
            new_seqs[n, :, t + 1] = tok[n]
        seqs = new_seqs.reshape(N * K, T)
        finished = np.take_along_axis(finished, beam_idx, axis=1) | (
            tok == eos_id
        )
        scores = new_scores
        if finished.all():
            break

    if length_penalty > 0:
        lens = (seqs.reshape(N, K, T) != eos_id).sum(-1)
        scores = scores / ((5.0 + lens) / 6.0) ** length_penalty
    order = np.argsort(-scores, axis=1)
    seqs = np.take_along_axis(
        seqs.reshape(N, K, T), order[..., None], axis=1
    )
    scores = np.take_along_axis(scores, order, axis=1)
    return seqs, scores
