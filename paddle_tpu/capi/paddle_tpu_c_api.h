/* C training/inference API (reference: paddle/fluid/framework/c/c_api.cc,
 * inference/capi/, train/demo/demo_trainer.cc).
 *
 * The runtime is the Python/JAX engine embedded via CPython; this header is
 * the stable C surface for embedding without writing Python. */
#ifndef PADDLE_TPU_C_API_H_
#define PADDLE_TPU_C_API_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

/* Initialize the embedded runtime. repo_root may be NULL if paddle_tpu is
 * importable from the default sys.path. Returns 0 on success. */
int pt_capi_init(const char* repo_root);

/* Load a program saved by fluid.io.save / save_inference_model.
 * kind: 0 = program state dir (train), 1 = inference model dir.
 * Returns a handle (>0) or -1. */
int64_t pt_capi_load_program(const char* path, int kind);

/* Build the reference train/demo program in-process: a linear regression
 * y = xW + b with SGD, returns a handle usable with pt_capi_run. */
int64_t pt_capi_demo_program(void);

/* Run one step: feeds are float32 row-major buffers. Returns 0 on success
 * and writes the first fetch value into *out_loss. */
int pt_capi_run(int64_t handle, const char** feed_names,
                const float** feed_bufs, const int64_t* feed_shapes,
                const int* feed_ndims, int n_feeds, double* out_loss);

/* Tear down the embedded runtime. */
void pt_capi_destroy(void);

#ifdef __cplusplus
}
#endif

#endif /* PADDLE_TPU_C_API_H_ */
