/* C API implementation: embeds CPython and drives the Python-side bridge
 * (paddle_tpu/capi/bridge.py). See header for the reference counterparts. */
#include "paddle_tpu_c_api.h"

#include <Python.h>

#include <string>

static PyObject* g_bridge = nullptr;

int pt_capi_init(const char* repo_root) {
  if (!Py_IsInitialized()) Py_Initialize();
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  do {
    if (repo_root) {
      PyObject* sys_path = PySys_GetObject("path");
      PyObject* p = PyUnicode_FromString(repo_root);
      PyList_Insert(sys_path, 0, p);
      Py_DECREF(p);
    }
    PyObject* mod = PyImport_ImportModule("paddle_tpu.capi.bridge");
    if (!mod) {
      PyErr_Print();
      break;
    }
    g_bridge = mod;
    rc = 0;
  } while (0);
  PyGILState_Release(gil);
  return rc;
}

static int64_t call_i64(const char* fn, PyObject* args) {
  PyGILState_STATE gil = PyGILState_Ensure();
  int64_t out = -1;
  PyObject* f = PyObject_GetAttrString(g_bridge, fn);
  if (f) {
    PyObject* r = PyObject_CallObject(f, args);
    if (r) {
      out = PyLong_AsLongLong(r);
      Py_DECREF(r);
    } else {
      PyErr_Print();
    }
    Py_DECREF(f);
  }
  Py_XDECREF(args);
  PyGILState_Release(gil);
  return out;
}

int64_t pt_capi_load_program(const char* path, int kind) {
  return call_i64("load_program", Py_BuildValue("(si)", path, kind));
}

int64_t pt_capi_demo_program(void) {
  return call_i64("demo_program", PyTuple_New(0));
}

int pt_capi_run(int64_t handle, const char** feed_names,
                const float** feed_bufs, const int64_t* feed_shapes,
                const int* feed_ndims, int n_feeds, double* out_loss) {
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  do {
    PyObject* feeds = PyDict_New();
    int off = 0;
    for (int i = 0; i < n_feeds; i++) {
      PyObject* shape = PyList_New(feed_ndims[i]);
      int64_t numel = 1;
      for (int d = 0; d < feed_ndims[i]; d++) {
        PyList_SetItem(shape, d, PyLong_FromLongLong(feed_shapes[off + d]));
        numel *= feed_shapes[off + d];
      }
      off += feed_ndims[i];
      PyObject* buf = PyBytes_FromStringAndSize(
          reinterpret_cast<const char*>(feed_bufs[i]),
          static_cast<Py_ssize_t>(numel * sizeof(float)));
      PyObject* pair = PyTuple_Pack(2, buf, shape);
      PyDict_SetItemString(feeds, feed_names[i], pair);
      Py_DECREF(pair);
      Py_DECREF(buf);
      Py_DECREF(shape);
    }
    PyObject* f = PyObject_GetAttrString(g_bridge, "run_step");
    if (!f) break;
    PyObject* r = PyObject_CallFunction(f, "LO", (long long)handle, feeds);
    Py_DECREF(f);
    Py_DECREF(feeds);
    if (!r) {
      PyErr_Print();
      break;
    }
    if (out_loss) *out_loss = PyFloat_AsDouble(r);
    Py_DECREF(r);
    rc = 0;
  } while (0);
  PyGILState_Release(gil);
  return rc;
}

void pt_capi_destroy(void) {
  Py_XDECREF(g_bridge);
  g_bridge = nullptr;
}
