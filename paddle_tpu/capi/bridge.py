"""Python side of the C API (paddle_tpu_c_api.cpp calls into this).

Holds (program, scope, executor, loss) sessions in a registry keyed by
handle; the C side only moves primitive buffers across the boundary."""

from __future__ import annotations

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

# a sitecustomize may have pinned jax_platforms via config, which beats the
# env var; embedded C hosts default to the CPU backend unless the caller
# exported a platform choice themselves
jax.config.update("jax_platforms", os.environ.get("JAX_PLATFORMS", "cpu"))

import numpy as np  # noqa: E402

_sessions = {}
_next = [1]


def _register(entry):
    h = _next[0]
    _next[0] += 1
    _sessions[h] = entry
    return h


def demo_program():
    """The reference train/demo program: linear regression + SGD
    (paddle/fluid/train/demo/demo_trainer.cc builds it from a saved model;
    here it is built directly so the demo is self-contained)."""
    import paddle_tpu.fluid as fluid

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 1
    with fluid.unique_name.guard(), fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[13], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(input=x, size=1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.01).minimize(
            loss, startup_program=startup
        )
    scope = fluid.core.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    return _register(
        dict(exe=exe, program=main, scope=scope, fetch=loss)
    )


def load_program(path, kind):
    import paddle_tpu.fluid as fluid

    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    if kind == 1:
        program, feed_names, fetch_vars = fluid.io.load_inference_model(
            path, exe
        )
        fetch = fetch_vars[0]
    elif kind == 0:
        # a consolidated fluid.io.save(program, path) bundle:
        # path.pdmodel (program) + path.pdparams/.pdopt (state)
        from paddle_tpu.fluid import proto

        with open(path + ".pdmodel", "rb") as f:
            program = proto.program_from_bytes(f.read())
        # io.load restores into the global scope; run this session there
        scope = fluid.global_scope()
        fluid.io.load(program, path, exe)
        # first fetchable loss-like var: last mean output, else last var
        fetch = None
        for op_ in program.global_block().ops:
            if op_.type == "mean":
                fetch = program.global_block().vars[
                    op_.output("Out")[0]
                ]
        if fetch is None:
            raise ValueError("no loss (mean) op found in saved program")
    else:
        raise ValueError("unknown kind=%d" % kind)
    return _register(
        dict(exe=exe, program=program, scope=scope, fetch=fetch)
    )


def run_step(handle, feeds):
    s = _sessions[int(handle)]
    feed = {}
    for name, (buf, shape) in feeds.items():
        feed[name] = np.frombuffer(buf, np.float32).reshape(
            [int(v) for v in shape]
        ).copy()
    outs = s["exe"].run(
        s["program"], feed=feed, fetch_list=[s["fetch"]], scope=s["scope"]
    )
    return float(np.asarray(outs[0]).ravel()[0])
