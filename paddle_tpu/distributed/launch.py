"""Multi-process launcher (reference: python/paddle/distributed/launch.py —
start_procs:147 spawns one proc per device and wires PADDLE_TRAINER_ID /
PADDLE_CURRENT_ENDPOINT / PADDLE_TRAINER_ENDPOINTS env).

TPU-native: one process per HOST (each process owns its local chips through
jax; per-chip parallelism is SPMD inside the process, not process-per-chip as
with CUDA). The same env contract is kept, plus JAX_* coordinator vars so
jax.distributed can bootstrap over DCN.

The launcher is a supervising agent (distributed/supervisor.py), not a
spawn-and-wait loop: worker crashes and heartbeat stalls tear down the
whole gang (one dead rank deadlocks every peer of the collective) and —
with ``--max_restarts > 0`` — restart it with exponential backoff,
resuming from the newest committed checkpoint (paddle_tpu/checkpoint).
SIGTERM preemption keeps its PR 3 contract: forwarded to workers (their
handlers commit one final save), grace window, SIGKILL survivors,
exit 143."""

from __future__ import annotations

import argparse
import os
import sys

from . import supervisor as _supervisor


def _parse_args(argv=None):
    parser = argparse.ArgumentParser(description="paddle_tpu distributed launcher")
    parser.add_argument(
        "--cluster_node_ips", type=str, default="127.0.0.1",
        help="comma-separated host ips",
    )
    parser.add_argument("--node_ip", type=str, default="127.0.0.1")
    parser.add_argument("--started_port", type=int, default=6170)
    parser.add_argument("--print_config", type=bool, default=True)
    parser.add_argument(
        "--nproc_per_node", type=int, default=1,
        help="processes per node (default 1: SPMD owns all local chips)",
    )
    parser.add_argument("--selected_gpus", type=str, default=None)
    parser.add_argument("--log_level", type=int, default=20)
    parser.add_argument("--log_dir", type=str, default=None)
    parser.add_argument(
        "--sigterm_grace_s", type=float, default=30.0,
        help="on SIGTERM: forward it to workers (their preemption "
        "handlers run one final checkpoint save), then SIGKILL "
        "survivors after this many seconds",
    )
    parser.add_argument(
        "--max_restarts", type=int, default=0,
        help="elastic restart budget: after a worker crash or heartbeat "
        "stall the supervisor tears the gang down and relaunches it up "
        "to this many times (workers resume from their newest committed "
        "checkpoint); 0 keeps the legacy fail-fast behavior",
    )
    parser.add_argument(
        "--max_preempt_restarts", type=int, default=None,
        help="separate restart budget for PREEMPTED workers (exit 143 / "
        "SIGTERM death / unspawnable slot): on a preemptible pool these "
        "are the normal lifecycle and must not consume --max_restarts "
        "(default FLAGS_dist_max_preempt_restarts)",
    )
    parser.add_argument(
        "--min_world_size", type=int, default=None,
        help="elastic resize floor: a restart may shrink the gang to "
        "the launchable survivors (rank ids remapped contiguously, new "
        "topology injected via PADDLE_TPU_WORLD_SIZE/PADDLE_TPU_RANK) "
        "as long as at least this many remain, growing back when downed "
        "slots return; unset/0 = fixed-size restarts only "
        "(default FLAGS_elastic_min_world_size)",
    )
    parser.add_argument(
        "--heartbeat_timeout_s", type=float, default=None,
        help="hang watchdog: a running worker whose heartbeat file "
        "(written each step by the trainer) goes stale beyond this is "
        "killed with the gang (default FLAGS_dist_heartbeat_timeout_s)",
    )
    parser.add_argument(
        "--startup_grace_s", type=float, default=None,
        help="staleness bound before a worker's FIRST heartbeat; unset "
        "= never hang-kill a worker that has not proven it beats "
        "(workers that did beat 'start' fall back to "
        "FLAGS_dist_startup_grace_s for the restore/compile window)",
    )
    parser.add_argument(
        "--supervisor_dir", type=str, default=None,
        help="where supervisor.log + heartbeat files live "
        "(default: --log_dir, else a temp dir)",
    )
    parser.add_argument(
        "training_script", type=str,
        help="the training script followed by its arguments",
    )
    parser.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return parser.parse_args(argv)


def build_specs(args):
    """Per-rank WorkerSpecs carrying the reference env contract
    (reference: launch.py:147 start_procs env wiring)."""
    node_ips = [ip.strip() for ip in args.cluster_node_ips.split(",")]
    node_id = node_ips.index(args.node_ip)
    num_nodes = len(node_ips)
    nproc = args.nproc_per_node
    all_endpoints = [
        "%s:%d" % (ip, args.started_port + i)
        for ip in node_ips
        for i in range(nproc)
    ]
    nranks = num_nodes * nproc
    coordinator = "%s:%d" % (node_ips[0], args.started_port + 1000)

    specs = []
    for i in range(nproc):
        rank = node_id * nproc + i
        current_endpoint = "%s:%d" % (args.node_ip, args.started_port + i)
        proc_env = {
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_CURRENT_ENDPOINT": current_endpoint,
            "PADDLE_TRAINERS_NUM": str(nranks),
            "PADDLE_TRAINER_ENDPOINTS": ",".join(all_endpoints),
            # jax.distributed bootstrap over DCN
            "JAX_COORDINATOR_ADDRESS": coordinator,
            "JAX_NUM_PROCESSES": str(nranks),
            "JAX_PROCESS_ID": str(rank),
        }
        cmd = [sys.executable, "-u", args.training_script] + list(
            args.training_script_args
        )
        log_path = (
            os.path.join(args.log_dir, "workerlog.%d" % i)
            if args.log_dir else None
        )
        specs.append(_supervisor.WorkerSpec(
            cmd, env=proc_env, log_path=log_path, rank=rank,
        ))
    return specs


def start_procs(args):
    """reference: launch.py:147 start_procs — now supervised: crashes
    and hangs tear down the whole gang; with --max_restarts the gang is
    relaunched (exponential backoff) and workers resume from their
    newest committed checkpoint; SIGTERM preemption exits 143."""
    import tempfile

    workdir = args.supervisor_dir or args.log_dir
    if workdir is None:
        workdir = tempfile.mkdtemp(prefix="paddle_tpu_supervisor_")
    sup = _supervisor.Supervisor(
        build_specs(args),
        workdir=workdir,
        max_restarts=args.max_restarts,
        max_preempt_restarts=args.max_preempt_restarts,
        min_world_size=args.min_world_size,
        heartbeat_timeout_s=args.heartbeat_timeout_s,
        startup_grace_s=args.startup_grace_s,
        sigterm_grace_s=args.sigterm_grace_s,
    )
    rc = sup.run()
    if rc != 0:
        if sup.failure_report is not None:
            # with no restart budget the accurate diagnosis is the
            # worker failure itself, not "budget exhausted"
            what = (
                "restart budget exhausted" if args.max_restarts > 0
                else "worker failed"
            )
            print(
                "launch: %s: %s" % (what, sup.failure_report),
                file=sys.stderr,
            )
        sys.exit(rc)


def launch():
    args = _parse_args()
    if args.print_config:
        print(
            "launch %d procs on node %s (of %s)"
            % (args.nproc_per_node, args.node_ip, args.cluster_node_ips)
        )
    start_procs(args)


if __name__ == "__main__":
    launch()
