"""Multi-process launcher (reference: python/paddle/distributed/launch.py —
start_procs:147 spawns one proc per device and wires PADDLE_TRAINER_ID /
PADDLE_CURRENT_ENDPOINT / PADDLE_TRAINER_ENDPOINTS env).

TPU-native: one process per HOST (each process owns its local chips through
jax; per-chip parallelism is SPMD inside the process, not process-per-chip as
with CUDA). The same env contract is kept, plus JAX_* coordinator vars so
jax.distributed can bootstrap over DCN."""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys


def _parse_args(argv=None):
    parser = argparse.ArgumentParser(description="paddle_tpu distributed launcher")
    parser.add_argument(
        "--cluster_node_ips", type=str, default="127.0.0.1",
        help="comma-separated host ips",
    )
    parser.add_argument("--node_ip", type=str, default="127.0.0.1")
    parser.add_argument("--started_port", type=int, default=6170)
    parser.add_argument("--print_config", type=bool, default=True)
    parser.add_argument(
        "--nproc_per_node", type=int, default=1,
        help="processes per node (default 1: SPMD owns all local chips)",
    )
    parser.add_argument("--selected_gpus", type=str, default=None)
    parser.add_argument("--log_level", type=int, default=20)
    parser.add_argument("--log_dir", type=str, default=None)
    parser.add_argument(
        "--sigterm_grace_s", type=float, default=30.0,
        help="on SIGTERM: forward it to workers (their preemption "
        "handlers run one final checkpoint save), then SIGKILL "
        "survivors after this many seconds",
    )
    parser.add_argument(
        "training_script", type=str,
        help="the training script followed by its arguments",
    )
    parser.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return parser.parse_args(argv)


def start_procs(args):
    """reference: launch.py:147 start_procs."""
    procs = []
    log_fns = []
    node_ips = [ip.strip() for ip in args.cluster_node_ips.split(",")]
    node_id = node_ips.index(args.node_ip)
    num_nodes = len(node_ips)
    nproc = args.nproc_per_node
    all_endpoints = [
        "%s:%d" % (ip, args.started_port + i)
        for ip in node_ips
        for i in range(nproc)
    ]
    nranks = num_nodes * nproc
    coordinator = "%s:%d" % (node_ips[0], args.started_port + 1000)

    current_env = copy_env = dict(os.environ)
    _ = copy_env
    for i in range(nproc):
        rank = node_id * nproc + i
        current_endpoint = "%s:%d" % (args.node_ip, args.started_port + i)
        proc_env = dict(current_env)
        proc_env.update(
            {
                "PADDLE_TRAINER_ID": str(rank),
                "PADDLE_CURRENT_ENDPOINT": current_endpoint,
                "PADDLE_TRAINERS_NUM": str(nranks),
                "PADDLE_TRAINER_ENDPOINTS": ",".join(all_endpoints),
                # jax.distributed bootstrap over DCN
                "JAX_COORDINATOR_ADDRESS": coordinator,
                "JAX_NUM_PROCESSES": str(nranks),
                "JAX_PROCESS_ID": str(rank),
            }
        )
        cmd = [sys.executable, "-u", args.training_script] + list(
            args.training_script_args
        )
        if args.log_dir:
            os.makedirs(args.log_dir, exist_ok=True)
            fn = open("%s/workerlog.%d" % (args.log_dir, i), "w")
            log_fns.append(fn)
            proc = subprocess.Popen(cmd, env=proc_env, stdout=fn, stderr=fn)
        else:
            proc = subprocess.Popen(cmd, env=proc_env)
        procs.append(proc)

    # preemption contract (paddle_tpu/checkpoint): when the fleet
    # scheduler SIGTERMs the launcher, forward the signal to every worker
    # so their PreemptionHandlers commit one final synchronous save, give
    # them a grace window, then SIGKILL any survivor and exit 143.
    preempted = {"flag": False}

    def _on_sigterm(signum, frame):
        preempted["flag"] = True
        terminate_procs(procs)

    prev_handler = None
    try:
        prev_handler = signal.signal(signal.SIGTERM, _on_sigterm)
    except ValueError:
        pass  # not the main thread; no forwarding possible

    import time

    try:
        alive = True
        error = False
        while alive and not error and not preempted["flag"]:
            alive = False
            for p in procs:
                ret = p.poll()
                if ret is None:
                    alive = True
                elif ret != 0 and not preempted["flag"]:
                    error = True
            time.sleep(0.25)
        if preempted["flag"]:
            deadline = time.monotonic() + args.sigterm_grace_s
            while any(p.poll() is None for p in procs):
                if time.monotonic() > deadline:
                    for p in procs:
                        if p.poll() is None:
                            p.kill()
                    break
                time.sleep(0.25)
            sys.exit(128 + signal.SIGTERM)
        if error:
            terminate_procs(procs)
            sys.exit(1)
    except KeyboardInterrupt:
        terminate_procs(procs)
        raise
    finally:
        if prev_handler is not None:
            try:
                signal.signal(signal.SIGTERM, prev_handler)
            except ValueError:
                pass
        for fn in log_fns:
            fn.close()


def terminate_procs(procs):
    for p in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGTERM)


def launch():
    args = _parse_args()
    if args.print_config:
        print(
            "launch %d procs on node %s (of %s)"
            % (args.nproc_per_node, args.node_ip, args.cluster_node_ips)
        )
    start_procs(args)


if __name__ == "__main__":
    launch()
