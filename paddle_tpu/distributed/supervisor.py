"""Elastic gang supervisor: crash/hang detection + restart with resume.

The Fluid-era reference kept jobs alive with ad-hoc pieces (the pserver
``HeartBeatMonitor``, ``checkpoint_notify``); the launcher itself just
spawned workers and waited. On a preemptible TPU pool that is fatal: one
SIGKILLed or silently hung worker deadlocks every peer of the collective
and the job dies without retry. The supervisor closes the loop:

- **Liveness**: every worker writes a heartbeat file (step, timestamp,
  status, pid — atomic tmp+rename) via a runtime hook in
  ``fluid/trainer.py``; the env var ``PADDLE_TPU_HEARTBEAT_FILE`` names
  it and is injected per rank by the supervisor.
- **Detection**: a poll loop watches process exits (crash = any nonzero
  exit) and heartbeat staleness (hang = a live worker whose newest beat
  is older than ``FLAGS_dist_heartbeat_timeout_s``; before the first
  beat a separate ``startup_grace_s`` covers imports + XLA compile).
- **Teardown**: ANY failure kills the WHOLE gang — a torn collective
  cannot make progress — via the PR 3 preemption path: SIGTERM (workers'
  PreemptionHandlers commit a final save when they still can), grace
  window, then SIGKILL survivors.
- **Restart**: exponential backoff with jitter
  (``FLAGS_dist_restart_backoff_s`` base, capped) under a restart budget
  (``max_restarts``); workers resume bit-exactly through
  ``CheckpointManager.restore_or_initialize`` (PR 3) — the supervisor
  itself is stateless about training progress. Workers that exit 143 /
  die to SIGTERM are *preempted*, not crashed: they draw from a separate
  (generous) ``max_preempt_restarts`` budget, so a preemption-churny
  pool can't eat the crash-loop budget.
- **Elastic resize** (any explicit ``min_world_size``): every restart
  re-plans the gang instead of assuming the full spec list. A
  launchability probe (``elastic.read_down_marker`` over
  ``workdir/avail/down_slot_<r>.json`` — written by the chaos
  ``lose_rank`` fault, by the supervisor itself on a spawn failure, or
  by an external scheduler) picks the available slots; the gang shrinks
  to the survivors (never below ``min_world_size``), rank ids are
  remapped contiguously, and the new topology is injected via
  ``PADDLE_TPU_WORLD_SIZE`` / ``PADDLE_TPU_RANK`` (plus remapped legacy
  ``PADDLE_TRAINER_*`` / ``JAX_*`` vars when the spec carried them).
  When a marker expires — ``down_for`` plans have observed it, or the
  file is deleted — the slot rejoins at the next restart boundary and
  the gang grows back. Resize decisions land as ``gang_resize`` events
  and the ``dist_resizes`` counter; each ``gang_start`` records the
  attempt's world size and rank->pid map so a resized run is auditable
  post-hoc. (Single-node scope: remapping cannot re-home a lost
  multi-node DCN coordinator — that needs a rendezvous service.)
- **SDC quarantine** (training guardian, ISSUE 14): workers publish a
  cross-replica state digest ring through their heartbeat files every
  ``FLAGS_guardian_digest_interval`` steps; the monitor majority-votes
  each complete round (>= 3 voters) and quarantines a diverging rank —
  open-ended down marker on its slot, ``replica_quarantined`` event,
  ``sdc_quarantines`` counter — then restarts the gang under the
  preempt budget (elastic gangs resize around the quarantined slot and
  resume from checkpoint). A worker mid checkpoint-restore beats
  ``status="rollback"`` and is judged under the startup-style
  instrumented grace, so a multi-second restore is never hang-killed.
- **Observability**: structured JSONL events in ``supervisor.log``
  (gang_start / worker_exit / crash_detected / hang_detected /
  gang_teardown / restart / gang_done / giveup / preempted; each
  carries ``schema_version``, wall-clock ``ts`` and monotonic
  ``ts_mono``) plus always-on profiler counters ``dist_restarts`` /
  ``dist_hang_kills`` and the ``dist_downtime_ms`` histogram (failure
  detection -> next gang start; MTTR for ``tools/dist_crash_probe.py``).
  The supervisor also injects ``FLAGS_obs_dir`` into every worker so
  each rank leaves JSONL telemetry snapshots, and merges them with this
  log into ``workdir/gang_report.json`` on every restart and on exit
  (``observability/aggregate.py``).
"""

from __future__ import annotations

import collections
import json
import os
import random
import signal
import subprocess
import sys
import threading
import time

from . import elastic

__all__ = [
    "HEARTBEAT_ENV",
    "RESTART_ENV",
    "WorkerHeartbeat",
    "worker_heartbeat",
    "read_heartbeat",
    "WorkerSpec",
    "Supervisor",
    "load_events",
]

HEARTBEAT_ENV = "PADDLE_TPU_HEARTBEAT_FILE"
RESTART_ENV = "PADDLE_TPU_RESTART_NUM"
SUPERVISOR_LOG = "supervisor.log"
# JSONL event schema: 1 added schema_version itself plus ts_mono (the
# monotonic-clock twin of the wall-clock ts — downtime/MTTR math must
# survive an NTP step; ts stays for humans and cross-host correlation)
LOG_SCHEMA_VERSION = 1


# ---------------------------------------------------------------------------
# worker-side heartbeat (the fluid/trainer.py runtime hook lands here)
# ---------------------------------------------------------------------------
def _flag(name, default):
    try:
        from ..fluid import flags as _flags

        return _flags.get_flag(name, default)
    except Exception:
        return default


class WorkerHeartbeat(object):
    """Throttled atomic progress file: ``{pid, step, status, time}``.

    ``beat()`` is called once per training step; writes are throttled to
    ``interval_s`` (FLAGS_dist_heartbeat_interval_s) so a fast step loop
    never turns into fs churn, and status transitions always force a
    write. Staleness detection on the supervisor side uses the file's
    mtime, so the write itself IS the beat."""

    def __init__(self, path, interval_s=None):
        self.path = str(path)
        self.interval_s = float(
            _flag("dist_heartbeat_interval_s", 0.5)
            if interval_s is None else interval_s
        )
        self._last_write = 0.0
        self._last_status = None
        # cross-replica SDC digests ride the heartbeat file as a small
        # ring of the newest (step, digest) pairs: every beat carries
        # the ring, so the supervisor's poll loop can miss individual
        # writes and still reconstruct every publish round
        self._digests = collections.deque(maxlen=8)
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)

    def publish_digest(self, step, digest):
        """Record one (step, digest) pair in the ring and force a beat
        — the worker half of the supervisor's SDC majority vote."""
        self._digests.append((int(step), str(digest)))
        return self.beat(step, force=True)

    def beat(self, step, status="step", force=False):
        now = time.monotonic()
        if (not force and status == self._last_status
                and now - self._last_write < self.interval_s):
            return False
        record = {
            "pid": os.getpid(),
            "step": int(step),
            "status": str(status),
            "time": time.time(),
        }
        if self._digests:
            record["digests"] = [[s, d] for s, d in self._digests]
        payload = json.dumps(record)
        tmp = "%s.tmp.%d" % (self.path, os.getpid())
        try:
            with open(tmp, "w") as f:
                f.write(payload)
            os.replace(tmp, self.path)
        except OSError:
            return False  # liveness reporting must never kill the worker
        self._last_write = now
        self._last_status = status
        return True


def worker_heartbeat(interval_s=None):
    """The heartbeat this process should write to, or None when not
    running under a supervisor (PADDLE_TPU_HEARTBEAT_FILE unset)."""
    path = os.environ.get(HEARTBEAT_ENV)
    if not path:
        return None
    return WorkerHeartbeat(path, interval_s=interval_s)


def read_heartbeat(path):
    """Parse one heartbeat file -> dict with an added ``mtime``, or None
    when absent/torn (a torn read loses one poll tick, nothing else)."""
    try:
        mtime = os.stat(path).st_mtime
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    data["mtime"] = mtime
    return data


# ---------------------------------------------------------------------------
# supervisor side
# ---------------------------------------------------------------------------
class WorkerSpec(object):
    """One gang member: argv, env overlay, and an optional log path the
    supervisor appends stdout+stderr to (one file across restarts, with
    an attempt banner between runs)."""

    def __init__(self, cmd, env=None, log_path=None, rank=None):
        self.cmd = list(cmd)
        self.env = dict(env or {})
        self.log_path = log_path
        self.rank = rank


class _Log(object):
    """Append-only JSONL event log (workdir/supervisor.log)."""

    def __init__(self, path, echo=False):
        self.path = path
        self.echo = echo
        self._lock = threading.Lock()

    def event(self, event, **fields):
        rec = dict(fields)
        rec["event"] = event
        rec["schema_version"] = LOG_SCHEMA_VERSION
        rec["ts"] = time.time()  # wall clock, for humans
        rec["ts_mono"] = time.monotonic()  # for interval math
        line = json.dumps(rec, sort_keys=True)
        with self._lock:
            with open(self.path, "a") as f:
                f.write(line + "\n")
        if self.echo:
            print("[supervisor] %s" % line, flush=True)
        return rec


def load_events(workdir, filename=SUPERVISOR_LOG):
    """Parse workdir/supervisor.log back into a list of event dicts
    (the probe's MTTR source). ``filename`` selects another log in the
    same JSONL dialect (the serving fleet's ``fleet.log`` reuses this
    parser and the ``_Log`` writer)."""
    path = os.path.join(workdir, filename)
    events = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    events.append(json.loads(line))
                except ValueError:
                    continue
    except OSError:
        pass
    return events


class GangOutcome(object):
    DONE = "done"
    CRASH = "crash"
    HANG = "hang"
    PREEMPTED = "preempted"  # the SUPERVISOR caught SIGTERM: exit 143
    # one WORKER exited 143 / died to SIGTERM (slice preemption): restart
    # under the separate preempt budget, re-planning the world size
    WORKER_PREEMPT = "worker_preempt"
    # the cross-replica digest vote quarantined a diverging rank: its
    # slot is marked down (suspect hardware), the gang restarts under
    # the preempt budget — SDC is the pool's lifecycle, not a crash loop
    SDC = "sdc_quarantine"


class _SpawnFailed(Exception):
    """A worker could not be spawned (its slot is unlaunchable)."""

    def __init__(self, slot, error):
        super().__init__("slot %s: %s" % (slot, error))
        self.slot = slot
        self.error = error


class Supervisor(object):
    """Supervising agent over one gang of worker processes.

    ``run()`` drives start -> monitor -> (teardown -> backoff ->
    restart)* until the gang completes, the restart budget is exhausted,
    or the supervisor itself is preempted. Exit codes follow the
    launcher's conventions: 0 done, 1 budget exhausted (a structured
    ``giveup`` report is logged and returned via ``failure_report``),
    143 preempted."""

    def __init__(self, specs, workdir, max_restarts=0,
                 heartbeat_timeout_s=None, startup_grace_s=None,
                 backoff_base_s=None, backoff_max_s=None,
                 sigterm_grace_s=5.0, poll_s=0.1, seed=None,
                 echo_events=False, min_world_size=None,
                 max_preempt_restarts=None):
        self.specs = list(specs)
        self.workdir = str(workdir)
        self.max_restarts = int(max_restarts)
        # preemptions (worker exit 143 / SIGTERM death, spawn failure on
        # a downed slot) draw from their own, deliberately generous,
        # budget: on a preemptible pool they are the NORMAL lifecycle,
        # and must not eat the crash-loop budget
        self.max_preempt_restarts = int(
            _flag("dist_max_preempt_restarts", 100)
            if max_preempt_restarts is None else max_preempt_restarts
        )
        # elastic floor: a restart may shrink the gang to the launchable
        # survivors as long as at least this many remain. Unset/0 means
        # "full size only" — the PR 4 fixed-gang behavior (availability
        # markers are then ignored entirely).
        mws = int(
            _flag("elastic_min_world_size", 0)
            if min_world_size is None else min_world_size
        )
        self.min_world_size = (
            min(mws, len(self.specs)) if mws > 0 else len(self.specs)
        )
        # any explicit floor arms the availability probe — even a floor
        # equal to the world size (then a downed slot means giveup, not
        # a blind full-size launch that crash-loops on the dead host)
        self._elastic = mws > 0
        self.heartbeat_timeout_s = float(
            _flag("dist_heartbeat_timeout_s", 60.0)
            if heartbeat_timeout_s is None else heartbeat_timeout_s
        )
        # the watchdog threshold must clear the worker-side beat
        # throttle (the same flag env reaches both sides): a throttle at
        # or above the timeout would hang-kill every HEALTHY worker
        # between two legitimate beats
        beat_interval = float(_flag("dist_heartbeat_interval_s", 0.5))
        self.heartbeat_timeout_s = max(
            self.heartbeat_timeout_s, 2.0 * beat_interval
        )
        # Pre-first-STEP staleness bounds. A worker that never beats at
        # all (not routed through the fluid.trainer hook) is
        # unobservable and must not be killed for its silence unless an
        # explicit grace was configured — crash detection still covers
        # it. A worker whose beat says status "start" HAS proven it is
        # instrumented, so a hang in jax re-init / restore / the first
        # XLA compile is detectable: it gets the configured grace, or a
        # generous finite default (big models compile for minutes, but
        # not forever).
        self.startup_grace_s = (
            None if startup_grace_s is None else float(startup_grace_s)
        )
        self._instrumented_grace_s = (
            self.startup_grace_s if self.startup_grace_s is not None
            else float(_flag("dist_startup_grace_s", 600.0))
        )
        self.backoff_base_s = float(
            _flag("dist_restart_backoff_s", 1.0)
            if backoff_base_s is None else backoff_base_s
        )
        self.backoff_max_s = float(
            _flag("dist_restart_backoff_max_s", 30.0)
            if backoff_max_s is None else backoff_max_s
        )
        self.sigterm_grace_s = float(sigterm_grace_s)
        self.poll_s = float(poll_s)
        self.restarts_used = 0
        self.preempt_restarts_used = 0
        self.resizes = 0
        self.failure_report = None
        os.makedirs(self.workdir, exist_ok=True)
        self._hb_dir = os.path.join(self.workdir, "heartbeats")
        os.makedirs(self._hb_dir, exist_ok=True)
        # availability markers (elastic.read_down_marker) live here; one
        # file per SLOT (the spec's stable global rank) — written by the
        # chaos lose_rank fault, by _start_gang on a spawn failure, or
        # by an external scheduler marking a host down
        self._avail_dir = os.path.join(self.workdir, "avail")
        os.makedirs(self._avail_dir, exist_ok=True)
        # the previous attempt's plan (resize detection by MEMBERSHIP,
        # not just size: one slot returning while another goes down is a
        # resize even at constant world size); a fresh supervisor
        # measures its first plan against the full spec list, so
        # starting degraded IS a resize event
        self._plan_prev = list(range(len(self.specs)))
        # per-rank telemetry snapshots land here (FLAGS_obs_dir injected
        # into every worker env below); aggregate.py merges them + this
        # log into workdir/gang_report.json. _obs_dir is the injected
        # DEFAULT; the merge reads the EFFECTIVE dir (_spawn records it,
        # because an operator's explicit FLAGS_obs_dir wins the
        # setdefault and the snapshots land there instead)
        self._obs_dir = os.path.join(self.workdir, "obs")
        self._obs_dir_effective = self._obs_dir
        self.log = _Log(
            os.path.join(self.workdir, SUPERVISOR_LOG), echo=echo_events
        )
        # default (seed=None) draws from OS entropy: many hosts' gangs
        # crashed by one shared outage must NOT respawn in lockstep —
        # decorrelation is the whole point of the jitter. A fixed seed
        # is for tests wanting reproducible backoff.
        self._rng = random.Random(seed)
        self._procs = []  # list[(spec, Popen)]
        self._procs_lock = threading.Lock()
        self._log_files = []
        self._preempted = threading.Event()

    # -- public ------------------------------------------------------------

    def alive_pids(self):
        """{rank: pid} of currently-running workers (probe killer API)."""
        with self._procs_lock:
            return {
                (s.rank if s.rank is not None else i): p.pid
                for i, (s, p) in enumerate(self._procs)
                if p.poll() is None
            }

    def run(self):
        from ..fluid import profiler as _profiler

        prev = self._install_sigterm()
        # run boundary for log consumers (aggregate._last_run): in a
        # reused workdir the report must scope to THIS run, and the
        # first in-run event is not always a gang_start — a supervisor
        # that starts degraded emits gang_resize first, one that starts
        # below the floor emits only giveup
        self.log.event(
            "supervisor_boot", world_size=len(self.specs),
            min_world_size=self.min_world_size,
            max_restarts=self.max_restarts,
            max_preempt_restarts=self.max_preempt_restarts,
        )
        try:
            attempt = 0
            t_detect = None
            while True:
                t_plan = time.monotonic()
                plan = self._plan_gang()
                if len(plan) < self.min_world_size:
                    # fewer launchable slots than the floor: a resize
                    # cannot save this gang — structured giveup, the
                    # scheduler resubmits when capacity returns
                    self.failure_report = {
                        "reason": "insufficient_ranks",
                        "available": len(plan),
                        "min_world_size": self.min_world_size,
                        "world_size": len(self.specs),
                        "workdir": self.workdir,
                    }
                    self.log.event("giveup", **self.failure_report)
                    return 1
                if plan != self._plan_prev:
                    self.resizes += 1
                    _profiler.bump_counter("dist_resizes")
                    down = sorted(
                        set(self._slot(i) for i in range(len(self.specs)))
                        - set(self._slot(i) for i in plan)
                    )
                    self.log.event(
                        "gang_resize", restart=attempt,
                        from_world=len(self._plan_prev),
                        to_world=len(plan),
                        down_slots=down,
                        plan_ms=round(
                            (time.monotonic() - t_plan) * 1000.0, 3
                        ),
                    )
                self._plan_prev = plan
                try:
                    self._start_gang(attempt, plan)
                except _SpawnFailed as e:
                    # the slot is unlaunchable right now: mark it down
                    # for one planning round and treat the attempt as a
                    # preemption (bounded by the preempt budget). With
                    # elasticity off there is no replanning that could
                    # ever succeed differently — keep PR 4's fail-fast.
                    if not self._elastic:
                        raise e.error
                    elastic.write_down_marker(
                        self._down_path(e.slot), down_for=1, slot=e.slot,
                        from_attempt=attempt, reason="spawn_failed",
                    )
                    self.log.event(
                        "spawn_failed", restart=attempt, slot=e.slot,
                        error=str(e.error),
                    )
                    outcome = GangOutcome.WORKER_PREEMPT
                    detail = {"slot": e.slot, "spawn_error": str(e.error)}
                else:
                    if t_detect is not None:
                        # MTTR as documented: failure detection -> the
                        # replacement gang is SPAWNED (spawn cost
                        # included)
                        _profiler.bump_histogram(
                            "dist_downtime_ms",
                            (time.monotonic() - t_detect) * 1000.0,
                        )
                    outcome, detail = self._monitor()
                t_detect = time.monotonic()
                if outcome == GangOutcome.DONE:
                    self.log.event("gang_done", restart=attempt)
                    return 0
                if outcome == GangOutcome.PREEMPTED:
                    self._teardown("preempted", self.sigterm_grace_s)
                    self.log.event("preempted", restart=attempt)
                    return 128 + signal.SIGTERM
                # crash / hang / worker preemption: the gang is torn —
                # kill it whole
                if outcome == GangOutcome.HANG:
                    _profiler.bump_counter("dist_hang_kills")
                self._teardown(outcome, self.sigterm_grace_s)
                # SDC quarantines draw from the preempt budget: like a
                # slice preemption they are the pool's lifecycle (the
                # quarantined slot is marked down and planned around),
                # not a crash loop worth damping
                preempt = outcome in (
                    GangOutcome.WORKER_PREEMPT, GangOutcome.SDC
                )
                used = (
                    self.preempt_restarts_used if preempt
                    else self.restarts_used
                )
                budget = (
                    self.max_preempt_restarts if preempt
                    else self.max_restarts
                )
                if used >= budget:
                    self.failure_report = {
                        "restarts_used": self.restarts_used,
                        "max_restarts": self.max_restarts,
                        "preempt_restarts_used": self.preempt_restarts_used,
                        "max_preempt_restarts": self.max_preempt_restarts,
                        "last_failure": dict(detail, kind=outcome),
                        "workdir": self.workdir,
                    }
                    self.log.event("giveup", **self.failure_report)
                    return 1
                if preempt:
                    self.preempt_restarts_used += 1
                else:
                    self.restarts_used += 1
                attempt += 1
                _profiler.bump_counter("dist_restarts")
                # backoff escalates with the CRASH count only: crashes
                # look like a loop worth damping, while preemptions are
                # the pool's normal lifecycle (that's why they have
                # their own generous budget) — penalizing the 7th
                # preemption with backoff_max would inflate MTTR
                # exactly where elasticity is supposed to help
                exponent = 1 if preempt else self.restarts_used
                delay = min(
                    self.backoff_base_s * (2.0 ** (exponent - 1)),
                    self.backoff_max_s,
                ) * (0.5 + 0.5 * self._rng.random())  # decorrelating jitter
                self.log.event(
                    "restart", restart=attempt, backoff_s=delay,
                    restarts_used=self.restarts_used,
                    preempt_restarts_used=self.preempt_restarts_used,
                    cause=dict(detail, kind=outcome),
                )
                # merged telemetry checkpoint at every restart: an
                # operator watching a flapping gang reads the report
                # without waiting for the run to end
                self._write_gang_report()
                # interruptible backoff: a SIGTERM preemption landing
                # here must not wait out the sleep and then spawn (and
                # immediately kill) a whole fresh gang
                if self._preempted.wait(delay):
                    self.log.event("preempted", restart=attempt)
                    return 128 + signal.SIGTERM
        finally:
            # exception/Ctrl-C unwind: the full SIGTERM grace applies —
            # workers' preemption handlers may be mid final-save, and
            # killing that save loses up to ckpt_save_interval_steps of
            # progress. Normal returns reach here with the gang already
            # dead, making this a no-op.
            self._teardown(
                "supervisor_exit", self.sigterm_grace_s, quiet=True
            )
            # final merged gang report — after teardown, so every
            # worker's exit-time snapshot file is already on disk
            self._write_gang_report()
            self._restore_sigterm(prev)
            for f in self._log_files:
                try:
                    f.close()
                except OSError:
                    pass
            self._log_files = []

    # -- internals ---------------------------------------------------------

    def _write_gang_report(self):
        """Best-effort workdir/gang_report.json (observability
        aggregate): telemetry merge failures must never take down the
        supervision loop itself."""
        try:
            from ..observability import aggregate as _aggregate

            _aggregate.write_gang_report(
                self.workdir, obs_dir=self._obs_dir_effective
            )
        except Exception:
            pass

    def _install_sigterm(self):
        if threading.current_thread() is not threading.main_thread():
            return None
        try:
            return signal.signal(
                signal.SIGTERM, lambda *_: self._preempted.set()
            )
        except ValueError:
            return None

    def _restore_sigterm(self, prev):
        if prev is None:
            return
        try:
            signal.signal(signal.SIGTERM, prev)
        except (ValueError, TypeError):
            pass

    def _hb_path(self, rank):
        return os.path.join(self._hb_dir, "heartbeat_%d.json" % rank)

    def _slot(self, i):
        """A spec's stable identity: its global rank (or list index)."""
        spec = self.specs[i]
        return spec.rank if spec.rank is not None else i

    def _down_path(self, slot):
        return os.path.join(self._avail_dir, "down_slot_%d.json" % slot)

    def _plan_gang(self):
        """Launchability probe -> spec indices for the next attempt.

        A slot with a live down marker is excluded; attempt-counted
        markers (``down_for >= 0``) expire after that many planning
        rounds have observed them — counted in the marker itself, so
        expiry is deterministic across supervisor restarts — and
        open-ended markers (``down_for < 0``) hold until the file is
        deleted. With elasticity off the probe is skipped entirely: the
        plan is always the full spec list (PR 4 behavior)."""
        if not self._elastic:
            return list(range(len(self.specs)))
        plan = []
        for i in range(len(self.specs)):
            slot = self._slot(i)
            path = self._down_path(slot)
            marker = elastic.read_down_marker(path)
            if marker is None:
                plan.append(i)
                continue
            down_for = int(marker.get("down_for", -1))
            seen = int(marker.get("attempts_down", 0))
            if 0 <= down_for <= seen:
                # the spare returned: clear the marker so the slot
                # rejoins this plan (and stays launchable)
                try:
                    os.remove(path)
                except OSError:
                    pass
                plan.append(i)
                continue
            if down_for >= 0:
                elastic.write_down_marker(
                    path, down_for=down_for, slot=slot,
                    from_attempt=marker.get("from_attempt"),
                    attempts_down=seen + 1,
                    reason=marker.get("reason"),
                )
        return plan

    def _start_gang(self, attempt, plan=None):
        """Spawn the gang for this attempt: one worker per planned spec,
        ranks remapped contiguously (gang position == rank), topology
        injected via the elastic env contract. ``plan`` is the list of
        spec indices (default: all)."""
        if plan is None:
            plan = list(range(len(self.specs)))
        world = len(plan)
        resized = plan != list(range(len(self.specs)))
        # previous attempt's log handles are dead with their processes
        for f in self._log_files:
            try:
                f.close()
            except OSError:
                pass
        self._log_files = []
        # stale beats from the previous attempt must not mask a worker
        # that hangs before its first beat
        for i in range(len(self.specs)):
            try:
                os.remove(self._hb_path(i))
            except OSError:
                pass
        # register the (still empty) gang list BEFORE spawning and
        # append per worker: if a mid-loop Popen/open fails, the
        # exception unwinds into run()'s finally, whose teardown must
        # see — and reap — the workers already spawned, not the previous
        # attempt's dead list
        procs = []
        with self._procs_lock:
            self._procs = procs
        # staleness bookkeeping: {local idx: (last seen mtime, monotonic
        # time that mtime was first observed)} — ages are measured on
        # the supervisor's monotonic clock between observed CHANGES, so
        # an NTP step of the wall clock can neither forge a hang nor
        # mask one
        self._hb_seen = {}
        # SDC digest rounds for THIS attempt: {digest_step: {local idx:
        # digest}} accumulated from heartbeat digest rings; a round is
        # judged once every current gang member has voted
        self._digest_votes = {}
        for j, idx in enumerate(plan):
            spec = self.specs[idx]
            slot = self._slot(idx)
            env = dict(os.environ)
            env.update(spec.env)
            env[HEARTBEAT_ENV] = self._hb_path(j)
            env[RESTART_ENV] = str(attempt)
            # the elastic topology contract: new rank = gang position,
            # slot = the spec's stable identity (chaos faults and down
            # markers address slots, not remapped ranks)
            env[elastic.WORLD_ENV] = str(world)
            env[elastic.RANK_ENV] = str(j)
            env[elastic.BASE_WORLD_ENV] = str(len(self.specs))
            env[elastic.SLOT_ENV] = str(slot)
            env[elastic.DOWN_FILE_ENV] = self._down_path(slot)
            if resized:
                # remap the legacy contract vars the launcher baked into
                # the spec — a shrunk gang must not see the old topology
                for key, val in (
                    ("PADDLE_TRAINER_ID", str(j)),
                    ("PADDLE_TRAINERS_NUM", str(world)),
                    ("JAX_PROCESS_ID", str(j)),
                    ("JAX_NUM_PROCESSES", str(world)),
                ):
                    if key in spec.env:
                        env[key] = val
                if "PADDLE_TRAINER_ENDPOINTS" in spec.env:
                    eps = [
                        self.specs[k].env.get("PADDLE_CURRENT_ENDPOINT")
                        for k in plan
                    ]
                    if all(eps):
                        env["PADDLE_TRAINER_ENDPOINTS"] = ",".join(eps)
            # flags are env-bridged, so this arms per-rank snapshot files
            # in every worker; an operator's explicit FLAGS_obs_dir
            # (spec.env or the supervisor's own environment) wins
            env.setdefault("FLAGS_obs_dir", self._obs_dir)
            if j == 0:
                # merge wherever the snapshots actually land
                self._obs_dir_effective = env["FLAGS_obs_dir"]
            stdout = stderr = None
            if spec.log_path:
                d = os.path.dirname(spec.log_path)
                if d:
                    os.makedirs(d, exist_ok=True)
                fn = open(spec.log_path, "a")
                fn.write("--- supervisor attempt %d ---\n" % attempt)
                fn.flush()
                self._log_files.append(fn)
                stdout = stderr = fn
            try:
                p = subprocess.Popen(
                    spec.cmd, env=env, stdout=stdout, stderr=stderr
                )
            except OSError as e:
                # this slot cannot spawn a process at all — the elastic
                # caller marks it down and re-plans around it
                raise _SpawnFailed(slot, e)
            with self._procs_lock:
                procs.append((spec, p))
        self._gang_t0 = time.monotonic()
        self.log.event(
            "gang_start", restart=attempt,
            pids=[p.pid for _s, p in procs],
            world_size=world,
            slots=[self._slot(idx) for idx in plan],
            rank_pids={str(j): p.pid for j, (_s, p) in enumerate(procs)},
        )

    def _monitor(self):
        """Poll until the gang completes or a failure is detected.
        Returns (outcome, detail). Events carry the spec's GLOBAL rank
        (multi-node: node 1's workers are ranks 4..7, not 0..3) so
        operators and MTTR tooling inspect the right worker."""
        finished = set()
        while True:
            if self._preempted.is_set():
                return GangOutcome.PREEMPTED, {}
            now = time.monotonic()
            for i, (spec, p) in enumerate(self._procs):
                rank = spec.rank if spec.rank is not None else i
                rc = p.poll()
                if rc is None or i in finished:
                    continue
                if rc == 0:
                    finished.add(i)
                    self.log.event("worker_exit", rank=rank, returncode=0)
                    continue
                if rc in (128 + signal.SIGTERM, -signal.SIGTERM):
                    # exit 143 / killed by SIGTERM: the worker was
                    # preempted, not buggy — restart under the separate
                    # preempt budget (and, when elastic, re-plan the
                    # world around any slot that marked itself down)
                    self.log.event(
                        "worker_preempted", rank=rank, returncode=rc,
                        pid=p.pid,
                    )
                    return GangOutcome.WORKER_PREEMPT, {
                        "rank": rank, "returncode": rc,
                    }
                self.log.event(
                    "crash_detected", rank=rank, returncode=rc, pid=p.pid,
                )
                return GangOutcome.CRASH, {"rank": rank, "returncode": rc}
            if len(finished) == len(self._procs):
                return GangOutcome.DONE, {}
            # hang watchdog over the still-running workers
            for i, (spec, p) in enumerate(self._procs):
                if i in finished or p.poll() is not None:
                    continue
                rank = spec.rank if spec.rank is not None else i
                hb = read_heartbeat(self._hb_path(i))
                status = (hb or {}).get("status")
                if hb is not None and hb.get("digests"):
                    self._collect_digests(i, hb)
                    verdict = self._sdc_vote()
                    if verdict is not None:
                        return verdict
                if hb is None:
                    # never beat: unobservable unless an explicit grace
                    # was configured
                    if self.startup_grace_s is None:
                        continue
                    age = now - self._gang_t0
                    limit = self.startup_grace_s
                elif status == "start":
                    # instrumented but pre-first-step (restore + first
                    # XLA compile): laxer, but FINITE, bound
                    age = now - self._gang_t0
                    limit = self._instrumented_grace_s
                elif status == "rollback":
                    # mid-run checkpoint restore (the training
                    # guardian's rollback): a multi-second restore
                    # inside a live worker must not be hang-killed by
                    # the per-step staleness bound — it gets the
                    # startup-style instrumented grace, measured from
                    # when this beat was first observed (the rollback
                    # may start late in a long run)
                    seen = self._hb_seen.get(i)
                    if seen is None or seen[0] != hb["mtime"]:
                        self._hb_seen[i] = (hb["mtime"], now)
                        continue
                    age = now - seen[1]
                    limit = self._instrumented_grace_s
                elif status == "done":
                    # Training progress is complete; what follows (final
                    # save teardown, then whatever post-train work the
                    # user script runs — eval, export) is unbeatable and
                    # of unknowable duration, so NO staleness bound
                    # applies: killing a healthy 20-minute export to
                    # guard against the rarer wedged-final-save would
                    # turn succeeding jobs into restart loops. The
                    # accepted tradeoff: a truly wedged post-'done'
                    # worker stalls the gang until the operator (or the
                    # fleet scheduler's own job timeout) intervenes —
                    # process exit is the remaining signal.
                    continue
                else:
                    seen = self._hb_seen.get(i)
                    if seen is None or seen[0] != hb["mtime"]:
                        self._hb_seen[i] = (hb["mtime"], now)
                        continue  # fresh beat observed this poll
                    age = now - seen[1]
                    limit = self.heartbeat_timeout_s
                if age > limit:
                    self.log.event(
                        "hang_detected", rank=rank, pid=p.pid,
                        stale_s=round(age, 3),
                        last_step=(hb or {}).get("step"),
                    )
                    return GangOutcome.HANG, {
                        "rank": rank, "stale_s": round(age, 3),
                    }
            time.sleep(self.poll_s)

    def _collect_digests(self, i, hb):
        """Accumulate one worker's heartbeat digest ring into the
        per-attempt vote table (the ring carries the newest 8 publishes,
        so a poll that missed individual beats still sees every
        round)."""
        for pair in hb.get("digests") or []:
            try:
                ds, dg = int(pair[0]), str(pair[1])
            except (TypeError, ValueError, IndexError):
                continue
            self._digest_votes.setdefault(ds, {})[i] = dg

    def _sdc_vote(self):
        """Majority-vote every COMPLETE digest round (all current gang
        members reported for the same step). A diverging minority rank
        is quarantined: its slot gets a down marker (open-ended — SDC
        means suspect hardware, an operator or scheduler clears it),
        a ``replica_quarantined`` event lands in the log, and the gang
        restarts under the preempt budget. Returns (outcome, detail) or
        None. Needs >= 3 voters: a 2-way disagreement cannot attribute
        blame (logged as ``sdc_vote_inconclusive``)."""
        from ..fluid import profiler as _profiler

        world = len(self._procs)
        for ds in sorted(self._digest_votes):
            votes = self._digest_votes.get(ds)
            if votes is None or len(votes) < world:
                continue
            # judged exactly once; older incomplete rounds are
            # superseded (a rank that never completes round K but
            # completes K+1 is healthy — the ring just rolled)
            del self._digest_votes[ds]
            for old in [s for s in self._digest_votes if s < ds]:
                del self._digest_votes[old]
            counts = {}
            for dg in votes.values():
                counts[dg] = counts.get(dg, 0) + 1
            if len(counts) == 1:
                continue  # unanimous round
            majority, n = max(counts.items(), key=lambda kv: kv[1])
            if world < 3 or n <= world // 2:
                self.log.event(
                    "sdc_vote_inconclusive", step=ds, world=world,
                    votes={str(k): v for k, v in votes.items()},
                )
                continue
            quarantined = []
            for i, dg in sorted(votes.items()):
                if dg == majority:
                    continue
                spec = self._procs[i][0]
                rank = spec.rank if spec.rank is not None else i
                # the down marker must land on the spec's STABLE slot
                # (the identity _plan_gang probes) — after a resize the
                # attempt-local index and the spec-list index differ,
                # and marking the wrong slot would bench a healthy
                # worker while re-planning the corrupt one back in
                try:
                    slot = (
                        spec.rank if spec.rank is not None
                        else self.specs.index(spec)
                    )
                except ValueError:
                    slot = rank
                if self._elastic:
                    elastic.write_down_marker(
                        self._down_path(slot), down_for=-1, slot=slot,
                        reason="sdc_quarantine",
                    )
                _profiler.bump_counter("sdc_quarantines")
                self.log.event(
                    "replica_quarantined", rank=rank, slot=slot,
                    step=ds, digest=dg, majority=majority,
                    pid=self._procs[i][1].pid,
                    marker_written=self._elastic,
                )
                quarantined.append(rank)
            return GangOutcome.SDC, {"ranks": quarantined, "step": ds}
        return None

    def _teardown(self, reason, grace_s, quiet=False):
        """SIGTERM the gang (the PR 3 preemption path: workers' handlers
        get a chance to commit a final save), then SIGKILL survivors
        after ``grace_s``."""
        with self._procs_lock:
            procs = list(self._procs)
        alive = [p for _s, p in procs if p.poll() is None]
        if not alive:
            return
        for p in alive:
            try:
                p.send_signal(signal.SIGTERM)
            except OSError:
                pass
        deadline = time.monotonic() + grace_s
        killed = []
        while any(p.poll() is None for p in alive):
            if time.monotonic() > deadline:
                for p in alive:
                    if p.poll() is None:
                        try:
                            p.kill()
                        except OSError:
                            pass
                        killed.append(p.pid)
                break
            time.sleep(0.05)
        for p in alive:
            try:
                p.wait(timeout=10)
            except Exception:
                pass
        if not quiet:
            self.log.event(
                "gang_teardown", reason=reason, sigkilled=killed,
            )
