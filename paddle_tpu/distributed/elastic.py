"""Elastic world-size contract: env vars, availability markers, batch math.

The PR 4 supervisor could only restore a gang at its original size — a
preempted TPU slice stranded the job until every worker returned. True
elasticity needs three pieces that live here because FOUR layers share
them (supervisor, trainer, chaos harness, checkpoint manager):

1. **Env contract** — the supervisor injects the CURRENT topology into
   every worker on each (re)start: ``PADDLE_TPU_WORLD_SIZE`` /
   ``PADDLE_TPU_RANK`` (contiguously remapped per attempt),
   ``PADDLE_TPU_BASE_WORLD_SIZE`` (the full gang the job was submitted
   with — degradation is measured against it), and
   ``PADDLE_TPU_GANG_SLOT`` (the worker's STABLE identity: its original
   spec rank, unchanged by remapping, so per-slot faults and
   availability stay addressable across resizes). ``world_info()``
   reads the contract back with legacy ``PADDLE_TRAINER_*`` fallbacks.

2. **Availability (down) markers** — the supervisor's launchability
   probe. A slot with a live marker file is excluded from the next gang
   plan; expiry is counted in *planning events* (``down_for`` plans
   observe it down, then the slot is launchable again — deterministic
   across supervisor restarts, which wall-clock TTLs are not), or
   ``down_for < 0`` keeps the slot down until the marker is deleted
   (operator / resource manager says the host is back). Markers are
   written by whoever knows the slot is gone: the chaos ``lose_rank``
   fault (worker self-reports then exits 143), the supervisor itself on
   a spawn failure, or an external scheduler via plain ``echo >file``.
   Each worker learns its own marker path via ``PADDLE_TPU_DOWN_FILE``.

3. **Global-batch / LR math** — a shrunk gang must converge like the
   fixed gang. ``batch_plan()`` computes the gradient-accumulation
   factor that preserves the global batch (arXiv:2004.13336's
   per-replica weight update survives because step index == global
   batch index stays true); ``maybe_rescale_lr()`` is the alternative
   strategy (keep per-rank batch, linearly rescale LR to the shrunk
   global batch, opt-in via ``FLAGS_elastic_lr_rescale``) applied
   relative to the world size the checkpoint was SAVED at, so repeated
   resumes never compound the factor.
"""

from __future__ import annotations

import collections
import json
import math
import os

__all__ = [
    "WORLD_ENV",
    "RANK_ENV",
    "BASE_WORLD_ENV",
    "SLOT_ENV",
    "DOWN_FILE_ENV",
    "WorldInfo",
    "world_info",
    "write_down_marker",
    "read_down_marker",
    "BatchPlan",
    "batch_plan",
    "maybe_rescale_lr",
]

WORLD_ENV = "PADDLE_TPU_WORLD_SIZE"
RANK_ENV = "PADDLE_TPU_RANK"
BASE_WORLD_ENV = "PADDLE_TPU_BASE_WORLD_SIZE"
SLOT_ENV = "PADDLE_TPU_GANG_SLOT"
DOWN_FILE_ENV = "PADDLE_TPU_DOWN_FILE"


WorldInfo = collections.namedtuple(
    "WorldInfo", ["rank", "world_size", "base_world_size", "slot"]
)


def _env_int(env, name, default):
    try:
        return int(env.get(name, ""))
    except (TypeError, ValueError):
        return default


def world_info(environ=None):
    """The topology this process runs under. Prefers the elastic
    ``PADDLE_TPU_*`` contract (remapped per restart attempt), falls back
    to the legacy launcher vars, then to a single-process default.
    ``base_world_size`` is the submitted gang size; ``world_size <
    base_world_size`` means this attempt runs degraded."""
    env = os.environ if environ is None else environ
    world = _env_int(env, WORLD_ENV, None)
    if world is None:
        world = _env_int(env, "PADDLE_TRAINERS_NUM", 1)
    rank = _env_int(env, RANK_ENV, None)
    if rank is None:
        rank = _env_int(env, "PADDLE_TRAINER_ID", 0)
    base = _env_int(env, BASE_WORLD_ENV, world)
    slot = _env_int(env, SLOT_ENV, rank)
    return WorldInfo(rank=rank, world_size=max(world, 1),
                     base_world_size=max(base, 1), slot=slot)


# ---------------------------------------------------------------------------
# availability markers (the supervisor's launchability probe)
# ---------------------------------------------------------------------------
def write_down_marker(path, down_for=-1, slot=None, from_attempt=None,
                      attempts_down=0, reason=None):
    """Atomically write a down marker: this slot is unlaunchable for the
    next ``down_for`` gang plans (< 0 = until the file is deleted)."""
    import time

    payload = {
        "down_for": int(down_for),
        "attempts_down": int(attempts_down),
        "ts": time.time(),
    }
    if slot is not None:
        payload["slot"] = int(slot)
    if from_attempt is not None:
        payload["from_attempt"] = int(from_attempt)
    if reason is not None:
        payload["reason"] = str(reason)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = "%s.tmp.%d" % (path, os.getpid())
    with open(tmp, "w") as f:
        f.write(json.dumps(payload, sort_keys=True))
    os.replace(tmp, path)
    return payload


def read_down_marker(path):
    """Parse a down marker -> dict, or None when absent. A torn/garbage
    marker reads as ``down_for=-1`` (down until deleted): an unreadable
    availability claim must fail SAFE — never launch onto a slot whose
    state is unknown."""
    try:
        with open(path) as f:
            text = f.read()
    except OSError as e:
        import errno

        if e.errno in (errno.ENOENT, errno.ENOTDIR):
            return None
        # the marker EXISTS but cannot be read (EACCES, EIO, ...): same
        # fail-safe as a torn payload — the slot stays down until the
        # claim becomes readable or the file is deleted
        return {
            "down_for": -1, "attempts_down": 0, "torn": True,
            "read_error": str(e),
        }
    try:
        data = json.loads(text)
        if not isinstance(data, dict):
            raise ValueError(text)
    except ValueError:
        data = {"down_for": -1, "attempts_down": 0, "torn": True}
    data.setdefault("down_for", -1)
    data.setdefault("attempts_down", 0)
    return data


# ---------------------------------------------------------------------------
# global-batch preservation / LR rescaling math
# ---------------------------------------------------------------------------
BatchPlan = collections.namedtuple(
    "BatchPlan",
    [
        "world_size",            # ranks this attempt runs with
        "base_world_size",       # ranks the job was submitted with
        "per_rank_batch",        # unchanged per-rank micro-batch
        "accum_steps",           # grad-accumulation factor preserving G
        "global_batch",          # target G = base * per_rank_batch
        "effective_global_batch",  # world * per_rank_batch * accum
        "lr_scale",              # linear-scaling correction for the
                                 # (rounded-up) effective batch; 1.0
                                 # when base % world == 0
    ],
)


def batch_plan(base_world_size, world_size, per_rank_batch=1):
    """How a ``world_size``-rank attempt preserves the global batch of a
    ``base_world_size``-rank job: keep the per-rank batch, accumulate
    ``accum_steps`` micro-batches per optimizer update. When the shrink
    doesn't divide evenly the effective batch rounds UP (never silently
    train on a smaller batch than submitted) and ``lr_scale`` carries
    the linear-scaling correction for the overshoot. With this plan one
    optimizer step consumes >= one submitted global batch, so a
    step-indexed LR schedule stays in global-sample space across
    shrink/regrow — the convergence property dist_crash_probe asserts."""
    base = max(int(base_world_size), 1)
    world = max(int(world_size), 1)
    b = max(int(per_rank_batch), 1)
    accum = max(int(math.ceil(base / float(world))), 1)
    global_batch = base * b
    effective = world * b * accum
    return BatchPlan(
        world_size=world,
        base_world_size=base,
        per_rank_batch=b,
        accum_steps=accum,
        global_batch=global_batch,
        effective_global_batch=effective,
        lr_scale=effective / float(global_batch),
    )


def _scope_or_global(scope):
    from ..fluid import core

    return scope if scope is not None else core.global_scope()


def maybe_rescale_lr(program, scope=None, restore_info=None):
    """Opt-in (``FLAGS_elastic_lr_rescale``) alternative to gradient
    accumulation: per-rank batch stays fixed, so a shrunk gang's global
    batch shrinks by ``world/base`` — apply the linear-scaling rule to
    the program's global learning-rate variable(s) by the same factor.

    The factor is computed against the world size the restored
    checkpoint was SAVED at (``restore_info['world_size_saved']``,
    stamped by CheckpointManager) — the LR variable is itself a
    persistable that round-trips through checkpoints, so scaling
    against the BASE each life would compound the correction on every
    resume at the same degraded size. A fresh start scales against the
    base. Returns the factor applied, or None when disarmed / at parity.
    """
    import numpy as np

    from ..fluid import flags as _flags
    from ..fluid import profiler as _profiler

    if not bool(_flags.get_flag("elastic_lr_rescale", False)):
        return None
    info = world_info()
    saved_world = None
    if restore_info:
        saved_world = restore_info.get("world_size_saved")
    if not saved_world:
        saved_world = info.base_world_size
    factor = info.world_size / float(saved_world)
    if factor == 1.0:
        return None
    scope = _scope_or_global(scope)
    scaled = 0
    for v in program.list_vars():
        if not getattr(v, "persistable", False):
            continue
        if not v.name.startswith("learning_rate"):
            continue
        val = scope.get(v.name)
        if val is None:
            continue
        arr = np.asarray(val.numpy() if hasattr(val, "numpy") else val)
        scope.set(v.name, (arr * factor).astype(arr.dtype))
        scaled += 1
    if scaled:
        _profiler.bump_counter("elastic_lr_rescales")
        print(
            "elastic: rescaled %d learning-rate var(s) by %.4f "
            "(world %d, checkpoint saved at world %d)"
            % (scaled, factor, info.world_size, saved_world),
            flush=True,
        )
        return factor
    return None
