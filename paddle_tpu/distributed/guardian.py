"""Training guardian — data-plane fault tolerance for long runs.

Every robustness layer before this one guards *process* failure: the
supervisor restarts crashed/hung gangs (PR 4), elastic resize routes
around lost slots (PR 6), the router fails streams over dead replicas
(PR 13). At production scale the step that actually kills a long run is
a *data* failure — a NaN-poisoned batch, a loss spike, a gradient
explosion, or silent data corruption (SDC) in one replica's update —
which propagates into every checkpoint until the run is unsalvageable.
The guardian closes that gap with four pieces:

1. **In-graph health signal** — ``attach_health_fetch`` folds a
   per-gradient partial reduction (cast-to-fp32 square/sum, one scalar
   per parameter gradient) into the EXISTING step program; the
   guardian host-sums the partials into the global grad norm. NaN/Inf
   in any gradient propagates into its partial and from there into the
   sum, so the one series is both the grad-norm signal and the
   isfinite detector. The fetch list is constant across steps, so the
   strict compile gate's invariant holds: 0 steady-state recompiles
   with the guardian armed — and because no in-graph op joins every
   gradient, the reduction tail never serializes the backward's
   inter-op concurrency (measured: the fused-single-scalar form cost
   ~20% of a CPU step at batch 4096; the partials form ~0.2%).

2. **Host-side anomaly policy** — NaN/Inf (via
   ``fluid.debugger.nonfinite_kind``, the FLAGS_check_nan_inf detector)
   is an immediate anomaly; loss spikes and grad-norm explosions are
   judged by a robust rolling window (EWMA center, MAD scale,
   ``FLAGS_guardian_spike_sigma`` z-score) that a drifting loss curve
   cannot fool. AMP dynamic-loss-scaling backoff steps are explicitly
   exempt: non-finite grads under a finite loss while the scale is
   shrinking (or holding inside a decr window) are the scaler
   *working* (it masks them and backs the scale off), not an anomaly
   — but the exemption is bounded (``_AMP_BACKOFF_RUN_LIMIT``
   consecutive steps; a grown scale, or non-finite grads that outlast
   the bound, is corruption and walks the ladder). Under AMP the
   health series is normalized by the loss scale the grads were
   computed under, so routine scale moves never read as explosions.

3. **Graduated response ladder** — skip-step (discard the update by
   re-referencing the pre-step buffers — the executor's
   ``program._keep_mutable`` keeps them undonated — and advance the
   data stream; ``train_skipped_steps``), then rollback to the newest
   *verified* checkpoint (``CheckpointManager.newest_verified_step``,
   kept warm by the FLAGS_ckpt_scrub writer-side scrubber) with
   deterministic replay that drops the poisoned batch window
   (``train_rollbacks``), then structured ``GuardianGiveup``. Poisoned
   steps persist as chaos-style marker files
   (``FLAGS_guardian_marker_dir``), so a deterministic bad batch can
   never rollback-loop — not even across process restarts.

4. **Cross-replica SDC digest** — every
   ``FLAGS_guardian_digest_interval`` steps each DP rank publishes a
   cheap state digest (crc32 over the health scalar's bits + a strided
   sample of every parameter) through its heartbeat file; the
   supervisor majority-votes complete rounds and quarantines a
   diverging rank via the elastic down-marker path
   (``replica_quarantined`` event, ``sdc_quarantines`` counter).

Closed loop: ``tools/train_guardian_probe.py --fast`` (tier-1 via
``tests/test_train_guardian.py``)."""

from __future__ import annotations

import collections
import json
import math
import os
import re
import time
import zlib

import numpy as np

__all__ = [
    "GuardianGiveup",
    "RollbackSignal",
    "RobustWindow",
    "Guardian",
    "attach_health_fetch",
    "state_digest",
]

# persisted poisoned-step markers (FLAGS_guardian_marker_dir): chaos-style
# one-shot files — `poisoned_step_<N>` exists means batch N is dropped
# from every (re)play in this run's lineage
_MARKER_RE = re.compile(r"^poisoned_step_(\d+)$")

# digest sampling bound: at most this many elements per tensor feed the
# crc32 (strided), so the per-publish D2H stays O(KB) on big models
_DIGEST_SAMPLE = 4096

# AMP backoff exemption bound: a LEGITIMATE found_inf episode resolves
# in a handful of steps (each backoff shrinks the scale by decr_ratio);
# this many CONSECUTIVE backoffs means the grads are non-finite at any
# scale — a NaN weight or corrupted state, not overflow — and the
# ladder takes over (skip restores nothing useful, but rollback does)
_AMP_BACKOFF_RUN_LIMIT = 50


class GuardianGiveup(RuntimeError):
    """The response ladder is exhausted (skips spent, rollbacks spent —
    or no verified checkpoint to roll back to). Carries a structured
    ``report`` dict so the supervisor log / operator sees what was
    tried, not just a traceback."""

    def __init__(self, report):
        self.report = dict(report)
        super().__init__(
            "guardian giveup: %s" % json.dumps(self.report, sort_keys=True)
        )


class RollbackSignal(Exception):
    """Control flow, not an error: the trainer unwinds its step loop to
    restore the newest verified checkpoint and replay the stream."""

    def __init__(self, step, kind):
        super().__init__("guardian rollback from step %d (%s)" % (step, kind))
        self.step = int(step)
        self.kind = str(kind)


class RobustWindow(object):
    """Spike detector over one scalar series: EWMA center + MAD scale.

    ``judge(x)`` returns ``(is_spike, z)``. The center is an EWMA (so a
    trending loss curve is followed, not flagged); the scale is the
    median absolute residual from the center over a bounded window,
    made Gaussian-consistent by the 1.4826 factor, with a floor of
    ``1e-3 + 1%% of |center|`` so a plateaued series (MAD -> 0) does not
    flag every fluctuation. Spikes are NOT admitted into the window —
    one outlier must not inflate the scale that judges the next."""

    def __init__(self, sigma, window, warmup, alpha=0.2):
        self.sigma = float(sigma)
        self.warmup = max(int(warmup), 1)
        self.alpha = float(alpha)
        self._ewma = None
        self._resid = collections.deque(maxlen=max(int(window), 4))
        self._n = 0

    def _admit(self, x):
        if self._ewma is None:
            self._ewma = x
        else:
            self._resid.append(abs(x - self._ewma))
            self._ewma += self.alpha * (x - self._ewma)
        self._n += 1

    def judge(self, x):
        x = float(x)
        if not math.isfinite(x):
            return True, float("inf")
        if self._n < self.warmup or len(self._resid) < 2:
            self._admit(x)
            return False, 0.0
        resid = sorted(self._resid)
        mad = resid[len(resid) // 2]
        scale = max(1.4826 * mad, 1e-3 + 0.01 * abs(self._ewma))
        z = abs(x - self._ewma) / scale
        if z > self.sigma:
            return True, z
        self._admit(x)
        return False, z

    def reset(self):
        self._ewma = None
        self._resid.clear()
        self._n = 0


# ---------------------------------------------------------------------------
# in-graph health fetch
# ---------------------------------------------------------------------------
def attach_health_fetch(program):
    """Append per-gradient partial reductions to ``program``: one
    ``sum(cast(g_p, fp32)^2)`` scalar PER parameter gradient. Returns
    the list of partial Variables (fetch them alongside the loss; the
    guardian host-sums the scalars and takes the sqrt — the global grad
    norm), or an empty list when the program has no parameter gradients
    (inference / forward-only programs).

    The ops ride the SAME step program — the fetch set stays constant
    across steps, so the executor's program cache compiles exactly once
    and the PR 7 strict gate sees 0 steady-state recompiles. Grads are
    cast to fp32 before squaring so an fp16 build cannot overflow
    inside the detector itself; a NaN/Inf in ANY grad propagates into
    its partial and from there into the host sum, making the series
    both the grad-norm signal and the isfinite reduction.

    Deliberately NOT one fused in-graph scalar: each partial's only
    input is its own gradient, so no single op joins every grad. A
    joined form (add-chain or concat into one reduce) was measured to
    serialize XLA CPU's inter-op concurrency — the whole backward had
    to finish before the join could schedule, costing ~20%% of the step
    at batch 4096 on a 2-core box, vs ~0.2%% for the per-grad partials
    (PERF.md "Training guardian"). The host pays len(grads) tiny
    scalar conversions instead — O(µs) each."""
    from ..fluid import core
    from ..fluid.framework import program_guard
    from ..fluid.layers import nn as _lnn
    from ..fluid.layers import ops as _lops
    from ..fluid.layers import tensor as _ltensor
    from ..fluid.ops.registry import GRAD_SUFFIX

    # idempotent per program: train() is legitimately re-entered on the
    # same Program (a driver surviving SIGTERM), and a second set of
    # appended reductions would be compiled and run every step without
    # ever being fetched — and would force one recompile
    cached = program.__dict__.get("_guardian_health_partials")
    if cached is not None:
        return list(cached)
    block = program.global_block()
    grads = []
    for p in program.all_parameters():
        g = block._find_var_recursive(p.name + GRAD_SUFFIX)
        if g is not None:
            grads.append(g)
    partials = []
    with program_guard(program):
        for g in grads:
            if g.dtype != core.VarDesc.VarType.FP32:
                g = _ltensor.cast(g, "float32")
            partials.append(_lnn.reduce_sum(_lops.square(g)))
    program._guardian_health_partials = list(partials)
    return partials


# ---------------------------------------------------------------------------
# cross-replica state digest
# ---------------------------------------------------------------------------
def state_digest(param_names, scope, health=None):
    """Cheap deterministic digest of one replica's post-update state:
    crc32 over the health scalar's bits plus a strided sample (at most
    ``_DIGEST_SAMPLE`` elements) of every parameter. Identical replicas
    produce identical digests bit-for-bit; a single flipped parameter
    bit (SDC) diverges it. Returns an 8-hex-digit string."""
    crc = 0
    if health is not None and math.isfinite(float(health)):
        crc = zlib.crc32(np.float64(health).tobytes(), crc)
    for name in param_names:
        val = scope.get(name)
        if val is None:
            continue
        arr = np.asarray(val.numpy() if hasattr(val, "numpy") else val)
        flat = np.ascontiguousarray(arr).reshape(-1)
        if flat.size > _DIGEST_SAMPLE:
            flat = flat[:: max(1, flat.size // _DIGEST_SAMPLE)]
        crc = zlib.crc32(np.ascontiguousarray(flat).tobytes(), crc)
    return "%08x" % (crc & 0xFFFFFFFF)


# ---------------------------------------------------------------------------
# the guardian
# ---------------------------------------------------------------------------
class Guardian(object):
    """One training run's health guardian (created per ``train()`` call
    by ``fluid/trainer.py`` when ``FLAGS_guardian_enable``)."""

    VERDICT_OK = "ok"
    VERDICT_SKIP = "skip"

    @classmethod
    def maybe_create(cls, program, ckpt_manager=None):
        from ..fluid import flags as _flags

        if not bool(_flags.get_flag("guardian_enable", False)):
            return None
        if getattr(program, "_pipeline_config", None):
            # the stage-partitioned pipeline executor owns its own op
            # layout; appending reductions after the cut would straddle
            # stages — the guardian stays out
            return None
        return cls(program, ckpt_manager=ckpt_manager)

    def __init__(self, program, ckpt_manager=None):
        from ..fluid import flags as _flags
        from ..fluid.io import is_persistable

        self.program = program
        self.ckpt_manager = ckpt_manager
        self.sigma = float(_flags.get_flag("guardian_spike_sigma", 6.0))
        window = int(_flags.get_flag("guardian_spike_window", 64))
        warmup = int(_flags.get_flag("guardian_warmup_steps", 8))
        self.max_skips = int(_flags.get_flag("guardian_max_skips", 2))
        self.max_rollbacks = int(
            _flags.get_flag("guardian_max_rollbacks", 1)
        )
        self.digest_interval = int(
            _flags.get_flag("guardian_digest_interval", 0)
        )
        self.marker_dir = str(
            _flags.get_flag("guardian_marker_dir", "") or ""
        ) or None
        self._loss_window = RobustWindow(self.sigma, window, warmup)
        self._health_window = RobustWindow(self.sigma, window, warmup)
        # skip-step discards an update by re-referencing the pre-step
        # buffers: tell the executor to keep mutable state undonated
        # (one params-sized double buffer on accelerators)
        program._keep_mutable = True
        self.health_vars = attach_health_fetch(program)
        # AMP dynamic loss scaling present? fetch the scale so backoff
        # steps (scale shrinks, grads masked) are exempt, not anomalies
        # (name-prefix match: create_global_var may uniquify the
        # decorator's "loss_scaling"; the good-steps counter is not it)
        self.loss_scale_var = None
        for v in program.list_vars():
            if (getattr(v, "persistable", False)
                    and v.name.startswith("loss_scaling")
                    and "good_steps" not in v.name):
                self.loss_scale_var = v
                break
        self.extra_fetches = list(self.health_vars) + (
            [self.loss_scale_var] if self.loss_scale_var is not None else []
        )
        self._persist_names = sorted(
            v.name for v in program.list_vars() if is_persistable(v)
        )
        self._param_names = sorted(
            p.name for p in program.all_parameters()
        )
        self._shadow = None
        self._prev_scale = None
        self._shadow_prev_scale = None
        self._amp_backoff_run = 0
        self._last_health = None
        self.skips_used = 0
        self.rollbacks_used = 0
        self.drop_steps = set(self._read_markers())
        self.stats = {
            "anomalies": 0,
            "skips": 0,
            "rollbacks": 0,
            "amp_backoff_steps": 0,
            "dropped_steps": 0,
            "kinds": {},
        }

    # -- fetch plumbing -----------------------------------------------------

    def wrap_fetches(self, fetch_list):
        """The trainer's real fetch list: user fetches + the guardian's
        health/scale extras (constant across steps — same compiled
        program every step)."""
        return list(fetch_list or []) + self.extra_fetches

    def split_outs(self, outs):
        """(user_outs, extra_outs) from one run's fetched values."""
        n = len(self.extra_fetches)
        if n == 0:
            return outs, []
        return outs[:-n], outs[-n:]

    # -- markers (poisoned-batch persistence) --------------------------------

    def _read_markers(self):
        if not self.marker_dir:
            return []
        try:
            names = os.listdir(self.marker_dir)
        except OSError:
            return []
        steps = []
        for n in names:
            m = _MARKER_RE.match(n)
            if m:
                steps.append(int(m.group(1)))
        return steps

    def _write_marker(self, step, kind):
        if not self.marker_dir:
            return
        os.makedirs(self.marker_dir, exist_ok=True)
        path = os.path.join(self.marker_dir, "poisoned_step_%d" % step)
        tmp = "%s.tmp.%d" % (path, os.getpid())
        try:
            with open(tmp, "w") as f:
                f.write(json.dumps(
                    {"step": int(step), "kind": kind, "ts": time.time()}
                ))
            os.replace(tmp, path)
        except OSError:
            pass  # marker persistence is best-effort; in-memory set rules

    # -- per-step protocol ---------------------------------------------------

    def should_drop(self, step):
        """True when this batch was identified as poisoned by an earlier
        anomaly (this life or, via markers, a previous one): consume it
        from the stream without running — the surviving data schedule."""
        return step in self.drop_steps

    def note_dropped(self, step):
        self.stats["dropped_steps"] += 1

    def pre_step(self, scope):
        """Reference-grab the pre-step state (no copy): every
        persistable's current array. ``_keep_mutable`` guarantees these
        buffers survive the step un-donated, so a skip verdict can
        restore them byte-exactly."""
        from ..fluid import core

        scope = scope if scope is not None else core.global_scope()
        self._shadow = {
            n: scope.get(n) for n in self._persist_names
        }
        # a skip restores the loss_scaling var too — the host-side
        # mirror must revert with it or the next AMP normalization
        # divides by a scale the grads were never computed under
        self._shadow_prev_scale = self._prev_scale

    def digest_due(self, step):
        return (self.digest_interval > 0
                and step % self.digest_interval == 0)

    def state_digest(self, scope):
        from ..fluid import core

        scope = scope if scope is not None else core.global_scope()
        return state_digest(
            self._param_names, scope, health=self._last_health
        )

    def post_step(self, step, outs):
        """Judge one completed step from its fetched values. Returns
        ``(user_outs, verdict)`` — verdict ``"ok"`` or ``"skip"`` — or
        raises RollbackSignal / GuardianGiveup per the response
        ladder.

        Contract: ``user_outs[0]`` is treated as the training loss
        (the fluid trainer's loss-first fetch_list convention — every
        probe and print_period consumer shares it); a non-scalar or
        non-float first fetch is simply not judged by the loss
        policies (grad-norm health still is)."""
        from ..fluid.debugger import nonfinite_kind

        user_outs, extra = self.split_outs(outs)
        health = None
        scale = None
        n = len(self.health_vars)
        if n:
            # host-side join of the per-grad partials (see
            # attach_health_fetch for why the join is NOT in-graph):
            # sum of squares is >= 0 or non-finite, so sqrt never
            # domain-errors; NaN/Inf in any partial propagates
            ssq = math.fsum(
                float(np.asarray(extra[j]).ravel()[0]) for j in range(n)
            )
            health = math.sqrt(ssq) if math.isfinite(ssq) else ssq
        if self.loss_scale_var is not None:
            scale = float(np.asarray(extra[n]).ravel()[0])
            # under AMP the @GRAD vars hold grads of the SCALED loss,
            # so the raw series would step 2x on every routine
            # loss-scale increase — a fake "grad explosion" to the
            # spike window. Normalize by the scale the grads were
            # actually computed under: the value fetched LAST step
            # (update_loss_scaling rewrites the var in-graph before
            # this step's fetch sees it), making the health series the
            # UNSCALED global grad norm, invariant to scaler moves.
            norm_by = (
                self._prev_scale if self._prev_scale is not None
                else scale
            )
            if (health is not None and math.isfinite(health)
                    and math.isfinite(norm_by) and norm_by > 0):
                health /= norm_by
        loss = None
        if user_outs and user_outs[0] is not None:
            arr = np.asarray(user_outs[0])
            if arr.size and np.issubdtype(arr.dtype, np.floating):
                loss = float(arr.ravel()[0])
        self._last_health = health

        loss_bad = loss is not None and nonfinite_kind(
            np.float64(loss)
        ) is not None
        health_bad = health is not None and not math.isfinite(health)

        kind = None
        if loss_bad:
            kind = "nan_inf_loss"
        elif health_bad:
            # AMP dynamic loss scaling: non-finite grads under a
            # finite loss are the scaler WORKING (found_inf masks the
            # update and shrinks the scale) — exempt, keeping the
            # spike windows untouched (a backoff step is not a sample
            # of the healthy series). But only while the scaler's
            # story holds: the scale must not have GROWN (growth means
            # found_inf did not fire — the non-finite grads came from
            # somewhere else), and the consecutive-backoff run is
            # bounded (persistent non-finite grads at ever-shrinking
            # scale are corruption, not overflow).
            backed_off = (
                scale is not None
                and (self._prev_scale is None
                     or scale <= self._prev_scale)
            )
            if (backed_off
                    and self._amp_backoff_run < _AMP_BACKOFF_RUN_LIMIT):
                self._amp_backoff_run += 1
                self.stats["amp_backoff_steps"] += 1
                self._prev_scale = scale
                return user_outs, self.VERDICT_OK
            kind = "nan_inf_grad"
        else:
            self._amp_backoff_run = 0
            if loss is not None:
                spike, z = self._loss_window.judge(loss)
                if spike:
                    kind = "loss_spike"
            if kind is None and health is not None:
                spike, z = self._health_window.judge(health)
                if spike:
                    kind = "grad_explosion"
        self._prev_scale = scale
        if kind is None:
            return user_outs, self.VERDICT_OK
        return user_outs, self._anomaly(step, kind, loss, health)

    def on_nan_error(self, step, err):
        """The FLAGS_check_nan_inf post-run scan raised before the
        trainer saw any fetched values: same immediate-anomaly path,
        attributed to the offending fetch var."""
        return self._anomaly(
            step, "nan_inf_fetch:%s" % getattr(err, "var_name", "?"),
            None, None,
        )

    def _anomaly(self, step, kind, loss, health):
        """Walk the response ladder for one anomalous step. Returns
        VERDICT_SKIP, or raises RollbackSignal / GuardianGiveup."""
        from ..fluid import profiler as _profiler

        _profiler.bump_counter("train_anomalies")
        self.stats["anomalies"] += 1
        self.stats["kinds"][kind] = self.stats["kinds"].get(kind, 0) + 1
        self.drop_steps.add(step)
        self._write_marker(step, kind)
        print(
            "guardian: ANOMALY step=%d kind=%s loss=%s health=%s"
            % (step, kind, loss, health),
            flush=True,
        )
        if self.skips_used < self.max_skips:
            self.skips_used += 1
            self.stats["skips"] += 1
            _profiler.bump_counter("train_skipped_steps")
            return self.VERDICT_SKIP
        if (self.ckpt_manager is not None
                and self.rollbacks_used < self.max_rollbacks):
            raise RollbackSignal(step, kind)
        raise GuardianGiveup({
            "anomaly_step": step,
            "kind": kind,
            "loss": loss,
            "health": health,
            "skips_used": self.skips_used,
            "rollbacks_used": self.rollbacks_used,
            "max_skips": self.max_skips,
            "max_rollbacks": self.max_rollbacks,
            "has_ckpt_manager": self.ckpt_manager is not None,
        })

    # -- responses -----------------------------------------------------------

    def restore_skip(self, scope, program=None):
        """Discard the just-applied update: re-reference the pre-step
        buffers captured by ``pre_step`` and un-burn the PRNG run index
        the discarded run consumed (so dropout masks line up with a
        clean run on the surviving schedule)."""
        from ..fluid import core

        scope = scope if scope is not None else core.global_scope()
        program = program or self.program
        if self._shadow is None:
            raise RuntimeError("restore_skip without a pre_step shadow")
        for n, v in self._shadow.items():
            if v is not None:
                scope.set(n, v)
        self._prev_scale = self._shadow_prev_scale
        counters = program.__dict__.get("_rng_run_counters")
        if counters is not None and scope in counters:
            counters[scope] = max(int(counters[scope]) - 1, 0)

    def execute_rollback(self, signal, scope, hb=None):
        """Restore the newest VERIFIED checkpoint, discard now-stale
        newer step dirs, and return the restored step (the trainer
        resumes the stream at restored+1 with the poisoned batch
        dropped). A multi-second restore beats ``status="rollback"``
        so the supervisor judges it under the startup-style grace."""
        from ..fluid import core
        from ..fluid import profiler as _profiler

        mgr = self.ckpt_manager
        t0 = time.perf_counter()
        if hb is not None:
            hb.beat(signal.step, status="rollback", force=True)
        try:
            mgr.wait()  # drain in-flight saves; a stale writer error
        except Exception:  # must not mask the rollback itself
            pass
        target = mgr.newest_verified_step()
        if target is None:
            raise GuardianGiveup({
                "anomaly_step": signal.step,
                "kind": signal.kind,
                "reason": "no_verified_checkpoint",
                "skips_used": self.skips_used,
                "rollbacks_used": self.rollbacks_used,
            })
        mgr.discard_steps_after(target)
        scope = scope if scope is not None else core.global_scope()
        restored = mgr.restore(self.program, scope=scope, step=target)
        self.rollbacks_used += 1
        self.stats["rollbacks"] += 1
        # replayed steps re-enter the spike windows; judging them
        # against pre-rollback statistics would double-count the series
        self._loss_window.reset()
        self._health_window.reset()
        self._prev_scale = None
        self._shadow_prev_scale = None
        self._amp_backoff_run = 0
        self._shadow = None
        _profiler.bump_counter("train_rollbacks")
        _profiler.bump_histogram(
            "guardian_rollback_ms", (time.perf_counter() - t0) * 1000.0
        )
        print(
            "guardian: ROLLBACK anomaly_step=%d -> restored step %d "
            "(%.0f ms), dropping %s on replay"
            % (signal.step, restored,
               (time.perf_counter() - t0) * 1000.0,
               sorted(self.drop_steps)),
            flush=True,
        )
        return restored
