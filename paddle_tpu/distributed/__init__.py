"""Distributed launch + host services (reference: python/paddle/distributed/).

``launch`` keeps the reference CLI; ``supervisor`` is the elastic layer
under it (heartbeat liveness, gang teardown, restart-with-resume)."""

from . import elastic  # noqa: F401
from . import supervisor  # noqa: F401
from . import launch  # noqa: F401
