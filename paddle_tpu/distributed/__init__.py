"""Distributed launch + host services (reference: python/paddle/distributed/)."""

from . import launch  # noqa: F401
