"""Chrome-trace timeline export (reference: tools/timeline.py, which
converts profiler.proto to chrome://tracing JSON).

TPU note: device-side timelines come from the jax.profiler (xprof) trace
the profiler starts alongside; this file covers the host-event timeline in
the same chrome://tracing format the reference emitted, so existing
tooling/habits keep working."""

from __future__ import annotations

import json


def save_chrome_trace(records, path):
    """records: [(name, start_s, end_s, tid)] -> chrome trace JSON file."""
    events = []
    if records:
        t0 = min(r[1] for r in records)
    else:
        t0 = 0.0
    for name, start, end, tid in records:
        events.append(
            {
                "name": name,
                "cat": "host",
                "ph": "X",
                "ts": (start - t0) * 1e6,  # microseconds
                "dur": (end - start) * 1e6,
                "pid": 0,
                "tid": tid % 100000,
                "args": {},
            }
        )
    trace = {"traceEvents": events, "displayTimeUnit": "ms"}
    with open(path, "w") as f:
        json.dump(trace, f)
    return path


class Timeline(object):
    """API-compatible shim of the reference's Timeline class; consumes
    profiler.get_records() tuples [(name, start, end, tid)]."""

    def __init__(self, records):
        self._records = list(records or [])

    def generate_chrome_trace(self):
        events = []
        t0 = min((r[1] for r in self._records), default=0.0)
        for name, start, end, tid in self._records:
            events.append(
                {
                    "name": name, "cat": "host", "ph": "X",
                    "ts": (start - t0) * 1e6,
                    "dur": (end - start) * 1e6,
                    "pid": 0, "tid": tid % 100000, "args": {},
                }
            )
        return json.dumps({"traceEvents": events})
