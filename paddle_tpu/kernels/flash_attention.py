"""Flash attention as a Pallas TPU kernel — forward AND backward.

The hot op of every transformer (reference target: the CUDA
`multihead_matmul` fused kernel, fused_multihead_matmul_op.cu, built for
exactly this BERT attention pattern). A naive attention materializes the
[S, S] score matrix in HBM twice per direction — at seq 384+ that dwarfs
the useful traffic. These kernels keep the whole
softmax(QK^T·scale + bias)V pipeline in VMEM in both directions:

forward (online softmax, per (head, q-block) program):
  for each K/V block:  m' = max(m, rowmax(s))
                       acc = acc·e^(m-m') + e^(s-m') @ v_blk
                       l   = l·e^(m-m') + rowsum(e^(s-m'))
  o = acc / l;  lse = m + log(l)          (lse saved for the backward)

backward (two kernels, scores recomputed blockwise from q,k + lse — the
standard FlashAttention backward):
  delta = rowsum(dO ∘ O)                  (== rowsum(dP ∘ P), so the
                                           softmax jacobian needs no [S,S])
  p  = e^(s − lse)
  dq-kernel  (per q-block, sweep kv):  ds = p ∘ (dO V^T − delta)
                                       dq += ds @ K · scale
  dkv-kernel (per kv-block, sweep q):  dv += p^T @ dO
                                       dk += ds^T @ (q·scale)
                                       d(bias) accumulated blockwise

Layout [B, N, S, D] (batch, heads, seq, head_dim); fp32 accumulation
regardless of input dtype (MXU ``preferred_element_type``).

Bias comes in two flavors, usable together:
- ``key_bias`` [B*N, Sk]: additive per KEY (BERT padding masks) —
  broadcast over query rows inside the kernel; gradient accumulated to
  the same [B*N, Sk] shape in the dkv kernel.
- ``bias``: a general additive tensor broadcastable to [B, N, Sq, Sk]
  (relative-position tables, ALiBi slopes). Normalized to [G, Sq, Sk]
  with G ∈ {1, B, B·N}; flat head h reads row h // (B·N // G), so heads
  sharing a row are CONSECUTIVE, and the dkv grid is transposed (kv-block
  axis outermost, head axis innermost) so its gradient block is revisited
  by consecutive programs — the TPU grid is a sequential loop, which
  makes blockwise accumulation across programs well-defined. A per-head
  bias shared across the batch ([1, N, Sq, Sk]) is handled by running
  the whole attention head-major (role swap B↔N in ``flash_attention``).

The kernels run on the TPU backend (or anywhere under ``interpret=True``
for tests); ``flash_attention`` transparently falls back to the jnp
reference on other backends so models stay portable.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

BLOCK_Q = 128
BLOCK_K = 128
_NEG = -1e30

# splitmix32-style avalanche constants for the stateless dropout hash
_H1, _H2, _H3 = 0x9E3779B1, 0x85EBCA77, 0xC2B2AE3D
_M1, _M2 = 0x2C1B3C6D, 0x297A2D39


def _hash_keep(rows, cols, head, seed_u32, rate):
    """Deterministic keep-mask from ABSOLUTE (row, col) coordinates, the
    flat head index and a per-call seed — a counter-based splitmix32-style
    scramble, so the forward kernel, both backward kernels and the dense
    fallback all regenerate bit-identical masks with no stored [S, S]
    tensor. ``rows``/``cols`` are broadcast-compatible int32 arrays;
    ``head`` may be a traced scalar (pl.program_id) or an array."""
    u = jnp.uint32
    n = (
        rows.astype(u) * u(_H1)
        + cols.astype(u) * u(_H2)
        + (seed_u32 + jnp.asarray(head, u) * u(_H3))
    )
    n = n ^ (n >> u(15))
    n = n * u(_M1)
    n = n ^ (n >> u(12))
    n = n * u(_M2)
    n = n ^ (n >> u(15))
    # keep iff hash < keep_prob * 2^32 (threshold is static)
    thresh = int((1.0 - float(rate)) * 4294967296.0)
    return n < u(min(thresh, 4294967295))


def reference_attention(q, k, v, bias=None, causal=False, scale=None):
    """Pure-jnp oracle, [B, N, S, D]; bias broadcastable to [B, N, S, S]."""
    d = q.shape[-1]
    s = jnp.einsum("bnqd,bnkd->bnqk", q, k).astype(jnp.float32)
    s = s * (scale if scale is not None else 1.0 / np.sqrt(d))
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    if causal:
        S = q.shape[2]
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask[None, None], s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bnqk,bnkd->bnqd", p.astype(q.dtype), v)


def _scores(q, kblk, scale, key_bias_row, bias_blk, row_off, col_off,
            causal, block_q, block_k):
    """[BQ, BK] masked scores. ``q``/``kblk`` stay in their INPUT dtype:
    the MXU runs bf16×bf16→fp32 at full rate but fp32×fp32 at a fraction
    of it, so the dot takes the raw operands and only the accumulator is
    fp32 (``preferred_element_type``); the softmax scale lands on the
    fp32 scores. ``key_bias_row`` is a [1, BK] row that broadcasts over
    query rows. Shared by all three kernels so forward and backward can
    never disagree on masking."""
    s = jax.lax.dot_general(
        q, kblk, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    s = s * scale + key_bias_row
    if bias_blk is not None:
        s = s + bias_blk.astype(jnp.float32)
    if causal:
        row, col = _block_coords(row_off, col_off, block_q, block_k)
        s = jnp.where(col <= row, s, _NEG)
    return s


# --------------------------------------------------------------------------
# kernels
# --------------------------------------------------------------------------


def _block_coords(row_off, col_off, block_q, block_k):
    rows = row_off + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    cols = col_off + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    return rows, cols


def _hash_head(h, head_swap):
    """Flat head index in the CALLER's [B, N] layout for the dropout hash.
    Under the head-major role swap (per-head shared bias) the kernels run
    with heads flattened as n·B + b; remapping to b·N + n keeps the mask
    bit-identical to the unswapped kernels and the dense fallback, so the
    swap never changes which attention entries drop."""
    if head_swap is None:
        return h
    B0, N0 = head_swap
    return (h % B0) * N0 + h // B0


def _fwd_kernel(q_ref, k_ref, v_ref, key_bias_ref, bias_ref, seed_ref,
                o_ref, lse_ref, *, scale, causal, kv_len, block_q, block_k,
                dropout_rate, head_swap=None):
    """One (head, q-block) program: online softmax over kv blocks; also
    writes the per-row logsumexp residual for the backward. Dropout masks
    the accumulated probabilities only — ``l``/``lse`` stay unmasked, so
    out = (1/keep)·Σ_j mask_ij·P_ij·V_j (standard non-renormalizing
    dropout) and the backward's rowsum(dO∘O) trick still yields delta."""
    from jax.experimental import pallas as pl

    q = q_ref[0]                              # [BQ, D], input dtype
    h = pl.program_id(0)
    qi = pl.program_id(1)
    n_kb = kv_len // block_k
    # read the SMEM seed only when dropout is live: the rate-0 kernel
    # traces to exactly the pre-dropout op stream (the operand is
    # still bound, just never loaded)
    seed_u = (seed_ref[0, 0].astype(jnp.int32).astype(jnp.uint32)
              if dropout_rate > 0.0 else None)

    m = jnp.full((block_q, 1), _NEG, jnp.float32)
    l = jnp.zeros((block_q, 1), jnp.float32)
    acc = jnp.zeros((block_q, q.shape[-1]), jnp.float32)

    for kb in range(n_kb):
        ks = slice(kb * block_k, (kb + 1) * block_k)
        s = _scores(
            q, k_ref[0, ks, :], scale, key_bias_ref[0, :, ks],
            None if bias_ref is None else bias_ref[0, :, ks],
            qi * block_q, kb * block_k, causal, block_q, block_k,
        )
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l = l * alpha + p.sum(axis=-1, keepdims=True)
        if dropout_rate > 0.0:
            rows, cols = _block_coords(
                qi * block_q, kb * block_k, block_q, block_k
            )
            p = jnp.where(
                _hash_keep(rows, cols, _hash_head(h, head_swap), seed_u,
                           dropout_rate),
                p, 0.0,
            )
        # p rounds to the value dtype for the MXU (as the dense reference
        # does with p.astype(q.dtype) @ v); accumulation stays fp32
        acc = acc * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0, ks, :], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m = m_new
    l_safe = jnp.maximum(l, 1e-30)
    if dropout_rate > 0.0:
        l_safe = l_safe * (1.0 - dropout_rate)
    o_ref[0] = (acc / l_safe).astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(jnp.maximum(l, 1e-30))


def _bwd_dq_kernel(q_ref, k_ref, v_ref, key_bias_ref, bias_ref, do_ref,
                   lse_ref, delta_ref, seed_ref, dq_ref, *, scale, causal,
                   kv_len, block_q, block_k, dropout_rate, head_swap=None):
    """One (head, q-block) program: dq = Σ_kv (p∘(dO V^T − delta)) K·scale.
    With dropout the mask/keep lands on dp (= d out/d P path); p itself
    stays unmasked — that IS the softmax jacobian of the dropped output."""
    from jax.experimental import pallas as pl

    q = q_ref[0]                                # [BQ, D], input dtype
    do = do_ref[0]                              # [BQ, D], input dtype
    lse = lse_ref[0]                            # [BQ, 1]
    delta = delta_ref[0]                        # [BQ, 1]
    h = pl.program_id(0)
    qi = pl.program_id(1)
    n_kb = kv_len // block_k
    # read the SMEM seed only when dropout is live: the rate-0 kernel
    # traces to exactly the pre-dropout op stream (the operand is
    # still bound, just never loaded)
    seed_u = (seed_ref[0, 0].astype(jnp.int32).astype(jnp.uint32)
              if dropout_rate > 0.0 else None)
    inv_keep = 1.0 / (1.0 - dropout_rate) if dropout_rate > 0.0 else 1.0

    dq = jnp.zeros((block_q, q.shape[-1]), jnp.float32)
    for kb in range(n_kb):
        ks = slice(kb * block_k, (kb + 1) * block_k)
        kblk = k_ref[0, ks, :]                  # [BK, D], input dtype
        s = _scores(
            q, kblk, scale, key_bias_ref[0, :, ks],
            None if bias_ref is None else bias_ref[0, :, ks],
            qi * block_q, kb * block_k, causal, block_q, block_k,
        )
        p = jnp.exp(s - lse)                    # [BQ, BK]
        dp = jax.lax.dot_general(               # dO @ V^T
            do, v_ref[0, ks, :].astype(do.dtype), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if dropout_rate > 0.0:
            rows, cols = _block_coords(
                qi * block_q, kb * block_k, block_q, block_k
            )
            dp = jnp.where(
                _hash_keep(rows, cols, _hash_head(h, head_swap), seed_u,
                           dropout_rate),
                dp * inv_keep, 0.0,
            )
        # ds rounds to the key dtype for the MXU (standard flash backward);
        # fp32 accumulation via preferred_element_type
        ds = p * (dp - delta)
        dq = dq + jax.lax.dot_general(          # ds @ K
            ds.astype(kblk.dtype), kblk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, key_bias_ref, bias_ref, do_ref,
                    lse_ref, delta_ref, seed_ref, dk_ref, dv_ref, dkb_ref,
                    dbias_ref, *, scale, causal, q_len, block_q, block_k,
                    bias_group, dropout_rate, head_swap=None,
                    head_major=False):
    """One (kv-block, head) program. Two grid orders:

    - shared-bias path (``head_major=False``): TRANSPOSED grid, kv axis
      outermost / head axis innermost, so the shared-bias gradient block
      is revisited by consecutive programs (safe sequential accumulation
      on TPU);
    - KeyBias-only path (``head_major=True``): head axis outermost, so
      the full q/dO row blocks (index maps keyed on the head only) are
      REUSED across the inner kv sweep instead of refetched from HBM on
      every program — at seq 4096 that's ~1 MB of q+dO per program saved."""
    from jax.experimental import pallas as pl

    if head_major:
        h = pl.program_id(0)    # flat head index
        kb = pl.program_id(1)   # kv-block index
    else:
        kb = pl.program_id(0)   # kv-block index
        h = pl.program_id(1)    # flat head index
    k = k_ref[0]                                # [BK, D], input dtype
    v = v_ref[0]                                # [BK, D], input dtype
    key_bias_row = key_bias_ref[0]              # [1, BK]
    n_qb = q_len // block_q
    # read the SMEM seed only when dropout is live: the rate-0 kernel
    # traces to exactly the pre-dropout op stream (the operand is
    # still bound, just never loaded)
    seed_u = (seed_ref[0, 0].astype(jnp.int32).astype(jnp.uint32)
              if dropout_rate > 0.0 else None)
    inv_keep = 1.0 / (1.0 - dropout_rate) if dropout_rate > 0.0 else 1.0

    dk = jnp.zeros((block_k, k.shape[-1]), jnp.float32)
    dv = jnp.zeros((block_k, k.shape[-1]), jnp.float32)
    dkb = jnp.zeros((1, block_k), jnp.float32)
    dbias = (
        None if dbias_ref is None
        else jnp.zeros((q_len, block_k), jnp.float32)
    )

    for ib in range(n_qb):
        qs = slice(ib * block_q, (ib + 1) * block_q)
        q = q_ref[0, qs, :]                     # [BQ, D], input dtype
        do = do_ref[0, qs, :]                   # [BQ, D], input dtype
        lse = lse_ref[0, qs, :]                 # [BQ, 1]
        delta = delta_ref[0, qs, :]             # [BQ, 1]
        s = _scores(
            q, k, scale, key_bias_row,
            None if bias_ref is None else bias_ref[0, qs, :],
            ib * block_q, kb * block_k, causal, block_q, block_k,
        )
        p = jnp.exp(s - lse)                    # [BQ, BK]
        dp = jax.lax.dot_general(               # dO @ V^T
            do, v.astype(do.dtype), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        # fp32 intermediates round to the operand dtype for the MXU;
        # accumulators (dk/dv/dkb/dbias) stay fp32
        if dropout_rate > 0.0:
            rows, cols = _block_coords(
                ib * block_q, kb * block_k, block_q, block_k
            )
            keep = _hash_keep(rows, cols, _hash_head(h, head_swap),
                              seed_u, dropout_rate)
            dv = dv + jax.lax.dot_general(      # (mask∘p/keep)^T @ dO
                jnp.where(keep, p * inv_keep, 0.0).astype(do.dtype), do,
                (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            dp = jnp.where(keep, dp * inv_keep, 0.0)
        else:
            dv = dv + jax.lax.dot_general(      # p^T @ dO
                p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        ds = p * (dp - delta)
        dk = dk + jax.lax.dot_general(          # ds^T @ q (·scale at write)
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dkb = dkb + ds.sum(axis=0, keepdims=True)
        if dbias is not None:
            dbias = jax.lax.dynamic_update_slice(dbias, ds, (ib * block_q, 0))

    dk_ref[0] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)
    dkb_ref[0] = dkb
    if dbias_ref is not None:
        # heads h with equal h // bias_group share one gradient row;
        # they are consecutive on the (innermost) head axis
        @pl.when(h % bias_group == 0)
        def _init():
            dbias_ref[0] = dbias

        @pl.when(h % bias_group != 0)
        def _accumulate():
            dbias_ref[0] += dbias


def _decode_kernel(q_ref, k_ref, v_ref, key_bias_ref, o_ref, *, scale,
                   kv_len, block_q, block_k):
    """One head per program: the decode-mode single-query path. The whole
    (padded) query block is one [BQ, D] tile — autoregressive decode has
    exactly one live query row per slot, padded up to the Mosaic minimum —
    swept over the K/V cache blocks with the same online softmax as the
    training kernel. No lse output (nothing differentiates through
    decode), no dropout (is_test), no causal flag: the per-slot key bias
    carries ALL masking (cache positions at or beyond the slot's length
    ride in at -1e4), which is what makes one compiled program serve every
    mix of slot lengths."""
    q = q_ref[0]                              # [BQ, D], input dtype
    m = jnp.full((block_q, 1), _NEG, jnp.float32)
    l = jnp.zeros((block_q, 1), jnp.float32)
    acc = jnp.zeros((block_q, q.shape[-1]), jnp.float32)
    for kb in range(kv_len // block_k):
        ks = slice(kb * block_k, (kb + 1) * block_k)
        s = _scores(
            q, k_ref[0, ks, :], scale, key_bias_ref[0, :, ks],
            None, 0, kb * block_k, False, block_q, block_k,
        )
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l = l * alpha + p.sum(axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0, ks, :], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m = m_new
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def flash_decode_attention(q, k, v, key_bias=None, scale=None,
                           interpret=None):
    """Decode-mode attention: ONE query token per (batch-slot, head)
    against a fixed-shape K/V cache.

    q [B, N, 1, D]; k/v [B, N, S, D] (the cache, S = max cache length);
    ``key_bias`` additive mask over cache positions, [B, S] / [B*N, S] or
    broadcastable — the caller masks positions >= the slot's live length
    with -1e4 (and that mask alone carries causality: a slot's cache
    never holds a future token). Forward-only (no custom VJP — decode is
    inference), fp32 accumulation.

    Runs the Pallas kernel on TPU (or under ``interpret=True``), and a
    dense jnp reference on other backends — same dispatch contract as
    ``flash_attention``."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, N, Sq, D = q.shape
    Sk = k.shape[2]
    if Sq != 1:
        raise ValueError(
            "flash_decode_attention is the single-query path, got Sq=%d"
            % Sq
        )
    scale = scale if scale is not None else 1.0 / float(np.sqrt(D))
    kb = _normalize_key_bias(key_bias, B, N, Sk)
    on_tpu = jax.default_backend() == "tpu"
    if interpret is None and not on_tpu:
        # dense fallback: bit-compatible math with reference_attention
        s = jnp.einsum("bnqd,bnkd->bnqk", q, k).astype(jnp.float32) * scale
        if kb is not None:
            s = s + kb.reshape(B, N, 1, Sk)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bnqk,bnkd->bnqd", p.astype(q.dtype), v)
    if kb is None:
        kb = jnp.zeros((B * N, Sk), jnp.float32)
    qf, kf, vf, kbp, _bf, _g, geom = _prep(q, k, v, kb, None)
    _B, _N, _Sq, _Sk, Sqp, Skp, _bq, bk = geom
    kernel = functools.partial(
        _decode_kernel, scale=scale, kv_len=Skp, block_q=Sqp, block_k=bk,
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((B * N, Sqp, D), q.dtype),
        grid=(B * N,),
        in_specs=[
            pl.BlockSpec((1, Sqp, D), lambda h: (h, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Skp, D), lambda h: (h, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Skp, D), lambda h: (h, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, Skp), lambda h: (h, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, Sqp, D), lambda h: (h, 0, 0),
                               memory_space=pltpu.VMEM),
        interpret=bool(interpret),
    )(qf, kf, vf, kbp[:, None, :])
    return out[:, :1, :].reshape(B, N, 1, D)


def _decode_paged_kernel(tables_ref, q_ref, k_ref, v_ref, kb_ref, o_ref,
                         m_ref, l_ref, acc_ref, *, scale, block_q):
    """Paged decode step, one (slot, head, logical-block) program: the
    grid's innermost dimension sweeps a slot's LOGICAL blocks while the
    K/V BlockSpec index maps read the slot's block TABLE (a
    scalar-prefetch operand) to pick the physical pool block — the DMA
    engine chases the indirection, the kernel body never sees it. Online
    softmax state (m, l, acc) lives in VMEM scratch across the sweep;
    the output block is written once on the last logical block. Same
    masking contract as ``_decode_kernel``: the per-slot key bias
    carries ALL masking, including sink-block garbage past the slot's
    live length."""
    from jax.experimental import pallas as pl

    i = pl.program_id(2)

    @pl.when(i == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    q = q_ref[0, 0]                               # [BQ, D], input dtype
    kblk = k_ref[0, 0]                            # [blk, D]
    block_k = kblk.shape[0]
    s = _scores(q, kblk, scale, kb_ref[0], None, 0, 0, False,
                block_q, block_k)
    m = m_ref[...]
    m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new)
    l_ref[...] = l_ref[...] * alpha + p.sum(axis=-1, keepdims=True)
    acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
        p.astype(v_ref.dtype), v_ref[0, 0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_ref[...] = m_new

    @pl.when(i == pl.num_programs(2) - 1)
    def _emit():
        o_ref[0, 0] = (acc_ref[...]
                       / jnp.maximum(l_ref[...], 1e-30)).astype(o_ref.dtype)


def flash_decode_paged_attention(q, k_pool, v_pool, tables, key_bias=None,
                                 scale=None, interpret=None):
    """Decode-mode attention reading K/V THROUGH a block table: ``q``
    [B, N, 1, D] (one live token per slot) against a shared paged pool
    ``k_pool``/``v_pool`` [blocks, N, block, D], with ``tables``
    [B, max_blocks] int32 mapping each slot's logical block number to a
    physical pool block. ``key_bias`` [B, S] (S = max_blocks*block)
    additively masks positions at/beyond the slot's live length — which
    also covers any garbage the mapped blocks hold (the serving layer
    parks idle table entries on a sink block). Tables are runtime data:
    on TPU they ride scalar prefetch, so the index maps resolve the
    indirection before each DMA and ONE compiled kernel serves every
    table layout. Forward-only; dense gather-then-softmax fallback off
    TPU — bit-compatible with gathering the logical rows and calling
    ``flash_decode_attention``."""
    from jax.experimental import pallas as pl  # noqa: F401 (dispatch)
    from jax.experimental.pallas import tpu as pltpu

    B, N, Sq, D = q.shape
    blocks, Np, blk, Dp = k_pool.shape
    MB = tables.shape[1]
    S = MB * blk
    if Sq != 1:
        raise ValueError(
            "flash_decode_paged_attention is the single-query path, "
            "got Sq=%d" % Sq
        )
    if (Np, Dp) != (N, D):
        raise ValueError(
            "pool geometry %r does not match q heads/depth (%d, %d)"
            % (k_pool.shape, N, D)
        )
    scale = scale if scale is not None else 1.0 / float(np.sqrt(D))
    kb = _normalize_key_bias(key_bias, B, N, S)
    on_tpu = jax.default_backend() == "tpu"
    tables = tables.astype(jnp.int32)
    if interpret is None and not on_tpu:
        # dense fallback: gather the logical rows, then the same math as
        # flash_decode_attention's reference path
        rows_k = k_pool[tables].transpose(0, 2, 1, 3, 4).reshape(
            B, N, S, D
        )
        rows_v = v_pool[tables].transpose(0, 2, 1, 3, 4).reshape(
            B, N, S, D
        )
        s = jnp.einsum("bnqd,bnkd->bnqk", q, rows_k).astype(
            jnp.float32
        ) * scale
        if kb is not None:
            s = s + kb.reshape(B, N, 1, S)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bnqk,bnkd->bnqd", p.astype(q.dtype), rows_v)
    if kb is None:
        kb = jnp.zeros((B * N, S), jnp.float32)
    kb = kb.reshape(B, N, S)
    BQ = _round_up(Sq, 8)                      # Mosaic sublane minimum
    qp = jnp.pad(q, ((0, 0), (0, 0), (0, BQ - Sq), (0, 0)))
    kernel = functools.partial(
        _decode_paged_kernel, scale=scale, block_q=BQ,
    )
    # index maps receive the grid indices first, then the prefetched
    # scalar ref (the table) — the K/V maps dereference it so each DMA
    # pulls the slot's PHYSICAL block for logical block i
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B, N, MB),
        in_specs=[
            pl.BlockSpec((1, 1, BQ, D), lambda b, n, i, t: (b, n, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, blk, D),
                         lambda b, n, i, t: (t[b, i], n, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, blk, D),
                         lambda b, n, i, t: (t[b, i], n, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, blk), lambda b, n, i, t: (b, n, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, 1, BQ, D),
                               lambda b, n, i, t: (b, n, 0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((BQ, 1), jnp.float32),
            pltpu.VMEM((BQ, 1), jnp.float32),
            pltpu.VMEM((BQ, D), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((B, N, BQ, D), q.dtype),
        grid_spec=grid_spec,
        interpret=bool(interpret),
    )(tables, qp, k_pool, v_pool, kb)
    return out[:, :, :1, :]


# --------------------------------------------------------------------------
# padding / plumbing
# --------------------------------------------------------------------------


def _round_up(x, m):
    return (x + m - 1) // m * m


def _pad_to(S, block):
    Sp = _round_up(S, 8)
    return _round_up(Sp, min(block, Sp))


def _prep(q, k, v, key_bias, bias, g=None):
    """Flatten heads, pad seq lens to tile multiples. Padded KEYS get
    key-bias −inf (never receive weight); padded QUERY rows are sliced
    away by the caller. Returns the padded operands + geometry."""
    B, N, Sq, D = q.shape
    Sk = k.shape[2]
    Sqp, Skp = _pad_to(Sq, BLOCK_Q), _pad_to(Sk, BLOCK_K)
    bq, bk = min(BLOCK_Q, Sqp), min(BLOCK_K, Skp)
    qf = q.reshape(B * N, Sq, D)
    kf = k.reshape(B * N, Sk, D)
    vf = v.reshape(B * N, Sk, D)
    kb = jnp.broadcast_to(key_bias, (B * N, Sk))
    if Sqp != Sq:
        qf = jnp.pad(qf, ((0, 0), (0, Sqp - Sq), (0, 0)))
    if Skp != Sk:
        kf = jnp.pad(kf, ((0, 0), (0, Skp - Sk), (0, 0)))
        vf = jnp.pad(vf, ((0, 0), (0, Skp - Sk), (0, 0)))
        kb = jnp.pad(kb, ((0, 0), (0, Skp - Sk)), constant_values=_NEG)
    bf = None
    if bias is not None:
        bf = bias
        if Sqp != Sq or Skp != Sk:
            # zero-padded: padded keys are already excluded via key-bias
            bf = jnp.pad(bf, ((0, 0), (0, Sqp - Sq), (0, Skp - Sk)))
    if g is not None and Sqp != Sq:
        g = jnp.pad(g.reshape(B * N, Sq, D), ((0, 0), (0, Sqp - Sq), (0, 0)))
    elif g is not None:
        g = g.reshape(B * N, Sq, D)
    return qf, kf, vf, kb, bf, g, (B, N, Sq, Sk, Sqp, Skp, bq, bk)


def _common_in_specs(pl, pltpu, geom, G, D):
    """in_specs for (q, k, v, key_bias[, bias]) shared by the two
    (head, q-block)-grid kernels (forward and dq). Vector operands ride
    with an explicit singleton dim ([BN, 1, S] rows / [BN, S, 1] columns)
    so every block's trailing two dims satisfy the Mosaic (8, 128) tiling
    rule (a (1, S) block of a rank-2 array does not)."""
    B, N, Sq, Sk, Sqp, Skp, bq, bk = geom
    specs = [
        pl.BlockSpec((1, bq, D), lambda h, i: (h, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, Skp, D), lambda h, i: (h, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, Skp, D), lambda h, i: (h, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1, Skp), lambda h, i: (h, 0, 0),
                     memory_space=pltpu.VMEM),
    ]
    if G is not None:
        group = (B * N) // G
        specs.append(
            pl.BlockSpec((1, bq, Skp), lambda h, i: (h // group, i, 0),
                         memory_space=pltpu.VMEM)
        )
    return specs


# --------------------------------------------------------------------------
# custom-vjp core
# --------------------------------------------------------------------------


def _seed_spec(pl, pltpu):
    # scalar param rides SMEM — the canonical Pallas-TPU scalar pattern,
    # exempt from the (8, 128) VMEM tiling rules
    return pl.BlockSpec((1, 1), lambda *_: (0, 0), memory_space=pltpu.SMEM)


def _flash_fwd_impl(q, k, v, key_bias, bias, seed, causal, scale,
                    dropout_rate, interpret, head_swap=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    qf, kf, vf, kb, bf, _, geom = _prep(q, k, v, key_bias, bias)
    B, N, Sq, Sk, Sqp, Skp, bq, bk = geom
    D = q.shape[-1]
    G = None if bf is None else bf.shape[0]

    kernel = functools.partial(
        _fwd_kernel if bf is not None else _no_bias(_fwd_kernel),
        scale=scale, causal=causal, kv_len=Skp, block_q=bq, block_k=bk,
        dropout_rate=dropout_rate, head_swap=head_swap,
    )
    in_specs = _common_in_specs(pl, pltpu, geom, G, D) + [_seed_spec(pl, pltpu)]
    operands = (
        [qf, kf, vf, kb[:, None, :]]
        + ([bf] if bf is not None else []) + [seed]
    )
    out, lse = pl.pallas_call(
        kernel,
        out_shape=[
            jax.ShapeDtypeStruct((B * N, Sqp, D), q.dtype),
            jax.ShapeDtypeStruct((B * N, Sqp, 1), jnp.float32),
        ],
        grid=(B * N, Sqp // bq),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda h, i: (h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bq, 1), lambda h, i: (h, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        interpret=interpret,
    )(*operands)
    return out[:, :Sq, :].reshape(B, N, Sq, D), lse[:, :Sq, 0]


def _no_bias(kernel):
    """Adapter: drop the bias ref from a kernel's signature (Pallas passes
    exactly one ref per operand, so the no-bias variant has one fewer)."""
    @functools.wraps(kernel)
    def wrapped(q_ref, k_ref, v_ref, key_bias_ref, *rest, **kw):
        return kernel(q_ref, k_ref, v_ref, key_bias_ref, None, *rest, **kw)
    return wrapped


def _flash_bwd_core(causal, scale, dropout_rate, interpret, head_swap, res,
                    g, g_lse):
    """Shared backward. ``g_lse`` is the logsumexp cotangent from the
    with-lse entry point (ring attention's combine differentiates through
    each block's lse): d s_ij gains p_ij·g_lse_i, which folds into the
    delta term — ds = p∘(dp − (delta − g_lse)) — so the kernels run
    unchanged with an adjusted delta operand. With dropout, delta =
    rowsum(dO∘O) already equals Σ_j P·dP̂ (O carries the mask), so the
    trick survives; the kernels regenerate the mask from the seed."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    q, k, v, key_bias, bias, seed, out, lse = res
    qf, kf, vf, kb, bf, gf, geom = _prep(q, k, v, key_bias, bias, g=g)
    B, N, Sq, Sk, Sqp, Skp, bq, bk = geom
    D = q.shape[-1]
    G = None if bf is None else bf.shape[0]

    # delta = rowsum(dO ∘ O): tiny elementwise pass XLA fuses on its own
    delta = (g.astype(jnp.float32) * out.astype(jnp.float32)).sum(-1)
    delta = delta.reshape(B * N, Sq)
    if g_lse is not None:
        delta = delta - g_lse.astype(jnp.float32).reshape(B * N, Sq)
    if Sqp != Sq:
        delta = jnp.pad(delta, ((0, 0), (0, Sqp - Sq)))
        lse_p = jnp.pad(lse, ((0, 0), (0, Sqp - Sq)))
    else:
        lse_p = lse

    # ---- dq: same (head, q-block) grid as the forward ----
    dq_kernel = functools.partial(
        _bwd_dq_kernel if bf is not None else _no_bias(_bwd_dq_kernel),
        scale=scale, causal=causal, kv_len=Skp, block_q=bq, block_k=bk,
        dropout_rate=dropout_rate, head_swap=head_swap,
    )
    row_spec = pl.BlockSpec((1, bq, D), lambda h, i: (h, i, 0),
                            memory_space=pltpu.VMEM)
    col_spec = pl.BlockSpec((1, bq, 1), lambda h, i: (h, i, 0),
                            memory_space=pltpu.VMEM)
    kb3 = kb[:, None, :]                       # [BN, 1, Skp]
    lse3 = lse_p[:, :, None]                   # [BN, Sqp, 1]
    delta3 = delta[:, :, None]
    dq = pl.pallas_call(
        dq_kernel,
        out_shape=jax.ShapeDtypeStruct((B * N, Sqp, D), q.dtype),
        grid=(B * N, Sqp // bq),
        in_specs=_common_in_specs(pl, pltpu, geom, G, D)
        + [row_spec, col_spec, col_spec, _seed_spec(pl, pltpu)],
        out_specs=row_spec,
        interpret=interpret,
    )(*([qf, kf, vf, kb3] + ([bf] if bf is not None else [])
        + [gf, lse3, delta3, seed]))

    # ---- dk/dv/dkey_bias/dbias ----
    # Grid order depends on the bias mode (see _bwd_dkv_kernel): shared
    # bias needs the transposed (kv, head) grid for safe dbias
    # accumulation; the KeyBias-only path runs (head, kv) so the full
    # q/dO row blocks are reused across the inner kv sweep.
    head_major = bf is None
    group = None if G is None else (B * N) // G
    dkv_kernel = functools.partial(
        _bwd_dkv_kernel if bf is not None else _no_bias(_bwd_dkv_kernel),
        scale=scale, causal=causal, q_len=Sqp, block_q=bq, block_k=bk,
        bias_group=group or 1, dropout_rate=dropout_rate,
        head_swap=head_swap, head_major=head_major,
    )
    if bf is None:
        # adapter also has to drop the dbias OUT ref
        base = dkv_kernel

        def dkv_kernel(q_ref, k_ref, v_ref, key_bias_ref, do_ref, lse_ref,
                       delta_ref, seed_ref, dk_ref, dv_ref, dkb_ref):
            return base(q_ref, k_ref, v_ref, key_bias_ref, do_ref, lse_ref,
                        delta_ref, seed_ref, dk_ref, dv_ref, dkb_ref, None)

    # index maps below are written head-first; the transposed grid swaps
    # the program-id arguments, the head-major grid uses them verbatim
    if head_major:
        def hj(f):
            return f
    else:
        def hj(f):
            return lambda j, h: f(h, j)

    in_specs = [
        pl.BlockSpec((1, Sqp, D), hj(lambda h, j: (h, 0, 0)),
                     memory_space=pltpu.VMEM),       # q (full rows)
        pl.BlockSpec((1, bk, D), hj(lambda h, j: (h, j, 0)),
                     memory_space=pltpu.VMEM),       # k block
        pl.BlockSpec((1, bk, D), hj(lambda h, j: (h, j, 0)),
                     memory_space=pltpu.VMEM),       # v block
        pl.BlockSpec((1, 1, bk), hj(lambda h, j: (h, 0, j)),
                     memory_space=pltpu.VMEM),       # key bias block
    ]
    if bf is not None:
        in_specs.append(
            pl.BlockSpec((1, Sqp, bk), hj(lambda h, j: (h // group, 0, j)),
                         memory_space=pltpu.VMEM)    # bias column block
        )
    in_specs += [
        pl.BlockSpec((1, Sqp, D), hj(lambda h, j: (h, 0, 0)),
                     memory_space=pltpu.VMEM),       # dO (full rows)
        pl.BlockSpec((1, Sqp, 1), hj(lambda h, j: (h, 0, 0)),
                     memory_space=pltpu.VMEM),       # lse
        pl.BlockSpec((1, Sqp, 1), hj(lambda h, j: (h, 0, 0)),
                     memory_space=pltpu.VMEM),       # delta
        _seed_spec(pl, pltpu),                       # dropout seed
    ]
    out_shape = [
        jax.ShapeDtypeStruct((B * N, Skp, D), k.dtype),      # dk
        jax.ShapeDtypeStruct((B * N, Skp, D), v.dtype),      # dv
        jax.ShapeDtypeStruct((B * N, 1, Skp), jnp.float32),  # dkey_bias
    ]
    out_specs = [
        pl.BlockSpec((1, bk, D), hj(lambda h, j: (h, j, 0)),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bk, D), hj(lambda h, j: (h, j, 0)),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1, bk), hj(lambda h, j: (h, 0, j)),
                     memory_space=pltpu.VMEM),
    ]
    if bf is not None:
        out_shape.append(jax.ShapeDtypeStruct((G, Sqp, Skp), jnp.float32))
        out_specs.append(
            pl.BlockSpec((1, Sqp, bk), hj(lambda h, j: (h // group, 0, j)),
                         memory_space=pltpu.VMEM)
        )
    outs = pl.pallas_call(
        dkv_kernel,
        out_shape=out_shape,
        grid=(
            (B * N, Skp // bk) if head_major else (Skp // bk, B * N)
        ),
        in_specs=in_specs,
        out_specs=out_specs,
        interpret=interpret,
    )(*([qf, kf, vf, kb3] + ([bf] if bf is not None else [])
        + [gf, lse3, delta3, seed]))
    if bf is not None:
        dkf, dvf, dkb, dbias = outs
        dbias = dbias[:, :Sq, :Sk]
    else:
        dkf, dvf, dkb = outs
        dbias = None

    dq = dq[:, :Sq, :].reshape(q.shape)
    dk = dkf[:, :Sk, :].reshape(k.shape)
    dv = dvf[:, :Sk, :].reshape(v.shape)
    dkey_bias = dkb[:, 0, :Sk].astype(key_bias.dtype)
    return dq, dk, dv, dkey_bias, dbias, jnp.zeros_like(seed)


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9, 10))
def _flash_lse(q, k, v, key_bias, bias, seed, causal, scale, dropout_rate,
               interpret, head_swap):
    """(out, lse) variant: lse [B*N, Sq] is the per-row logsumexp of the
    masked scores — the residual blockwise/ring attention needs to
    combine per-block outputs across hops without renormalizing."""
    return _flash_fwd_impl(q, k, v, key_bias, bias, seed, causal, scale,
                           dropout_rate, interpret, head_swap)


def _flash_lse_fwd(q, k, v, key_bias, bias, seed, causal, scale,
                   dropout_rate, interpret, head_swap):
    out, lse = _flash_fwd_impl(q, k, v, key_bias, bias, seed, causal, scale,
                               dropout_rate, interpret, head_swap)
    return (out, lse), (q, k, v, key_bias, bias, seed, out, lse)


def _flash_lse_bwd(causal, scale, dropout_rate, interpret, head_swap, res,
                   cotangents):
    g, g_lse = cotangents
    return _flash_bwd_core(causal, scale, dropout_rate, interpret, head_swap,
                           res, g, g_lse)


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


# --------------------------------------------------------------------------
# public entry
# --------------------------------------------------------------------------


def _normalize_bias(bias, B, N, Sq, Sk):
    """-> (bias [G, Sq, Sk] with G ∈ {1, B, B·N}, head_major_swap)."""
    b = jnp.asarray(bias, jnp.float32)
    if b.ndim == 2:
        return b[None], False
    if b.ndim == 3:
        if b.shape[0] in (1, B * N) or (b.shape[0] == B and N == 1):
            return b, False
        raise ValueError(
            "3-D flash-attention bias must have leading dim 1 or B*N, got %r"
            % (b.shape,)
        )
    if b.ndim == 4:
        b0, b1 = b.shape[:2]
        if (b0, b1) == (1, 1):
            return b.reshape(1, Sq, Sk), False
        if b1 == 1 and b0 == B:
            return b.reshape(B, Sq, Sk), False          # per-batch rows
        if b0 == 1 and b1 == N:
            # per-head shared across batch: run attention head-major so
            # heads sharing a bias row stay consecutive (role swap B<->N)
            return b.reshape(N, Sq, Sk), True
        if (b0, b1) == (B, N):
            return b.reshape(B * N, Sq, Sk), False
        raise ValueError(
            "4-D flash-attention bias must broadcast from (1|B, 1|N, Sq, Sk),"
            " got %r" % (b.shape,)
        )
    raise ValueError("flash-attention bias must be 2-/3-/4-D, got %r"
                     % (b.shape,))


def _fallback_keep(B, N, Sq, Sk, seed, rate):
    """[B, N, Sq, Sk] keep-mask, bit-identical to what the kernels
    regenerate from the same seed (flat head h = b·N + n, absolute
    row/col — padding sits past the valid region so coords agree)."""
    heads = jnp.arange(B * N, dtype=jnp.int32).reshape(B, N, 1, 1)
    rows = jnp.arange(Sq, dtype=jnp.int32).reshape(1, 1, Sq, 1)
    cols = jnp.arange(Sk, dtype=jnp.int32).reshape(1, 1, 1, Sk)
    seed_u = seed.reshape(()).astype(jnp.uint32)
    return _hash_keep(rows, cols, heads, seed_u, rate)


def _norm_seed(dropout_seed):
    """Normalize any user seed (python int of any size, or traced int/f32
    scalar) to a (1, 1) f32 carrying a 23-bit value. A plain ``% 2^23``
    would ALIAS seeds (s and s + 2^23 give identical masks, and f32
    rounding collapses seeds ≥ 2^24 before the mod), so the full value is
    avalanche-mixed down to 23 bits first — distinct seeds give
    decorrelated masks."""
    s = 0 if dropout_seed is None else dropout_seed
    if isinstance(s, (int, np.integer)):
        # fold arbitrary-width python ints into 32 bits before the mix
        s = int(s)
        s = (s ^ (s >> 32) ^ (s >> 64)) & 0xFFFFFFFF
    u = jnp.asarray(s).reshape(()).astype(jnp.uint32)
    u = u ^ (u >> jnp.uint32(16))
    u = u * jnp.uint32(0x7FEB352D)
    u = u ^ (u >> jnp.uint32(15))
    u = u * jnp.uint32(0x846CA68B)
    u = u ^ (u >> jnp.uint32(16))
    return (u >> jnp.uint32(9)).astype(jnp.float32).reshape(1, 1)


def _normalize_key_bias(key_bias, B, N, Sk):
    """Raw key bias ([Sk] / [1, Sk] / [B, Sk] / [B*N, Sk] / broadcastable)
    -> the kernels' canonical [B*N, Sk] fp32 layout."""
    if key_bias is None:
        return None
    kb = key_bias.astype(jnp.float32)
    if kb.ndim == 1:
        kb = kb[None]
    kb = kb.reshape(-1, Sk)
    if kb.shape[0] == B and N > 1:
        kb = jnp.broadcast_to(kb[:, None, :], (B, N, Sk)).reshape(-1, Sk)
    return jnp.broadcast_to(kb, (B * N, Sk))


def flash_attention_bwd_from_residuals(q, k, v, key_bias, seed, out, lse, g,
                                       causal=False, scale=None,
                                       dropout_rate=0.0, interpret=None):
    """Backward kernels driven by SAVED forward residuals (out, lse and
    the dropout seed) instead of a forward replay.

    The fluid ``flash_attention_grad`` lowering uses this: its generic
    grad machinery re-traces the forward under jax.vjp, which XLA CSE's
    for pure ops but NOT for Pallas custom calls — so the forward kernel
    ran twice per training step (verified by custom-call count in the
    lowered module). The reference saves softmax statistics on its fused
    attention ops for exactly this reason (multihead_matmul_op.cu).

    KeyBias-only entry (no general [S, S] bias — callers with one take
    the replay path). ``seed`` is the RAW dropout seed exactly as the
    caller passed it to the forward entry (None when dropout was off) —
    it is re-normalized through the same ``_norm_seed`` pipeline here,
    so the backward kernels hash the identical keep-mask. Returns
    (dq, dk, dv, dkey_bias[B*N, Sk] fp32)."""
    B, N, Sq, d = q.shape
    Sk = k.shape[2]
    rate = float(dropout_rate or 0.0)
    scale = scale if scale is not None else 1.0 / float(np.sqrt(d))
    kb = _normalize_key_bias(key_bias, B, N, Sk)
    if kb is None:
        kb = jnp.zeros((B * N, Sk), jnp.float32)
    seed = _norm_seed(seed)
    lse = lse.reshape(B * N, Sq)
    res = (q, k, v, kb, None, seed, out, lse)
    dq, dk, dv, dkb, _dbias, _dseed = _flash_bwd_core(
        causal, scale, rate, bool(interpret), None, res, g, None
    )
    return dq, dk, dv, dkb


def flash_attention_lse(q, k, v, key_bias=None, bias=None, causal=False,
                        scale=None, dropout_rate=0.0, dropout_seed=None,
                        interpret=None):
    """Like ``flash_attention`` but also returns the per-row logsumexp
    [B, N, Sq] of the masked scores. This is the building block for
    blockwise/ring attention: per-hop block outputs combine as
    out = Σ_b o_b · exp(lse_b − logaddexp_b(lse)) with no [S, S] tensor
    and no renormalization pass. Fully differentiable (the lse cotangent
    folds into the backward's delta term).

    ``dropout_rate``/``dropout_seed``: standard attention-probability
    dropout (mask∘P/keep, no renormalization; lse reports the undropped
    distribution). The mask is a stateless counter-based hash of
    (head, row, col, seed) regenerated inside every kernel AND the dense
    fallback — bit-identical across all paths, nothing stored. The rate
    is static (recompile on change); the seed is traced (vary per step
    for free)."""
    B, N, Sq, d = q.shape
    Sk = k.shape[2]
    if causal and Sq != Sk:
        raise ValueError("causal flash attention needs Sq == Sk")
    rate = float(dropout_rate or 0.0)
    if not 0.0 <= rate < 1.0:
        raise ValueError("dropout_rate must be in [0, 1), got %r" % rate)
    if rate > 0.0 and dropout_seed is None:
        import warnings

        # a None seed normalizes to one CONSTANT seed: every call drops
        # the identical (head, row, col) entries — in a training loop
        # that is a frozen mask (biased training), not dropout. The fluid
        # op lowering threads a fresh per-step seed; direct users must too.
        warnings.warn(
            "flash_attention: dropout_rate > 0 with dropout_seed=None "
            "reuses ONE fixed dropout mask on every call; pass a "
            "per-step seed for real dropout", stacklevel=3)
    seed = _norm_seed(dropout_seed)
    scale = scale if scale is not None else 1.0 / float(np.sqrt(d))
    kb = _normalize_key_bias(key_bias, B, N, Sk)
    on_tpu = jax.default_backend() == "tpu"
    if interpret is None and not on_tpu:
        # dense fallback with an explicit lse (same math as the kernels)
        s = jnp.einsum("bnqd,bnkd->bnqk", q, k).astype(jnp.float32) * scale
        if kb is not None:
            s = s + kb.reshape(B, N, 1, Sk)
        if bias is not None:
            nb, swap = _normalize_bias(bias, B, N, Sq, Sk)
            G = nb.shape[0]
            if swap:
                s = s + nb.reshape(1, N, Sq, Sk)
            elif G == 1:
                s = s + nb.reshape(1, 1, Sq, Sk)
            elif G == B * N:
                s = s + nb.reshape(B, N, Sq, Sk)
            else:
                s = s + nb.reshape(B, 1, Sq, Sk)
        if causal:
            mask = jnp.tril(jnp.ones((Sq, Sk), bool))
            s = jnp.where(mask[None, None], s, _NEG)
        lse = jax.scipy.special.logsumexp(s, axis=-1)
        # bit-identical to reference_attention (softmax then cast), so the
        # no-lse entry point's fallback contract — "transparently the jnp
        # reference" — holds exactly
        p = jax.nn.softmax(s, axis=-1)
        if rate > 0.0:
            p = jnp.where(_fallback_keep(B, N, Sq, Sk, seed, rate),
                          p / (1.0 - rate), 0.0)
        out = jnp.einsum("bnqk,bnkd->bnqd", p.astype(q.dtype), v)
        return out, lse
    if kb is None:
        kb = jnp.zeros((B * N, Sk), jnp.float32)
    bf, swap = (None, False) if bias is None else _normalize_bias(
        bias, B, N, Sq, Sk
    )
    if swap:
        qT = q.transpose(1, 0, 2, 3)
        kT = k.transpose(1, 0, 2, 3)
        vT = v.transpose(1, 0, 2, 3)
        kbT = kb.reshape(B, N, Sk).transpose(1, 0, 2).reshape(N * B, Sk)
        # head_swap remaps the dropout-hash head ids back to the caller's
        # b*N+n layout so the swap never changes the mask (and the shared
        # bias needs no B-fold expansion)
        out, lse = _flash_lse(qT, kT, vT, kbT, bf, seed, causal, scale,
                              rate, bool(interpret),
                              (B, N) if rate > 0.0 else None)
        return (
            out.transpose(1, 0, 2, 3),
            lse.reshape(N, B, Sq).transpose(1, 0, 2),
        )
    out, lse = _flash_lse(q, k, v, kb, bf, seed, causal, scale, rate,
                          bool(interpret), None)
    return out, lse.reshape(B, N, Sq)


def flash_attention(q, k, v, key_bias=None, bias=None, causal=False,
                    scale=None, dropout_rate=0.0, dropout_seed=None,
                    interpret=None):
    """Fused attention, [B, N, S, D] -> [B, N, S, D].

    ``key_bias``: optional additive mask over KEYS, shape [B*N, S] or
    broadcastable — BERT-style padding masks ((mask-1)*1e4 per key).
    ``bias``: optional general additive bias broadcastable to
    [B, N, Sq, Sk] (relative-position / ALiBi). Both may be given.
    ``dropout_rate``/``dropout_seed``: in-kernel attention dropout (see
    ``flash_attention_lse``) — training with dropout rides the kernels.
    ``interpret``: force the Pallas interpreter (tests); default runs the
    kernels on TPU and the jnp reference elsewhere. Forward AND backward
    are Pallas kernels — no [S, S] tensor ever reaches HBM.

    Single implementation: this is ``flash_attention_lse`` with the
    logsumexp dropped (its zero cotangent folds away in the backward), so
    the two entry points can never diverge on normalization/dispatch.
    """
    out, _lse = flash_attention_lse(
        q, k, v, key_bias=key_bias, bias=bias, causal=causal, scale=scale,
        dropout_rate=dropout_rate, dropout_seed=dropout_seed,
        interpret=interpret,
    )
    return out
