"""Flash attention as a Pallas TPU kernel.

The hot op of every transformer (reference target: the CUDA
`multihead_matmul` fused kernel, fused_multihead_matmul_op.cu, built for
exactly this BERT attention pattern). A naive attention materializes the
[S, S] score matrix in HBM twice (write after QK^T, read for @V) — at
seq 512+ that dwarfs the useful traffic. This kernel keeps the whole
softmax(QK^T/sqrt(d) + bias)V pipeline in VMEM with the online-softmax
recurrence, writing only the [S, D] output per head:

  for each K/V block:  m' = max(m, rowmax(s))
                       acc = acc * e^(m-m') + e^(s-m') @ v_block
                       l   = l * e^(m-m') + rowsum(e^(s-m'))

Layout [B, N, S, D] (batch, heads, seq, head_dim); fp32 accumulation
regardless of input dtype (MXU `preferred_element_type`).

Backward: jax.custom_vjp recomputes through the pure-jnp reference —
activation-light (no S×S residual is saved), numerically identical to
differentiating the reference, and XLA already fuses the backward matmul
chain well; the forward is where the hand-scheduling pays.

The kernel runs on the TPU backend (or anywhere under ``interpret=True``
for tests); ``flash_attention`` transparently falls back to the jnp
reference on other backends so models stay portable.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

BLOCK_Q = 128
BLOCK_K = 128
_NEG = -1e30


def reference_attention(q, k, v, bias=None, causal=False, scale=None):
    """Pure-jnp oracle, [B, N, S, D]; bias broadcastable to [B, N, S, S]."""
    d = q.shape[-1]
    s = jnp.einsum("bnqd,bnkd->bnqk", q, k).astype(jnp.float32)
    s = s * (scale if scale is not None else 1.0 / np.sqrt(d))
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    if causal:
        S = q.shape[2]
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask[None, None], s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bnqk,bnkd->bnqd", p.astype(q.dtype), v)


def _kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, *, scale, causal,
            kv_len, block_q, block_k):
    """One (head, q-block) program: online softmax over k blocks."""
    from jax.experimental import pallas as pl

    q = q_ref[0].astype(jnp.float32) * scale  # [BQ, D]
    qi = pl.program_id(1)
    n_kb = kv_len // block_k

    m = jnp.full((block_q, 1), _NEG, jnp.float32)
    l = jnp.zeros((block_q, 1), jnp.float32)
    acc = jnp.zeros((block_q, q.shape[-1]), jnp.float32)

    row = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    for kb in range(n_kb):
        kblk = k_ref[0, kb * block_k:(kb + 1) * block_k, :].astype(jnp.float32)
        vblk = v_ref[0, kb * block_k:(kb + 1) * block_k, :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [BQ, BK]
        s = s + bias_ref[0, kb * block_k:(kb + 1) * block_k][None, :]
        if causal:
            col = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(col <= row, s, _NEG)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l = l * alpha + p.sum(axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p, vblk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m = m_new
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _pallas_forward(q, k, v, key_bias, causal, scale, interpret):
    """q [BN, Sq, D], k/v [BN, Sk, D] (both block-multiples), key_bias
    [BN, Sk] additive."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    BN, Sq, D = q.shape
    Sk = k.shape[1]
    bq = min(BLOCK_Q, Sq)
    bk = min(BLOCK_K, Sk)
    assert Sq % bq == 0 and Sk % bk == 0, (Sq, bq, Sk, bk)
    grid = (BN, Sq // bq)
    kernel = functools.partial(
        _kernel, scale=scale, causal=causal, kv_len=Sk,
        block_q=bq, block_k=bk,
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((BN, Sq, D), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda h, i: (h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Sk, D), lambda h, i: (h, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Sk, D), lambda h, i: (h, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Sk), lambda h, i: (h, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda h, i: (h, i, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(q, k, v, key_bias)


def _round_up(x, m):
    return (x + m - 1) // m * m


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _flash(q, k, v, key_bias, causal, scale, interpret):
    return _flash_fwd_impl(q, k, v, key_bias, causal, scale, interpret)


def _flash_fwd_impl(q, k, v, key_bias, causal, scale, interpret):
    B, N, Sq, D = q.shape
    Sk = k.shape[2]

    def pad_to(S, block):
        Sp = _round_up(S, 8)
        return _round_up(Sp, min(block, Sp))

    # queries pad to the q-tile, keys to the K-TILE — n_kb = Skp // bk in
    # the kernel truncates silently if this invariant ever breaks
    Sqp, Skp = pad_to(Sq, BLOCK_Q), pad_to(Sk, BLOCK_K)
    qf = q.reshape(B * N, Sq, D)
    kf = k.reshape(B * N, Sk, D)
    vf = v.reshape(B * N, Sk, D)
    bias = jnp.broadcast_to(key_bias, (B * N, Sk))
    if Sqp != Sq:
        # padded QUERY rows are sliced away below (their uniform/empty
        # softmax is harmless)
        qf = jnp.pad(qf, ((0, 0), (0, Sqp - Sq), (0, 0)))
    if Skp != Sk:
        # padded KEYS must never receive weight
        kf = jnp.pad(kf, ((0, 0), (0, Skp - Sk), (0, 0)))
        vf = jnp.pad(vf, ((0, 0), (0, Skp - Sk), (0, 0)))
        bias = jnp.pad(bias, ((0, 0), (0, Skp - Sk)), constant_values=_NEG)
    out = _pallas_forward(qf, kf, vf, bias, causal, scale, interpret)
    return out[:, :Sq, :].reshape(B, N, Sq, D)


def _flash_fwd(q, k, v, key_bias, causal, scale, interpret):
    return _flash_fwd_impl(q, k, v, key_bias, causal, scale, interpret), (
        q, k, v, key_bias,
    )


def _flash_bwd(causal, scale, interpret, res, g):
    q, k, v, key_bias = res
    B, N = q.shape[:2]
    Sk = k.shape[2]

    def ref(q, k, v, key_bias):
        return reference_attention(
            q, k, v, bias=key_bias.reshape(B, N, 1, Sk),
            causal=causal, scale=scale,
        )

    _, vjp = jax.vjp(ref, q, k, v, key_bias)
    dq, dk, dv, dbias = vjp(g)
    return dq, dk, dv, dbias


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, key_bias=None, causal=False, scale=None,
                    interpret=None):
    """Fused attention, [B, N, S, D] -> [B, N, S, D].

    ``key_bias``: optional additive mask over KEYS, shape [B*N, S] or
    broadcastable — BERT-style padding masks ((mask-1)*1e4 per key).
    ``interpret``: force the Pallas interpreter (tests); default runs the
    kernel on TPU and the jnp reference elsewhere.
    """
    B, N, Sq, d = q.shape
    Sk = k.shape[2]  # key length (cross attention: != query length)
    if causal and Sq != Sk:
        # guard here so the non-TPU reference fallback can't silently
        # mis-mask (a 1-query causal call would broadcast tril((1,1)))
        raise ValueError("causal flash attention needs Sq == Sk")
    scale = scale if scale is not None else 1.0 / float(np.sqrt(d))
    kb = None
    if key_bias is not None:
        # normalize [Sk] / [B, Sk] / [B*N, Sk] / [B, N, Sk] -> [B*N, Sk]
        kb = key_bias.astype(jnp.float32)
        if kb.ndim == 1:
            kb = kb[None]
        kb = kb.reshape(-1, Sk)
        if kb.shape[0] == B and N > 1:
            kb = jnp.broadcast_to(kb[:, None, :], (B, N, Sk)).reshape(-1, Sk)
        kb = jnp.broadcast_to(kb, (B * N, Sk))
    on_tpu = jax.default_backend() == "tpu"
    if interpret is None and not on_tpu:
        return reference_attention(
            q, k, v,
            bias=None if kb is None else kb.reshape(B, N, 1, Sk),
            causal=causal, scale=scale,
        )
    if kb is None:
        kb = jnp.zeros((B * N, Sk), jnp.float32)
    return _flash(q, k, v, kb, causal, scale, bool(interpret))
