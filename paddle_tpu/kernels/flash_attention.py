"""Flash attention as a Pallas TPU kernel.

The hot op of every transformer (reference target: the CUDA
`multihead_matmul` fused kernel, fused_multihead_matmul_op.cu, built for
exactly this BERT attention pattern). A naive attention materializes the
[S, S] score matrix in HBM twice (write after QK^T, read for @V) — at
seq 512+ that dwarfs the useful traffic. This kernel keeps the whole
softmax(QK^T/sqrt(d) + bias)V pipeline in VMEM with the online-softmax
recurrence, writing only the [S, D] output per head:

  for each K/V block:  m' = max(m, rowmax(s))
                       acc = acc * e^(m-m') + e^(s-m') @ v_block
                       l   = l * e^(m-m') + rowsum(e^(s-m'))

Layout [B, N, S, D] (batch, heads, seq, head_dim); fp32 accumulation
regardless of input dtype (MXU `preferred_element_type`).

Backward: jax.custom_vjp recomputes through the pure-jnp reference —
activation-light (no S×S residual is saved), numerically identical to
differentiating the reference, and XLA already fuses the backward matmul
chain well; the forward is where the hand-scheduling pays.

The kernel runs on the TPU backend (or anywhere under ``interpret=True``
for tests); ``flash_attention`` transparently falls back to the jnp
reference on other backends so models stay portable.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

BLOCK_Q = 128
BLOCK_K = 128
_NEG = -1e30


def reference_attention(q, k, v, bias=None, causal=False, scale=None):
    """Pure-jnp oracle, [B, N, S, D]; bias broadcastable to [B, N, S, S]."""
    d = q.shape[-1]
    s = jnp.einsum("bnqd,bnkd->bnqk", q, k).astype(jnp.float32)
    s = s * (scale if scale is not None else 1.0 / np.sqrt(d))
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    if causal:
        S = q.shape[2]
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask[None, None], s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bnqk,bnkd->bnqd", p.astype(q.dtype), v)


def _kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, *, scale, causal,
            seq_len, block_q, block_k):
    """One (head, q-block) program: online softmax over k blocks."""
    from jax.experimental import pallas as pl

    q = q_ref[0].astype(jnp.float32) * scale  # [BQ, D]
    qi = pl.program_id(1)
    n_kb = seq_len // block_k

    m = jnp.full((block_q, 1), _NEG, jnp.float32)
    l = jnp.zeros((block_q, 1), jnp.float32)
    acc = jnp.zeros((block_q, q.shape[-1]), jnp.float32)

    row = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    for kb in range(n_kb):
        kblk = k_ref[0, kb * block_k:(kb + 1) * block_k, :].astype(jnp.float32)
        vblk = v_ref[0, kb * block_k:(kb + 1) * block_k, :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [BQ, BK]
        s = s + bias_ref[0, kb * block_k:(kb + 1) * block_k][None, :]
        if causal:
            col = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(col <= row, s, _NEG)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l = l * alpha + p.sum(axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p, vblk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m = m_new
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _pallas_forward(q, k, v, key_bias, causal, scale, interpret):
    """q/k/v [BN, S, D] (S % block == 0), key_bias [BN, S] additive."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    BN, S, D = q.shape
    bq = min(BLOCK_Q, S)
    bk = min(BLOCK_K, S)
    grid = (BN, S // bq)
    kernel = functools.partial(
        _kernel, scale=scale, causal=causal, seq_len=S,
        block_q=bq, block_k=bk,
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((BN, S, D), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda h, i: (h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, S, D), lambda h, i: (h, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, S, D), lambda h, i: (h, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, S), lambda h, i: (h, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda h, i: (h, i, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(q, k, v, key_bias)


def _round_up(x, m):
    return (x + m - 1) // m * m


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _flash(q, k, v, key_bias, causal, scale, interpret):
    return _flash_fwd_impl(q, k, v, key_bias, causal, scale, interpret)


def _flash_fwd_impl(q, k, v, key_bias, causal, scale, interpret):
    B, N, S, D = q.shape
    Sp = _round_up(S, min(BLOCK_Q, _round_up(S, 8)))
    if Sp % 8:
        Sp = _round_up(Sp, 8)
    qf = q.reshape(B * N, S, D)
    kf = k.reshape(B * N, S, D)
    vf = v.reshape(B * N, S, D)
    bias = jnp.broadcast_to(key_bias, (B * N, S))
    if Sp != S:
        pad = ((0, 0), (0, Sp - S), (0, 0))
        qf = jnp.pad(qf, pad)
        kf = jnp.pad(kf, pad)
        vf = jnp.pad(vf, pad)
        # padded KEYS must never receive weight; padded QUERY rows are
        # sliced away below (their uniform softmax is harmless)
        bias = jnp.pad(bias, ((0, 0), (0, Sp - S)), constant_values=_NEG)
    out = _pallas_forward(qf, kf, vf, bias, causal, scale, interpret)
    return out[:, :S, :].reshape(B, N, S, D)


def _flash_fwd(q, k, v, key_bias, causal, scale, interpret):
    return _flash_fwd_impl(q, k, v, key_bias, causal, scale, interpret), (
        q, k, v, key_bias,
    )


def _flash_bwd(causal, scale, interpret, res, g):
    q, k, v, key_bias = res
    B, N, S, _ = q.shape

    def ref(q, k, v, key_bias):
        return reference_attention(
            q, k, v, bias=key_bias.reshape(B, N, 1, S),
            causal=causal, scale=scale,
        )

    _, vjp = jax.vjp(ref, q, k, v, key_bias)
    dq, dk, dv, dbias = vjp(g)
    return dq, dk, dv, dbias


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, key_bias=None, causal=False, scale=None,
                    interpret=None):
    """Fused attention, [B, N, S, D] -> [B, N, S, D].

    ``key_bias``: optional additive mask over KEYS, shape [B*N, S] or
    broadcastable — BERT-style padding masks ((mask-1)*1e4 per key).
    ``interpret``: force the Pallas interpreter (tests); default runs the
    kernel on TPU and the jnp reference elsewhere.
    """
    B, N, S, d = q.shape
    scale = scale if scale is not None else 1.0 / float(np.sqrt(d))
    kb = None
    if key_bias is not None:
        # normalize [S] / [B, S] / [B*N, S] / [B, N, S] -> [B*N, S]
        kb = key_bias.astype(jnp.float32)
        if kb.ndim == 1:
            kb = kb[None]
        kb = kb.reshape(-1, S)
        if kb.shape[0] == B and N > 1:
            kb = jnp.broadcast_to(kb[:, None, :], (B, N, S)).reshape(-1, S)
        kb = jnp.broadcast_to(kb, (B * N, S))
    on_tpu = jax.default_backend() == "tpu"
    if interpret is None and not on_tpu:
        return reference_attention(
            q, k, v,
            bias=None if kb is None else kb.reshape(B, N, 1, S),
            causal=causal, scale=scale,
        )
    if kb is None:
        kb = jnp.zeros((B * N, S), jnp.float32)
    return _flash(q, k, v, kb, causal, scale, bool(interpret))
