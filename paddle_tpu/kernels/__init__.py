"""Hand-written Pallas TPU kernels for the hot ops the compiler can't
fuse optimally on its own. Each kernel ships with a pure-jnp reference
(used for the backward pass and for CPU fallback) and interpret-mode
tests."""

from .flash_attention import flash_attention  # noqa: F401
