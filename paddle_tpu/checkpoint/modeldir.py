"""Versioned model-dir repository — the rollout side of persistence.

Training persistence (manager.py) versions *steps* of one run; serving
persistence versions *models*: a rollout needs an immutable, numbered
directory per published model and one atomic pointer to the newest, so
a FleetController can say "deploy latest" and an operator can roll
back by pointing at an older version. Layout::

    repo/
      v_1/                  <- one published model (immutable)
        __model__ ...        <- the save_inference_model artifacts
        warmup.npz           <- optional warmup example (replica warms
                                its bucket ladder from it)
        model_version.json   <- manifest: {version, ts, src}
      v_2/
      LATEST                 <- atomic pointer: {"version": 2, "dir": "v_2"}

The same two-phase discipline as the checkpoint manager: a publish
stages the copy under ``tmp.v_<n>.<pid>/``, writes the manifest, then
``os.replace``s into place and finally flips ``LATEST`` — a reader
(the fleet controller resolving a deploy) can never observe a torn or
half-copied version.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import time

__all__ = [
    "LATEST",
    "MANIFEST",
    "publish",
    "versions",
    "latest",
    "read_manifest",
    "commit_json",
]

LATEST = "LATEST"
MANIFEST = "model_version.json"
_VERSION_DIR = re.compile(r"^v_(\d+)$")


def commit_json(path, obj, indent=None):
    """Two-phase atomic JSON commit: stage to ``<path>.tmp.<pid>``,
    then ``os.replace`` — a concurrent reader sees the old document or
    the new one, never a torn line. This is the ONE write discipline
    for every fleet shared file (``LATEST``, endpoint files,
    ``kv_peers.json``, ``fleet_state.json``, the fleet report), so
    reader-side torn-file handling has exactly one failure mode to
    cover: a file that predates its writer's crash. Returns ``path``."""
    path = str(path)
    tmp = "%s.tmp.%d" % (path, os.getpid())
    with open(tmp, "w") as f:
        json.dump(obj, f, sort_keys=True, indent=indent)
    os.replace(tmp, path)
    return path


def versions(repo):
    """Sorted ``[(version, abs_dir), ...]`` of fully published versions
    (a version dir without a manifest is a torn publish — invisible,
    exactly like a checkpoint dir without its manifest)."""
    out = []
    try:
        names = os.listdir(str(repo))
    except OSError:
        return out
    for name in names:
        m = _VERSION_DIR.match(name)
        if not m:
            continue
        path = os.path.join(str(repo), name)
        if os.path.isfile(os.path.join(path, MANIFEST)):
            out.append((int(m.group(1)), path))
    out.sort()
    return out


def read_manifest(model_dir):
    """The publish manifest of one version dir, or None for a plain
    (unpublished) export dir."""
    try:
        with open(os.path.join(str(model_dir), MANIFEST)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def latest(repo):
    """(version, abs_dir) the ``LATEST`` pointer names — falling back
    to the highest published version when the pointer is missing or
    torn — or (None, None) for an empty repo."""
    repo = str(repo)
    try:
        with open(os.path.join(repo, LATEST)) as f:
            rec = json.load(f)
        path = os.path.join(repo, rec["dir"])
        if os.path.isfile(os.path.join(path, MANIFEST)):
            return int(rec["version"]), path
    except (OSError, ValueError, KeyError, TypeError):
        pass
    pub = versions(repo)
    return pub[-1] if pub else (None, None)


def publish(export_dir, repo, version=None):
    """Copy ``export_dir`` (a ``save_inference_model`` directory) into
    the repo as the next version (or an explicit higher ``version``)
    and flip ``LATEST``. Returns (version, published_dir)."""
    export_dir, repo = str(export_dir), str(repo)
    if not os.path.isdir(export_dir):
        raise ValueError("export dir %r does not exist" % export_dir)
    os.makedirs(repo, exist_ok=True)
    pub = versions(repo)
    next_v = (pub[-1][0] + 1) if pub else 1
    if version is not None:
        if int(version) < next_v:
            raise ValueError(
                "version %d already published (next free is %d)"
                % (int(version), next_v)
            )
        next_v = int(version)
    final = os.path.join(repo, "v_%d" % next_v)
    stage = os.path.join(repo, "tmp.v_%d.%d" % (next_v, os.getpid()))
    shutil.rmtree(stage, ignore_errors=True)
    try:
        shutil.copytree(export_dir, stage)
        with open(os.path.join(stage, MANIFEST), "w") as f:
            json.dump({
                "version": next_v,
                "ts": time.time(),
                "src": os.path.abspath(export_dir),
            }, f, sort_keys=True)
        os.replace(stage, final)
    except BaseException:
        shutil.rmtree(stage, ignore_errors=True)
        raise
    # LATEST flips last, atomically: a concurrent reader sees either
    # the old pointer or the new one, never a torn line
    commit_json(os.path.join(repo, LATEST),
                {"version": next_v, "dir": "v_%d" % next_v})
    return next_v, final
