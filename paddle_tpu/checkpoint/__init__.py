"""paddle_tpu.checkpoint — fault-tolerant async checkpointing + resume.

The L7.5 persistence subsystem: Orbax-style step-tagged checkpoint
directories with a two-phase atomic commit (stage under ``tmp.step_<N>/``,
fsync, ``os.replace`` to ``step_<N>/`` — a torn directory is never
discoverable), a background writer thread that keeps serialization and
disk I/O off the step critical path, per-rank sharded save/restore for
multi-process DP/TP, retention GC, and SIGTERM preemption handling with
one final synchronous save.

Quickstart::

    from paddle_tpu import checkpoint

    mgr = checkpoint.CheckpointManager("ckpts", keep_max=3)
    start = mgr.restore_or_initialize(main, exe, startup_program=startup)
    for step in range(start + 1, num_steps):
        exe.run(main, feed=batch(step), fetch_list=[loss])
        if step % 50 == 0:
            mgr.save(step, main)          # async: returns after snapshot
    mgr.save(num_steps - 1, main, async_=False)
    mgr.close()
"""

from . import modeldir  # noqa: F401
from .manager import (  # noqa: F401
    CheckpointError,
    CheckpointManager,
    ChecksumError,
    latest_step,
    list_steps,
)
from .preempt import (  # noqa: F401
    PreemptionHandler,
    preemption_requested,
    request_preemption,
)

__all__ = [
    "modeldir",
    "CheckpointManager",
    "CheckpointError",
    "ChecksumError",
    "PreemptionHandler",
    "preemption_requested",
    "request_preemption",
    "latest_step",
    "list_steps",
]
