"""CheckpointManager — fault-tolerant async checkpointing (L7.5).

The reference's persistence layer (fluid/io.py save/load) is synchronous
and crash-oblivious: a SIGKILL mid-write leaves a torn pickle that still
loads. On a preemptible TPU fleet a checkpoint must instead be (a)
atomic — either fully committed or invisible, (b) off the step critical
path — serialization and disk I/O on a background thread while the chip
keeps stepping, and (c) resumable bit-exactly — params, optimizer
accumulators, the step counter, AND the executor's per-scope RNG run
index all round-trip.

Commit protocol (Orbax-style two-phase):

    1. snapshot  — device->host fetch of every persistable at save()
                   time on the caller thread (cheap: one blocking copy),
                   so the writer thread serializes an immutable snapshot
                   while training mutates the live scope.
    2. stage     — the writer serializes tensors in the reference's
                   LoDTensor stream format into ``tmp.step_<N>/`` next
                   to the final location, with a ``manifest.json``
                   recording per-tensor shape/dtype/offset/crc32.
    3. fsync     — data file, manifest, and the staging dir itself.
    4. publish   — ``os.replace(tmp.step_<N>, step_<N>)`` + fsync of the
                   parent dir. A rename is atomic on POSIX, so
                   ``latest_step()`` (which requires ``step_*/
                   manifest.json``) can never observe a torn state.

Sharded saves (multi-process DP/TP) keep the same protocol with one
twist: every rank stages ``tmp.step_<N>/shard_<rank>/`` independently
(its own data + ``shard_manifest.json``, renamed into place inside the
staging dir as the per-shard commit marker), and rank 0 alone performs
the publish once all shard manifests exist — mirroring the sharded
inference export's manifest conventions (inference SHARD_MANIFEST).

Elastic N->M restore (the resize contract, per arXiv:2112.01075's
redistribution framing — ours is filesystem-mediated, not collective):
``restore`` accepts a checkpoint written by N ranks into a manager with
M ranks. TP vars (``dist_attrs``) are concatenated along their saved
axis and re-sliced into M contiguous ``np.array_split`` pieces (exact
concat: the M pieces joined along the axis reproduce the N pieces
joined, bit for bit); replicated params and optimizer accumulators
(arXiv:2004.13336's per-replica weight-update state) pass through
bit-exactly — every rank reads all shards, so the round-robin write
partition at N is invisible at M. The N=1 edge replicates-and-partitions:
a var saved unsharded but listed in the restoring manager's
``dist_attrs`` is sliced to this rank's piece. Each manifest stamps the
gang ``world_size`` at save time and every restore records
``last_restore_info`` (step, world_size_saved, resharded, reshard_ms) —
``distributed/elastic.maybe_rescale_lr`` keys off it so LR corrections
never compound across repeated degraded resumes.
"""

from __future__ import annotations

import json
import os
import queue
import re
import shutil
import threading
import time
import zlib

import numpy as np

MANIFEST = "manifest.json"
SHARD_MANIFEST = "shard_manifest.json"
DATA_FILE = "state.pdckpt"
_STEP_RE = re.compile(r"^step_(\d+)$")
_TMP_PREFIX = "tmp.step_"
_FORMAT = 1


class CheckpointError(RuntimeError):
    pass


class ChecksumError(CheckpointError):
    """A committed tensor's bytes no longer match the manifest crc32."""


def _fsync_dir(path):
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _write_atomic(path, data):
    """Write bytes to ``path`` via same-dir tmp + fsync + os.replace
    (shared helper: ops/io_ops.py owns the one implementation)."""
    from ..fluid.ops.io_ops import _atomic_write

    _atomic_write(path, data)


def _step_dirname(step):
    return "step_%08d" % int(step)


def _shard_dirname(rank):
    return "shard_%05d" % int(rank)


def list_steps(dirname):
    """Committed steps (ascending). A step is committed iff its dir
    matched ``step_<N>`` AND contains a manifest — a crashed writer's
    ``tmp.step_*`` staging dir or a half-deleted GC victim is invisible."""
    steps = []
    try:
        entries = os.listdir(dirname)
    except OSError:
        return steps
    for name in entries:
        m = _STEP_RE.match(name)
        if not m:
            continue
        if os.path.isfile(os.path.join(dirname, name, MANIFEST)):
            steps.append(int(m.group(1)))
    return sorted(steps)


def latest_step(dirname):
    steps = list_steps(dirname)
    return steps[-1] if steps else None


def _snapshot_value(value):
    """Device->host fetch of one scope value at snapshot time. LoDTensors
    keep their wrapper (the stream format carries the LoD); device arrays
    become host ndarrays NOW so the writer thread never touches a buffer
    the next step might donate."""
    from ..fluid import core

    if isinstance(value, core.LoDTensor):
        return value
    return np.asarray(value)


class CheckpointManager(object):
    """Step-tagged atomic checkpoints with an async background writer.

    Args:
        dirname: root directory; step dirs are created under it.
        keep_max: newest K committed steps survive GC (None -> FLAGS_
            ckpt_keep_max; 0 = unbounded).
        keep_every_n_steps: steps divisible by N are additionally kept
            forever (None -> FLAGS_ckpt_keep_every_n_steps; 0 = off).
        async_depth: bounded writer-queue depth — at most this many
            snapshots in flight; a full queue back-pressures save()
            (None -> FLAGS_ckpt_async_depth).
        rank / nranks: sharded mode when nranks > 1 — this process
            writes ``shard_<rank>/`` and only rank 0 publishes.
        dist_attrs: {var_name: axis} for vars whose LOCAL shard each
            rank holds (TP); restore concatenates shards along ``axis``.
            Vars not listed are treated as replicated and partitioned
            round-robin across ranks for writing.
    """

    def __init__(self, dirname, keep_max=None, keep_every_n_steps=None,
                 async_depth=None, rank=0, nranks=1, dist_attrs=None,
                 commit_timeout_s=None):
        from ..fluid import flags as _flags

        self.dirname = str(dirname)
        self.keep_max = int(
            _flags.get_flag("ckpt_keep_max", 5) if keep_max is None
            else keep_max
        )
        self.keep_every_n_steps = int(
            _flags.get_flag("ckpt_keep_every_n_steps", 0)
            if keep_every_n_steps is None else keep_every_n_steps
        )
        depth = int(
            _flags.get_flag("ckpt_async_depth", 2)
            if async_depth is None else async_depth
        )
        self.commit_timeout_s = float(
            _flags.get_flag("ckpt_commit_timeout_s", 120.0)
            if commit_timeout_s is None else commit_timeout_s
        )
        self.rank = int(rank)
        self.nranks = int(nranks)
        self.dist_attrs = dict(dist_attrs or {})
        # stamped by every successful restore(): {step, world_size_saved,
        # nranks_saved, resharded, reshard_ms}. Elasticity-aware callers
        # (trainer LR rescale) read the world size the checkpoint was
        # SAVED at from here rather than assuming the submitted topology.
        self.last_restore_info = None
        # background scrubbing (FLAGS_ckpt_scrub): after each commit the
        # writer thread re-verifies committed steps' checksums off the
        # critical path, so rollback consumers (the training guardian)
        # can ask for the newest KNOWN-GOOD step instead of merely the
        # newest one. {step: bool} of scrub outcomes, lock-guarded —
        # the writer thread records, the trainer thread reads.
        self._auto_scrub = bool(_flags.get_flag("ckpt_scrub", False))
        self._scrub_state = {}
        self._scrub_lock = threading.Lock()
        os.makedirs(self.dirname, exist_ok=True)
        # resume-time hygiene: a crashed run's staging dirs are garbage.
        # Only rank 0 sweeps (peers may be slower to start, but no save
        # can be in flight before training begins, so this cannot race a
        # live writer).
        if self.rank == 0:
            self._sweep_stale_tmp()
        self._queue = queue.Queue(maxsize=max(depth, 1))
        self._error = None
        self._error_lock = threading.Lock()
        self._closed = False
        self._writer = threading.Thread(
            target=self._writer_loop, name="ckpt-writer", daemon=True
        )
        self._writer.start()

    # -- public API ---------------------------------------------------------

    def latest_step(self):
        return latest_step(self.dirname)

    def all_steps(self):
        return list_steps(self.dirname)

    def save(self, step, program=None, scope=None, async_=True):
        """Snapshot persistables from ``scope`` and commit them as
        ``step_<step>``. With ``async_`` the serialization + write + GC
        happen on the writer thread (bounded queue; a full queue blocks
        — back-pressure, never an unbounded host-memory pileup) and this
        returns after the device->host snapshot; ``wait()`` barriers."""
        from ..fluid import profiler as _profiler
        from ..fluid.framework import default_main_program
        from ..observability import trace as _trace

        self._raise_pending()
        if self._closed:
            raise CheckpointError("save() on a closed CheckpointManager")
        program = program or default_main_program()
        t0 = time.perf_counter()
        # the D2H snapshot is the only critical-path work of an async
        # save — its span sits on the caller's (step loop's) thread row
        with _trace.span("ckpt_snapshot", cat="ckpt", step=int(step)):
            snap = self._snapshot(program, scope)
        _profiler.bump_histogram(
            "ckpt_snapshot_ms", (time.perf_counter() - t0) * 1000.0
        )
        if async_:
            self._queue.put((int(step), snap))
        else:
            # serialize with in-flight async saves FIRST: the staging dir
            # name is deterministic per step (sharded peers must agree on
            # it), so a sync save racing the writer on the same step
            # would tear each other's tmp files; draining also keeps
            # commits arriving in step order for retention
            self._queue.join()
            self._raise_pending()
            self._write_checkpoint(int(step), snap)
            self._raise_pending()
        return self

    def wait(self):
        """Barrier: returns when every queued save has committed (or
        re-raises the writer's failure)."""
        self._queue.join()
        self._raise_pending()
        return self

    def close(self):
        """wait() then stop the writer thread. Idempotent."""
        if self._closed:
            return
        try:
            self._queue.join()
        finally:
            self._closed = True
            self._queue.put(None)  # sentinel
            self._writer.join(timeout=30)
        self._raise_pending()

    def restore(self, program=None, scope=None, step=None, executor=None):
        """Load ``step`` (default: latest committed) into the scope,
        verifying every tensor's crc32 against the manifest. Accepts a
        checkpoint written at any shard count (see module docstring:
        N->M resharding). Returns the restored step. Raises
        CheckpointError when nothing is committed and ChecksumError on
        corruption."""
        from ..fluid import core
        from ..fluid import profiler as _profiler
        from ..fluid.framework import default_main_program

        program = program or default_main_program()
        scope = scope or core.global_scope()
        if step is None:
            step = self.latest_step()
            if step is None:
                raise CheckpointError(
                    "no committed checkpoint under %r" % self.dirname
                )
        step_dir = os.path.join(self.dirname, _step_dirname(step))
        manifest_path = os.path.join(step_dir, MANIFEST)
        if not os.path.isfile(manifest_path):
            raise CheckpointError(
                "step %d is not committed under %r" % (step, self.dirname)
            )
        with open(manifest_path) as f:
            manifest = json.load(f)
        nranks_saved = int(manifest.get("nranks", 1))
        state = {}
        if nranks_saved > 1:
            for shard in manifest["shards"]:
                self._read_shard(
                    os.path.join(step_dir, shard["dir"]), state
                )
        else:
            self._read_shard(step_dir, state)
        t0 = time.perf_counter()
        state, resliced = self._reassemble(state)
        reshard_ms = (time.perf_counter() - t0) * 1000.0
        resharded = nranks_saved != self.nranks and resliced > 0
        if resharded:
            _profiler.bump_counter("ckpt_resharded_restores")
            _profiler.bump_histogram("ckpt_reshard_ms", reshard_ms)
        for name, val in state.items():
            scope.set(name, val)
        self._restore_rng(manifest, program, scope)
        self.last_restore_info = {
            "step": int(manifest["step"]),
            "nranks_saved": nranks_saved,
            # the gang size the writing job ran at. Manifests predating
            # the stamp report None — NOT the shard count, which is 1
            # for per-rank managers regardless of gang size, and a wrong
            # "saved at world 1" claim would make maybe_rescale_lr
            # multiply the LR by the full world. Unknown provenance must
            # read as "assume the submitted topology" (the rescaler's
            # None fallback), i.e. no correction.
            "world_size_saved": (
                int(manifest["world_size"])
                if manifest.get("world_size") else None
            ),
            "resharded": resharded,
            "resliced_vars": resliced,
            "reshard_ms": reshard_ms,
        }
        return int(manifest["step"])

    def restore_or_initialize(self, program=None, executor=None,
                              startup_program=None, scope=None):
        """Resume path for trainers: restore the latest committed step
        and return it, or run ``startup_program`` (when given) for a
        fresh start and return -1.

        Resilience (FLAGS_ckpt_restore_fallback, default on): when the
        newest step fails its crc32 manifest check (bit rot, a torn
        write that slipped past the atomic-commit protocol's
        assumptions, a half-synced remote mount), log the ChecksumError
        and fall back to the next-newest valid step — losing a few
        steps of progress beats losing the job. Only when EVERY
        committed step is damaged does the resume hard-fail (silently
        fresh-starting would discard the whole run's progress)."""
        import logging

        from ..fluid import flags as _flags
        from ..fluid import profiler as _profiler

        steps = self.all_steps()
        if not steps:
            if startup_program is not None:
                if executor is None:
                    raise CheckpointError(
                        "restore_or_initialize needs an executor to run "
                        "the startup program on a fresh start"
                    )
                executor.run(startup_program, scope=scope)
            return -1
        fallback = bool(_flags.get_flag("ckpt_restore_fallback", True))
        # Gang safety: ranks restore independently, so one rank falling
        # back to an older step while its peers load the newest would
        # silently train divergent replicas / misaligned collectives.
        # Inside a multi-worker gang the fallback therefore requires the
        # operator's EXPLICIT opt-in (identical-replica workloads, or
        # checkpoint storage shared by all ranks) — the default-on
        # behavior is for single-process training only.
        in_gang = self.nranks > 1 or int(
            os.environ.get("PADDLE_TRAINERS_NUM", "1")
        ) > 1
        if fallback and in_gang and not _flags.is_explicit(
            "ckpt_restore_fallback"
        ):
            fallback = False
        log = logging.getLogger("paddle_tpu.checkpoint")
        newest_err = None
        # fallback is scoped to ON-DISK damage (failed crc, torn/missing
        # manifest or data file): a ValueError from e.g. restoring into a
        # mismatched program is a caller bug and must surface on the
        # first (newest) step, not walk the history mislabeled as bit rot
        for s in reversed(steps):
            try:
                return self.restore(
                    program, scope=scope, step=s, executor=executor
                )
            except (ChecksumError, CheckpointError, OSError,
                    json.JSONDecodeError) as e:
                if not fallback:
                    raise
                if newest_err is None:
                    newest_err = e
                _profiler.bump_counter("ckpt_restore_fallbacks")
                log.warning(
                    "restore_or_initialize: step %d under %r is damaged "
                    "(%s: %s); falling back to the next-newest "
                    "checkpoint", s, self.dirname, type(e).__name__, e,
                )
        raise CheckpointError(
            "every committed checkpoint under %r failed to restore "
            "(newest step's error: %s)" % (self.dirname, newest_err)
        )

    def verify(self, step=None):
        """Re-checksum a committed step without touching any scope (the
        crash probe's torn-checkpoint detector). Returns the tensor
        count; raises ChecksumError/CheckpointError on any damage."""
        count = 0
        for _name, _val in self._iter_step_tensors(step):
            count += 1
        return count

    # -- scrubbing (known-good rollback targets) ----------------------------

    def _scrub_one(self, step):
        """Verify one committed step, record the outcome, bump the
        ckpt_scrub_ok/_corrupt counters. Returns the bool outcome."""
        import logging

        from ..fluid import profiler as _profiler

        try:
            self.verify(step)
            ok = True
            _profiler.bump_counter("ckpt_scrub_ok")
        except (ChecksumError, CheckpointError, OSError, ValueError,
                KeyError) as e:
            ok = False
            _profiler.bump_counter("ckpt_scrub_corrupt")
            logging.getLogger("paddle_tpu.checkpoint").warning(
                "scrub: step %d under %r is damaged (%s: %s)",
                step, self.dirname, type(e).__name__, e,
            )
        with self._scrub_lock:
            self._scrub_state[step] = ok
        return ok

    def scrub(self, recheck=False):
        """Re-verify committed steps' checksums (off the critical path
        when called from the writer thread — FLAGS_ckpt_scrub arms that
        automatically after every commit). Incremental by default: each
        committed step is verified once, newest first; ``recheck=True``
        forgets prior outcomes and re-reads everything (bit-rot after a
        first pass). Returns {step: ok}."""
        if recheck:
            with self._scrub_lock:
                self._scrub_state.clear()
        results = {}
        for s in reversed(self.all_steps()):
            with self._scrub_lock:
                known = self._scrub_state.get(s)
            results[s] = self._scrub_one(s) if known is None else known
        return results

    def newest_verified_step(self):
        """The newest committed step that passed a scrub — the training
        guardian's rollback target. Steps the scrubber has not covered
        yet are verified on demand, newest first. Returns None when no
        committed step verifies."""
        for s in reversed(self.all_steps()):
            with self._scrub_lock:
                ok = self._scrub_state.get(s)
            if ok is None:
                ok = self._scrub_one(s)
            if ok:
                return s
        return None

    def discard_steps_after(self, step):
        """Delete committed steps NEWER than ``step`` (guardian
        rollback: checkpoints from the rolled-past window must not
        shadow the replay's fresh saves through the already-committed
        early return, and a corrupt newest step must not survive the
        rollback that routed around it). Manifest-first deletion, like
        GC, so a racing reader never sees a half-deleted dir as
        committed. Returns the discarded steps."""
        doomed = [s for s in list_steps(self.dirname) if s > int(step)]
        for s in doomed:
            victim = os.path.join(self.dirname, _step_dirname(s))
            try:
                os.unlink(os.path.join(victim, MANIFEST))
            except OSError:
                pass
            shutil.rmtree(victim, ignore_errors=True)
            with self._scrub_lock:
                self._scrub_state.pop(s, None)
        return doomed

    # -- snapshot -----------------------------------------------------------

    def _snapshot(self, program, scope):
        from ..fluid import core
        from ..fluid.io import is_persistable

        scope = scope or core.global_scope()
        names = sorted(
            v.name for v in program.list_vars() if is_persistable(v)
        )
        owned = self._owned_names(names)
        tensors = []
        for name in names:
            if name not in owned:
                continue
            val = scope.get(name)
            if val is None:
                continue  # e.g. pruned/unused accumulator never ran
            tensors.append((name, _snapshot_value(val)))
        # executor RNG run index for this (program, scope): restoring it
        # makes dropout masks replay identically across a resume, the
        # last piece of bit-exact resume besides params + accumulators
        rng_index = None
        counters = program.__dict__.get("_rng_run_counters")
        if counters is not None:
            rng_index = counters.get(scope)
        return {"tensors": tensors, "rng_run_index": rng_index}

    def _owned_names(self, names):
        """Which vars THIS rank writes. TP-sharded vars (dist_attrs) are
        written by every rank (each holds a distinct shard); replicated
        vars are partitioned round-robin so a big DP save spreads its
        write bandwidth across hosts."""
        if self.nranks <= 1:
            return set(names)
        owned = set()
        i = 0
        for name in names:  # names arrive sorted -> same partition on all ranks
            if name in self.dist_attrs:
                owned.add(name)
            else:
                if i % self.nranks == self.rank:
                    owned.add(name)
                i += 1
        return owned

    # -- writer -------------------------------------------------------------

    def _writer_loop(self):
        while True:
            item = self._queue.get()
            if item is None:
                self._queue.task_done()
                return
            step, snap = item
            try:
                self._write_checkpoint(step, snap)
            except BaseException as e:  # surfaced via wait()/next save()
                with self._error_lock:
                    if self._error is None:
                        self._error = e
            finally:
                self._queue.task_done()

    def _write_checkpoint(self, step, snap):
        from ..observability import trace as _trace

        # serialize + fsync + commit, on the writer thread's trace row
        # (or the caller's for a sync save)
        with _trace.span("ckpt_write", cat="ckpt", step=int(step)):
            self._write_checkpoint_traced(step, snap)

    def _write_checkpoint_traced(self, step, snap):
        from ..fluid import profiler as _profiler

        t0 = time.perf_counter()
        final_dir = os.path.join(self.dirname, _step_dirname(step))
        if os.path.isfile(os.path.join(final_dir, MANIFEST)):
            return  # already committed (e.g. preempt save after interval save)
        if os.path.isdir(final_dir):
            # manifest-less husk (GC crashed between unlink and rmtree):
            # invisible to list_steps, and it must not block a re-save
            shutil.rmtree(final_dir, ignore_errors=True)
        # the staging name is deterministic (no pid/uuid) because sharded
        # peers must agree on it; a stale one from a crashed run was swept
        # at init
        tmp_dir = os.path.join(self.dirname, _TMP_PREFIX + "%d" % step)
        shard_dir = (
            os.path.join(tmp_dir, _shard_dirname(self.rank))
            if self.nranks > 1 else tmp_dir
        )
        os.makedirs(shard_dir, exist_ok=True)
        nbytes = self._write_shard(shard_dir, step, snap)
        if self.nranks > 1:
            _fsync_dir(tmp_dir)
            if self.rank == 0:
                shards = self._await_peer_shards(tmp_dir, step)
                self._publish(tmp_dir, final_dir, step, snap, shards)
            else:
                self._await_publish(final_dir, step)
        else:
            self._publish(tmp_dir, final_dir, step, snap, shards=None)
        _profiler.bump_histogram(
            "ckpt_save_ms", (time.perf_counter() - t0) * 1000.0
        )
        _profiler.bump_histogram("ckpt_save_bytes", float(nbytes))
        _profiler.bump_counter("ckpt_saves_committed")
        if (self._auto_scrub and (self.nranks <= 1 or self.rank == 0)
                and threading.current_thread() is self._writer):
            # FLAGS_ckpt_scrub: verify the just-committed step (and any
            # step the scrubber hasn't covered) right here on the
            # writer thread — off the step critical path, so the
            # guardian's newest_verified_step() answer is usually
            # already warm when a rollback needs it. Sync saves (the
            # preemption final save inside the supervisor's SIGTERM
            # grace) run this method on the CALLER thread and must not
            # pay a full read-back there; their steps stay uncovered
            # until newest_verified_step() verifies on demand.
            self.scrub()

    def _write_shard(self, shard_dir, step, snap):
        """Serialize the snapshot into ``shard_dir`` (reference LoDTensor
        stream format, one concatenated file) + a shard manifest with
        per-tensor shape/dtype/offset/crc32. The manifest lands via
        same-dir rename so its presence IS the per-shard commit marker."""
        from ..fluid.ops.io_ops import serialize_lod_tensor

        from ..testing import chaos as _chaos

        data_path = os.path.join(shard_dir, DATA_FILE)
        catalog = {}
        offset = 0
        with open(data_path, "wb") as f:
            for name, val in snap["tensors"]:
                blob = serialize_lod_tensor(val)
                # fault-injection point: chaos corrupt_ckpt flips a data
                # byte AFTER the manifest crc32 below is computed from
                # the clean bytes — the exact torn-file signature the
                # restore fallback must survive (no-op when disarmed)
                f.write(_chaos.corrupt_ckpt_bytes(blob))
                entry = {
                    "shape": [int(d) for d in np.shape(
                        val.numpy() if hasattr(val, "numpy") else val
                    )],
                    "dtype": str(
                        (val.numpy() if hasattr(val, "numpy") else val).dtype
                    ),
                    "offset": offset,
                    "nbytes": len(blob),
                    "crc32": zlib.crc32(blob) & 0xFFFFFFFF,
                }
                if name in self.dist_attrs:
                    entry["dist"] = {
                        "axis": int(self.dist_attrs[name]),
                        "rank": self.rank,
                        "nranks": self.nranks,
                    }
                catalog[name] = entry
                offset += len(blob)
            f.flush()
            os.fsync(f.fileno())
        shard_manifest = {
            "format": _FORMAT,
            "step": int(step),
            "rank": self.rank,
            "nranks": self.nranks,
            "data_file": DATA_FILE,
            "tensors": catalog,
        }
        _write_atomic(
            os.path.join(shard_dir, SHARD_MANIFEST),
            json.dumps(shard_manifest, indent=1, sort_keys=True).encode(),
        )
        _fsync_dir(shard_dir)
        return offset

    def _publish(self, tmp_dir, final_dir, step, snap, shards):
        from ..distributed import elastic as _elastic

        manifest = {
            "format": _FORMAT,
            "step": int(step),
            "nranks": self.nranks,
            # the gang size the writing JOB ran at (>= nranks when each
            # rank keeps its own checkpoint dir): a later restore reads
            # it back as world_size_saved so elasticity-aware LR math is
            # relative to the topology that produced these tensors
            "world_size": _elastic.world_info().world_size,
            "rng_run_index": snap.get("rng_run_index"),
        }
        if shards is not None:
            manifest["shards"] = [
                {"rank": r, "dir": _shard_dirname(r)} for r in shards
            ]
        _write_atomic(
            os.path.join(tmp_dir, MANIFEST),
            json.dumps(manifest, indent=1, sort_keys=True).encode(),
        )
        _fsync_dir(tmp_dir)
        try:
            os.replace(tmp_dir, final_dir)  # THE commit point
        except OSError:
            if os.path.isfile(os.path.join(final_dir, MANIFEST)):
                # lost a benign same-step race to another committer
                shutil.rmtree(tmp_dir, ignore_errors=True)
            else:
                raise
        _fsync_dir(self.dirname)
        self._gc()

    def _await_peer_shards(self, tmp_dir, step):
        deadline = time.monotonic() + self.commit_timeout_s
        want = set(range(self.nranks))
        while True:
            have = {
                r for r in want
                if os.path.isfile(os.path.join(
                    tmp_dir, _shard_dirname(r), SHARD_MANIFEST
                ))
            }
            if have == want:
                return sorted(want)
            if time.monotonic() > deadline:
                raise CheckpointError(
                    "step %d: shards %s missing after %.0fs"
                    % (step, sorted(want - have), self.commit_timeout_s)
                )
            time.sleep(0.02)

    def _await_publish(self, final_dir, step):
        deadline = time.monotonic() + self.commit_timeout_s
        while not os.path.isfile(os.path.join(final_dir, MANIFEST)):
            if time.monotonic() > deadline:
                raise CheckpointError(
                    "step %d: rank 0 did not publish within %.0fs"
                    % (step, self.commit_timeout_s)
                )
            time.sleep(0.02)

    # -- restore ------------------------------------------------------------

    def _read_shard(self, shard_dir, state):
        """state[name] = (value, dist_or_None) for every tensor in the
        shard, crc-verified. For sharded manifests dist-sharded entries
        accumulate as {rank: piece} dicts for reassembly."""
        from ..fluid import core
        from ..fluid.ops.io_ops import deserialize_lod_tensor

        manifest_path = os.path.join(shard_dir, SHARD_MANIFEST)
        if not os.path.isfile(manifest_path):
            manifest_path = os.path.join(shard_dir, MANIFEST)
        with open(manifest_path) as f:
            shard = json.load(f)
        data_path = os.path.join(shard_dir, shard.get("data_file", DATA_FILE))
        with open(data_path, "rb") as f:
            buf = f.read()
        for name, entry in shard["tensors"].items():
            blob = buf[entry["offset"]: entry["offset"] + entry["nbytes"]]
            if len(blob) != entry["nbytes"] or (
                zlib.crc32(blob) & 0xFFFFFFFF
            ) != entry["crc32"]:
                raise ChecksumError(
                    "checkpoint tensor %r in %s fails its manifest crc32 "
                    "(torn or corrupted data file)" % (name, data_path)
                )
            t, _ = deserialize_lod_tensor(blob)
            val = t if t.lod() else t.numpy()
            dist = entry.get("dist")
            if dist is None:
                state[name] = (val, None)
            else:
                pieces = state.setdefault(name, ({}, dist))[0]
                pieces[int(dist["rank"])] = (
                    val.numpy() if isinstance(val, core.LoDTensor) else val
                )

    def _reassemble(self, state):
        """-> (out, resliced_count). Replicated vars pass through
        bit-exactly. Dist-sharded vars: a single-rank restore
        (gather/export) concatenates all shards to the full value; a
        sharded restore (this manager has nranks > 1 and the var in its
        dist_attrs) yields THIS rank's local shard — picked up directly
        when the topology matches, re-sliced from the concatenated full
        value when restoring into a different nranks (N->M resharding).
        The N=1 edge (saved unsharded, restored sharded) replicates the
        full value and partitions it. ``resliced_count`` counts vars
        whose bytes had to be regrouped (concat and/or re-split) —
        topology-matched pickups and pass-throughs are free."""
        out = {}
        resliced = 0
        for name, (val, dist) in state.items():
            if dist is None:
                if self.nranks > 1 and name in self.dist_attrs:
                    # replicate-and-partition: the checkpoint holds the
                    # full (unsharded) value but THIS manager wants a
                    # TP shard of it
                    out[name] = np.array_split(
                        np.asarray(
                            val.numpy() if hasattr(val, "numpy") else val
                        ),
                        self.nranks, axis=int(self.dist_attrs[name]),
                    )[self.rank]
                    resliced += 1
                else:
                    out[name] = val
                continue
            pieces = [val[r] for r in sorted(val)]
            if len(pieces) != int(dist["nranks"]):
                raise CheckpointError(
                    "sharded tensor %r: have %d of %d shards"
                    % (name, len(pieces), dist["nranks"])
                )
            saved_axis = int(dist["axis"])
            if self.nranks > 1 and name in self.dist_attrs:
                axis = int(self.dist_attrs[name])
                if int(dist["nranks"]) == self.nranks and axis == saved_axis:
                    out[name] = pieces[self.rank]
                else:
                    full = np.concatenate(pieces, axis=saved_axis)
                    out[name] = np.array_split(
                        full, self.nranks, axis=axis
                    )[self.rank]
                    resliced += 1
            else:
                out[name] = np.concatenate(pieces, axis=saved_axis)
                resliced += 1
        return out, resliced

    def _iter_step_tensors(self, step=None):
        if step is None:
            step = self.latest_step()
            if step is None:
                raise CheckpointError(
                    "no committed checkpoint under %r" % self.dirname
                )
        step_dir = os.path.join(self.dirname, _step_dirname(step))
        with open(os.path.join(step_dir, MANIFEST)) as f:
            manifest = json.load(f)
        state = {}
        if manifest.get("nranks", 1) > 1:
            for shard in manifest["shards"]:
                self._read_shard(os.path.join(step_dir, shard["dir"]), state)
        else:
            self._read_shard(step_dir, state)
        for name, (val, _dist) in state.items():
            yield name, val

    def _restore_rng(self, manifest, program, scope):
        idx = manifest.get("rng_run_index")
        if idx is None:
            return
        import weakref

        counters = program.__dict__.setdefault(
            "_rng_run_counters", weakref.WeakKeyDictionary()
        )
        counters[scope] = int(idx)

    # -- retention / hygiene ------------------------------------------------

    def _gc(self):
        """Retention after each commit (rank 0 / single-rank only — it
        runs on the publishing side): newest ``keep_max`` steps survive;
        steps divisible by ``keep_every_n_steps`` are pinned forever."""
        if self.keep_max <= 0:
            return
        steps = list_steps(self.dirname)
        doomed = steps[:-self.keep_max] if len(steps) > self.keep_max else []
        for s in doomed:
            if self.keep_every_n_steps > 0 and s % self.keep_every_n_steps == 0:
                continue
            victim = os.path.join(self.dirname, _step_dirname(s))
            # delete the manifest FIRST so a reader that races the rmtree
            # can never see a half-deleted dir as committed
            try:
                os.unlink(os.path.join(victim, MANIFEST))
            except OSError:
                pass
            shutil.rmtree(victim, ignore_errors=True)

    def _sweep_stale_tmp(self):
        """Remove a crashed run's staging dirs. Sharded mode sweeps only
        dirs older than the commit timeout: a faster-starting peer may
        already be staging its shard of a live save while this rank is
        still constructing its manager, and its fresh mtime spares it."""
        for name in os.listdir(self.dirname):
            if not name.startswith(_TMP_PREFIX):
                continue
            path = os.path.join(self.dirname, name)
            if self.nranks > 1:
                try:
                    age = time.time() - os.stat(path).st_mtime
                except OSError:
                    continue
                if age < self.commit_timeout_s:
                    continue
            shutil.rmtree(path, ignore_errors=True)

    def _raise_pending(self):
        with self._error_lock:
            err, self._error = self._error, None
        if err is not None:
            raise err

    # -- context manager ----------------------------------------------------

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
