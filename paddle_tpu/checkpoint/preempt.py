"""Preemption handling — one final synchronous checkpoint on SIGTERM.

Preemptible TPU VMs get a SIGTERM with a short grace window before the
SIGKILL. The contract here: ``distributed/launch.py`` forwards the
signal to every worker; each worker's installed ``PreemptionHandler``
runs ONE synchronous save of the current training state (async queue
drained first so the final save is the newest committed step), then
optionally exits with the conventional 128+SIGTERM status so the
launcher can tell a clean preemption from a crash.

Trainer loops that prefer to finish the in-flight step poll
``preemption_requested()`` instead of saving from the handler; the
handler supports both (``save_in_handler=False`` only sets the flag).
"""

from __future__ import annotations

import signal
import threading

_lock = threading.Lock()
_requested = threading.Event()


def preemption_requested():
    """True once any installed handler has seen its signal — loops poll
    this to stop cleanly at the next step boundary. STICKY for the life
    of the process (a preemption notice is a process-level fact); code
    that deliberately continues past one (e.g. a multi-epoch driver
    re-entering the trainer) should poll its own handler's per-install
    ``requested`` event instead, which each ``install()`` starts clear."""
    return _requested.is_set()


def request_preemption():
    """Set the process-wide preemption latch programmatically — the same
    sticky flag an installed ``PreemptionHandler`` sets on SIGTERM.
    Transports that own their own signal hook (the HTTP gateway's
    ``install_sigterm``) call this so every reader of the one latch —
    the exporter's ``/healthz``, the gateway's ``/readyz``, trainer
    step-boundary polls — flips to draining together."""
    _requested.set()


def _reset_for_tests():
    _requested.clear()


class PreemptionHandler(object):
    """Install with a state callback returning ``(step, program)`` (or
    ``(step, program, scope)``); on SIGTERM the handler drains the
    manager's async queue and commits one final synchronous save.

    Usage::

        handler = checkpoint.PreemptionHandler(
            mgr, lambda: (state.step, main_program)
        ).install()
        ...training loop...
        handler.uninstall()

    Consistency caveat for the in-handler save: Python runs the handler
    on the main thread between bytecodes, so the signal can land while
    ``executor.run`` is mid way through writing step N+1's results back
    to the scope — the snapshot would then interleave two steps and NOT
    be bit-exact (it still commits atomically and restores cleanly).
    Loops that need a guaranteed-consistent final checkpoint should pass
    ``save_in_handler=False`` and poll ``preemption_requested()`` at the
    step boundary (the fluid.trainer integration installs exactly that),
    or have ``state_fn`` return None while a step is in flight to skip
    the in-handler save."""

    def __init__(self, manager, state_fn, signals=(signal.SIGTERM,),
                 exit_after=True, save_in_handler=True):
        self.manager = manager
        self.state_fn = state_fn
        self.signals = tuple(signals)
        self.exit_after = exit_after
        self.save_in_handler = save_in_handler
        self._previous = {}
        self._installed = False
        self.final_step = None
        # per-install latch (cleared by install()), unlike the sticky
        # module-level flag: "did THIS handler see a signal"
        self.requested = threading.Event()

    def install(self):
        # signal handlers only install from the main thread; a trainer
        # driving from a worker thread falls back to the polling contract
        if threading.current_thread() is not threading.main_thread():
            return self
        self.requested.clear()
        for sig in self.signals:
            self._previous[sig] = signal.signal(sig, self._on_signal)
        self._installed = True
        return self

    def uninstall(self):
        if not self._installed:
            return self
        for sig, prev in self._previous.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, TypeError):
                pass
        self._previous.clear()
        self._installed = False
        return self

    def _on_signal(self, signum, frame):
        _requested.set()
        self.requested.set()
        if self.save_in_handler:
            with _lock:  # coalesce a SIGTERM burst into one final save
                self._final_save()
        if self.exit_after:
            raise SystemExit(128 + signum)

    def _final_save(self):
        state = self.state_fn()
        if state is None:
            return
        step, program = state[0], state[1]
        scope = state[2] if len(state) > 2 else None
        try:
            self.manager.wait()
        except Exception:
            pass  # a failed async save must not block the final sync one
        self.manager.save(step, program, scope=scope, async_=False)
        self.final_step = int(step)

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False
