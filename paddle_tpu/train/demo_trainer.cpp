/* C++ training demo (reference: paddle/fluid/train/demo/demo_trainer.cc +
 * test_train_recognize_digits.cc): trains a regression through the C API
 * without a line of user Python and asserts the loss decreases. */
#include <cstdio>
#include <cstdlib>
#include <vector>

#include "../capi/paddle_tpu_c_api.h"

int main(int argc, char** argv) {
  const char* root = argc > 1 ? argv[1] : nullptr;
  if (pt_capi_init(root) != 0) {
    std::fprintf(stderr, "init failed\n");
    return 1;
  }
  int64_t h = pt_capi_demo_program();
  if (h < 0) {
    std::fprintf(stderr, "program build failed\n");
    return 1;
  }
  const int B = 16, D = 13;
  std::vector<float> x(B * D), y(B);
  unsigned seed = 7;
  double first = 0.0, last = 0.0;
  for (int step = 0; step < 30; step++) {
    for (int i = 0; i < B; i++) {
      float s = 0.f;
      for (int d = 0; d < D; d++) {
        seed = seed * 1664525u + 1013904223u;
        float v = (seed >> 8) / 16777216.0f;
        x[i * D + d] = v;
        s += v;
      }
      y[i] = 0.3f * s;
    }
    const char* names[2] = {"x", "y"};
    const float* bufs[2] = {x.data(), y.data()};
    int64_t shapes[4] = {B, D, B, 1};
    int ndims[2] = {2, 2};
    double loss = 0.0;
    if (pt_capi_run(h, names, bufs, shapes, ndims, 2, &loss) != 0) {
      std::fprintf(stderr, "run failed at step %d\n", step);
      return 1;
    }
    if (step == 0) first = loss;
    last = loss;
  }
  std::printf("demo_trainer: loss %.6f -> %.6f\n", first, last);
  if (!(last < first)) {
    std::fprintf(stderr, "loss did not decrease\n");
    return 1;
  }
  std::printf("PASS\n");
  return 0;
}
