"""Tertiary benchmark: GPT-2-small causal-LM training throughput
(tokens/sec) on one chip. Exercises the CAUSAL flash-attention path (the
in-kernel `causal` flag, no dense [T, T] bias) that neither headline
metric covers. Same hardened architecture as bench.py / bench_bert.py:
the parent never imports jax; each attempt is a child process with a hard
wall-clock timeout, demoting batch on OOM/timeout with a labeled CPU
fallback. Prints ONE JSON line. ``vs_baseline`` compares the seq-1024
full config against the DERIVED V100-era constant below (BASELINE.md
provenance); other configs report null.
"""

import json
import os
import signal  # noqa: F401  (parity with sibling harnesses' imports)
import subprocess  # noqa: F401
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

METRIC = "gpt2_small_lm_throughput"
UNIT = "tokens/sec/chip"
DEFAULT_SEQ_LEN = int(os.environ.get("BENCH_GPT_SEQ", "1024"))

# V100-era GPT-2-small fp32 training baseline (tokens/sec, single V100).
# DERIVED, not independently reported (BASELINE.md provenance note, same
# method as the seq-384 BERT constant): FLOPs-scaled from the documented
# BERT-base seq-128 constant (40 seq/s = 5120 tok/s). Per-token per-layer
# FLOPs ∝ 24·H² + 4·S·H; H=768 over 12 layers gives 174.6M (BERT, S=128)
# vs 207.6M (GPT-2, S=1024), and GPT-2's untied lm_head adds 2·H·V ≈
# 77.2M/tok → ratio ≈ 1.63× → 5120 / 1.63 ≈ 3100 tok/s. Valid for the
# seq-1024 full config only.
V100_GPT2_SMALL_TOK_PER_SEC = 3100.0


def _hb(msg):
    print("HB %s" % msg, file=sys.stderr, flush=True)


def child_main(cfg):
    if cfg["platform"]:
        os.environ["JAX_PLATFORMS"] = cfg["platform"]
    import jax

    import bench

    bench.honor_jax_platforms(jax)
    bench.enable_compilation_cache(jax)
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu.models import gpt

    if cfg["platform"] == "cpu":
        place = fluid.CPUPlace()
        device = "cpu"
    elif fluid.core.get_tpu_device_count() == 0:
        print("CHILDERR " + json.dumps({"kind": "no_tpu", "msg": "no tpu"}),
              flush=True)
        sys.exit(1)
    else:
        place = fluid.TPUPlace(0)
        device = "tpu"
    dev = fluid.core.get_jax_device(place)
    import jax.numpy as jnp

    _hb("probe start")
    jax.jit(lambda a: (a @ a).sum())(
        jax.device_put(jnp.ones((256, 256), jnp.bfloat16), dev)
    ).block_until_ready()
    _hb("probe ok")

    batch = cfg["batch"]
    seq_len = int(cfg.get("seq_len", DEFAULT_SEQ_LEN))
    gcfg = (
        gpt.GPTConfig(
            # long-context rungs (seq 4096) need a position table larger
            # than GPT-2's stock 1024; growing it is the only change
            max_position_embeddings=max(1024, seq_len),
        ) if cfg["full"] else gpt.GPTConfig(
            vocab_size=2048, hidden_size=256, num_layers=4, num_heads=4,
            intermediate_size=1024, max_position_embeddings=seq_len,
        )
    )
    # throughput config: dropout off (same convention as bench_bert)
    gcfg.hidden_dropout = 0.0
    gcfg.attention_dropout = 0.0
    gcfg.use_flash_attention = bool(
        cfg.get("flash", os.environ.get("BENCH_FLASH", "0") == "1")
    )
    _hb("build start")
    main, startup, _feeds, loss = gpt.build_gpt_lm_train(
        gcfg, seq_len, learning_rate=3e-4,
        use_amp=os.environ.get("BENCH_AMP", "1") == "1",
    )
    exe = fluid.Executor(place)
    _hb("startup start")
    exe.run(startup)
    _hb("startup ok")
    rs = np.random.RandomState(0)
    feed = {
        "ids": jax.device_put(
            rs.randint(0, gcfg.vocab_size, (batch, seq_len, 1)).astype("int64"),
            dev,
        ),
        "pos_ids": jax.device_put(
            np.tile(np.arange(seq_len)[None, :, None], (batch, 1, 1))
            .astype("int64"), dev,
        ),
        "input_mask": jax.device_put(
            np.ones((batch, seq_len, 1), "float32"), dev
        ),
    }
    _hb("warmup start")
    for i in range(cfg["warmup"]):
        exe.run(main, feed=feed, fetch_list=[loss])
        _hb("warmup %d done" % i)
    exe.run(main, feed=feed, fetch_list=[])
    exe.run(main, feed=feed, fetch_list=[loss])
    _hb("timed start")
    t0 = time.perf_counter()
    steps = cfg["steps"]
    out = None
    for i in range(steps):
        out = exe.run(
            main, feed=feed, fetch_list=[loss] if i == steps - 1 else []
        )
    lval = float(np.asarray(out[0]).ravel()[0])
    dt = time.perf_counter() - t0
    assert np.isfinite(lval), lval
    tps = batch * seq_len * steps / dt
    _hb("timed ok %.2fs loss=%.4f tps=%.1f" % (dt, lval, tps))
    print("RESULT " + json.dumps({"tps": tps, "device": device, "loss": lval}),
          flush=True)


def _child_entry(cfg):
    try:
        child_main(cfg)
    except SystemExit:
        raise
    except Exception as e:  # classify for the parent (bench.py contract)
        msg = str(e)
        if "RESOURCE_EXHAUSTED" in msg or "out of memory" in msg.lower():
            kind = "oom"
        elif "UNAVAILABLE" in msg or "DEADLINE_EXCEEDED" in msg:
            kind = "transient"
        else:
            kind = "other"
        import traceback

        traceback.print_exc(file=sys.stderr)
        print("CHILDERR " + json.dumps({"kind": kind, "msg": msg[:300]}),
              flush=True)
        sys.exit(1)


def main():
    import bench

    deadline = time.time() + int(os.environ.get("BENCH_BUDGET_S", "1400"))
    seq = DEFAULT_SEQ_LEN
    flash = os.environ.get("BENCH_FLASH", "0") == "1"
    # batch scales down with seq len so the attempt fits the same slot
    big, small = (16, 4) if seq <= 1024 else (4, 1)
    attempts = [
        (dict(platform="", batch=big, steps=10, warmup=2, full=True,
              seq_len=seq, flash=flash), 420),
        (dict(platform="", batch=small, steps=10, warmup=2, full=True,
              seq_len=seq, flash=flash), 360),
        # CPU fallback: tiny config, short seq, flash off (the kernel
        # cannot run there — a flash:true CPU line would be false
        # provenance, same rule as bench_bert)
        (dict(platform="cpu", batch=4, steps=3, warmup=1, full=False,
              seq_len=128, flash=False), 280),
    ]
    for cfg, slot in attempts:
        label = "gpt-%s-b%d-s%d%s" % (
            cfg["platform"] or "tpu", cfg["batch"], cfg["seq_len"],
            "-flash" if cfg["flash"] else "",
        )
        res, _kind, err, _probe_ok = bench._run_attempt(
            label, cfg, slot, deadline,
            script=os.path.abspath(__file__),
        )
        if err:
            print("bench_gpt[%s]: %s" % (label, err), file=sys.stderr,
                  flush=True)
        if res:
            degraded = cfg["platform"] == "cpu" or not cfg["full"]
            # the derived V100 constant (BASELINE.md) covers exactly the
            # seq-1024 GPT-2-small config; anything else reports null
            vs = (
                round(res["tps"] / V100_GPT2_SMALL_TOK_PER_SEC, 3)
                if not degraded and cfg["seq_len"] == 1024
                else None
            )
            out = {
                "metric": METRIC,
                "value": round(res["tps"], 1),
                "unit": UNIT,
                "vs_baseline": vs,
                "batch": cfg["batch"],
                "seq_len": cfg["seq_len"],
                "device": res["device"],
            }
            if cfg["flash"]:
                out["flash_attention"] = True
            if res["device"] == "tpu" and not degraded:
                bench.bank_write(
                    "gpt_seq%d%s" % (
                        cfg["seq_len"], "_flash" if cfg["flash"] else ""
                    ),
                    bench._bank_entry(out),
                )
            if degraded:
                out["degraded"] = "cpu-fallback tiny-config"
            print(json.dumps(out), flush=True)
            return
    print(json.dumps({
        "metric": METRIC, "value": 0.0, "unit": UNIT, "vs_baseline": None,
        "error": "all attempts failed",
    }), flush=True)


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--child":
        _child_entry(json.loads(sys.argv[2]))
    else:
        main()
