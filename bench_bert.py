"""Secondary benchmark: BERT-base fine-tune throughput (sequences/sec) on
one chip (BASELINE.md metric 2). Same hardened architecture as bench.py:
the parent never imports jax; each attempt is a child process with a hard
wall-clock timeout, demoting batch on OOM/timeout with a labeled CPU
fallback. Prints ONE JSON line.
"""

import json
import os
import signal
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

METRIC = "bert_base_finetune_throughput"
UNIT = "sequences/sec/chip"
DEFAULT_SEQ_LEN = int(os.environ.get("BENCH_BERT_SEQ", "128"))


def _hb(msg):
    print("HB %s" % msg, file=sys.stderr, flush=True)


def child_main(cfg):
    if cfg["platform"]:
        os.environ["JAX_PLATFORMS"] = cfg["platform"]
    import jax

    import bench

    bench.honor_jax_platforms(jax)
    bench.enable_compilation_cache(jax)
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu.models import bert

    if cfg["platform"] == "cpu":
        place = fluid.CPUPlace()
        device = "cpu"
    elif fluid.core.get_tpu_device_count() == 0:
        print("CHILDERR " + json.dumps({"kind": "no_tpu", "msg": "no tpu"}),
              flush=True)
        sys.exit(1)
    else:
        place = fluid.TPUPlace(0)
        device = "tpu"
    dev = fluid.core.get_jax_device(place)
    import jax.numpy as jnp

    _hb("probe start")
    jax.jit(lambda a: (a @ a).sum())(
        jax.device_put(jnp.ones((256, 256), jnp.bfloat16), dev)
    ).block_until_ready()
    _hb("probe ok")

    batch = cfg["batch"]
    seq_len = int(cfg.get("seq_len", DEFAULT_SEQ_LEN))
    bcfg = (
        bert.BertConfig() if cfg["full"] else bert.BertConfig(
            hidden_size=256, num_layers=4, num_heads=4,
            intermediate_size=1024,
        )
    )
    bcfg.hidden_dropout = 0.0
    bcfg.attention_dropout = 0.0
    # fused Pallas flash attention (opt-in probe: BENCH_FLASH=1 or cfg)
    bcfg.use_flash_attention = bool(
        cfg.get("flash", os.environ.get("BENCH_FLASH", "0") == "1")
    )
    _hb("build start")
    main, startup, feeds, loss, acc = bert.build_bert_classifier(
        bcfg, seq_len, learning_rate=2e-5,
        # bf16 matmuls on the MXU (BENCH_AMP=0 opts out, bench.py parity)
        use_amp=os.environ.get("BENCH_AMP", "1") == "1",
    )
    exe = fluid.Executor(place)
    _hb("startup start")
    exe.run(startup)
    _hb("startup ok")
    rs = np.random.RandomState(0)
    feed = {
        "src_ids": jax.device_put(
            rs.randint(0, bcfg.vocab_size, (batch, seq_len, 1)).astype("int64"), dev
        ),
        "pos_ids": jax.device_put(
            np.tile(np.arange(seq_len)[None, :, None], (batch, 1, 1)).astype("int64"),
            dev,
        ),
        "sent_ids": jax.device_put(
            np.zeros((batch, seq_len, 1), "int64"), dev
        ),
        "input_mask": jax.device_put(
            np.ones((batch, seq_len, 1), "float32"), dev
        ),
        "label": jax.device_put(rs.randint(0, 2, (batch, 1)).astype("int64"), dev),
    }
    _hb("warmup start")
    for i in range(cfg["warmup"]):
        exe.run(main, feed=feed, fetch_list=[loss])
        _hb("warmup %d done" % i)
    # compile + fully drain the fetch-free variant BEFORE the clock starts
    # (async dispatch would otherwise leak this step into the timed window)
    exe.run(main, feed=feed, fetch_list=[])
    exe.run(main, feed=feed, fetch_list=[loss])
    _hb("timed start")
    t0 = time.perf_counter()
    steps = cfg["steps"]
    out = None
    for i in range(steps):
        out = exe.run(
            main, feed=feed, fetch_list=[loss] if i == steps - 1 else []
        )
    lval = float(np.asarray(out[0]).ravel()[0])
    dt = time.perf_counter() - t0
    assert np.isfinite(lval), lval
    sps = batch * steps / dt
    _hb("timed ok %.2fs loss=%.4f sps=%.1f" % (dt, lval, sps))
    result = {"sps": sps, "device": device, "loss": lval}
    # dense path only: cost analysis cannot see inside the flash Pallas
    # custom call, so a flash census would undercount (PERF.md round-5)
    if not bcfg.use_flash_attention:
        try:
            from paddle_tpu.observability import xla_stats as _xla_stats

            _xla_stats.attach_headline_census(result)
        except Exception as e:  # census must never sink a measurement
            _hb("census unavailable: %s" % e)
    print("RESULT " + json.dumps(result), flush=True)


def _child_entry(cfg):
    try:
        child_main(cfg)
    except SystemExit:
        raise
    except Exception as e:  # classify for the parent (bench.py contract)
        msg = str(e)
        if "RESOURCE_EXHAUSTED" in msg or "out of memory" in msg.lower():
            kind = "oom"
        elif "UNAVAILABLE" in msg or "DEADLINE_EXCEEDED" in msg:
            kind = "transient"
        else:
            kind = "other"
        import traceback

        traceback.print_exc(file=sys.stderr)
        print("CHILDERR " + json.dumps({"kind": kind, "msg": msg[:300]}),
              flush=True)
        sys.exit(1)


def main():
    import bench

    deadline = time.time() + int(os.environ.get("BENCH_BUDGET_S", "1400"))
    seq = DEFAULT_SEQ_LEN
    flash = os.environ.get("BENCH_FLASH", "0") == "1"
    # batch scales down with seq len so the attempt fits the same slot
    big, small = (64, 16) if seq <= 128 else (24, 8)
    attempts = [
        (dict(platform="", batch=big, steps=10, warmup=2, full=True,
              seq_len=seq, flash=flash), 420),
        (dict(platform="", batch=small, steps=10, warmup=2, full=True,
              seq_len=seq, flash=flash), 360),
        # the CPU fallback pins seq 128 AND flash off: the Pallas kernel
        # cannot run there (the op silently uses the dense reference), so
        # a flash_attention:true CPU line would be false provenance
        (dict(platform="cpu", batch=4, steps=3, warmup=1, full=False,
              seq_len=128, flash=False), 280),
    ]
    for cfg, slot in attempts:
        label = "bert-%s-b%d-s%d%s" % (
            cfg["platform"] or "tpu", cfg["batch"], cfg["seq_len"],
            "-flash" if cfg["flash"] else "",
        )
        res, _kind, err, _probe_ok = bench._run_attempt(
            label, cfg, slot, deadline,
            script=os.path.abspath(__file__),
        )
        if err:
            print("bench_bert[%s]: %s" % (label, err), file=sys.stderr,
                  flush=True)
        if res:
            degraded = cfg["platform"] == "cpu" or not cfg["full"]
            # single source of truth for baselines: bench.py (BASELINE.md
            # documents the per-seq-len provenance)
            baseline = bench.V100_BERT_BASE_SEQ_PER_SEC.get(cfg["seq_len"])
            out = {
                "metric": METRIC,
                "value": round(res["sps"], 2),
                "unit": UNIT,
                # null when degraded OR the seq len has no documented constant
                "vs_baseline": (
                    round(res["sps"] / baseline, 3)
                    if baseline and not degraded else None
                ),
                "batch": cfg["batch"],
                "seq_len": cfg["seq_len"],
                "device": res["device"],
            }
            if cfg["flash"]:
                out["flash_attention"] = True
            # propagate the child's fresh census (dense rungs only — the
            # child skips it for flash) so a standalone run re-banks
            # flops/bytes like the bench.py driver path does
            for k in ("flops", "bytes_accessed", "out_bytes"):
                if res.get(k) is not None:
                    out[k] = res[k]
                    out["census_source"] = "live_census"
            if res["device"] == "tpu" and not degraded:
                bench.bank_write(
                    "bert_seq%d%s" % (cfg["seq_len"], "_flash" if cfg["flash"] else ""),
                    bench._bank_entry(out),
                )
            if degraded:
                out["degraded"] = "cpu-fallback tiny-config"
            print(json.dumps(out), flush=True)
            return
    print(json.dumps({
        "metric": METRIC, "value": 0.0, "unit": UNIT, "vs_baseline": None,
        "error": "all attempts failed",
    }), flush=True)


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--child":
        _child_entry(json.loads(sys.argv[2]))
    else:
        main()
