"""Op-gap audit: reference operator registry vs paddle_tpu registry.

Extracts forward op registrations from the reference
(`REGISTER_OPERATOR` / `REGISTER_OP_WITHOUT_GRADIENT` in
/root/reference/paddle/fluid/operators, multiline-aware), diffs them against
`registry.all_op_types()`, and writes OPS_AUDIT.md with a disposition for
every reference op we do not register. Run:

    JAX_PLATFORMS=cpu python tools/op_audit.py

Dispositions:
- implemented: registered in paddle_tpu (possibly under this same name).
- gpu-backend: kernel exists only to target CUDA/cuDNN/TensorRT/Anakin/
  nGraph/MKLDNN machinery whose role XLA subsumes on TPU.
- external-dep: wraps an external service/library the build intentionally
  excludes (BoxPS, PSLib federated variant).
- subsumed: capability delivered by a different paddle_tpu mechanism;
  registering the op name would be a dead alias (noted inline).
- todo: genuine gap worth implementing.
"""

from __future__ import annotations

import os
import re
import subprocess
import sys

REF_OPS_DIR = "/root/reference/paddle/fluid/operators"
OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "OPS_AUDIT.md")

# Disposition for every reference op not in the registry. Ops that get
# implemented later simply disappear from this table on the next run.
DISPOSITIONS = {
    # --- GPU/backend-specific (role subsumed by XLA / not meaningful on TPU)
    "anakin_engine": ("gpu-backend", "Anakin inference engine subgraph op"),
    "tensorrt_engine": ("gpu-backend", "TensorRT subgraph op"),
    "ngraph_engine": ("gpu-backend", "Intel nGraph subgraph op"),
    "cudnn_lstm": ("gpu-backend", "cuDNN-specific LSTM; fused scan LSTM covers it (ops/rnn_fused_ops.py)"),
    "nccl": ("gpu-backend", "NCCL init/allreduce trio; mesh collectives cover it (ops/collective_ops.py)"),
    "conv2d_fusion": ("gpu-backend", "cuDNN fused conv+bias+act; XLA fuses this pattern automatically"),
    "conv2d_inception_fusion": ("gpu-backend", "cuDNN inception-block fusion; XLA fusion"),
    "quantize": ("gpu-backend", "MKLDNN INT8 pipeline entry; fake_quant/dequant family covers QAT (ops/quant_ops.py)"),
    "dequantize": ("gpu-backend", "MKLDNN INT8 pipeline"),
    "requantize": ("gpu-backend", "MKLDNN INT8 pipeline"),
    "get_places": ("gpu-backend", "enumerates CUDA places for ParallelDo (deprecated API); mesh replaces it"),
    # --- external-dependency ops
    "pull_box_sparse": ("external-dep", "BoxPS (internal ads serving) sparse pull"),
    "push_box_sparse": ("external-dep", "BoxPS sparse push"),
    "pyramid_hash": ("external-dep", "xxhash-based feature hashing for PSLib CTR"),
    "fl_listen_and_serv": ("external-dep", "federated-learning pserver variant (PSLib)"),
    # --- subsumed by a different mechanism
    "cross_entropy_grad2": ("subsumed", "explicit grad kernel of cross_entropy2; generic vjp grad path covers it"),
    "conditional_block_infer": ("subsumed", "inference-mode conditional_block; lower_conditional_block handles both"),
    "merge_lod_tensor_infer": ("subsumed", "inference-mode merge_lod_tensor; merge_lod_tensor lowering handles both"),
    "read": ("subsumed", "reader-queue pop; DataLoader/PyReader feed path (fluid/reader.py) delivers batches"),
    "create_custom_reader": ("subsumed", "reader decorators compose in Python (reader/decorator.py)"),
    "delete_var": ("subsumed", "eager deletion; XLA buffer donation owns lifetime (executor.py)"),
    "rnn_memory_helper": ("subsumed", "StaticRNN scratch-var plumbing; fused-scan StaticRNN needs no helper vars"),
    "beam_search": ("subsumed", "layers.rnn BeamSearchDecoder runs the whole search as one lax.while_loop"),
    "beam_search_decode": ("subsumed", "same: decode folded into the loop (layers/rnn.py)"),
    "reorder_lod_tensor_by_rank": ("subsumed", "LoDRankTable time-major batching; fused-scan RNNs consume padded+length form"),
    "dgc": ("subsumed", "DGC compression runs inside DGCMomentumOptimizer lowering (ops/optimizer_ops.py, test_dgc.py)"),
    "dgc_clip_by_norm": ("subsumed", "folded into DGC optimizer lowering"),
    "average_accumulates": ("subsumed", "ModelAverage optimizer keeps sum_1/sum_2/sum_3 accumulators itself (optimizer.py)"),
    "lookup_sparse_table": ("subsumed", "pserver-side auto-growth table; distributed_lookup_table + SelectedRows path covers the capability"),
    # --- everything below is 'todo' until implemented; keep reasons short.
}

TODO_NOTES = {
    "hierarchical_sigmoid": "word2vec-style hsigmoid loss",
    "nce": "noise-contrastive estimation loss",
    "multihead_matmul": "fused transformer attention (valuable as one XLA segment)",
}


def ref_forward_ops():
    pat = re.compile(rb"REGISTER_OP(?:ERATOR|_WITHOUT_GRADIENT)\(\s*([a-z0-9_]+)")
    ops = set()
    for root, _dirs, files in os.walk(REF_OPS_DIR):
        for fn in files:
            if fn.endswith((".cc", ".cu")):
                with open(os.path.join(root, fn), "rb") as fh:
                    for m in pat.finditer(fh.read()):
                        ops.add(m.group(1).decode())
    return {o for o in ops if not o.endswith("_grad")}


def main():
    sys.path.insert(0, os.path.dirname(OUT))
    from paddle_tpu.fluid.ops import registry

    ours = set(registry.all_op_types())
    ref = ref_forward_ops()
    missing = sorted(ref - ours)
    rows = []
    counts = {}
    for name in missing:
        disp, why = DISPOSITIONS.get(name, ("todo", TODO_NOTES.get(name, "")))
        counts[disp] = counts.get(disp, 0) + 1
        rows.append((name, disp, why))

    with open(OUT, "w") as f:
        f.write("# Operator-gap audit (generated by tools/op_audit.py)\n\n")
        f.write(
            "Reference forward-op registrations: **%d** "
            "(`REGISTER_OPERATOR`/`REGISTER_OP_WITHOUT_GRADIENT` under "
            "`paddle/fluid/operators`, grads excluded).\n"
            "paddle_tpu registry: **%d** op types.\n"
            "Reference ops not registered here: **%d** (%s).\n\n"
            % (
                len(ref),
                len(ours),
                len(missing),
                ", ".join("%s %d" % (k, v) for k, v in sorted(counts.items())),
            )
        )
        f.write("| op | disposition | why |\n|---|---|---|\n")
        for name, disp, why in rows:
            f.write("| %s | %s | %s |\n" % (name, disp, why))
        extra = sorted(ours - ref)
        f.write(
            "\npaddle_tpu-only op types (%d): v2 spellings, TPU-native ops "
            "(ring attention, collectives), and composites the reference "
            "builds in Python:\n\n" % len(extra)
        )
        f.write(", ".join("`%s`" % e for e in extra) + "\n")
    print("wrote %s: ref=%d ours=%d missing=%d %s" % (OUT, len(ref), len(ours), len(missing), counts))


if __name__ == "__main__":
    main()
