"""CPU-runnable closed-loop load probe for the serving runtime.

Drives an InferenceServer at N concurrent closed-loop clients (each
submits, waits for its result, immediately resubmits) against the serial
baseline — the same requests one predictor.run() call at a time, which
is exactly what every caller did before paddle_tpu.serving existed. The
probe asserts the serving acceptance bars:

- dynamic batching >= 2x the serial requests/sec at 8 clients (the
  coalescer amortizes per-call dispatch overhead across the batch and
  the device sees batch-parallel work);
- bucket-plan hit rate == 100% after warmup AND zero predictor
  plan-cache misses (zero steady-state XLA compiles: every padded shape
  was eagerly compiled at server start);
- batch-fill ratio >= 0.5 (the coalescer actually coalesces).

The 2-core driver box throttles under external load (same finding as
feed_overlap_probe / decode_probe), so throughput uses LOAD-ROBUST
estimators: the serial loop keeps the best of interleaved rounds, and
the dynamic side takes the best >= 0.5 s sliding window over the live
``serving_completed`` counter (``bench.best_window_rate``, shared with
the decode probe) — the steady-state rate without the client ramp-up
tail, since external load only ever subtracts throughput.

Run directly (prints one REPORT json line + PROBE PASS/FAIL)::

    JAX_PLATFORMS=cpu python tools/serving_load_probe.py [--fast]

or via tests/test_serving.py, which runs ``--fast`` in a subprocess as
a tier-1 regression guard (with the decode-probe retry policy: one
retry for a throughput-ONLY miss, never for correctness misses).
"""

import argparse
import json
import os
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPORT_SCHEMA_VERSION = 2


def build_model(dirname, dim=64, hidden=128, classes=8, seed=0):
    """Init (no training needed) and save a small classifier inference
    model; returns an example single-row input."""
    import numpy as np

    import paddle_tpu.fluid as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[dim], dtype="float32")
        h = fluid.layers.fc(x, size=hidden, act="relu", name="probe_fc1")
        h = fluid.layers.fc(h, size=hidden, act="relu", name="probe_fc2")
        out = fluid.layers.softmax(
            fluid.layers.fc(h, size=classes, name="probe_cls")
        )
    scope = fluid.core.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup, scope=scope)
        fluid.io.save_inference_model(
            dirname, ["x"], [out], exe, main_program=main
        )
    return np.random.RandomState(seed).rand(1, dim).astype("float32")


def run_probe(clients=8, requests_per_client=25, serial_requests=40,
              max_batch=8, batch_timeout_ms=8.0, workers=1, rounds=3,
              verbose=False):
    """Returns a dict of measurements; callers assert on the numbers.

    Shared/loaded hosts drift between back-to-back runs (same finding as
    tools/feed_overlap_probe.py), so the serial and dynamic loops are
    measured in INTERLEAVED rounds and compared by per-mode BEST rps —
    load only ever subtracts throughput, so the max is the undisturbed
    figure. Correctness is verified once per client outside the timed
    windows: numpy assert machinery inside the loop would serialize the
    closed-loop clients on the GIL and measure the assert, not the
    server. One dispatch worker (the default here) lets all N clients
    coalesce into ONE full device batch per cadence — the configuration
    the >= 2x bar is about; more workers trade fill for lower latency."""
    import numpy as np

    from paddle_tpu import inference, serving
    from paddle_tpu.fluid import profiler

    with tempfile.TemporaryDirectory() as d:
        xd = build_model(d)

        serial_pred = inference.create_paddle_predictor(
            inference.AnalysisConfig(d)
        )
        expect = serial_pred.run([xd])[0]  # warm (compiles batch-1 plan)

        server_pred = inference.create_paddle_predictor(
            inference.AnalysisConfig(d)
        )
        server = serving.InferenceServer(
            server_pred, max_batch_size=max_batch,
            batch_timeout_ms=batch_timeout_ms, queue_depth=4 * clients,
            num_workers=workers,
        ).start(warmup_inputs=[xd])
        # correctness once, outside any timed window
        np.testing.assert_allclose(
            server.infer([xd], deadline_ms=30000)[0], expect,
            rtol=1e-4, atol=1e-5,
        )
        c_after_warm = profiler.get_counters()

        errors = []

        def client_loop():
            out = None
            try:
                for _ in range(requests_per_client):
                    (out,) = server.infer([xd], deadline_ms=30000)
            except Exception as e:  # noqa: BLE001 - surfaced via errors
                errors.append(e)
                return
            if not np.allclose(out, expect, rtol=1e-4, atol=1e-5):
                errors.append(AssertionError("served output diverged"))

        from bench import best_window_rate

        def completed_now():
            return profiler.get_counters().get("serving_completed", 0)

        def dynamic_round():
            threads = [
                threading.Thread(target=client_loop) for _ in range(clients)
            ]
            samples = [(time.perf_counter(), completed_now())]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            while any(t.is_alive() for t in threads):
                time.sleep(0.02)
                samples.append((time.perf_counter(), completed_now()))
            for t in threads:
                t.join()
            t1 = time.perf_counter()
            samples.append((t1, completed_now()))
            wall = clients * requests_per_client / (t1 - t0)
            # best >= 0.5 s window over the live served-request counter:
            # the steady-state rate with the thread-startup ramp outside
            # the window (falls back to the full span on short rounds);
            # the wall rate stays a floor so the estimator can only help
            return max(wall, best_window_rate(samples, 0.5))

        def serial_round():
            t0 = time.perf_counter()
            for _ in range(serial_requests):
                serial_pred.run([xd])
            return serial_requests / (time.perf_counter() - t0)

        # Box contention correlates WITHIN a round: a stall squeezes
        # that round's serial loop and its batched burst together. So
        # the bar rides the best per-round RATIO — a clean serial round
        # is never paired against a contended dynamic round, which was
        # the one residual flake after the windowed-rate estimator.
        # The headline rates stay best-of-rounds for reporting.
        serial_rps = dynamic_rps = speedup = 0.0
        for _ in range(rounds):
            s_rps = serial_round()
            d_rps = dynamic_round()
            serial_rps = max(serial_rps, s_rps)
            dynamic_rps = max(dynamic_rps, d_rps)
            speedup = max(speedup, d_rps / s_rps)
        stats = server.stats()
        server.stop()
        if errors:
            raise AssertionError("client errors: %r" % errors[:3])

        c_end = profiler.get_counters()
        recompiles = c_end.get("predictor_plan_cache_misses", 0) - \
            c_after_warm.get("predictor_plan_cache_misses", 0)
        result = {
            "schema_version": REPORT_SCHEMA_VERSION,
            "clients": clients,
            "requests": rounds * clients * requests_per_client,
            "rounds": rounds,
            "serial_rps": round(serial_rps, 1),
            "dynamic_rps": round(dynamic_rps, 1),
            "speedup": round(speedup, 3),
            "batch_fill_ratio": stats.batch_fill_ratio,
            "bucket_hit_rate": stats.bucket_hit_rate,
            "recompiles_after_warmup": int(recompiles),
            "shed_deadline": stats.shed_deadline,
            "shed_overload": stats.shed_overload,
            "p50_ms": stats.latency_ms["p50"],
            "p99_ms": stats.latency_ms["p99"],
        }
        if verbose:
            print(json.dumps(result, indent=1), file=sys.stderr)
        return result


def evaluate(result):
    """Acceptance-bar failures (empty = pass). A miss that names only
    'speedup' is throughput-only — the one class the tier-1 wrapper may
    retry once (box contention compresses throughput; it cannot corrupt
    outputs, bucket hits, or the recompile count)."""
    failures = []
    if result["speedup"] < 2.0:
        failures.append("speedup %.3f < 2x" % result["speedup"])
    if result["batch_fill_ratio"] < 0.5:
        failures.append("batch_fill_ratio %.3f < 0.5"
                        % result["batch_fill_ratio"])
    if result["bucket_hit_rate"] != 1.0:
        failures.append("bucket_hit_rate %.3f != 1.0"
                        % result["bucket_hit_rate"])
    if result["recompiles_after_warmup"] != 0:
        failures.append("%d recompiles after warmup"
                        % result["recompiles_after_warmup"])
    return failures


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--fast", action="store_true",
                    help="tier-1 budget subset")
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)
    if args.fast:
        result = run_probe(clients=8, requests_per_client=15,
                           serial_requests=30, rounds=2,
                           verbose=args.verbose)
    else:
        result = run_probe(verbose=args.verbose)
    failures = evaluate(result)
    result["pass"] = not failures
    result["failures"] = failures
    print("REPORT " + json.dumps(result, sort_keys=True), flush=True)
    print("PROBE PASS" if result["pass"]
          else "PROBE FAIL: %s" % "; ".join(failures))
    return 0 if result["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
