"""CPU-runnable closed-loop probe for the SPMD mesh mainline.

Exercises the GSPMD execution subsystem (paddle_tpu/parallel/spmd.py +
the executor/compiler graft) end to end on a single process exposing 8
virtual CPU devices via ``--xla_force_host_platform_device_count``, and
asserts the mainlining acceptance bars:

- TP SERVING PARITY: a tensor-parallel (TP=2) paged DecodeEngine —
  weights Megatron column/row-sharded, KV block pools heads-partitioned
  over the ``model`` axis, block tables replicated — is token-exact vs
  the single-device ``gpt._reference_generate`` oracle across the miss,
  zero-copy prefix-hit, chunked-window, and resume admission paths;
- TRAIN -> SERVE RESHARD: a DP=4-trained checkpoint (params updated
  under the GSPMD data mesh, saved by a 4-rank CheckpointManager gang)
  loads into a TP=2 serving replica via
  ``spmd.load_train_checkpoint`` — every param bit-exact after the
  topology conversion, restored weights committed on the serve mesh,
  and the replica's output token-exact vs the oracle on the trained
  params;
- TRAIN DIGESTS (child process, ``JAX_ENABLE_X64``): DP=2 and FSDP=2
  loss streams digest byte-equal the single-device run on the same
  data stream once the f64 accumulation noise (~1e-13) is rounded back
  to f32 — the reduction-order ULP wiggle that makes raw f32 streams
  diverge is below the cast;
- OPTIMIZER SHARDING: under FSDP=2 the Momentum velocity state holds
  ~1/2 the bytes per device of the single-device run (the ZeRO-style
  weight-update sharding of PAPERS "Automatic Cross-Replica Sharding");
- ZERO RECOMPILES: the whole TP serving schedule (miss/hit/chunked/
  resume churn) finishes with ``serving_steady_recompiles`` unchanged
  under the armed strict gate — sharded placement enters the compile
  key once at warmup and never drifts;
- TELEMETRY: the active mesh/policy summary reaches the ``/compiles``
  payload and the ``spmd_mesh_shape``/``spmd_sharded_params`` gauges
  render on the exporter registry.

Run directly (prints one REPORT json line + PROBE PASS/FAIL)::

    python tools/spmd_probe.py --fast

or via tests/test_spmd.py, which runs --fast as a tier-1 gate.
"""

import argparse
import json
import os
import subprocess
import sys

# virtual multi-device SPMD must be armed BEFORE jax initializes; the
# test harness wipes XLA_FLAGS in probe subprocesses, so self-set here
_N_DEV = 8
_cur = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _cur:
    os.environ["XLA_FLAGS"] = (
        _cur + " --xla_force_host_platform_device_count=%d" % _N_DEV
    ).strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPORT_SCHEMA_VERSION = 1


def _build_mlp_train(seed=90, dtype="float32"):
    """Small fc->relu->fc->softmax-CE trainer with MOMENTUM (per-param
    velocity state — the optimizer-sharding measurement needs real
    accumulator bytes). Guard-reset names keep param init identical
    across builds. fc params inherit the data dtype, so dtype="float64"
    yields an end-to-end f64 graph."""
    import paddle_tpu.fluid as fluid

    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[16], dtype=dtype)
        y = fluid.layers.data(name="y", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, size=32, act="relu")
        logits = fluid.layers.fc(h, size=5)
        loss = fluid.layers.softmax_with_cross_entropy(logits, y)
        avg = fluid.layers.mean(loss)
        opt = fluid.optimizer.Momentum(learning_rate=0.1, momentum=0.9)
        opt.minimize(avg)
    return main, startup, avg


def run_train_leg(fast=True):
    """The byte-equality + optimizer-bytes legs, run in a CHILD process
    under ``JAX_ENABLE_X64`` with an all-f64 graph (empirical finding:
    f32 GSPMD loss streams drift from single-device by reduction-order
    ULPs — ~1.5e-8 — from step ~2; X64 alone does NOT help because
    explicitly-f32 program vars stay f32, so the graph itself is built
    float64 — there the same wiggle is ~1e-13 and vanishes when the
    stream is cast back to f32 for digesting)."""
    import hashlib

    import numpy as np

    import paddle_tpu.fluid as fluid

    steps = 6 if fast else 12

    def digest(losses):
        arr = np.asarray(losses, np.float64).astype(np.float32)
        return hashlib.sha256(arr.tobytes()).hexdigest()

    def run(mode):
        from paddle_tpu.fluid import compiler

        scope = fluid.core.Scope()
        exe = fluid.Executor(fluid.CPUPlace())
        main, startup, avg = _build_mlp_train(dtype="float64")
        with fluid.scope_guard(scope):
            exe.run(startup)
            if mode == "single":
                prog = main
            else:
                prog = compiler.CompiledProgram(main).with_mesh(
                    loss_name=avg.name, mesh_axes={"data": 2},
                    fsdp=(mode == "fsdp"),
                )
            losses = []
            for step in range(steps):
                rng = np.random.RandomState(77 + step)
                bx = rng.rand(32, 16).astype("float64")
                by = rng.randint(0, 5, size=(32, 1)).astype("int64")
                out = exe.run(prog, feed={"x": bx, "y": by},
                              fetch_list=[avg.name])
                losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
            # per-device optimizer-state bytes: every velocity
            # accumulator's single-shard footprint (single-device arrays
            # are their own one shard)
            opt_bytes = 0
            for v in main.list_vars():
                if not (v.persistable and "velocity" in v.name):
                    continue
                val = scope.get(v.name)
                shards = getattr(val, "addressable_shards", None)
                if shards:
                    opt_bytes += int(shards[0].data.nbytes)
                else:
                    opt_bytes += int(np.asarray(val).nbytes)
        return digest(losses), losses, opt_bytes

    d_single, l_single, b_single = run("single")
    d_dp, _l, _b = run("dp")
    d_fsdp, _l, b_fsdp = run("fsdp")
    ratio = b_fsdp / max(b_single, 1)
    return {
        "steps": steps,
        "digest_single": d_single,
        "digest_dp2": d_dp,
        "digest_fsdp2": d_fsdp,
        "dp_equal": d_dp == d_single,
        "fsdp_equal": d_fsdp == d_single,
        "losses": [round(v, 6) for v in l_single],
        "opt_bytes_single": b_single,
        "opt_bytes_fsdp2_per_dev": b_fsdp,
        "opt_bytes_ratio": round(ratio, 4),
        "x64": bool(os.environ.get("JAX_ENABLE_X64")),
    }


def run_probe(fast=True, verbose=False):
    import shutil
    import tempfile

    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu import checkpoint
    from paddle_tpu.fluid import compiler, flags as _flags, profiler
    from paddle_tpu.models import gpt
    from paddle_tpu.observability import registry as obs_registry
    from paddle_tpu.observability import xla_stats
    from paddle_tpu.parallel import spmd
    from paddle_tpu.serving.decode import DecodeEngine

    _flags.set_flags({"FLAGS_serving_strict_compiles": True})

    report = {"schema_version": REPORT_SCHEMA_VERSION, "fast": bool(fast),
              "devices": _N_DEV}
    failures = []

    max_len = 32 if fast else 48
    block = 4
    slots = 4
    cfg = gpt.GPTConfig.tiny(hidden_dropout=0.0, attention_dropout=0.0)
    cfg.max_position_embeddings = max_len

    with fluid.unique_name.guard():
        infer, startup, _names, logits = gpt.build_gpt_infer(cfg, max_len)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()

    # ---- DP=4 training on the GSPMD data mesh: the params this probe
    # serves are the product of a data-parallel update loop, so the
    # checkpoint below is genuinely "DP=4-trained". The train startup
    # initializes the shared canonical params AND the Adam accumulators
    # (the guard-built infer program reads the same names) ----
    with fluid.unique_name.guard():
        tmain, tstartup, _tfeeds, tloss = gpt.build_gpt_lm_train(
            cfg, seq_len=16, learning_rate=1e-3
        )
    with fluid.executor.scope_guard(scope):
        exe.run(tstartup)
    train_prog = compiler.CompiledProgram(tmain).with_mesh(
        loss_name=tloss.name, mesh_axes={"data": 4}
    )
    rs = np.random.RandomState(7)
    train_losses = []
    with fluid.executor.scope_guard(scope):
        for _ in range(2):
            ids = rs.randint(0, cfg.vocab_size, (8, 16, 1)).astype("int64")
            pos = np.tile(np.arange(16).reshape(1, 16, 1), (8, 1, 1))
            mask = np.ones((8, 16, 1), "float32")
            out = exe.run(train_prog, feed={
                "ids": ids, "pos_ids": pos.astype("int64"),
                "input_mask": mask,
            }, fetch_list=[tloss.name])
            train_losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
    report["dp4_train_losses"] = [round(v, 5) for v in train_losses]

    def oracle(prompt):
        return gpt._reference_generate(
            exe, infer, logits, cfg, prompt, max_len, scope=scope
        )

    # ---- TP=2 serving replica over the trained params ----
    engine = DecodeEngine(
        cfg, scope=scope, slots=slots, max_len=max_len,
        param_program=infer, block_size=block, tp=2,
        prefill_chunk=8,
        prefix_cache_mb=4 * gpt.paged_block_bytes(cfg, block) / 2.0 ** 20,
    ).start()
    ckpt_dir = tempfile.mkdtemp(prefix="spmd_probe_ckpt_")
    engine2 = None
    try:
        c_warm = profiler.get_counters()
        tp_parity = {}
        # miss + chunked windows: a 17-token prompt tiles as 8/8/1
        p_long = list(rs.randint(0, cfg.vocab_size, 17))
        full_long = oracle(p_long)
        s = engine.generate(p_long, max_new_tokens=6)
        tp_parity["miss"] = (
            s.tokens(timeout=240) == full_long[17:23]
            and s.cached_prefix_tokens == 0
        )
        tp_parity["chunked_windows"] = s.admit_windows == 3
        # zero-copy hit over the heads-sharded pool
        s = engine.generate(p_long, max_new_tokens=6)
        tp_parity["hit"] = (
            s.tokens(timeout=240) == full_long[17:23]
            and s.cached_prefix_tokens >= block
        )
        # resume: re-admit prompt + generated suffix, continue exact
        s = engine.generate(p_long, max_new_tokens=6,
                            resume_tokens=full_long[17:20])
        tp_parity["resume"] = s.tokens(timeout=240) == full_long[20:23]
        # slot churn: more requests than slots through the shared pool
        churn_ok = True
        for i in range(2 * slots):
            p = list(rs.randint(0, cfg.vocab_size, 3 + (i % 3)))
            got = engine.generate(p, max_new_tokens=4).tokens(timeout=240)
            churn_ok = churn_ok and got == oracle(p)[len(p):len(p) + 4]
        tp_parity["slot_churn"] = churn_ok
        report["tp_parity"] = {k: bool(v) for k, v in tp_parity.items()}
        if not all(tp_parity.values()):
            failures.append("tp parity: %r" % tp_parity)

        steady = (profiler.get_counters()
                  .get("serving_steady_recompiles", 0)
                  - c_warm.get("serving_steady_recompiles", 0))
        report["strict"] = {"steady_recompiles": int(steady),
                            "gate_armed": True}
        if steady != 0:
            failures.append("%d steady-state recompiles" % steady)

        # ---- train-mesh -> serve-mesh conversion: 4-rank DP gang saves
        # (params replicated -> round-robin shard ownership), a fresh
        # TP=2 replica restores through the nranks=1 reassembly and
        # commits every param onto the serve mesh ----
        mgrs = [
            checkpoint.CheckpointManager(
                ckpt_dir, rank=r, nranks=4, commit_timeout_s=60
            )
            for r in range(4)
        ]
        for m in mgrs[1:]:
            m.save(3, infer, scope=scope, async_=True)
        mgrs[0].save(3, infer, scope=scope, async_=False)
        for m in mgrs[1:]:
            m.wait()
        for m in mgrs:
            m.close()

        scope2 = fluid.core.Scope()
        plan2 = spmd.lower(infer, spmd.tp_mesh(2))
        step = spmd.load_train_checkpoint(ckpt_dir, infer, scope2, plan2)
        params = [v.name for v in infer.list_vars() if v.persistable]
        bit_exact = all(
            np.array_equal(np.asarray(scope2.get(n)),
                           np.asarray(scope.get(n)))
            for n in params
        )
        qkv = next(n for n in params if n.endswith("_att_q.w_0"))
        on_mesh = len(getattr(scope2.get(qkv), "devices", lambda: [])()) == 2
        engine2 = DecodeEngine(
            cfg, scope=scope2, slots=2, max_len=max_len,
            param_program=infer, block_size=block, tp=2,
        ).start()
        p = list(rs.randint(0, cfg.vocab_size, 5))
        served = engine2.generate(p).result(timeout=240)
        reshard_parity = served == oracle(p)
        report["reshard"] = {
            "restored_step": int(step),
            "params": len(params),
            "bit_exact": bool(bit_exact),
            "qkv_on_serve_mesh": bool(on_mesh),
            "serve_parity": bool(reshard_parity),
        }
        if step != 3:
            failures.append("reshard restored step %r != 3" % step)
        if not bit_exact:
            failures.append("train->serve reshard not bit-exact")
        if not on_mesh:
            failures.append("restored params not committed on the TP mesh")
        if not reshard_parity:
            failures.append("resharded replica output != oracle")

        # ---- telemetry: active plan on /compiles + registry gauges ----
        gauges = obs_registry.gauge_values()
        rendered = obs_registry.render_prometheus()
        endpoint = xla_stats.compiles_endpoint()
        spmd_stanza = endpoint.get("spmd") or {}
        mesh_gauge = 'spmd_mesh_shape{axis="model"}'
        telemetry = {
            "compiles_spmd": dict(spmd_stanza, mesh=list(
                spmd_stanza.get("mesh", ())
            )),
            "mesh_gauge": gauges.get(mesh_gauge),
            "sharded_params_gauge": gauges.get("spmd_sharded_params"),
            "rendered_ok": "spmd_mesh_shape" in rendered
            and "spmd_sharded_params" in rendered,
        }
        report["telemetry"] = telemetry
        if not spmd_stanza.get("sharded_params"):
            failures.append("/compiles carries no active spmd summary")
        if gauges.get(mesh_gauge) != 2.0:
            failures.append("spmd_mesh_shape model-axis gauge != 2")
        if not telemetry["rendered_ok"]:
            failures.append("spmd gauges missing from the exporter render")
    finally:
        engine.stop()
        if engine2 is not None:
            engine2.stop()
        shutil.rmtree(ckpt_dir, ignore_errors=True)

    # ---- f64 child: DP/FSDP byte-equal digests + optimizer bytes ----
    env = dict(os.environ)
    env["JAX_ENABLE_X64"] = "true"
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=%d" % _N_DEV
    cmd = [sys.executable, os.path.abspath(__file__), "--train-leg"]
    if fast:
        cmd.append("--fast")
    child = subprocess.run(cmd, capture_output=True, text=True, env=env,
                           timeout=900)
    train = None
    for line in child.stdout.splitlines():
        if line.startswith("TRAINREPORT "):
            train = json.loads(line[len("TRAINREPORT "):])
    report["train"] = train
    if train is None:
        failures.append(
            "train leg child produced no TRAINREPORT (rc=%d): %s"
            % (child.returncode, (child.stderr or "")[-400:])
        )
    else:
        if not train["dp_equal"]:
            failures.append("dp=2 digest != single-device digest")
        if not train["fsdp_equal"]:
            failures.append("fsdp=2 digest != single-device digest")
        # velocity tensors split dim 0 across 2 devices: ~0.5 plus the
        # replicated odd-shaped stragglers
        if not train["opt_bytes_ratio"] <= 0.6:
            failures.append(
                "fsdp=2 per-device optimizer bytes ratio %.3f > 0.6"
                % train["opt_bytes_ratio"]
            )

    report["pass"] = not failures
    report["failures"] = failures
    if verbose:
        print(json.dumps(report, indent=1), file=sys.stderr)
    return report


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--fast", action="store_true",
                    help="tier-1 budget subset")
    ap.add_argument("--verbose", action="store_true")
    ap.add_argument("--train-leg", action="store_true",
                    help=argparse.SUPPRESS)  # internal: f64 child mode
    args = ap.parse_args(argv)
    if args.train_leg:
        print("TRAINREPORT " + json.dumps(run_train_leg(fast=args.fast),
                                          sort_keys=True), flush=True)
        return 0
    report = run_probe(fast=args.fast, verbose=args.verbose)
    print("REPORT " + json.dumps(report, sort_keys=True), flush=True)
    print("PROBE PASS" if report["pass"]
          else "PROBE FAIL: %s" % "; ".join(report["failures"]))
    return 0 if report["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
