"""Closed-loop probe for the training guardian (ISSUE 14 acceptance).

Proves the data-plane fault-tolerance properties of
``paddle_tpu/distributed/guardian.py`` end to end, on real trainers and
(for the SDC trial) a real supervised OS-process gang:

  1. **NaN defense, skip path** — a chaos ``nan_grad_at_step`` batch is
     detected within ONE step (the in-graph health scalar + loss both go
     non-finite), the update is discarded (skip-step) and the run's
     final param digest is byte-equal to a clean run on the surviving
     data schedule (the same stream with the poisoned batch dropped —
     built by pre-seeding the guardian's poisoned-step marker).
  2. **Spike defense** — a chaos ``loss_spike_at_step`` batch (finite
     but far outside the robust rolling window) takes the same skip
     path with the same digest parity.
  3. **Rollback path** — with the skip budget at 0, the same NaN fault
     forces a rollback to the newest VERIFIED checkpoint
     (FLAGS_ckpt_scrub keeps it warm) and a deterministic replay that
     drops the poisoned batch: digest parity again, ``train_rollbacks``
     == 1, rollback MTTR measured from ``guardian_rollback_ms``.
  4. **SDC quarantine** — a 3-proc supervised gang whose rank 2 takes a
     chaos ``bitflip_grad`` (silent post-update corruption, invisible
     to its own health fetch) is caught by the supervisor's
     cross-replica digest majority vote: the corrupt rank is
     quarantined via the elastic down-marker path
     (``replica_quarantined`` event, ``sdc_quarantines`` counter), the
     gang resizes to the survivors and converges — surviving ranks'
     digests byte-equal the clean fixed-gang reference.
  5. **Zero-recompile + overhead** — every worker asserts the XLA
     compile count is flat after its first step (guardian armed = 0
     steady-state recompiles), and an interleaved A/B bench measures
     the health-fetch cost per step (< 2% of the CPU step).

Modes::

    python tools/train_guardian_probe.py --fast   # tier-1 subset
    python tools/train_guardian_probe.py          # same, more bench steps

The worker is this file with ``--worker``: the ckpt_crash_probe MLP
trained through ``fluid.trainer.MultiTrainer`` with the guardian armed
via FLAGS (env-bridged by the driver)."""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")
for _p in (REPO, TOOLS):
    if _p not in sys.path:
        sys.path.insert(0, _p)

STEPS = 24
INTERVAL = 3
POISON_STEP = 16  # past the guardian's default 8-step spike warmup
REPORT_SCHEMA_VERSION = 1

# SDC gang trial geometry: bitflip early, steps padded with a per-step
# sleep so the supervisor's vote lands while the gang is mid-run
GANG_STEPS = 12
GANG_BITFLIP_STEP = 2
GANG_DIGEST_INTERVAL = 2
GANG_STEP_SLEEP_MS = 40.0


def _finalize_report(report):
    report["schema_version"] = REPORT_SCHEMA_VERSION
    report["ts"] = time.time()
    report["ts_mono"] = time.monotonic()
    return report


# -- worker ------------------------------------------------------------------

def run_worker(args):
    import paddle_tpu.fluid as fluid
    from paddle_tpu import checkpoint
    from paddle_tpu.fluid import profiler
    from paddle_tpu.fluid.trainer import MultiTrainer
    from paddle_tpu.observability import xla_stats

    from ckpt_crash_probe import _build, _StepDataset, _params_digest

    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    fluid.set_flags({"FLAGS_ckpt_save_interval_steps": args.interval})
    main, startup, loss = _build()
    exe = fluid.Executor(fluid.CPUPlace())
    mgr = checkpoint.CheckpointManager(
        os.path.join(args.dir, "rank_%d" % rank), keep_max=4
    )
    resumed = mgr.latest_step()
    print("RESUMED %s" % ("FRESH" if resumed is None else resumed),
          flush=True)
    dataset = _StepDataset(
        [main.global_block().var("x"), main.global_block().var("y")],
        args.steps,
    )

    # zero-recompile evidence: the XLA compile count after the FIRST
    # step must equal the count at the end — an armed guardian adds one
    # constant fetch, never a steady-state recompile
    compile_mark = {}

    def on_step(_s):
        if "first" not in compile_mark:
            compile_mark["first"] = xla_stats.summary()["compiles"]
        if args.step_sleep_ms > 0:
            time.sleep(args.step_sleep_ms / 1000.0)

    trained = MultiTrainer().train(
        exe, main, dataset, fetch_list=[loss], print_period=0,
        on_step=on_step, ckpt_manager=mgr, startup_program=startup,
    )
    if trained < args.steps or checkpoint.preemption_requested():
        mgr.close()
        print("PREEMPTED %d" % trained, flush=True)
        return 143
    mgr.save(args.steps - 1, main, async_=False)
    mgr.close()
    digest = _params_digest(main, fluid.global_scope())
    path = os.path.join(args.dir, "digest_%d.txt" % rank)
    tmp = "%s.tmp.%d" % (path, os.getpid())
    with open(tmp, "w") as f:
        f.write(digest)
    os.replace(tmp, path)
    report = {
        "train_anomalies": profiler.get_counter("train_anomalies"),
        "train_skipped_steps": profiler.get_counter("train_skipped_steps"),
        "train_rollbacks": profiler.get_counter("train_rollbacks"),
        "guardian_rollback_ms": profiler.summarize_histogram(
            "guardian_rollback_ms"
        ),
        "ckpt_scrub_ok": profiler.get_counter("ckpt_scrub_ok"),
        "ckpt_scrub_corrupt": profiler.get_counter("ckpt_scrub_corrupt"),
        "compiles_first": compile_mark.get("first"),
        "compiles_final": xla_stats.summary()["compiles"],
    }
    print("REPORT_GUARDIAN " + json.dumps(report, sort_keys=True),
          flush=True)
    print("FINAL %s" % digest, flush=True)
    return 0


# -- driver helpers ----------------------------------------------------------

def _worker_cmd(dirname, steps=STEPS, interval=INTERVAL, step_sleep_ms=0.0):
    cmd = [
        sys.executable, os.path.abspath(__file__), "--worker",
        "--dir", dirname, "--steps", str(steps),
        "--interval", str(interval),
    ]
    if step_sleep_ms:
        cmd += ["--step_sleep_ms", str(step_sleep_ms)]
    return cmd


def _guardian_env(trial_dir, max_skips=2, digest_interval=0, extra=None):
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "",
        "PADDLE_TRAINER_ID": "0",
        "FLAGS_guardian_enable": "1",
        "FLAGS_guardian_max_skips": str(max_skips),
        "FLAGS_guardian_marker_dir": os.path.join(
            trial_dir, "guardian_markers"
        ),
        "FLAGS_ckpt_scrub": "1",
    })
    if digest_interval:
        env["FLAGS_guardian_digest_interval"] = str(digest_interval)
    env.pop("PADDLE_TPU_HEARTBEAT_FILE", None)
    env.update(extra or {})
    return env


def _run_worker_proc(trial_dir, env, steps=STEPS, interval=INTERVAL):
    os.makedirs(trial_dir, exist_ok=True)
    p = subprocess.run(
        _worker_cmd(trial_dir, steps, interval), env=env,
        capture_output=True, text=True, timeout=600, cwd=REPO,
    )
    out = p.stdout + p.stderr
    assert p.returncode == 0, (
        "worker under %s failed rc=%d:\n%s" % (trial_dir, p.returncode, out)
    )
    digest = None
    report = None
    for line in out.splitlines():
        if line.startswith("FINAL "):
            digest = line.split()[1]
        elif line.startswith("REPORT_GUARDIAN "):
            report = json.loads(line[len("REPORT_GUARDIAN "):])
    assert digest and report, "worker printed no FINAL/REPORT:\n%s" % out
    assert report["compiles_final"] == report["compiles_first"], (
        "steady-state recompile with guardian armed: %s" % report
    )
    return digest, report, out


def _seed_drop_marker(trial_dir, step):
    mdir = os.path.join(trial_dir, "guardian_markers")
    os.makedirs(mdir, exist_ok=True)
    with open(os.path.join(mdir, "poisoned_step_%d" % step), "w") as f:
        f.write(json.dumps({"step": step, "kind": "seed"}))


def _assert_detection_at(trial_dir, step):
    """Detection within one step: the anomaly was attributed to exactly
    the poisoned batch — one marker, naming that step."""
    mdir = os.path.join(trial_dir, "guardian_markers")
    markers = sorted(
        n for n in os.listdir(mdir) if n.startswith("poisoned_step_")
    )
    assert markers == ["poisoned_step_%d" % step], (
        "anomaly misattributed: markers %s != [poisoned_step_%d]"
        % (markers, step)
    )


# -- SDC gang trial ----------------------------------------------------------

def _sdc_quarantine_trial(tmp, ref_full):
    from paddle_tpu.distributed.supervisor import (
        Supervisor, WorkerSpec, load_events,
    )

    d = os.path.join(tmp, "sdc_gang")
    os.makedirs(d, exist_ok=True)
    specs = []
    for r in range(3):
        env = {
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "",
            "PADDLE_TRAINER_ID": str(r),
            "PADDLE_TRAINERS_NUM": "3",
            "FLAGS_guardian_enable": "1",
            "FLAGS_guardian_digest_interval": str(GANG_DIGEST_INTERVAL),
            "FLAGS_guardian_marker_dir": os.path.join(
                d, "guardian_markers_%d" % r
            ),
            "FLAGS_ckpt_scrub": "1",
            # rank-addressed silent corruption: rank 2's step-2 update
            # gets one sign bit flipped, one-shot across restarts
            "FLAGS_chaos_bitflip_grad_at_step": str(GANG_BITFLIP_STEP),
            "FLAGS_chaos_target_rank": "2",
            "FLAGS_chaos_marker_dir": os.path.join(d, "chaos_markers"),
        }
        specs.append(WorkerSpec(
            _worker_cmd(d, GANG_STEPS, INTERVAL, GANG_STEP_SLEEP_MS),
            env=env,
            log_path=os.path.join(d, "workerlog.%d" % r),
            rank=r,
        ))
    sup = Supervisor(
        specs, workdir=d, max_restarts=1, heartbeat_timeout_s=30.0,
        startup_grace_s=120.0, backoff_base_s=0.1, backoff_max_s=0.5,
        sigterm_grace_s=5.0, poll_s=0.02, min_world_size=2,
        max_preempt_restarts=3,
    )
    rc = sup.run()
    assert rc == 0, "sdc gang: supervisor rc %d" % rc
    assert sup.alive_pids() == {}, "stranded gang"
    events = load_events(d)
    quar = [e for e in events if e["event"] == "replica_quarantined"]
    assert quar, "no replica_quarantined event:\n%s" % events
    assert quar[0]["slot"] == 2 and quar[0]["rank"] == 2, quar
    assert quar[0]["digest"] != quar[0]["majority"], quar
    resizes = [
        (e["from_world"], e["to_world"])
        for e in events if e["event"] == "gang_resize"
    ]
    assert (3, 2) in resizes, "gang never resized around the corrupt rank"
    # the quarantine drew from the preempt budget, not the crash budget
    assert sup.restarts_used == 0, (
        "SDC leaked into the crash budget: %d" % sup.restarts_used
    )
    # survivors converged to the clean fixed-gang reference
    for r in (0, 1):
        dpath = os.path.join(d, "digest_%d.txt" % r)
        assert os.path.isfile(dpath), "survivor %d wrote no digest" % r
        with open(dpath) as f:
            got = f.read().strip()
        assert got == ref_full, (
            "survivor %d diverged\n  ref %s\n  got %s" % (r, ref_full, got)
        )
    # the corrupt rank never finished
    assert not os.path.isfile(os.path.join(d, "digest_2.txt")), (
        "the quarantined rank completed anyway"
    )
    # merged gang report tells the same story post-hoc
    with open(os.path.join(d, "gang_report.json")) as f:
        gang_report = json.load(f)
    assert gang_report["sdc_quarantines"] == 1, gang_report
    # quarantine-detection -> respawn MTTR
    detect = None
    mttr = []
    for e in events:
        if e["event"] == "replica_quarantined":
            detect = e["ts_mono"]
        elif e["event"] == "gang_start" and detect is not None:
            mttr.append((e["ts_mono"] - detect) * 1000.0)
            detect = None
    print(
        "sdc quarantine trial OK: rank 2 quarantined at digest step %d, "
        "world 3 -> 2, survivors == reference, MTTR %s ms"
        % (quar[0]["step"], [round(m) for m in mttr]),
        flush=True,
    )
    return {
        "quarantined_slot": quar[0]["slot"],
        "vote_step": quar[0]["step"],
        "resizes": resizes,
        "mttr_ms": mttr,
        "sdc_quarantines": gang_report["sdc_quarantines"],
    }


# -- health-fetch overhead bench --------------------------------------------

def _overhead_bench(pairs=30, hidden=512, batch=2048):
    """Interleaved A/B: the same MLP step with and without the attached
    health fetch, alternating so machine drift hits both arms equally.
    Returns {base_ms, health_ms, overhead_pct}."""
    import numpy as np

    import paddle_tpu.fluid as fluid
    import paddle_tpu.fluid.core as core
    from paddle_tpu.distributed import guardian as _guardian

    from ckpt_crash_probe import _build

    def build(with_health):
        main, startup, loss = _build(hidden=hidden)
        partials = _guardian.attach_health_fetch(main) if with_health else []
        scope = core.Scope()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup, scope=scope)
        return exe, main, scope, [loss] + partials

    def batch_of(s):
        r = np.random.RandomState(1000 + s)
        return {
            "x": r.rand(batch, 8).astype("float32"),
            "y": r.randint(0, 4, (batch, 1)).astype("int64"),
        }

    arms = {"base": build(False), "health": build(True)}
    # warmup compiles both arms
    for exe, main, scope, fetches in arms.values():
        exe.run(main, feed=batch_of(0), fetch_list=fetches, scope=scope)
    times = {"base": [], "health": []}
    for s in range(pairs):
        feed = batch_of(s)
        for name in ("base", "health"):
            exe, main, scope, fetches = arms[name]
            t0 = time.perf_counter()
            outs = exe.run(main, feed=feed, fetch_list=fetches, scope=scope)
            for o in outs:  # force every D2H — the guardian's real cost
                float(np.asarray(o).ravel()[0])
            times[name].append((time.perf_counter() - t0) * 1000.0)
    base = sorted(times["base"])[pairs // 2]
    health = sorted(times["health"])[pairs // 2]
    return {
        "pairs": pairs,
        "hidden": hidden,
        "batch": batch,
        "base_ms": round(base, 3),
        "health_ms": round(health, 3),
        "overhead_pct": round((health - base) / base * 100.0, 3),
    }


# -- driver ------------------------------------------------------------------

def run_probe(args):
    import tempfile

    tmp = args.workdir or tempfile.mkdtemp(prefix="train_guardian_probe_")
    t0 = time.time()

    # reference on the SURVIVING schedule: the same deterministic stream
    # with the poisoned batch dropped (marker-seeded — the exact skip
    # machinery under test, minus any fault)
    ref_dir = os.path.join(tmp, "ref_surviving")
    os.makedirs(ref_dir, exist_ok=True)
    _seed_drop_marker(ref_dir, POISON_STEP)
    ref, ref_rep, _ = _run_worker_proc(
        ref_dir, _guardian_env(ref_dir), steps=args.steps,
    )
    assert ref_rep["train_anomalies"] == 0, ref_rep
    print("surviving-schedule reference %s" % ref[:16], flush=True)

    # 1. NaN -> detected within one step -> skip -> digest parity
    d = os.path.join(tmp, "nan_skip")
    dig, rep, _ = _run_worker_proc(
        d,
        _guardian_env(d, extra={
            "FLAGS_chaos_nan_grad_at_step": str(POISON_STEP),
            "FLAGS_chaos_marker_dir": os.path.join(d, "chaos_markers"),
        }),
        steps=args.steps,
    )
    assert rep["train_anomalies"] == 1, rep
    assert rep["train_skipped_steps"] == 1, rep
    assert rep["train_rollbacks"] == 0, rep
    _assert_detection_at(d, POISON_STEP)
    assert dig == ref, (
        "nan-skip digest diverged\n  ref %s\n  got %s" % (ref, dig)
    )
    print("nan skip trial OK (detected at step %d, digest == reference)"
          % POISON_STEP, flush=True)

    # 2. loss spike -> robust-window detection -> skip -> digest parity
    d = os.path.join(tmp, "spike_skip")
    dig, rep, _ = _run_worker_proc(
        d,
        _guardian_env(d, extra={
            "FLAGS_chaos_loss_spike_at_step": str(POISON_STEP),
            "FLAGS_chaos_marker_dir": os.path.join(d, "chaos_markers"),
        }),
        steps=args.steps,
    )
    assert rep["train_anomalies"] == 1 and rep["train_skipped_steps"] == 1, rep
    _assert_detection_at(d, POISON_STEP)
    assert dig == ref, (
        "spike-skip digest diverged\n  ref %s\n  got %s" % (ref, dig)
    )
    print("loss spike trial OK (digest == reference)", flush=True)

    # 3. skip budget 0 -> rollback to the newest verified checkpoint,
    # replay drops the poisoned batch, digest parity holds
    d = os.path.join(tmp, "rollback")
    dig, rep, _ = _run_worker_proc(
        d,
        _guardian_env(d, max_skips=0, extra={
            "FLAGS_chaos_nan_grad_at_step": str(POISON_STEP),
            "FLAGS_chaos_marker_dir": os.path.join(d, "chaos_markers"),
        }),
        steps=args.steps,
    )
    assert rep["train_rollbacks"] == 1, rep
    assert rep["train_anomalies"] == 1, rep
    assert rep["ckpt_scrub_ok"] > 0, rep
    rollback_ms = rep["guardian_rollback_ms"]
    _assert_detection_at(d, POISON_STEP)
    assert dig == ref, (
        "rollback digest diverged\n  ref %s\n  got %s" % (ref, dig)
    )
    print("rollback trial OK (MTTR %s ms, digest == reference)"
          % rollback_ms.get("mean"), flush=True)

    # 4. full-schedule reference + SDC quarantine gang
    ref_full_dir = os.path.join(tmp, "ref_full")
    ref_full, _, _ = _run_worker_proc(
        ref_full_dir, _guardian_env(ref_full_dir), steps=GANG_STEPS,
    )
    sdc = _sdc_quarantine_trial(tmp, ref_full)

    # 5. health-fetch overhead (interleaved medians)
    bench = _overhead_bench(pairs=args.bench_pairs)
    assert bench["overhead_pct"] < 2.0, (
        "health fetch costs %.2f%% of the step (>= 2%%): %s"
        % (bench["overhead_pct"], bench)
    )
    print("health-fetch overhead %.3f%% of a %.1f ms step"
          % (bench["overhead_pct"], bench["base_ms"]), flush=True)

    report = _finalize_report({
        "trials": ["nan_skip", "spike_skip", "rollback", "sdc_quarantine"],
        "poison_step": POISON_STEP,
        "rollback_ms": rollback_ms,
        "sdc": sdc,
        "health_fetch": bench,
        "wall_s": round(time.time() - t0, 1),
    })
    print("REPORT " + json.dumps(report, sort_keys=True), flush=True)
    print(
        "PROBE PASS: NaN + spike each detected within one step and "
        "recovered (skip and rollback digests == surviving-schedule "
        "reference), rank 2 quarantined by digest vote (gang 3 -> 2, "
        "survivors == clean reference), 0 steady-state recompiles "
        "armed, health fetch %.2f%% of the CPU step (%.1fs)"
        % (bench["overhead_pct"], report["wall_s"])
    )
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--worker", action="store_true")
    ap.add_argument("--dir", type=str, default=None)
    ap.add_argument("--steps", type=int, default=STEPS)
    ap.add_argument("--interval", type=int, default=INTERVAL)
    ap.add_argument("--step_sleep_ms", type=float, default=0.0)
    ap.add_argument("--fast", action="store_true",
                    help="tier-1 subset (fewer bench pairs)")
    ap.add_argument("--bench_pairs", type=int, default=None)
    ap.add_argument("--workdir", type=str, default=None)
    args = ap.parse_args(argv)
    if args.worker:
        assert args.dir, "--worker needs --dir"
        return run_worker(args)
    if args.bench_pairs is None:
        args.bench_pairs = 20 if args.fast else 60
    return run_probe(args)


if __name__ == "__main__":
    sys.exit(main())
