"""Closed-loop probe for the device-plane compile telemetry (ISSUE 7).

Drives REAL train + serving workloads with the telemetry armed and then
verifies the four properties the subsystem promises:

  1. **Recompile attribution** — every synthetic recompile trigger
     (cold start, feed-order change, program version bump, LRU
     eviction, feed-shape change) produces a record with the right
     trigger label AND a cache-key diff naming the changed component;
     an evicted block is also dropped from the dispatch-plan cache
     (the two executor caches stay aligned).
  2. **Strict serving gate** — a warmed `InferenceServer` under
     `FLAGS_serving_strict_compiles` serves steady-state traffic with 0
     recompiles; an UNWARMED strict server fails its first request with
     the sentinel's attribution attached (warmup is the contract).
  3. **Exporter round-trip** — `/compiles` serves the records + census
     as JSON matching the in-process state, and `/metrics` carries the
     `xla_*` counters and per-key census gauges at their exact values.
  4. **Census ground truth** — the flops/bytes the executor recorded at
     compile time equal a direct census of the same segment through the
     `hlo_scan.py` code path (`jax.jit(raw_fn).lower().compile()` + the
     shared `xla_stats` census library). Full mode additionally runs
     `tools/hlo_scan.py --model resnet` as a subprocess and checks the
     executor-recorded ResNet census against the scan's JSON line.

Modes::

    python tools/compile_probe.py          # full: adds the ResNet
                                           # hlo_scan cross-check
    python tools/compile_probe.py --fast   # tier-1 subset (1-4 on the
                                           # probe MLP / tiny serving
                                           # model)

The fast subset runs inside tier-1 via tests/test_xla_stats.py.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")
for _p in (REPO, TOOLS):
    if _p not in sys.path:
        sys.path.insert(0, _p)

REPORT_SCHEMA_VERSION = 1


def _http_get(url, timeout=10.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read().decode("utf-8")


def _records_since(n0):
    from paddle_tpu.observability import xla_stats

    return xla_stats.get_records()[n0:]


# -- property 1: trigger classification + key-diff attribution ---------------

def _check_triggers():
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import profiler
    from paddle_tpu.observability import xla_stats

    from ckpt_crash_probe import _build

    main, startup, loss = _build()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rs = np.random.RandomState(0)
    feed = {
        "x": rs.rand(16, 8).astype("float32"),
        "y": rs.randint(0, 4, (16, 1)).astype("int64"),
    }

    # cold: first run of the main program compiles its segment
    n0 = len(xla_stats.get_records())
    exe.run(main, feed=feed, fetch_list=[loss])
    recs = _records_since(n0)
    cold = [r for r in recs if r["kind"] == "compile"]
    assert cold and all(r["trigger"] == "cold" for r in cold), recs
    main_fp = cold[-1]["fingerprint"]

    # steady state: repeat runs add NO records
    n0 = len(xla_stats.get_records())
    for _ in range(3):
        exe.run(main, feed=feed, fetch_list=[loss])
    assert _records_since(n0) == [], "steady-state runs left records"

    # feed-order change: same feed SET, different dict order -> the
    # canonical (sorted-key) cache absorbs it; the sentinel records a
    # dispatch rebind, no recompile
    n0 = len(xla_stats.get_records())
    exe.run(main, feed={"y": feed["y"], "x": feed["x"]},
            fetch_list=[loss])
    recs = _records_since(n0)
    assert [r["kind"] for r in recs] == ["dispatch"], recs
    assert recs[0]["trigger"] == "feed_order_change"
    assert recs[0]["recompiled"] is False
    assert recs[0]["diff"]["detail"]["feed_order"] == ["y", "x"]

    # feed-shape change: a new batch size at the same key
    n0 = len(xla_stats.get_records())
    exe.run(main, feed={
        "x": rs.rand(8, 8).astype("float32"),
        "y": rs.randint(0, 4, (8, 1)).astype("int64"),
    }, fetch_list=[loss])
    recs = [r for r in _records_since(n0) if r["kind"] == "compile"]
    assert recs and recs[0]["trigger"] == "shape_change", recs
    shapes = recs[0]["diff"]["detail"]["feed_shapes"]
    assert shapes.get("x") == [[16, 8], [8, 8]], shapes

    # program version bump: mutation recompiles under the same program
    # with the diff naming the version component
    main._bump_version()
    n0 = len(xla_stats.get_records())
    exe.run(main, feed=feed, fetch_list=[loss])
    recs = _records_since(n0)
    builds = [r for r in recs if r["kind"] == "build"]
    assert builds and builds[0]["trigger"] == "program_mutation", recs
    assert builds[0]["diff"]["changed"] == ["version"], builds[0]["diff"]
    compiles = [r for r in recs if r["kind"] == "compile"]
    assert compiles and compiles[0]["trigger"] == "program_mutation"

    # LRU eviction: cap the cache at 1, compile another program (evicts
    # main), re-run main -> lru_eviction, and the dispatch-plan cache
    # must have dropped the evicted block (cache-alignment satellite)
    exe._CACHE_CAPACITY = 1
    other, other_startup, other_loss = _build(hidden=8)
    exe.run(other_startup)
    exe.run(other, feed=feed, fetch_list=[other_loss])
    assert all(
        getattr(c, "program", None) is not main
        for c in exe._plans.values()
    ), "evicted block still live in the dispatch-plan cache"
    c0 = profiler.get_counter("executor_plan_cache_misses")
    n0 = len(xla_stats.get_records())
    exe.run(main, feed=feed, fetch_list=[loss])
    recs = _records_since(n0)
    builds = [r for r in recs if r["kind"] == "build"]
    assert builds and builds[0]["trigger"] == "lru_eviction", recs
    assert profiler.get_counter("executor_plan_cache_misses") > c0, (
        "eviction-survivor plan entry masked the recompile"
    )
    assert profiler.get_counter("executor_compiled_block_evictions") >= 2

    by_trigger = xla_stats.summary()["by_trigger"]
    for trig in ("cold", "shape_change", "program_mutation",
                 "lru_eviction"):
        assert by_trigger.get(trig), (trig, by_trigger)
    return {
        "by_trigger": by_trigger,
        "main_fingerprint": main_fp,
        "artifacts": (main, exe, feed, loss),
    }


# -- property 4: census ground truth -----------------------------------------

def _check_census(main, exe, feed, loss):
    """The executor-recorded census equals a direct census through the
    hlo_scan code path (jax.jit(raw_fn).lower().compile() + the shared
    library) for the same segment at the same shapes."""
    import jax
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import executor as _ex
    from paddle_tpu.observability import xla_stats

    # compile a FRESH block exactly as hlo_scan.main() does: same
    # _CompiledBlock lowering, same largest-segment choice, same
    # scope-value feed/mutable/const binding, same jit(raw_fn) AOT path,
    # same shared census library
    scope = fluid.global_scope()
    cb = _ex._CompiledBlock(
        main, 0, list(feed), [loss.name], fluid.CPUPlace()
    )
    xla = [p for _k, _s, p in cb._plans if _k == "xla"]
    plan = max(xla, key=lambda p: len(p["feeds"]) + len(p["mutable"])
               + len(p["const"]))
    feed_vals = tuple(feed[n] for n in plan["feeds"])
    mutable_vals = tuple(np.asarray(scope.get(n)) for n in plan["mutable"])
    const_map = {
        n: np.asarray(scope.get(n))
        for n in plan["const"]
        if scope.get(n) is not None
    }
    rng = jax.random.key(0)
    compiled = jax.jit(plan["raw_fn"]).lower(
        feed_vals, mutable_vals, (), const_map, rng
    ).compile()
    direct = xla_stats.executable_census(compiled)

    # the executor's record for the SAME key/segment at these shapes
    fp = xla_stats.fingerprint(cb._obs_key)
    recorded = [
        r for r in xla_stats.get_records()
        if r["kind"] == "compile" and r["fingerprint"] == fp
        and r["segment"] == plan["seg_index"]
        and r["feed_shapes"].get(plan["feeds"][0])
        == list(np.shape(feed_vals[0]))
        and r.get("census")
    ]
    assert recorded, "no censused record for the probe segment"
    cen = recorded[-1]["census"]
    assert cen["flops"] == direct["flops"], (cen["flops"], direct["flops"])
    assert cen["bytes_accessed"] == direct["bytes_accessed"], (
        cen["bytes_accessed"], direct["bytes_accessed"]
    )
    assert cen["hlo_ops"] == direct["hlo_ops"], "op census diverged"
    return {"flops": cen["flops"], "bytes_accessed": cen["bytes_accessed"],
            "total_hlo_ops": cen["total_hlo_ops"]}


def _check_census_vs_hlo_scan_resnet():
    """Full mode: the executor-recorded ResNet census equals a real
    ``tools/hlo_scan.py --model resnet`` subprocess run (same model,
    same batch, same backend)."""
    import subprocess

    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu.models import resnet
    from paddle_tpu.observability import xla_stats

    batch = 4
    p = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "hlo_scan.py"),
         "--model", "resnet", "--batch", str(batch), "--amp", "1"],
        cwd=REPO, capture_output=True, text=True, timeout=1800,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert p.returncode == 0, "hlo_scan failed:\n%s" % p.stderr[-2000:]
    scan = json.loads(p.stdout.strip().splitlines()[-1])

    main, startup, feeds, loss, acc = resnet.build_resnet_train(
        depth=50, class_num=1000, image_size=224, use_amp=True,
        recompute=False,
    )
    scope = fluid.core.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup, scope=scope)
        rs = np.random.RandomState(0)
        n0 = len(xla_stats.get_records())
        exe.run(main, feed={
            "img": rs.rand(batch, 3, 224, 224).astype("float32"),
            "label": rs.randint(0, 1000, (batch, 1)).astype("int64"),
        }, fetch_list=[loss], scope=scope)
    recs = [r for r in xla_stats.get_records()[n0:]
            if r["kind"] == "compile" and r.get("census")]
    assert recs, "executor left no censused resnet records"
    best = max(recs, key=lambda r: r["census"]["flops"] or 0)
    assert best["census"]["flops"] == scan["flops"], (
        best["census"]["flops"], scan["flops"]
    )
    assert best["census"]["bytes_accessed"] == scan["bytes_accessed"]
    return {"resnet_flops": scan["flops"],
            "resnet_bytes_accessed": scan["bytes_accessed"]}


# -- property 2: strict serving gate -----------------------------------------

def _serving_model(tmp):
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu import inference

    d = os.path.join(tmp, "model")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.unique_name.guard():
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[8], dtype="float32")
            out = fluid.layers.softmax(fluid.layers.fc(x, size=3))
    scope = fluid.core.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup, scope=scope)
        fluid.io.save_inference_model(d, ["x"], [out], exe,
                                      main_program=main)
    _ = np
    return d


def _check_strict_serving(tmp):
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu import inference, serving
    from paddle_tpu.fluid import profiler
    from paddle_tpu.serving.batcher import ServingError
    from paddle_tpu.observability import xla_stats

    d = _serving_model(tmp)
    fluid.set_flags({"FLAGS_serving_strict_compiles": True})
    rng = np.random.RandomState(0)
    one = [rng.rand(1, 8).astype("float32")]
    try:
        # warmed strict server: steady-state traffic must see ZERO
        # compiles with the gate armed
        pred = inference.create_paddle_predictor(inference.AnalysisConfig(d))
        server = serving.InferenceServer(
            pred, max_batch_size=4, batch_timeout_ms=1.0, num_workers=2
        )
        server.start(warmup_inputs=one)
        v0 = profiler.get_counter("serving_steady_recompiles")
        try:
            for _ in range(8):
                server.infer([rng.rand(1, 8).astype("float32")])
            steady = profiler.get_counter("serving_steady_recompiles") - v0
            assert steady == 0, (
                "%d steady-state recompiles on warmed traffic" % steady
            )
        finally:
            server.stop()

        # UNWARMED strict server: the first request compiles in steady
        # state -> the gate fires with the sentinel's attribution
        pred2 = inference.create_paddle_predictor(
            inference.AnalysisConfig(d)
        )
        server2 = serving.InferenceServer(
            pred2, max_batch_size=4, batch_timeout_ms=1.0, num_workers=1
        )
        server2.start()  # no warmup_inputs: ladder not compiled
        v0 = profiler.get_counter("serving_steady_recompiles")
        try:
            try:
                server2.infer(one)
            except ServingError as e:
                msg = str(e)
                assert "SteadyStateRecompileError" in msg or "steady" in msg, msg
            else:
                raise AssertionError(
                    "strict gate let an unwarmed compile through"
                )
            tripped = profiler.get_counter("serving_steady_recompiles") - v0
            assert tripped >= 1, "gate raised but counter did not move"
        finally:
            server2.stop()
    finally:
        fluid.set_flags({"FLAGS_serving_strict_compiles": False})
    assert not xla_stats.compiles_endpoint()["serving_steady"], (
        "stop() left the steady gate armed"
    )
    return {"steady_recompiles_warmed": 0, "strict_gate_fired": True}


# -- property 3: exporter round-trip -----------------------------------------

def _check_exporter_roundtrip():
    from paddle_tpu.fluid import profiler
    from paddle_tpu.observability import exporter, registry, xla_stats

    exp = exporter.Exporter(port=0, rank=0).start()
    try:
        doc = json.loads(_http_get(exp.url("/compiles")))
        live = xla_stats.compiles_endpoint()
        assert doc["schema_version"] == live["schema_version"]
        assert len(doc["records"]) == len(live["records"])
        assert [r["fingerprint"] for r in doc["records"]] == [
            r["fingerprint"] for r in live["records"]
        ]
        assert doc["summary"]["by_trigger"] == live["summary"]["by_trigger"]
        assert doc["census"].keys() == live["census"].keys()

        parsed = registry.parse_prometheus(_http_get(exp.url("/metrics")))
        for name in ("xla_builds", "xla_compiles", "xla_recompiles"):
            key = (registry.prom_name(name), "")
            assert key in parsed, "%s missing from /metrics" % name
            assert parsed[key] == float(profiler.get_counter(name)), name
        gauges = registry.gauge_values()
        census_gauges = {
            n: v for n, v in gauges.items() if n.startswith("xla_flops_")
        }
        assert census_gauges, "no census gauges registered"
        for n, v in census_gauges.items():
            assert parsed[(registry.prom_name(n), "")] == float(v), n
    finally:
        exp.stop()
    return {
        "records": len(doc["records"]),
        "census_gauges": len(census_gauges),
    }


def run_probe(args):
    import tempfile

    from paddle_tpu.observability import xla_stats

    tmp = args.workdir or tempfile.mkdtemp(prefix="compile_probe_")
    t0 = time.time()
    xla_stats.reset()
    report = {"workdir": tmp}
    trig = _check_triggers()
    main, exe, feed, loss = trig.pop("artifacts")
    report["triggers"] = trig
    report["census"] = _check_census(main, exe, feed, loss)
    report["strict_serving"] = _check_strict_serving(tmp)
    report["exporter"] = _check_exporter_roundtrip()
    if not args.fast:
        report["hlo_scan"] = _check_census_vs_hlo_scan_resnet()
    report["wall_s"] = round(time.time() - t0, 1)
    report["schema_version"] = REPORT_SCHEMA_VERSION
    report["ts"] = time.time()
    print("REPORT " + json.dumps(report, sort_keys=True), flush=True)
    print(
        "PROBE PASS: triggers %s all classified + key-diff-attributed, "
        "census flops=%s bytes=%s match the hlo_scan path, strict gate: "
        "0 steady recompiles warmed + fired on the unwarmed compile, "
        "/compiles round-tripped %d records + %d census gauges%s (%.1fs)"
        % (sorted(report["triggers"]["by_trigger"]),
           report["census"]["flops"], report["census"]["bytes_accessed"],
           report["exporter"]["records"],
           report["exporter"]["census_gauges"],
           "" if args.fast else "; resnet census == hlo_scan subprocess",
           report["wall_s"])
    )
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--fast", action="store_true",
                    help="tier-1 subset: skip the ResNet hlo_scan "
                         "cross-check")
    ap.add_argument("--workdir", type=str, default=None)
    args = ap.parse_args(argv)
    return run_probe(args)


if __name__ == "__main__":
    sys.exit(main())
