"""Knob + metrics-name lint (op_audit.py-style consistency check, run
inside tier-1).

Every ``FLAGS_obs_*``, ``FLAGS_dist_*``, ``FLAGS_elastic_*`` and
``FLAGS_serving_*`` knob must be (1) registered in
``paddle_tpu/fluid/flags.py`` — an unregistered reference silently reads
its fallback and ``FLAGS_`` env vars for it are dropped by the bridge —
and (2) mentioned in README.md, so the Observability / Fault-tolerance /
Serving quickstarts can't drift behind the code. The reverse direction
is linted too: a registered knob nobody reads is a dead knob. (Scope
grew obs_* -> +dist_*/elastic_* with the elastic-resize PR,
-> +serving_* with the compile-telemetry PR, -> +decode_* with the
KV-cache decode runtime, -> +gateway_* with the HTTP gateway,
-> +fleet_*/router_* with the serving fleet control plane,
-> +chaos_* with the durable-generations failover PR,
-> +guardian_* with the training-guardian PR,
-> +trace_* with the fleet-wide distributed-tracing PR,
-> +kv_tier_* with the fleet KV tier PR,
-> +sim_*/slo_*/sched_* with the fleet-simulator / SLO-scheduling PR,
-> +fleet_lease_*/fleet_state_*/chaos_kill_controller_* with the
control-plane durability PR — covered by the existing fleet_*/chaos_*
prefixes, noted here so the scope history stays complete —
and -> +spmd_*/mesh_* with the SPMD-mesh mainline PR.)

A second pass lints METRIC names: every counter / histogram /
scrape-time gauge the registry can render (every literal name at a
``bump_counter`` / ``bump_histogram`` / ``register_gauge`` call site)
must appear in the README "Metrics reference" table — a metric an
operator can scrape but can't look up is a support ticket. Dynamic
families built from a literal prefix (``register_gauge("xla_flops_" +
key)``) document as ``<prefix>*``.

Run standalone (``python tools/flags_lint.py``, exit 1 on findings) or
via ``tests/test_observability.py::test_flags_lint_clean``.
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the linted knob families (prefix with trailing underscore)
PREFIXES = ("obs_", "dist_", "elastic_", "serving_", "decode_",
            "gateway_", "fleet_", "router_", "chaos_", "guardian_",
            "trace_", "kv_tier_", "sim_", "slo_", "sched_",
            "spmd_", "mesh_")
_NAME = r"((?:%s)[a-z0-9_]+)" % "|".join(p.rstrip("_") + "_" for p in PREFIXES)

# the spellings a knob is consumed under: the env-bridge name and the
# get_flag/_flag/set_flags key (supervisor.py wraps get_flag in a local
# ``_flag(name, default)`` helper; the substring match covers both)
_REF_PATTERNS = (
    re.compile(r"FLAGS_" + _NAME),
    re.compile(r"""_flag\(\s*['"]""" + _NAME + r"""['"]"""),
)
_SCAN_DIRS = ("paddle_tpu", "tools", "tests")
_FLAGS_PY = os.path.join("paddle_tpu", "fluid", "flags.py")

# registered-but-unread knobs that are NOT dead: the reference's env
# whitelist includes them, so scripts that set them must keep working
# (flags.py's accepted-and-recorded contract). Anything added here needs
# that justification — a knob of OURS nobody reads is still a finding.
_LEGACY_COMPAT = {
    "dist_threadpool_size",  # reference flags.cc threading knob; XLA
                             # owns threading on TPU, value is recorded
}


def find_flag_refs():
    """{flag_name: [relpath, ...]} for every linted-family knob
    referenced in Python sources (the flags registry file itself
    excluded — defining a flag is not consuming it)."""
    refs = {}
    for top in _SCAN_DIRS:
        for root, _dirs, files in os.walk(os.path.join(REPO, top)):
            if "__pycache__" in root:
                continue
            for fn in files:
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(root, fn)
                rel = os.path.relpath(path, REPO)
                if rel == _FLAGS_PY:
                    continue
                with open(path, errors="replace") as f:
                    text = f.read()
                for pat in _REF_PATTERNS:
                    for m in pat.finditer(text):
                        refs.setdefault(m.group(1), []).append(rel)
    return refs


# backwards-compatible alias (pre-elastic name)
find_obs_flag_refs = find_flag_refs


# -- metrics-name lint -------------------------------------------------------

# call sites that PUBLISH a metric the registry renders. The NAME
# argument (everything before the first comma / closing paren — a
# conditional like ``"hits" if hit else "misses"`` keeps both literals)
# is scanned for string literals: plain literals are exact metric names;
# a literal ending in "_" that is concatenated (``"xla_flops_" + slug``)
# is a dynamic FAMILY, documented as ``<prefix>*`` in the README table.
_METRIC_CALLS = re.compile(
    r"\b(?:bump_counter|bump_histogram|register_gauge)\s*\(\s*([^),]*)"
)
_METRIC_LIT = re.compile(r"""['"]([a-z][a-z0-9_]*)['"]\s*([%+]?)""")


def find_metric_names():
    """(exact_names, family_prefixes): every literal metric name (and
    dynamic-family prefix) at a publish call site under paddle_tpu/ and
    tools/, each mapped to the files referencing it."""
    exact, families = {}, {}
    self_rel = os.path.relpath(os.path.abspath(__file__), REPO)
    for top in ("paddle_tpu", "tools"):
        for root, _dirs, files in os.walk(os.path.join(REPO, top)):
            if "__pycache__" in root:
                continue
            for fn in files:
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(root, fn)
                rel = os.path.relpath(path, REPO)
                if rel == self_rel:  # this file's docstring examples
                    continue
                with open(path, errors="replace") as f:
                    text = f.read()
                for m in _METRIC_CALLS.finditer(text):
                    for lit in _METRIC_LIT.finditer(m.group(1)):
                        name, op = lit.group(1), lit.group(2)
                        if op and name.endswith("_"):
                            families.setdefault(name, []).append(rel)
                        elif not op:
                            exact.setdefault(name, []).append(rel)
    return exact, families


def lint_metrics():
    """Problem strings for metric names missing from the README
    "Metrics reference" table (empty = clean)."""
    with open(os.path.join(REPO, "README.md"), errors="replace") as f:
        readme = f.read()
    problems = []
    exact, families = find_metric_names()
    for name in sorted(exact):
        if "`%s`" % name not in readme:
            problems.append(
                "metric %r published (%s) but missing from the README "
                "metrics table" % (name, ", ".join(sorted(set(exact[name]))[:3]))
            )
    for prefix in sorted(families):
        if "`%s*`" % prefix not in readme:
            problems.append(
                "metric family %r published (%s) but `%s*` missing from "
                "the README metrics table"
                % (prefix, ", ".join(sorted(set(families[prefix]))[:3]),
                   prefix)
            )
    return problems


def lint():
    """Returns a list of human-readable problem strings (empty = clean)."""
    sys.path.insert(0, REPO)
    from paddle_tpu.fluid import flags

    refs = find_flag_refs()
    with open(os.path.join(REPO, "README.md"), errors="replace") as f:
        readme = f.read()
    problems = []
    for name in sorted(refs):
        where = ", ".join(sorted(set(refs[name]))[:3])
        if not flags.is_registered(name):
            problems.append(
                "FLAGS_%s referenced (%s) but not registered in %s"
                % (name, where, _FLAGS_PY)
            )
        if "FLAGS_" + name not in readme:
            problems.append(
                "FLAGS_%s referenced (%s) but not documented in README.md"
                % (name, where)
            )
    registered = {
        n for n in flags._DEFAULTS
        if any(n.startswith(p) for p in PREFIXES)
    }
    for name in sorted(registered - set(refs) - _LEGACY_COMPAT):
        problems.append(
            "FLAGS_%s registered in %s but never read anywhere (dead knob)"
            % (name, _FLAGS_PY)
        )
    return problems


def main():
    problems = lint() + lint_metrics()
    for p in problems:
        print("LINT: %s" % p)
    if problems:
        return 1
    exact, families = find_metric_names()
    print(
        "flags lint clean: %d %s knobs registered + documented; "
        "%d metrics + %d families documented"
        % (len(find_flag_refs()), "/".join(p + "*" for p in PREFIXES),
           len(exact), len(families))
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
