"""Knob lint (op_audit.py-style consistency check, run inside tier-1).

Every ``FLAGS_obs_*``, ``FLAGS_dist_*`` and ``FLAGS_elastic_*`` knob
must be (1) registered in ``paddle_tpu/fluid/flags.py`` — an
unregistered reference silently reads its fallback and ``FLAGS_`` env
vars for it are dropped by the bridge — and (2) mentioned in README.md,
so the Observability / Fault-tolerance quickstarts can't drift behind
the code. The reverse direction is linted too: a registered knob nobody
reads is a dead knob. (Scope grew obs_* -> +dist_*/elastic_* with the
elastic-resize PR: the resize knobs are exactly the kind an operator
reaches for mid-incident, when stale docs hurt most.)

Run standalone (``python tools/flags_lint.py``, exit 1 on findings) or
via ``tests/test_observability.py::test_flags_lint_clean``.
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the linted knob families (prefix with trailing underscore)
PREFIXES = ("obs_", "dist_", "elastic_")
_NAME = r"((?:%s)[a-z0-9_]+)" % "|".join(p.rstrip("_") + "_" for p in PREFIXES)

# the spellings a knob is consumed under: the env-bridge name and the
# get_flag/_flag/set_flags key (supervisor.py wraps get_flag in a local
# ``_flag(name, default)`` helper; the substring match covers both)
_REF_PATTERNS = (
    re.compile(r"FLAGS_" + _NAME),
    re.compile(r"""_flag\(\s*['"]""" + _NAME + r"""['"]"""),
)
_SCAN_DIRS = ("paddle_tpu", "tools", "tests")
_FLAGS_PY = os.path.join("paddle_tpu", "fluid", "flags.py")

# registered-but-unread knobs that are NOT dead: the reference's env
# whitelist includes them, so scripts that set them must keep working
# (flags.py's accepted-and-recorded contract). Anything added here needs
# that justification — a knob of OURS nobody reads is still a finding.
_LEGACY_COMPAT = {
    "dist_threadpool_size",  # reference flags.cc threading knob; XLA
                             # owns threading on TPU, value is recorded
}


def find_flag_refs():
    """{flag_name: [relpath, ...]} for every linted-family knob
    referenced in Python sources (the flags registry file itself
    excluded — defining a flag is not consuming it)."""
    refs = {}
    for top in _SCAN_DIRS:
        for root, _dirs, files in os.walk(os.path.join(REPO, top)):
            if "__pycache__" in root:
                continue
            for fn in files:
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(root, fn)
                rel = os.path.relpath(path, REPO)
                if rel == _FLAGS_PY:
                    continue
                with open(path, errors="replace") as f:
                    text = f.read()
                for pat in _REF_PATTERNS:
                    for m in pat.finditer(text):
                        refs.setdefault(m.group(1), []).append(rel)
    return refs


# backwards-compatible alias (pre-elastic name)
find_obs_flag_refs = find_flag_refs


def lint():
    """Returns a list of human-readable problem strings (empty = clean)."""
    sys.path.insert(0, REPO)
    from paddle_tpu.fluid import flags

    refs = find_flag_refs()
    with open(os.path.join(REPO, "README.md"), errors="replace") as f:
        readme = f.read()
    problems = []
    for name in sorted(refs):
        where = ", ".join(sorted(set(refs[name]))[:3])
        if not flags.is_registered(name):
            problems.append(
                "FLAGS_%s referenced (%s) but not registered in %s"
                % (name, where, _FLAGS_PY)
            )
        if "FLAGS_" + name not in readme:
            problems.append(
                "FLAGS_%s referenced (%s) but not documented in README.md"
                % (name, where)
            )
    registered = {
        n for n in flags._DEFAULTS
        if any(n.startswith(p) for p in PREFIXES)
    }
    for name in sorted(registered - set(refs) - _LEGACY_COMPAT):
        problems.append(
            "FLAGS_%s registered in %s but never read anywhere (dead knob)"
            % (name, _FLAGS_PY)
        )
    return problems


def main():
    problems = lint()
    for p in problems:
        print("LINT: %s" % p)
    if problems:
        return 1
    print(
        "flags lint clean: %d %s knobs registered + documented"
        % (len(find_flag_refs()), "/".join(p + "*" for p in PREFIXES))
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
