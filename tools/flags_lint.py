"""Knob lint (op_audit.py-style consistency check, run inside tier-1).

Every ``FLAGS_obs_*`` knob must be (1) registered in
``paddle_tpu/fluid/flags.py`` — an unregistered reference silently reads
its fallback and ``FLAGS_`` env vars for it are dropped by the bridge —
and (2) mentioned in README.md, so the Observability quickstart can't
drift behind the code. The reverse direction is linted too: a registered
``obs_*`` flag nobody reads is a dead knob.

Run standalone (``python tools/flags_lint.py``, exit 1 on findings) or
via ``tests/test_observability.py::test_obs_flags_lint_clean``.
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# both spellings a knob is consumed under: the env-bridge name and the
# get_flag/set_flags key
_REF_PATTERNS = (
    re.compile(r"FLAGS_(obs_[a-z0-9_]+)"),
    re.compile(r"""get_flag\(\s*['"](obs_[a-z0-9_]+)['"]"""),
)
_SCAN_DIRS = ("paddle_tpu", "tools", "tests")
_FLAGS_PY = os.path.join("paddle_tpu", "fluid", "flags.py")


def find_obs_flag_refs():
    """{flag_name: [relpath, ...]} for every obs_* knob referenced in
    Python sources (the flags registry file itself excluded — defining a
    flag is not consuming it)."""
    refs = {}
    for top in _SCAN_DIRS:
        for root, _dirs, files in os.walk(os.path.join(REPO, top)):
            if "__pycache__" in root:
                continue
            for fn in files:
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(root, fn)
                rel = os.path.relpath(path, REPO)
                if rel == _FLAGS_PY:
                    continue
                with open(path, errors="replace") as f:
                    text = f.read()
                for pat in _REF_PATTERNS:
                    for m in pat.finditer(text):
                        refs.setdefault(m.group(1), []).append(rel)
    return refs


def lint():
    """Returns a list of human-readable problem strings (empty = clean)."""
    sys.path.insert(0, REPO)
    from paddle_tpu.fluid import flags

    refs = find_obs_flag_refs()
    with open(os.path.join(REPO, "README.md"), errors="replace") as f:
        readme = f.read()
    problems = []
    for name in sorted(refs):
        where = ", ".join(sorted(set(refs[name]))[:3])
        if not flags.is_registered(name):
            problems.append(
                "FLAGS_%s referenced (%s) but not registered in %s"
                % (name, where, _FLAGS_PY)
            )
        if "FLAGS_" + name not in readme:
            problems.append(
                "FLAGS_%s referenced (%s) but not documented in README.md"
                % (name, where)
            )
    registered = {
        n for n in flags._DEFAULTS if n.startswith("obs_")
    }
    for name in sorted(registered - set(refs)):
        problems.append(
            "FLAGS_%s registered in %s but never read anywhere (dead knob)"
            % (name, _FLAGS_PY)
        )
    return problems


def main():
    problems = lint()
    for p in problems:
        print("LINT: %s" % p)
    if problems:
        return 1
    print("flags lint clean: %d obs_* knobs registered + documented"
          % len(find_obs_flag_refs()))
    return 0


if __name__ == "__main__":
    sys.exit(main())
