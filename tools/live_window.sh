#!/bin/bash
# Live-TPU-window playbook: the moment the axon tunnel answers, bank
# everything a short window can give us:
#   1. the full bench ladder (resnet 64->256->1024 + remat probe + BERT),
#      which also leaves a warm persistent compile cache for the driver's
#      end-of-round run;
#   2. TPU cost/HLO census for both bench models (the PERF.md MFU inputs).
# Everything runs with hard timeouts; partial results are kept.
set -u
cd "$(dirname "$0")/.."
OUT=MEASURED_r04
mkdir -p "$OUT"
stamp() { date -u +%H:%M:%S; }

echo "$(stamp) live window: starting bench ladder" | tee -a "$OUT/log.txt"
BENCH_TIMEOUT=${BENCH_TIMEOUT:-1100} timeout 1150 python bench.py \
  > "$OUT/bench.json" 2> "$OUT/bench.log"
rc=$?
echo "$(stamp) bench rc=$rc ->" | tee -a "$OUT/log.txt"
cat "$OUT/bench.json" | tee -a "$OUT/log.txt"

# flash-attention probe: the fused Pallas kernel vs the banked dense
# number (bank-best in bench.py does NOT see this; recorded separately)
echo "$(stamp) bert flash-attention probe" | tee -a "$OUT/log.txt"
BENCH_FLASH=1 BENCH_BUDGET_S=500 timeout 550 python bench_bert.py \
  > "$OUT/bench_bert_flash.json" 2>> "$OUT/bench.log"
rc=$?
echo "$(stamp) flash probe rc=$rc ->" | tee -a "$OUT/log.txt"
cat "$OUT/bench_bert_flash.json" | tee -a "$OUT/log.txt"

for spec in "resnet 256" "bert 64" "bert 64 --flash 1"; do
  set -- $spec
  model=$1; batch=$2; shift 2
  tag=$model${1:+_flash}
  echo "$(stamp) hlo_scan $tag b$batch" | tee -a "$OUT/log.txt"
  timeout 700 python tools/hlo_scan.py --model "$model" --batch "$batch" "$@" \
    > "$OUT/hlo_$tag.json" 2>> "$OUT/bench.log"
  rc=$?
  echo "$(stamp) hlo_scan $tag rc=$rc" | tee -a "$OUT/log.txt"
  cat "$OUT/hlo_$tag.json" | tee -a "$OUT/log.txt"
done
echo "$(stamp) live window playbook done" | tee -a "$OUT/log.txt"
