#!/bin/bash
# Live-TPU-window playbook (round 5): the moment the axon tunnel answers,
# bank everything a short window can give us. Normally the background
# watcher (tools/tpu_watcher.py) runs this flow automatically; this script
# is the manual/interactive equivalent.
#   1. the full bench ladder (resnet 64->256->1024 + remat probe + BERT
#      seq128 -> seq384 -> flash) — every TPU success is banked into
#      BENCH_BANK.json with git sha + timestamp, and the run leaves a warm
#      persistent compile cache for the driver's end-of-round run;
#   2. a seq-384 flash-attention probe (runs AFTER the dense number is
#      banked, so an untested kernel can never cost the headline);
#   3. TPU cost/HLO census for both bench models (the PERF.md MFU inputs).
# Everything runs with hard timeouts; partial results are kept and banked.
set -u
cd "$(dirname "$0")/.."
# separate default dir from the watcher's MEASURED_r05 so a manual run
# can never clobber (or get half-committed with) an automated window
OUT=${OUT:-MEASURED_manual}
mkdir -p "$OUT"
stamp() { date -u +%H:%M:%S; }

echo "$(stamp) live window: starting bench ladder" | tee -a "$OUT/log.txt"
BENCH_TIMEOUT=${BENCH_TIMEOUT:-1100} timeout 1150 python bench.py \
  > "$OUT/bench.json" 2> "$OUT/bench.log"
rc=$?
echo "$(stamp) bench rc=$rc ->" | tee -a "$OUT/log.txt"
cat "$OUT/bench.json" | tee -a "$OUT/log.txt"

# flash-attention probe at the defensible seq length (bank slot
# bert_seq384_flash; bank-best means it can only improve the record)
echo "$(stamp) bert seq-384 flash-attention probe" | tee -a "$OUT/log.txt"
BENCH_BERT_SEQ=384 BENCH_FLASH=1 BENCH_BUDGET_S=500 timeout 550 \
  python bench_bert.py \
  > "$OUT/bench_bert_flash.json" 2>> "$OUT/bench.log"
rc=$?
echo "$(stamp) flash probe rc=$rc ->" | tee -a "$OUT/log.txt"
cat "$OUT/bench_bert_flash.json" | tee -a "$OUT/log.txt"

for spec in "hlo_resnet resnet 256" \
            "hlo_bert bert 24 --seq 384" \
            "hlo_bert_flash bert 24 --seq 384 --flash 1"; do
  set -- $spec
  tag=$1; model=$2; batch=$3; shift 3
  echo "$(stamp) hlo_scan $tag b$batch" | tee -a "$OUT/log.txt"
  timeout 700 python tools/hlo_scan.py --model "$model" --batch "$batch" \
    "$@" --out "$OUT/$tag.json" \
    > /dev/null 2>> "$OUT/bench.log"
  rc=$?
  echo "$(stamp) hlo_scan $tag rc=$rc" | tee -a "$OUT/log.txt"
  cat "$OUT/$tag.json" 2>/dev/null | tee -a "$OUT/log.txt"
done
echo "$(stamp) live window playbook done — remember: git add BENCH_BANK.json $OUT && git commit" | tee -a "$OUT/log.txt"
