#!/bin/bash
# Bounded late-window bench refresher — the complement of
# tools/tpu_watcher.py for the END of a round, when every watcher goal
# is already banked but the numbers were measured at an older sha:
# probe the tunnel every 10 min (default 18 tries ~= 3h); on a live
# window run the bench ladder + the GPT flash rung ONCE at current HEAD
# (bank-best semantics: a re-measurement can only improve the record,
# and the run leaves a warm persistent compile cache for the driver's
# end-of-round bench), commit the bank, and exit.
#   TRIES=N  override the probe count
set -u
cd "$(dirname "$0")/.."
LOG=MEASURED_r05/late_window.log
for i in $(seq 1 "${TRIES:-18}"); do
  if timeout 150 python -c "
import jax, jax.numpy as jnp
assert any(d.platform != 'cpu' for d in jax.devices())
jax.jit(lambda a: (a @ a).sum())(jnp.ones((256,256), jnp.bfloat16)).block_until_ready()
" >/dev/null 2>&1; then
    echo "$(date -u +%H:%M:%S) late window open; running ladder" >> "$LOG"
    BENCH_TIMEOUT=1100 timeout 1150 python bench.py >> "$LOG" 2>&1
    BENCH_FLASH=1 timeout 500 python bench_gpt.py >> "$LOG" 2>&1
    git add BENCH_BANK.json MEASURED_r05 2>/dev/null && \
      git commit -q -m "bank TPU measurements from late live window

No-Verification-Needed: measurement-data-only commit" 2>/dev/null
    echo "$(date -u +%H:%M:%S) late window done" >> "$LOG"
    break
  fi
  sleep 600
done
