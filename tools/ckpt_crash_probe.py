"""Closed-loop crash/resume probe for paddle_tpu.checkpoint.

Proves the two acceptance properties of the checkpoint subsystem on a
real OS-process boundary:

  1. **Atomicity** — SIGKILL at ANY point (including mid-async-save)
     never yields a loadable torn checkpoint: after every kill the
     probe re-checksums every committed step (``CheckpointManager.
     verify``) and asserts ``latest_step()`` only ever lands on a fully
     committed step.
  2. **Bit-exact resume** — a worker killed and relaunched (resuming
     from ``latest_step()`` through the trainer integration) finishes
     with params byte-identical to an uninterrupted run.

Modes::

    # full probe: N trials, each SIGKILLs the worker at a random moment
    python tools/ckpt_crash_probe.py --trials 20

    # fast deterministic subset (wired into tier-1 via
    # tests/test_checkpoint.py): self-SIGKILL at fixed steps
    python tools/ckpt_crash_probe.py --fast

    # async-save overlap measurement for PERF.md: mean step time with
    # no / background / synchronous saving
    python tools/ckpt_crash_probe.py --bench

The worker is this same file with ``--worker``: a deterministic MLP +
Adam trained through ``fluid.trainer.MultiTrainer`` with a
``CheckpointManager`` (so the probe exercises the real trainer
integration: restore_or_initialize, batch replay past the resume point,
interval saves on the background writer)."""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import random
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

STEPS = 24
INTERVAL = 3
SEED = 17


# -- deterministic workload --------------------------------------------------

def _build(hidden=16):
    import paddle_tpu.fluid as fluid

    with fluid.unique_name.guard():
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = SEED
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[8], dtype="float32")
            y = fluid.layers.data(name="y", shape=[1], dtype="int64")
            h = fluid.layers.fc(input=x, size=hidden, act="relu")
            logits = fluid.layers.fc(input=h, size=4)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, y)
            )
            fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
    return main, startup, loss


class _StepDataset(object):
    """Batches are a pure function of the step index — the determinism
    the trainer's resume-replay contract needs."""

    def __init__(self, use_var, steps, batch=16):
        import numpy as np

        self.use_var = use_var
        self.thread_num = 1
        self._steps = steps
        self._batch = batch
        self._np = np

    def _iter_batches(self):
        for s in range(self._steps):
            r = self._np.random.RandomState(1000 + s)
            yield (
                r.rand(self._batch, 8).astype("float32"),
                r.randint(0, 4, (self._batch, 1)).astype("int64"),
            )


def _params_digest(program, scope):
    import numpy as np

    h = hashlib.sha256()
    for v in sorted(program.list_vars(), key=lambda v: v.name):
        if not v.persistable or v.name in ("feed", "fetch"):
            continue
        val = scope.get(v.name)
        if val is None:
            continue
        arr = np.asarray(val.numpy() if hasattr(val, "numpy") else val)
        h.update(v.name.encode())
        h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()


# -- worker ------------------------------------------------------------------

def run_worker(args):
    import paddle_tpu.fluid as fluid
    from paddle_tpu import checkpoint
    from paddle_tpu.fluid.trainer import MultiTrainer

    fluid.set_flags({"FLAGS_ckpt_save_interval_steps": args.interval})
    main, startup, loss = _build()
    exe = fluid.Executor(fluid.CPUPlace())
    mgr = checkpoint.CheckpointManager(args.dir, keep_max=3)
    resumed = mgr.latest_step()
    print("RESUMED %s" % ("FRESH" if resumed is None else resumed), flush=True)

    state = {"step": -1}
    handler = checkpoint.PreemptionHandler(
        mgr, lambda: (state["step"], main)
    ).install()

    def on_step(s):
        state["step"] = s
        if args.die_at_step is not None and s == args.die_at_step:
            # simulate fleet preemption's SIGKILL right after this step's
            # async save was enqueued — the writer may be mid-write
            os.kill(os.getpid(), signal.SIGKILL)

    dataset = _StepDataset([main.global_block().var("x"),
                            main.global_block().var("y")], args.steps)
    trained = MultiTrainer().train(
        exe, main, dataset, fetch_list=[loss], print_period=0,
        on_step=on_step, ckpt_manager=mgr, startup_program=startup,
    )
    handler.uninstall()
    if trained < args.steps or checkpoint.preemption_requested():
        # preempted at a step boundary (trainer already committed the
        # final save there) — exit 143 so the driver relaunches; the
        # incomplete state must NOT be labeled as the final step
        mgr.close()
        print("PREEMPTED %d" % trained, flush=True)
        return 143
    mgr.save(args.steps - 1, main, async_=False)
    mgr.close()
    digest = _params_digest(main, fluid.global_scope())
    print("FINAL %s" % digest, flush=True)
    return 0


# -- driver ------------------------------------------------------------------

def _worker_cmd(dirname, steps, interval, die_at_step=None):
    cmd = [
        sys.executable, os.path.abspath(__file__), "--worker",
        "--dir", dirname, "--steps", str(steps),
        "--interval", str(interval),
    ]
    if die_at_step is not None:
        cmd += ["--die_at_step", str(die_at_step)]
    return cmd


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    return env


def _parse_final(text):
    for line in text.splitlines():
        if line.startswith("FINAL "):
            return line.split()[1]
    return None


def _validate_dir(dirname):
    """No torn checkpoint is ever discoverable: every step listed as
    committed must pass a full re-checksum."""
    from paddle_tpu import checkpoint

    steps = checkpoint.list_steps(dirname)
    mgr = checkpoint.CheckpointManager(dirname, keep_max=0)
    try:
        for s in steps:
            mgr.verify(s)
    finally:
        mgr.close()
    return steps


def _reference_hash(tmp, steps, interval):
    d = os.path.join(tmp, "ref")
    p = subprocess.run(
        _worker_cmd(d, steps, interval), env=_env(), capture_output=True,
        text=True, timeout=600, cwd=REPO,
    )
    assert p.returncode == 0, "reference run failed:\n%s%s" % (
        p.stdout, p.stderr
    )
    digest = _parse_final(p.stdout)
    assert digest, "reference run printed no FINAL line:\n%s" % p.stdout
    return digest


def run_probe(args):
    import tempfile

    tmp = args.workdir or tempfile.mkdtemp(prefix="ckpt_probe_")
    t0 = time.time()
    ref = _reference_hash(tmp, args.steps, args.interval)
    ref_s = time.time() - t0
    print("reference digest %s (%.1fs)" % (ref[:16], ref_s))
    # random kills must LAND: cap the delay below the observed runtime
    window = min(args.kill_window_s, max(2.0, ref_s * 0.9))

    kills = resumes_from = 0
    for trial in range(args.trials):
        d = os.path.join(tmp, "trial_%02d" % trial)
        if args.fast:
            # deterministic: self-SIGKILL right after these steps
            plan = [args.steps // 3, (2 * args.steps) // 3]
        attempt = 0
        killed = 0
        while True:
            attempt += 1
            if args.fast:
                die = plan[killed] if killed < len(plan) else None
                p = subprocess.run(
                    _worker_cmd(d, args.steps, args.interval,
                                die_at_step=die),
                    env=_env(), capture_output=True, text=True,
                    timeout=600, cwd=REPO,
                )
                out, rc = p.stdout + p.stderr, p.returncode
                if die is not None:
                    kills += 1
                    killed += 1
            else:
                p = subprocess.Popen(
                    _worker_cmd(d, args.steps, args.interval), env=_env(),
                    stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                    text=True, cwd=REPO,
                )
                if not killed:
                    # anywhere from mid-import to near-completion; if
                    # the worker beat the timer, retry the kill on the
                    # next (re)launch — every trial lands >= 1 SIGKILL
                    time.sleep(random.uniform(0.5, window))
                    if p.poll() is None:
                        p.kill()
                        kills += 1
                        killed = 1
                out, _ = p.communicate(timeout=300)
                rc = p.returncode
            committed = _validate_dir(d)
            if rc == 0 and killed:
                digest = _parse_final(out)
                assert digest == ref, (
                    "trial %d: resumed run diverged from the "
                    "uninterrupted run\n  ref   %s\n  trial %s\n%s"
                    % (trial, ref, digest, out)
                )
                if "RESUMED FRESH" not in out:
                    resumes_from += 1
                break
            assert rc != 1, "worker crashed (not killed):\n%s" % out
            if rc != 0:
                print(
                    "  trial %d attempt %d: killed; committed steps %s "
                    "all verify" % (trial, attempt, committed),
                    flush=True,
                )
        print("trial %d OK (attempts=%d)" % (trial, attempt), flush=True)

    print(
        "PROBE PASS: %d trials, %d kills, %d checkpoint resumes, 0 torn "
        "checkpoints, all resumed digests == reference (%.1fs)"
        % (args.trials, kills, resumes_from, time.time() - t0)
    )
    return 0


# -- bench: async-save overlap ----------------------------------------------

def run_bench(args):
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu import checkpoint

    main, startup, loss = _build(hidden=args.bench_hidden)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)

    def batch(s):
        r = np.random.RandomState(1000 + s)
        return {
            "x": r.rand(args.bench_batch, 8).astype("float32"),
            "y": r.randint(0, 4, (args.bench_batch, 1)).astype("int64"),
        }

    k = max(args.bench_interval, 1)

    def loop(mode, steps, mgr=None):
        # warmup compile
        exe.run(main, feed=batch(0), fetch_list=[loss])
        t0 = time.perf_counter()
        for s in range(steps):
            exe.run(main, feed=batch(s), fetch_list=[loss])
            if mgr is not None and (s + 1) % k == 0:
                mgr.save(s, main, async_=(mode == "async"))
        if mgr is not None:
            mgr.wait()
        return (time.perf_counter() - t0) / steps * 1000.0

    import tempfile

    results = {"save_interval_steps": k}
    results["no_save_ms"] = loop("none", args.bench_steps)
    for mode in ("sync", "async"):
        d = tempfile.mkdtemp(prefix="ckpt_bench_%s_" % mode)
        mgr = checkpoint.CheckpointManager(d, keep_max=2)
        results["%s_save_ms_per_step" % mode] = loop(
            mode, args.bench_steps, mgr
        )
        mgr.close()
    from paddle_tpu.fluid import profiler

    results["ckpt_save_ms"] = profiler.summarize_histogram("ckpt_save_ms")
    results["ckpt_save_bytes"] = profiler.summarize_histogram(
        "ckpt_save_bytes"
    )
    results["ckpt_snapshot_ms"] = profiler.summarize_histogram(
        "ckpt_snapshot_ms"
    )
    base, async_ = results["no_save_ms"], results["async_save_ms_per_step"]
    sync = results["sync_save_ms_per_step"]
    added_sync, added_async = sync - base, async_ - base
    results["hidden_fraction"] = (
        (added_sync - added_async) / added_sync if added_sync > 0 else 0.0
    )
    print("BENCH " + json.dumps(results, indent=1, sort_keys=True))
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--worker", action="store_true")
    ap.add_argument("--dir", type=str, default=None)
    ap.add_argument("--steps", type=int, default=STEPS)
    ap.add_argument("--interval", type=int, default=INTERVAL)
    ap.add_argument("--die_at_step", type=int, default=None)
    ap.add_argument("--trials", type=int, default=20)
    ap.add_argument("--fast", action="store_true",
                    help="deterministic 1-trial subset for tier-1")
    ap.add_argument("--kill_window_s", type=float, default=12.0)
    ap.add_argument("--workdir", type=str, default=None)
    ap.add_argument("--bench", action="store_true")
    ap.add_argument("--bench_steps", type=int, default=60)
    ap.add_argument("--bench_hidden", type=int, default=512)
    ap.add_argument("--bench_batch", type=int, default=2048)
    ap.add_argument(
        "--bench_interval", type=int, default=5,
        help="save every K steps in --bench (overlap needs K*step_time "
        "to be on the order of one save)",
    )
    args = ap.parse_args(argv)
    if args.worker:
        assert args.dir, "--worker needs --dir"
        return run_worker(args)
    if args.bench:
        return run_bench(args)
    if args.fast:
        args.trials = 1
    return run_probe(args)


if __name__ == "__main__":
    sys.exit(main())
