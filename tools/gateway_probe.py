"""CPU-runnable closed-loop probe for the HTTP serving gateway.

Drives ``paddle_tpu/serving/gateway.py`` — the network front door over
the whole serving stack (micro-batcher + bucket ladder + KV-cache
decode engine + strict compile gate) — end to end over real sockets,
and asserts the gateway acceptance bars:

- CONCURRENCY + PARITY: >= 8 concurrent HTTP clients mixing
  ``POST /v1/infer`` and chunked-SSE ``POST /v1/generate`` all get
  results equal to the in-process APIs (token-exact for generation;
  bit-exact through the JSON tensor codec for inference — every float32
  survives the double round-trip);
- ZERO RECOMPILES: the whole HTTP storm runs under the armed PR 7
  strict gate (``FLAGS_serving_strict_compiles``) with
  ``serving_steady_recompiles`` unchanged — the network layer adds no
  compiled surface;
- BACKPRESSURE MAPPING: a rate-limited tenant's burst returns 429 with
  a ``Retry-After`` header (shed at admission), a microsecond deadline
  returns 504 (shed at dispatch), and the two land in distinct
  counters;
- OBSERVABILITY: per-tenant ``gateway_*`` counters/histograms
  round-trip through the PR 5 exporter's ``/metrics`` (HTTP scrape +
  ``parse_prometheus``), ``gateway_request`` spans surface on
  ``/trace``, and the JSONL access log carries one line per request
  with unique request ids;
- GRACEFUL DRAIN: a real ``SIGTERM`` mid-stream flips ``/readyz``
  NOT-READY (shared preemption latch), every in-flight SSE stream
  completes in full, and only then does the listener close.

The probe also measures the HTTP hop's added latency vs the in-process
``infer()`` / ``generate()`` calls (the PERF.md gateway-overhead
numbers).

Run directly (prints one REPORT json line + PROBE PASS/FAIL)::

    JAX_PLATFORMS=cpu python tools/gateway_probe.py --fast

or via tests/test_gateway.py, which runs --fast as a tier-1 gate (in a
subprocess — the probe SIGTERMs itself).
"""

import argparse
import json
import os
import signal
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPORT_SCHEMA_VERSION = 1


def build_classifier(dirname, dim=32, hidden=64, classes=8, seed=0):
    """Init + save a small classifier inference model (the /v1/infer
    workload); returns an example single-row input."""
    import numpy as np

    import paddle_tpu.fluid as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[dim], dtype="float32")
        h = fluid.layers.fc(x, size=hidden, act="relu", name="gwp_fc1")
        out = fluid.layers.softmax(
            fluid.layers.fc(h, size=classes, name="gwp_cls")
        )
    scope = fluid.core.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup, scope=scope)
        fluid.io.save_inference_model(
            dirname, ["x"], [out], exe, main_program=main
        )
    return np.random.RandomState(seed).rand(1, dim).astype("float32")


def _post(url, body, headers=None, timeout=60):
    """(status, parsed json body, headers) — HTTPError unwrapped."""
    req = urllib.request.Request(
        url, data=json.dumps(body).encode("utf-8"),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def _sse(url, body, headers=None, timeout=120, on_token=None):
    """POST and consume a chunked SSE stream: returns (tokens, done).
    ``on_token`` fires per token as it arrives (tests hook it to act
    mid-stream). Shared with tests/test_gateway.py — one copy of the
    SSE framing/assembly logic."""
    req = urllib.request.Request(
        url, data=json.dumps(body).encode("utf-8"),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    toks, done = [], None
    with urllib.request.urlopen(req, timeout=timeout) as r:
        for line in r:
            line = line.decode("utf-8").strip()
            if not line.startswith("data: "):
                continue
            obj = json.loads(line[len("data: "):])
            if "token" in obj:
                toks.append(obj["token"])
                if on_token is not None:
                    on_token(obj["token"])
            else:
                done = obj
    return toks, done


def _percentile(samples, p):
    import numpy as np

    return round(float(np.percentile(np.asarray(samples), p)), 3)


def run_probe(fast=True, verbose=False):
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu import inference, serving
    from paddle_tpu.fluid import flags as _flags
    from paddle_tpu.fluid import profiler
    from paddle_tpu.models import gpt
    from paddle_tpu.observability import exporter as obs_exporter
    from paddle_tpu.observability import registry as obs_registry
    from paddle_tpu.serving.decode import DecodeEngine
    from paddle_tpu.serving.gateway import decode_tensor, encode_tensor

    # strict gate + a real /metrics listener: the probe's entire HTTP
    # storm must hold 0 steady-state recompiles AND be scrapeable
    _flags.set_flags({
        "FLAGS_serving_strict_compiles": True,
        "FLAGS_obs_http_port": 0,
    })

    report = {"schema_version": REPORT_SCHEMA_VERSION, "fast": bool(fast)}
    failures = []
    max_len = 48
    clients = 8
    infer_reqs = 8 if fast else 20
    gen_max_new = 10 if fast else 16

    cfg = gpt.GPTConfig.tiny(hidden_dropout=0.0, attention_dropout=0.0)
    cfg.max_position_embeddings = max_len
    with fluid.unique_name.guard():
        infer_prog, startup, _n, _l = gpt.build_gpt_infer(cfg, max_len)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    with fluid.executor.scope_guard(scope):
        exe.run(startup)
    engine = DecodeEngine(cfg, scope=scope, slots=clients, max_len=max_len,
                          prefill_buckets=[16, max_len],
                          param_program=infer_prog)

    tmp = tempfile.mkdtemp(prefix="gateway_probe_")
    access_path = os.path.join(tmp, "access.jsonl")
    xd = build_classifier(os.path.join(tmp, "model"))
    pred = inference.create_paddle_predictor(
        inference.AnalysisConfig(os.path.join(tmp, "model"))
    )
    server = serving.InferenceServer(
        pred, max_batch_size=8, batch_timeout_ms=5.0, queue_depth=64,
        num_workers=1, decode_engine=engine,
    ).start(warmup_inputs=[xd])
    gw = serving.Gateway(server, port=0, access_log=access_path).start()
    base = "http://127.0.0.1:%d" % gw.port

    rs = np.random.RandomState(11)
    prompts = [list(map(int, rs.randint(0, cfg.vocab_size, n)))
               for n in (2, 5, 9, 14)]

    try:
        # ---- in-process oracles (the APIs the gateway must match) ----
        expect_infer = server.infer([xd], deadline_ms=30000)
        expect_tokens = {
            tuple(p): server.generate(p, max_new_tokens=gen_max_new)
            .tokens(timeout=120)
            for p in prompts
        }
        c_warm = profiler.get_counters()

        # ---- concurrency + parity: 8 HTTP clients, mixed endpoints ----
        errors = []

        def infer_client(tenant):
            try:
                for _ in range(infer_reqs):
                    st, body, _ = _post(
                        base + "/v1/infer",
                        {"inputs": [encode_tensor(xd)],
                         "deadline_ms": 30000},
                        headers={"X-Tenant-Id": tenant},
                    )
                    assert st == 200, (st, body)
                    got = [decode_tensor(t) for t in body["outputs"]]
                    assert len(got) == len(expect_infer)
                    for g, e in zip(got, expect_infer):
                        # float32 -> double -> json -> float32 is exact
                        assert np.array_equal(g, np.asarray(e)), "drift"
            except Exception as e:  # noqa: BLE001 - surfaced via errors
                errors.append(e)

        def gen_client(tenant, prompt):
            try:
                toks, done = _sse(
                    base + "/v1/generate",
                    {"prompt_ids": prompt, "max_new_tokens": gen_max_new},
                    headers={"X-Tenant-Id": tenant},
                )
                assert toks == expect_tokens[tuple(prompt)], \
                    (toks, expect_tokens[tuple(prompt)])
                assert done and done.get("done") and \
                    done.get("finish_reason") in ("length", "eos"), done
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = []
        for i in range(clients // 2):
            threads.append(threading.Thread(
                target=infer_client, args=("tenant_a",)))
            threads.append(threading.Thread(
                target=gen_client, args=("tenant_b", prompts[i % 4])))
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        storm_s = time.perf_counter() - t0
        report["http"] = {
            "clients": len(threads),
            "infer_requests": (clients // 2) * infer_reqs,
            "generate_streams": clients // 2,
            "errors": len(errors),
            "wall_s": round(storm_s, 2),
        }
        if errors:
            failures.append("%d client errors: %r" % (len(errors),
                                                      errors[:3]))

        # ---- strict gate: the HTTP layer added zero recompiles ----
        c_now = profiler.get_counters()
        steady = (c_now.get("serving_steady_recompiles", 0)
                  - c_warm.get("serving_steady_recompiles", 0))
        report["strict"] = {"steady_recompiles": int(steady),
                           "gate_armed": True}
        if steady != 0:
            failures.append("%d steady-state recompiles" % steady)

        # ---- HTTP-hop overhead vs the in-process APIs ----
        inproc, overhttp = [], []
        for _ in range(30):
            t1 = time.perf_counter()
            server.infer([xd], deadline_ms=30000)
            inproc.append((time.perf_counter() - t1) * 1e3)
        for _ in range(30):
            t1 = time.perf_counter()
            st, _b, _h = _post(base + "/v1/infer",
                               {"inputs": [encode_tensor(xd)],
                                "deadline_ms": 30000})
            assert st == 200
            overhttp.append((time.perf_counter() - t1) * 1e3)
        t1 = time.perf_counter()
        server.generate(prompts[1], max_new_tokens=gen_max_new)\
            .tokens(timeout=120)
        gen_inproc_ms = (time.perf_counter() - t1) * 1e3
        t1 = time.perf_counter()
        _sse(base + "/v1/generate",
             {"prompt_ids": prompts[1], "max_new_tokens": gen_max_new})
        gen_http_ms = (time.perf_counter() - t1) * 1e3
        report["overhead"] = {
            "inproc_infer_p50_ms": _percentile(inproc, 50),
            "inproc_infer_p99_ms": _percentile(inproc, 99),
            "http_infer_p50_ms": _percentile(overhttp, 50),
            "http_infer_p99_ms": _percentile(overhttp, 99),
            "inproc_generate_ms": round(gen_inproc_ms, 3),
            "http_generate_ms": round(gen_http_ms, 3),
            "tokens_per_stream": gen_max_new,
        }

        # ---- gauges scraped while the main gateway owns them: the
        # rate-limited gateway below will take over the shared gauge
        # names, and its ownership-scoped stop() removes them (the same
        # succession semantics the serving_queue_depth gauge has) ----
        exp = obs_exporter.global_exporter()
        with urllib.request.urlopen(exp.url("/metrics"), timeout=10) as r:
            flat_live = {
                k[0] for k in obs_registry.parse_prometheus(
                    r.read().decode("utf-8"))
            }
        gauges_ok = ("gateway_inflight" in flat_live
                     and "gateway_draining" in flat_live)

        # ---- overload: a rate-limited tenant's burst -> 429 ----
        gw_limited = serving.Gateway(
            server, port=0, rate_limit_rps=0.5, rate_burst=1,
        ).start()
        try:
            lim = "http://127.0.0.1:%d" % gw_limited.port
            st1, _, _ = _post(lim + "/v1/infer",
                              {"inputs": [encode_tensor(xd)]},
                              headers={"X-Tenant-Id": "bursty"})
            st2, body2, hdr2 = _post(lim + "/v1/infer",
                                     {"inputs": [encode_tensor(xd)]},
                                     headers={"X-Tenant-Id": "bursty"})
            report["overload"] = {
                "first_status": st1, "second_status": st2,
                "reason": body2.get("reason"),
                "retry_after_s": hdr2.get("Retry-After"),
                "retry_after_ms": body2.get("retry_after_ms"),
            }
            if not (st1 == 200 and st2 == 429
                    and body2.get("reason") == "ratelimit"
                    and int(hdr2.get("Retry-After", 0)) >= 1):
                failures.append("overload mapping wrong: %r"
                                % report["overload"])
        finally:
            gw_limited.stop()

        # ---- deadline: shed at dispatch -> 504 ----
        st, body, _ = _post(base + "/v1/infer",
                            {"inputs": [encode_tensor(xd)],
                             "deadline_ms": 0.001})
        report["deadline"] = {"status": st, "reason": body.get("reason")}
        if st != 504 or body.get("reason") != "deadline":
            failures.append("deadline mapping wrong: %r"
                            % report["deadline"])

        # ---- metrics + spans + access log round-trip ----
        with urllib.request.urlopen(exp.url("/metrics"), timeout=10) as r:
            scraped = obs_registry.parse_prometheus(
                r.read().decode("utf-8")
            )
        flat = {k[0] for k in scraped}
        need = [
            "gateway_requests", "gateway_shed_admission",
            "gateway_shed_dispatch", "gateway_stream_tokens",
            "gateway_tenant_requests_tenant_a",
            "gateway_tenant_requests_tenant_b",
            "gateway_tenant_shed_bursty",
            "gateway_latency_ms_count", "gateway_ttft_ms_count",
            "gateway_tenant_latency_ms_tenant_a_count",
        ]
        missing = [m for m in need if m not in flat]
        if not gauges_ok:
            missing.append("gateway_inflight/gateway_draining gauges")
        sheds_distinct = (
            scraped.get(("gateway_shed_admission", ""), 0) >= 1
            and scraped.get(("gateway_shed_dispatch", ""), 0) >= 1
        )
        with urllib.request.urlopen(exp.url("/trace"), timeout=10) as r:
            trace = json.loads(r.read())
        gw_spans = [e for e in trace["traceEvents"]
                    if e.get("name") == "gateway_request"]
        with open(access_path) as f:
            log_lines = [json.loads(ln) for ln in f if ln.strip()]
        rids = [ln["request_id"] for ln in log_lines]
        report["observability"] = {
            "metrics_missing": missing,
            "sheds_distinct": bool(sheds_distinct),
            "gateway_request_spans": len(gw_spans),
            "access_log_lines": len(log_lines),
            "access_log_ids_unique": len(set(rids)) == len(rids),
        }
        if missing:
            failures.append("metrics missing on /metrics: %r" % missing)
        if not sheds_distinct:
            failures.append("admission/dispatch sheds not distinct")
        if not gw_spans:
            failures.append("no gateway_request spans on /trace")
        if not log_lines or len(set(rids)) != len(rids):
            failures.append("access log incomplete or ids not unique")

        # ---- SIGTERM mid-stream: drain before the listener closes ----
        drain_tokens = 30 if fast else 40
        got = {}
        drain_errors = []

        def drain_client(i):
            try:
                toks, done = _sse(
                    base + "/v1/generate",
                    {"prompt_ids": prompts[i % 4],
                     "max_new_tokens": drain_tokens},
                )
                got[i] = (toks, done)
            except Exception as e:  # noqa: BLE001
                drain_errors.append(e)

        tok_base = profiler.get_counters().get("gateway_stream_tokens", 0)
        streams = [threading.Thread(target=drain_client, args=(i,))
                   for i in range(4)]
        for t in streams:
            t.start()
        # SIGTERM only once every stream is demonstrably mid-flight: all
        # 4 admitted (the gateway's inflight accounting) AND tokens
        # already on the wire — otherwise a not-yet-admitted client
        # would correctly get the drain 503 and fail the completeness
        # check for the wrong reason
        wait_deadline = time.monotonic() + 60
        while time.monotonic() < wait_deadline and (
            gw.admission.total_inflight < 4
            or profiler.get_counters().get("gateway_stream_tokens", 0)
            <= tok_base
        ):
            time.sleep(0.01)
        gw.install_sigterm()
        os.kill(os.getpid(), signal.SIGTERM)
        # readiness must flip NOT-READY while the drain holds the
        # listener open for the in-flight streams
        readyz_during = None
        try:
            with urllib.request.urlopen(base + "/readyz", timeout=5) as r:
                readyz_during = r.status
        except urllib.error.HTTPError as e:
            readyz_during = e.code
        except (urllib.error.URLError, OSError):
            readyz_during = "closed"
        for t in streams:
            t.join(timeout=120)
        for _ in range(200):
            if gw.port is None:
                break
            time.sleep(0.05)
        closed = gw.port is None
        complete = (not drain_errors and len(got) == 4 and all(
            len(toks) == drain_tokens and done and done.get("done")
            for toks, done in got.values()
        ))
        report["drain"] = {
            "streams": 4,
            "streams_complete": bool(complete),
            "readyz_during_drain": readyz_during,
            "listener_closed": bool(closed),
            "errors": len(drain_errors),
        }
        if not complete:
            failures.append("drain lost in-flight streams: %r"
                            % (drain_errors[:2],))
        if not closed:
            failures.append("listener still open after drain")
        if readyz_during not in (503, "closed"):
            failures.append("readyz stayed ready during drain: %r"
                            % readyz_during)
    finally:
        gw.stop()
        server.stop()
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)

    report["pass"] = not failures
    report["failures"] = failures
    if verbose:
        print(json.dumps(report, indent=1), file=sys.stderr)
    return report


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--fast", action="store_true",
                    help="tier-1 budget subset")
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)
    report = run_probe(fast=args.fast, verbose=args.verbose)
    print("REPORT " + json.dumps(report, sort_keys=True), flush=True)
    print("PROBE PASS" if report["pass"]
          else "PROBE FAIL: %s" % "; ".join(report["failures"]))
    return 0 if report["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
