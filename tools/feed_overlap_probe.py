"""CPU-runnable microbench proving the double-buffered input pipeline
hides host feed latency.

Deterministic design (no TPU window needed): a synthetic per-batch host
latency (``time.sleep`` — it releases the GIL exactly like real decode
I/O) is injected into the batch generator, and the compute step is a
compiled fc stack sized so compute dominates. If the pipeline works,
wall-clock per step ~= max(compute, feed); if the feed serializes, it is
their SUM. The probe reports the overlap efficiency

    (t_compute + t_feed - t_pipelined) / min(t_compute, t_feed)

(1.0 = the whole smaller side disappeared into the larger; 0.0 = fully
serial) plus the executor dispatch-plan cache hit rate over the timed
loop (steady state must be 100%: every step after the first resolves its
compiled block with one dict lookup).

Run directly (prints one JSON line)::

    JAX_PLATFORMS=cpu python tools/feed_overlap_probe.py

or via tests/test_io_pipeline.py, which asserts the >=80% bar (ISSUE 1
acceptance criterion) as a fast tier-1 regression guard.
"""

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _build(batch, dim, layers):
    import paddle_tpu.fluid as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="probe_x", shape=[dim], dtype="float32")
        h = x
        for i in range(layers):
            h = fluid.layers.fc(
                input=h, size=dim, act="relu", name="probe_fc%d" % i
            )
        loss = fluid.layers.mean(h)
        # a TRAINING step, not a forward pass: persistable param updates
        # keep the fetch-free timed steps from being dead-code-eliminated
        # by XLA (a fetchless forward-only program computes nothing)
        fluid.optimizer.SGD(learning_rate=1e-4).minimize(loss)
    return main, startup, x, loss


def _timed_steps(exe, main, loss, feed_iter, steps):
    """Run ``steps`` batches, fetch-synchronizing only on the last one
    (the bench convention: per-step fetches serialize the pipeline)."""
    t0 = time.perf_counter()
    out = None
    for i in range(steps):
        feed = next(feed_iter)
        out = exe.run(
            main, feed=feed, fetch_list=[loss] if i == steps - 1 else []
        )
    _ = float(__import__("numpy").asarray(out[0]).ravel()[0])
    return (time.perf_counter() - t0) / steps


def run_probe(steps=8, rounds=3, feed_fraction=2.0, min_feed_s=0.05,
              verbose=False):
    """Returns a dict of measurements; raises AssertionError only for
    setup problems (callers assert on the returned numbers).

    Shared/loaded hosts drift by 2x between back-to-back runs, so the
    compute-only and pipelined loops are measured in INTERLEAVED rounds
    and compared by per-mode minimum (load only ever adds time; the
    minimum is the undisturbed figure). The injected feed is sized to
    DOMINATE compute: the sleep is the one load-insensitive quantity in
    the probe, so the pipelined wall-clock pins to it deterministically
    (wall ~= max(compute, feed) = feed) and the efficiency ratio measures
    how much of the hideable side — compute, the min — the overlap
    actually hid, rather than measuring this box's load spikes."""
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import profiler

    place = fluid.CPUPlace()
    exe = fluid.Executor(place)

    import jax

    dev = fluid.core.get_jax_device(place)
    rs = np.random.RandomState(0)

    # size the compute so it comfortably DOMINATES the injected feed
    # latency plus scheduling noise (the pipeline then has to hide the
    # whole feed inside it); escalate until a fast many-core host's XLA
    # CPU backend actually takes >= ~35 ms/step
    t_compute = 0.0
    batches = staged = main = loss = None
    for batch, dim, layers in (
        (256, 512, 4), (256, 1024, 8), (512, 2048, 8), (1024, 4096, 8),
    ):
        main, startup, x, loss = _build(batch, dim, layers)
        exe.run(startup)
        batches = [rs.rand(batch, dim).astype("float32") for _ in range(4)]
        staged = [{"probe_x": jax.device_put(b, dev)} for b in batches]

        def compute_only():
            i = 0
            while True:
                yield staged[i % len(staged)]
                i += 1

        # warm up (compiles both the fetching and fetch-free variants)
        it = compute_only()
        exe.run(main, feed=next(it), fetch_list=[loss])
        exe.run(main, feed=next(it), fetch_list=[])
        t_compute = _timed_steps(exe, main, loss, compute_only(), steps)
        if t_compute >= 0.035:
            break

    # injected host latency: a fixed fraction of compute, floored so it
    # cannot vanish into timer noise — compute stays the max() side, so
    # a perfect pipeline hides the ENTIRE feed
    t_feed = max(t_compute * feed_fraction, min_feed_s)

    def slow_batches():
        # total batches: warmup step consumed below + timed steps
        for i in range(steps + 2):
            time.sleep(t_feed)  # synthetic decode/read latency
            yield (batches[i % len(batches)],)

    def pipelined_round(count_hits):
        loader = fluid.DataLoader.from_generator(
            feed_list=[x], capacity=64, use_double_buffer=True
        )
        loader.set_batch_generator(slow_batches, places=[place])
        it = iter(loader)
        # warmup pull: pays the feeder thread spin-up, not the steady state
        exe.run(main, feed=next(it), fetch_list=[loss])
        if count_hits:
            profiler.reset_counters()
        t = _timed_steps(exe, main, loss, it, steps)
        counters = profiler.get_counters() if count_hits else None
        loader.reset()
        return t, counters

    compute_times, pipe_times, counters = [], [], None
    for r in range(rounds):
        compute_times.append(
            _timed_steps(exe, main, loss, compute_only(), steps)
        )
        t, c = pipelined_round(count_hits=(r == rounds - 1))
        pipe_times.append(t)
        if c is not None:
            counters = c
    t_compute = min(compute_times)
    t_pipe = min(pipe_times)

    hits = counters.get("executor_plan_cache_hits", 0)
    misses = counters.get("executor_plan_cache_misses", 0)
    plan_hit_rate = hits / max(hits + misses, 1)
    overlap_efficiency = (t_compute + t_feed - t_pipe) / min(
        t_compute, t_feed
    )
    result = {
        "steps": steps,
        "rounds": rounds,
        "compute_s_per_step": round(t_compute, 5),
        "injected_feed_s_per_step": round(t_feed, 5),
        "serial_estimate_s_per_step": round(t_compute + t_feed, 5),
        "pipelined_s_per_step": round(t_pipe, 5),
        "overlap_efficiency": round(overlap_efficiency, 4),
        "plan_cache_hit_rate": round(plan_hit_rate, 4),
        "fast_lane_steps": counters.get("executor_feed_fast_lane_steps", 0),
        "h2d_overlapped_batches": counters.get("io_pipeline_h2d_batches", 0),
    }
    if verbose:
        print(json.dumps(result, indent=1), file=sys.stderr)
    return result


def main():
    result = run_probe(verbose=False)
    ok = (
        result["overlap_efficiency"] >= 0.8
        and result["plan_cache_hit_rate"] >= 0.999
    )
    result["pass"] = bool(ok)
    print(json.dumps(result))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
