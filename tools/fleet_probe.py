"""CPU-runnable closed-loop probe for the serving fleet control plane.

Drives ``paddle_tpu/serving/fleet.py`` + ``router.py`` end to end —
a real FleetController spawning real replica processes (each an
InferenceServer + Gateway over a saved model, strict compile gate
armed) behind a real Router — and asserts the control-plane bars:

- FAILOVER: a replica SIGKILLed mid-load costs ZERO failed client
  requests — the router retries the idempotent ``/v1/infer`` calls on
  the survivor — and the controller replaces the dead replica;
- AUTOSCALE: induced queue-depth pressure (scraped from each replica's
  ``/metrics``) raises a scale-up event, and the measured request
  throughput is higher after the new replica joins than before; when
  the pressure stops, hysteresis scales back down to the floor with a
  live trickle of traffic seeing zero drops through the drain;
- ROLLOUT: ``deploy()`` of a second model version swaps the fleet with
  zero dropped requests and zero wrong answers — every response
  bit-matches the oracle of the version its ``X-Model-Version`` header
  claims, and post-deploy traffic is all new-version;
- STRICT GATE: every replica holds 0 steady-state recompiles across
  the whole storm (``FLAGS_serving_strict_compiles`` armed);
- DURABLE GENERATIONS: a second fleet of GPT decode replicas (seeded
  identical params via ``--gpt-decode``) serves concurrent SSE streams
  while the chaos harness SIGKILLs one replica after EXACTLY N stream
  tokens (``FLAGS_chaos_die_after_tokens``) — every client stream
  still completes token-exact vs the uninterrupted oracle (greedy AND
  seeded sampling), with zero in-band errors: the router resumes each
  interrupted generation on the survivor with the emitted suffix, the
  resume re-prefill rides the windowed/prefix admission
  (``admit_windows``/``cached_prefix_tokens`` on the done event), the
  failover blip is measured, and the fleet still holds 0 steady
  recompiles;
- CONTROLLER DURABILITY: the controller itself is SIGKILLed mid-load
  (the ``FLAGS_chaos_kill_controller_after_s`` fault, fired from its
  own supervision tick) over a 3-replica GPT decode fleet — the
  headless pool keeps serving token-exact streams with zero client
  failures, a replica SIGKILLed WHILE headless is detected and
  replaced under the journaled crash budget by the restarted
  controller, which ADOPTS the live survivors instead of respawning
  them; a second controller started on the held workdir fails fast
  with ``FleetLockError``; and a rollout interrupted by a controller
  kill on either side of the traffic flip lands consistent (pre-flip
  aborts to the old version, post-flip resumes the old pool's drain);
- the router hop's added latency is measured (PERF.md), and
  ``fleet_report.json`` carries the replica timeline + scale/rollout
  events + per-replica tallies.

Run directly (prints one REPORT json line + PROBE PASS/FAIL)::

    JAX_PLATFORMS=cpu python tools/fleet_probe.py --fast

or via tests/test_fleet.py (tier-1, subprocess). Throughput-only
misses are prefixed "throughput" so the shared retry policy can
re-run a probe squeezed by a loaded box without retrying correctness.
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# one copy of the HTTP client helpers across the probes (and
# tests/test_fleet.py imports them from here)
from gateway_probe import _post, _percentile  # noqa: E402

REPORT_SCHEMA_VERSION = 1


def build_model(dirname, seed, dim=24, hidden=48, classes=8):
    """Init + save one classifier version (weights differ per build, so
    two exports are distinguishable models); writes warmup.npz beside
    the model so replicas can warm their bucket ladder. Returns an
    example single-row input."""
    import numpy as np

    import paddle_tpu.fluid as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.unique_name.guard():
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[dim], dtype="float32")
            h = fluid.layers.fc(x, size=hidden, act="relu",
                                name="flp_fc1_s%d" % seed)
            out = fluid.layers.softmax(
                fluid.layers.fc(h, size=classes, name="flp_cls_s%d" % seed)
            )
    scope = fluid.core.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup, scope=scope)
        fluid.io.save_inference_model(
            dirname, ["x"], [out], exe, main_program=main
        )
    xd = np.random.RandomState(7).rand(1, dim).astype("float32")
    np.savez(os.path.join(dirname, "warmup.npz"), xd)
    return xd


def _sse_collect(url, body, headers=None, timeout=120):
    """POST and consume a chunked SSE stream, keeping EVERYTHING:
    (status, data_events, comment_lines, inter_event_gaps_s,
    response_headers). Comment lines (":"-prefixed — the router's
    failover seam) are invisible to the plain ``_sse`` helper, and the
    gaps measure the client-felt blip. The ONE SSE-with-comments
    parser — tests/test_fleet.py imports it (same contract as _post)."""
    req = urllib.request.Request(
        url, data=json.dumps(body).encode("utf-8"),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    events, comments, gaps = [], [], []
    t_last = time.monotonic()
    with urllib.request.urlopen(req, timeout=timeout) as r:
        status, hdrs = r.status, dict(r.headers)
        for line in r:
            line = line.decode("utf-8").strip()
            if line.startswith("data: "):
                now = time.monotonic()
                gaps.append(now - t_last)
                t_last = now
                events.append(json.loads(line[len("data: "):]))
            elif line.startswith(":"):
                # (comment_line, index of the NEXT data event): gaps[i]
                # then brackets the comment — the client-felt blip of a
                # failover seam, as opposed to e.g. the TTFT gap
                comments.append((line, len(events)))
    return status, events, comments, gaps, hdrs


def run_generate_failover_trial(tmp, model_dir, report, failures, fast):
    """Durable streaming generations: chaos-kill a GPT decode replica at
    an exact stream-token boundary under concurrent streams and demand
    token-exact, zero-error completion of every stream via router
    failover + resume."""
    import numpy as np

    from paddle_tpu.observability import registry as _reg
    from paddle_tpu.serving import fleet as fleet_mod
    from paddle_tpu.serving.fleet import FleetController
    from paddle_tpu.serving.replica import build_gpt_decode_engine

    spec = {"seed": 17, "vocab_size": 97, "hidden_size": 32,
            "num_layers": 2, "num_heads": 2, "intermediate_size": 64,
            "max_len": 48, "slots": 8, "prefill_buckets": [8, 16, 48]}
    # the uninterrupted ORACLE: an in-process engine built from the same
    # seeded spec as every replica (seeded startup => bit-identical
    # params across processes), run with no chaos and no failover
    oracle_engine = build_gpt_decode_engine(spec).start()
    rs = np.random.RandomState(23)
    streams = []
    for i in range(4):
        prompt = [int(t) for t in rs.randint(0, spec["vocab_size"],
                                             10 + i)]
        knobs = ({} if i % 2 == 0 else
                 {"temperature": 1.3, "top_k": 20, "seed": 100 + i})
        streams.append({"prompt": prompt, "knobs": knobs})
    try:
        for s in streams:
            s["oracle"] = oracle_engine.generate(
                s["prompt"], max_new_tokens=10, **s["knobs"]
            ).tokens(timeout=120)
    finally:
        oracle_engine.stop()

    workdir = os.path.join(tmp, "fleet_gen")
    gen_env = {
        "FLAGS_serving_strict_compiles": "1",
        # chunked prefill + prefix store armed: a resume's re-prefill
        # must ride the windowed/prefix admission, not a monolithic
        # full prefill
        "FLAGS_decode_prefill_chunk": "8",
        "FLAGS_decode_prefix_cache_mb": "2",
        "FLAGS_decode_prefix_block": "8",
        # the deterministic mid-stream fault: replica 0 SIGKILLs itself
        # after its 6th stream token hits the wire
        "FLAGS_chaos_die_after_tokens": "6",
        "FLAGS_chaos_die_replica": "0",
        "FLAGS_obs_snapshot_interval_s": "1.0",
    }
    ctrl = FleetController(
        model_dir=model_dir, workdir=workdir, replicas=2,
        replica_env=gen_env, autoscale=False, seed=0,
        replica_args=["--gpt-decode", json.dumps(spec)],
    )
    t0 = time.monotonic()
    ctrl.start()
    results = [None] * len(streams)
    try:
        ctrl.wait_ready(timeout=180 if fast else 300)
        url = ctrl.router.url("/v1/generate")

        def client(i):
            s = streams[i]
            body = dict(prompt_ids=s["prompt"], max_new_tokens=10,
                        deadline_ms=60000, **s["knobs"])
            try:
                _st, events, comments, gaps, _h = _sse_collect(
                    url, body, timeout=90)
                results[i] = {"events": events, "comments": comments,
                              "gaps": gaps}
            except Exception as e:  # noqa: BLE001 - surfaced below
                results[i] = {"error": repr(e)}

        ths = [threading.Thread(target=client, args=(i,))
               for i in range(len(streams))]
        for t in ths:
            t.start()
        for t in ths:
            t.join()

        failed_over, resume_gaps = 0, []
        for i, (s, res) in enumerate(zip(streams, results)):
            if res is None or "error" in (res or {}):
                failures.append(
                    "gen-failover stream %d transport error: %r"
                    % (i, res)
                )
                continue
            evs = res["events"]
            toks = [e["token"] for e in evs if "token" in e]
            errs = [e for e in evs if "error" in e]
            done = [e for e in evs if e.get("done")]
            if errs:
                failures.append(
                    "gen-failover stream %d saw an in-band error: %r"
                    % (i, errs[:1])
                )
            if not done:
                failures.append(
                    "gen-failover stream %d never finished" % i
                )
            if toks != s["oracle"]:
                failures.append(
                    "gen-failover stream %d tokens diverge from the "
                    "uninterrupted oracle: %r != %r"
                    % (i, toks, s["oracle"])
                )
            if res["comments"]:
                failed_over += 1
                # the blip is the gap BRACKETING the failover comment
                # (event i-1 -> seam -> event i), not max(gaps) — the
                # first gap is TTFT (connect + admission + prefill) and
                # can dominate an otherwise fast stream
                blips = [res["gaps"][i]
                         for _c, i in res["comments"]
                         if i < len(res["gaps"])]
                if blips:
                    resume_gaps.append(max(blips) * 1e3)
                if done and not (
                    done[0].get("cached_prefix_tokens", 0) > 0
                    or done[0].get("admit_windows", 0) > 1
                ):
                    failures.append(
                        "gen-failover stream %d resume did not ride "
                        "the prefix/chunked path: %r" % (i, done[0])
                    )
        if failed_over == 0:
            failures.append(
                "gen-failover: no stream failed over (the chaos kill "
                "never hit a pinned stream)"
            )

        # the controller replaced the chaos-killed replica. Wait for
        # the crash to be DETECTED first: the streams finish (failover
        # is fast) well before the supervision tick polls the corpse,
        # and wait_ready would sail through while the dead replica
        # still counts as ready
        deadline = time.monotonic() + 60
        crashed = False
        while time.monotonic() < deadline:
            if any(e.get("event") == "replica_crash"
                   for e in fleet_mod.load_events(workdir)):
                crashed = True
                break
            time.sleep(0.1)
        if not crashed:
            failures.append(
                "gen-failover: no replica_crash event after the kill"
            )
        try:
            ctrl.wait_ready(timeout=120)
        except Exception as e:  # noqa: BLE001
            failures.append("gen-failover pool never recovered: %r" % e)

        # strict gate + resume-admission facts, fleet-wide
        steady = resumes = scraped = 0
        for info in ctrl.replica_info():
            port = info.get("metrics_port")
            if not port or info["state"] != "ready":
                continue
            try:
                with urllib.request.urlopen(
                    "http://127.0.0.1:%d/metrics" % port, timeout=5
                ) as r:
                    parsed = _reg.parse_prometheus(
                        r.read().decode("utf-8"))
                scraped += 1
                steady += int(parsed.get(
                    ("serving_steady_recompiles", ""), 0))
                resumes += int(parsed.get(
                    ("decode_resume_admissions", ""), 0))
            except Exception as e:  # noqa: BLE001
                failures.append(
                    "gen-failover metrics scrape failed: %r" % e)
        if not scraped:
            failures.append("gen-failover: no replica metrics scraped")
        if steady != 0:
            failures.append(
                "gen-failover: %d steady-state recompiles under the "
                "armed strict gate" % steady
            )
        if failed_over and resumes == 0:
            failures.append(
                "gen-failover: failovers happened but no replica "
                "counted a resume admission"
            )
        report["generate_failover"] = {
            "streams": len(streams),
            "failed_over": failed_over,
            "resume_admissions": resumes,
            "steady_recompiles": steady,
            "resume_blip_ms": (round(max(resume_gaps), 1)
                               if resume_gaps else None),
            "wall_s": round(time.monotonic() - t0, 1),
        }
    finally:
        try:
            ctrl.stop()
        except Exception as e:  # noqa: BLE001
            failures.append(
                "gen-failover controller stop failed: %r" % e)


def run_kv_tier_trial(tmp, model_dir, report, failures, fast):
    """Fleet KV tier, closed loop: (a) cache-affinity routing — three
    replicas under an 80%-shared-prefix load must serve hits with a
    fleet mean TTFT within 1.5x of a single warmed replica's hit TTFT
    (the router steering repeats to the replica already holding the
    chain); (b) spill churn — a device index squeezed to one block
    spills every chain to host, and H2D re-admission must still beat
    chunked re-prefill past the banked crossover (~2 blocks; PERF.md).
    Every stream stays token-exact against an in-process oracle and
    the strict compile gate stays at zero fleet-wide."""
    import numpy as np

    from paddle_tpu.fluid import flags as _flags
    from paddle_tpu.observability import registry as _reg
    from paddle_tpu.serving.fleet import FleetController
    from paddle_tpu.serving.replica import build_gpt_decode_engine

    spec = {"seed": 17, "vocab_size": 97, "hidden_size": 32,
            "num_layers": 2, "num_heads": 2, "intermediate_size": 64,
            "max_len": 48, "slots": 8, "prefill_buckets": [8, 16, 48]}
    oracle_engine = build_gpt_decode_engine(spec).start()
    rs = np.random.RandomState(31)
    shared = [int(t) for t in rs.randint(0, spec["vocab_size"], 24)]
    streams = []
    for i in range(10):
        if i < 8:  # 80% share the 24-token prefix
            prompt = shared + [int(t) for t in rs.randint(0, 97, 2)]
        else:
            prompt = [int(t) for t in rs.randint(0, 97, 26)]
        streams.append({"prompt": prompt})
    try:
        for s in streams:
            s["oracle"] = oracle_engine.generate(
                s["prompt"], max_new_tokens=4).tokens(timeout=120)
    finally:
        oracle_engine.stop()

    workdir = os.path.join(tmp, "fleet_kv")
    kv_env = {
        "FLAGS_serving_strict_compiles": "1",
        "FLAGS_decode_block_size": "8",
        "FLAGS_decode_prefill_chunk": "8",
        "FLAGS_decode_prefix_cache_mb": "2",
        "FLAGS_kv_tier_host_mb": "4",
        "FLAGS_obs_snapshot_interval_s": "1.0",
    }
    ctrl = FleetController(
        model_dir=model_dir, workdir=workdir, replicas=3,
        replica_env=kv_env, autoscale=False, seed=0,
        replica_args=["--gpt-decode", json.dumps(spec)],
    )
    t0 = time.monotonic()
    ctrl.start()
    try:
        ctrl.wait_ready(count=3, timeout=180 if fast else 300)
        url = ctrl.router.url("/v1/generate")

        def one(target_url, s):
            body = dict(prompt_ids=s["prompt"], max_new_tokens=4,
                        deadline_ms=60000)
            _st, events, _c, gaps, _h = _sse_collect(
                target_url, body, timeout=90)
            toks = [e["token"] for e in events if "token" in e]
            done = next((e for e in events if e.get("done")), {})
            return toks, done, (gaps[0] * 1e3 if gaps else None)

        # warm wave: seed the caches wherever the router lands them
        for s in streams:
            toks, _d, _t = one(url, s)
            if toks != s["oracle"]:
                failures.append(
                    "kv-tier warm stream diverged: %r != %r"
                    % (toks, s["oracle"]))
        # let the router's health sweep pick up the new adverts
        time.sleep(1.2)

        hit_ttfts, hits = [], 0
        for s in streams:
            toks, done, ttft = one(url, s)
            if toks != s["oracle"]:
                failures.append(
                    "kv-tier measure stream diverged: %r != %r"
                    % (toks, s["oracle"]))
            if done.get("cached_prefix_tokens", 0) > 0:
                hits += 1
                if ttft is not None:
                    hit_ttfts.append(ttft)
        if hits < len(streams) // 2:
            failures.append(
                "kv-tier: only %d/%d measure streams hit the prefix "
                "cache" % (hits, len(streams)))

        # single-replica hit baseline: one warmed backend, direct
        info = [i for i in ctrl.replica_info() if i["state"] == "ready"]
        base_ttft = None
        if info:
            direct = "http://127.0.0.1:%d/v1/generate" \
                % info[0]["gateway_port"]
            s0 = streams[0]
            one(direct, s0)  # warm this exact replica
            samples = []
            for _ in range(3):
                _t, _d, ttft = one(direct, s0)
                if ttft is not None:
                    samples.append(ttft)
            base_ttft = sorted(samples)[len(samples) // 2] \
                if samples else None
        fleet_mean = (sum(hit_ttfts) / len(hit_ttfts)
                      if hit_ttfts else None)
        if fleet_mean is not None and base_ttft is not None:
            if fleet_mean > 1.5 * max(base_ttft, 2.0):
                failures.append(
                    "throughput: kv-tier fleet mean hit TTFT %.1fms "
                    "exceeds 1.5x single-replica hit TTFT %.1fms"
                    % (fleet_mean, base_ttft))
        else:
            failures.append("kv-tier: no TTFT samples collected")

        # the router steered by affinity, and /backends says how
        aff_hits = int(_reg.snapshot()["counters"].get(
            "router_affinity_hits", 0))
        if aff_hits == 0:
            failures.append("kv-tier: router never scored an affinity "
                            "hit under a shared-prefix load")
        with urllib.request.urlopen(ctrl.router.url("/backends"),
                                    timeout=5) as r:
            backends = json.loads(r.read().decode()).get("backends", [])
        if not any(b.get("prefix_heads") for b in backends):
            failures.append("kv-tier: no backend advertises prefix "
                            "heads on /backends")
        for key in ("advert_block", "affinity_score", "role"):
            if backends and key not in backends[0]:
                failures.append("kv-tier: /backends rows missing %r"
                                % key)

        # strict gate + spill traffic, fleet-wide
        steady = spills = readmits = scraped = 0
        for i in info:
            port = i.get("metrics_port")
            if not port:
                continue
            try:
                with urllib.request.urlopen(
                    "http://127.0.0.1:%d/metrics" % port, timeout=5
                ) as r:
                    parsed = _reg.parse_prometheus(
                        r.read().decode("utf-8"))
                scraped += 1
                steady += int(parsed.get(
                    ("serving_steady_recompiles", ""), 0))
                spills += int(parsed.get(("kv_tier_spills", ""), 0))
                readmits += int(parsed.get(("kv_tier_readmits", ""), 0))
            except Exception as e:  # noqa: BLE001
                failures.append("kv-tier metrics scrape failed: %r" % e)
        if not scraped:
            failures.append("kv-tier: no replica metrics scraped")
        if steady != 0:
            failures.append(
                "kv-tier: %d steady-state recompiles under the armed "
                "strict gate" % steady)
        report["kv_tier"] = {
            "streams": len(streams),
            "measure_hits": hits,
            "fleet_mean_hit_ttft_ms": (round(fleet_mean, 1)
                                       if fleet_mean else None),
            "single_replica_hit_ttft_ms": (round(base_ttft, 1)
                                           if base_ttft else None),
            "router_affinity_hits": aff_hits,
            "fleet_spills": spills,
            "fleet_readmits": readmits,
            "steady_recompiles": steady,
            "wall_s": round(time.monotonic() - t0, 1),
        }
    finally:
        try:
            ctrl.stop()
        except Exception as e:  # noqa: BLE001
            failures.append("kv-tier controller stop failed: %r" % e)

    # ---- spill churn: re-admission vs chunked re-prefill -------------
    # device index squeezed to ONE block => every admitted chain spills
    # to host and comes back H2D on the next admission. Past the banked
    # crossover (PERF.md: ~2 blocks of 8) that round-trip must beat
    # re-running chunked prefill over the prefix.
    churn_spec = {"seed": 17, "vocab_size": 97, "hidden_size": 64,
                  "num_layers": 4, "num_heads": 4,
                  "intermediate_size": 128, "max_len": 96, "slots": 8,
                  "prefill_buckets": [8, 16, 48, 96]}
    saved = {k: _flags.get_flag(k) for k in
             ("decode_prefix_cache_mb", "decode_block_size",
              "decode_prefill_chunk", "kv_tier_host_mb")}
    engR = engP = None
    try:
        _flags.set_flags({
            "FLAGS_decode_prefix_cache_mb": 8.0,
            "FLAGS_decode_block_size": 8,
            "FLAGS_decode_prefill_chunk": 8,
            "FLAGS_kv_tier_host_mb": 8.0,
        })
        engR = build_gpt_decode_engine(churn_spec).start()
        engR.pindex.max_blocks = 1  # force evict->spill on every chain
        _flags.set_flags({"FLAGS_kv_tier_host_mb": 0.0})
        engP = build_gpt_decode_engine(churn_spec).start()
        engP.pindex.max_blocks = 0  # nothing cached: always re-prefill

        def ttft_ms(eng, prompt, n=5):
            ts = []
            for _ in range(n):
                t1 = time.monotonic()
                eng.generate(list(prompt),
                             max_new_tokens=1).tokens(timeout=60)
                ts.append((time.monotonic() - t1) * 1e3)
            return sorted(ts)[len(ts) // 2]

        rows = []
        for ln in ((16, 48) if fast else (8, 16, 32, 48, 64, 80)):
            prefix = [int(t) for t in rs.randint(0, 97, ln)]
            # warm: prefill once; the squeezed index spills it to host
            wa = engR.generate(prefix + [3],
                               max_new_tokens=2).tokens(timeout=60)
            wb = engP.generate(prefix + [3],
                               max_new_tokens=2).tokens(timeout=60)
            if wa != wb:
                failures.append(
                    "kv-tier churn diverged at len %d: %r != %r"
                    % (ln, wa, wb))
            rows.append({
                "prefix_tokens": ln,
                "readmit_ttft_ms": round(
                    ttft_ms(engR, prefix + [5]), 1),
                "reprefill_ttft_ms": round(
                    ttft_ms(engP, prefix + [5]), 1),
            })
        past = [r for r in rows if r["prefix_tokens"] >= 48]
        for r in past:
            if r["readmit_ttft_ms"] >= r["reprefill_ttft_ms"]:
                failures.append(
                    "throughput: kv-tier re-admission (%.1fms) did not "
                    "beat chunked re-prefill (%.1fms) at %d tokens — "
                    "past the banked crossover"
                    % (r["readmit_ttft_ms"], r["reprefill_ttft_ms"],
                       r["prefix_tokens"]))
        st = engR.stats().get("kv_tier") or {}
        if not st.get("spills") or not st.get("readmits"):
            failures.append(
                "kv-tier churn moved no blocks through the host tier: "
                "%r" % st)
        report["kv_tier_churn"] = {
            "rows": rows,
            "spills": st.get("spills"),
            "readmits": st.get("readmits"),
        }
    finally:
        for eng in (engR, engP):
            try:
                if eng is not None:
                    eng.stop()
            except Exception:  # noqa: BLE001
                pass
        _flags.set_flags({"FLAGS_" + k: v for k, v in saved.items()})


# -- controller-durability trial (ISSUE 19) ---------------------------------
#
# The controller must die by SIGKILL with no drain, so it runs in a
# RUNNER subprocess (this same script, hidden ``--runner`` mode) while
# the probe process plays the client fleet-operator: driving SSE load
# direct to the replica gateways through the headless window, killing a
# replica while nobody supervises, then restarting the runner and
# auditing the adoption from the journal + event log.

GPT_SPEC = {"seed": 29, "vocab_size": 97, "hidden_size": 32,
            "num_layers": 2, "num_heads": 2, "intermediate_size": 64,
            "max_len": 48, "slots": 8, "prefill_buckets": [8, 16, 48]}


def run_runner(args):
    """``--runner`` child: a real FleetController over ``--workdir``.
    ``serve`` supervises until the ``arm_kill`` file appears (then arms
    the chaos controller-kill fault via flags — the next supervision
    tick SIGKILLs this process; the marker dir makes it one-shot, so a
    RESTARTED runner that re-arms never re-fires) or ``stop_runner``
    appears (clean stop, exit 0). ``rollout`` deploys ``--deploy-dir``
    and SIGKILLs itself the moment the journaled rollout phase reaches
    ``--kill-at-phase``."""
    from paddle_tpu.checkpoint import modeldir as _modeldir
    from paddle_tpu.fluid import flags as _flags
    from paddle_tpu.serving.fleet import FleetController

    replica_env = {
        "FLAGS_serving_strict_compiles": "1",
        "FLAGS_obs_snapshot_interval_s": "1.0",
    }
    kwargs = {}
    if args.gpt_decode:
        kwargs["replica_args"] = ["--gpt-decode", args.gpt_decode]
    ctrl = FleetController(
        model_dir=args.model_dir, workdir=args.workdir,
        replicas=args.replicas, replica_env=replica_env,
        autoscale=False, seed=0,
        # generous replica-lease TTL: 3 replicas + stream load on a
        # 2-core box can starve a serve loop past the 5s default, and
        # a false lease expiry would corrupt the adoption arithmetic
        lease_ttl_s=15.0,
        **kwargs,
    )
    ctrl.start()
    ctrl.wait_ready(timeout=240)
    _modeldir.commit_json(args.ready_file, {
        "pid": os.getpid(),
        "router_port": ctrl.router.port,
    })
    if args.runner == "rollout":
        dep_err = []

        def _deploy():
            try:
                ctrl.deploy(args.deploy_dir)
            except Exception as e:  # noqa: BLE001 - surfaced below
                dep_err.append(repr(e))

        th = threading.Thread(target=_deploy, daemon=True)
        th.start()
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            meta = ctrl._rollout_meta
            if (isinstance(meta, dict)
                    and meta.get("phase") == args.kill_at_phase):
                os.kill(os.getpid(), signal.SIGKILL)
            if not th.is_alive():
                print("RUNNER rollout finished before the %r kill: %r"
                      % (args.kill_at_phase, dep_err), flush=True)
                return 1
            time.sleep(0.001)
        print("RUNNER rollout never reached phase %r"
              % args.kill_at_phase, flush=True)
        return 1
    arm = os.path.join(args.workdir, "arm_kill")
    stop = os.path.join(args.workdir, "stop_runner")
    armed = False
    while True:
        if not armed and os.path.exists(arm):
            _flags.set_flags({
                "FLAGS_chaos_kill_controller_after_s": 0.001,
                "FLAGS_chaos_marker_dir":
                    os.path.join(args.workdir, "chaos_markers"),
            })
            armed = True
        if os.path.exists(stop):
            ctrl.stop()
            return 0
        time.sleep(0.05)


def _spawn_runner(mode, workdir, model_dir, ready_file, replicas,
                  gpt_decode=None, kill_at_phase=None, deploy_dir=None):
    cmd = [sys.executable, os.path.abspath(__file__), "--runner", mode,
           "--workdir", workdir, "--model-dir", model_dir,
           "--ready-file", ready_file, "--replicas", str(replicas)]
    if gpt_decode:
        cmd += ["--gpt-decode", gpt_decode]
    if kill_at_phase:
        cmd += ["--kill-at-phase", kill_at_phase]
    if deploy_dir:
        cmd += ["--deploy-dir", deploy_dir]
    return subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )


def _await_file(path, timeout, what, failures):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.exists(path):
            try:
                with open(path) as f:
                    return json.load(f)
            except (OSError, ValueError):
                pass  # torn mid-commit: stale-until-rewritten
        time.sleep(0.1)
    failures.append("controller-crash: %s never appeared (%.0fs)"
                    % (what, timeout))
    return None


def run_controller_crash_trial(tmp, report, failures, fast):
    """Kill the CONTROLLER (not a replica) mid-load and demand the
    durability bars: headless serving is client-invisible, restart
    adopts instead of respawning, a headless replica death is detected
    and replaced under the journaled budget, a double-start is refused,
    and an interrupted rollout lands consistent on either side of the
    flip. Failures are UNPREFIXED: every bar here is correctness — a
    squeezed box earns no retry."""
    import numpy as np

    from paddle_tpu import inference
    from paddle_tpu.checkpoint import modeldir
    from paddle_tpu.observability import registry as _reg
    from paddle_tpu.serving import fleet as fleet_mod
    from paddle_tpu.serving.fleet import (FleetController, FleetLockError,
                                          read_fleet_state)
    from paddle_tpu.serving.replica import build_gpt_decode_engine

    t0 = time.monotonic()
    cc = {}
    workdir = os.path.join(tmp, "fleet_ctl_crash")
    model_dir = os.path.join(tmp, "export_v1")

    # the uninterrupted oracle, same seeded spec as every replica
    oracle_engine = build_gpt_decode_engine(GPT_SPEC).start()
    rs = np.random.RandomState(41)
    streams = []
    for i in range(6):
        prompt = [int(t) for t in rs.randint(0, GPT_SPEC["vocab_size"],
                                             9 + i)]
        knobs = ({} if i % 2 == 0 else
                 {"temperature": 1.2, "top_k": 16, "seed": 300 + i})
        streams.append({"prompt": prompt, "knobs": knobs})
    try:
        for s in streams:
            s["oracle"] = oracle_engine.generate(
                s["prompt"], max_new_tokens=8, **s["knobs"]
            ).tokens(timeout=120)
    finally:
        oracle_engine.stop()

    def run_stream(s, port):
        body = dict(prompt_ids=s["prompt"], max_new_tokens=8,
                    deadline_ms=60000, **s["knobs"])
        try:
            _st, events, _c, _g, _h = _sse_collect(
                "http://127.0.0.1:%d/v1/generate" % port, body,
                timeout=90)
        except Exception as e:  # noqa: BLE001 - surfaced below
            return {"error": repr(e)}
        toks = [e["token"] for e in events if "token" in e]
        errs = [e for e in events if "error" in e]
        if errs:
            return {"error": "in-band %r" % errs[:1]}
        if toks != s["oracle"]:
            return {"error": "diverged %r != %r" % (toks, s["oracle"])}
        return {}

    def read_endpoint(rid):
        try:
            with open(os.path.join(workdir, "endpoints",
                                   "replica_%d.json" % rid)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    # ---- phase A: 3-replica GPT fleet; SIGKILL the controller --------
    ready1 = os.path.join(tmp, "ctl_ready_1.json")
    runner = _spawn_runner("serve", workdir, model_dir, ready1,
                           replicas=3, gpt_decode=json.dumps(GPT_SPEC))
    runner2 = None
    try:
        if _await_file(ready1, 300, "serve runner ready", failures) is None:
            raise RuntimeError("runner never came up")
        eps = {rid: read_endpoint(rid) for rid in (0, 1, 2)}
        if not all(isinstance(e, dict) and e.get("gateway_port")
                   for e in eps.values()):
            failures.append("controller-crash: endpoint files "
                            "incomplete: %r" % eps)
            raise RuntimeError("no endpoints")
        # survivors 1 and 2 carry the client load; 0 dies headless
        survivor_ports = [eps[1]["gateway_port"], eps[2]["gateway_port"]]
        results = [None] * len(streams)

        def client(i, port):
            results[i] = run_stream(streams[i], port)

        # round 1: streams in flight WHILE the controller is killed
        ths = [threading.Thread(target=client,
                                args=(i, survivor_ports[i % 2]))
               for i in range(4)]
        for t in ths:
            t.start()
        with open(os.path.join(workdir, "arm_kill"), "w") as f:
            f.write("1")
        runner.wait(timeout=60)
        t_dead = time.monotonic()
        if runner.returncode != -signal.SIGKILL:
            failures.append(
                "controller-crash: runner exited %r, not SIGKILL"
                % runner.returncode)
        # a replica dies while NOBODY is supervising
        os.kill(eps[0]["pid"], signal.SIGKILL)
        # round 2: streams born fully headless
        for i in (4, 5):
            ths.append(threading.Thread(
                target=client, args=(i, survivor_ports[i % 2])))
            ths[-1].start()
        for t in ths:
            t.join()
        stream_errors = [(i, r["error"])
                         for i, r in enumerate(results)
                         if r and "error" in r]
        if stream_errors:
            failures.append(
                "controller-crash: %d/%d headless streams failed: %r"
                % (len(stream_errors), len(streams), stream_errors[:2]))
        cc["streams"] = len(streams)
        cc["stream_errors"] = len(stream_errors)

        # ---- phase C: restart; adopt survivors, replace the dead -----
        ready2 = os.path.join(tmp, "ctl_ready_2.json")
        runner2 = _spawn_runner("serve", workdir, model_dir, ready2,
                                replicas=3,
                                gpt_decode=json.dumps(GPT_SPEC))
        r2 = _await_file(ready2, 300, "recovery runner ready", failures)
        if r2 is None:
            raise RuntimeError("recovery runner never came up")
        cc["headless_window_s"] = round(time.monotonic() - t_dead, 1)
        ev = fleet_mod.load_events(workdir)
        rec = [e for e in ev if e.get("event") == "controller_recover"]
        cc["adopted"] = rec[-1]["adopted"] if rec else None
        cc["lost"] = rec[-1]["lost"] if rec else None
        cc["headless_ms"] = rec[-1]["headless_ms"] if rec else None
        if not rec or rec[-1]["adopted"] != 2:
            failures.append(
                "controller-crash: expected 2 adopted survivors, "
                "got %r" % (rec[-1] if rec else None))
        if not rec or rec[-1]["lost"] != 1:
            failures.append(
                "controller-crash: expected 1 journaled replica lost "
                "headless, got %r" % (rec[-1] if rec else None))
        if not rec or not rec[-1]["headless_ms"] or \
                rec[-1]["headless_ms"] <= 0:
            failures.append("controller-crash: headless_ms not "
                            "measured: %r" % (rec[-1] if rec else None))
        boots = [i for i, e in enumerate(ev)
                 if e.get("event") == "fleet_boot"]
        since_boot = ev[boots[-1]:] if boots else ev
        respawned = [e for e in since_boot
                     if e.get("event") == "replica_spawn"
                     and e.get("replacement")]
        cc["respawned"] = len(respawned)
        if len(respawned) != 1:
            failures.append(
                "controller-crash: expected exactly 1 replacement "
                "spawn after recovery, got %d" % len(respawned))

        # ---- split-brain guard: a second controller must refuse ------
        blocked = False
        try:
            dup = FleetController(
                model_dir=model_dir, workdir=workdir, replicas=3,
                autoscale=False, seed=0,
                replica_args=["--gpt-decode", json.dumps(GPT_SPEC)],
            )
            dup.start()
            dup.stop()  # should be unreachable
        except FleetLockError as e:
            blocked = True
            if e.pid != r2["pid"]:
                failures.append(
                    "controller-crash: lock error blames pid %r, the "
                    "live runner is %r" % (e.pid, r2["pid"]))
        except Exception as e:  # noqa: BLE001
            failures.append(
                "controller-crash: double start died with %r, not "
                "FleetLockError" % e)
        cc["split_brain_blocked"] = blocked
        if not blocked:
            failures.append("controller-crash: double-started "
                            "controller was NOT refused")

        # ---- the adopted pool serves through the NEW router ----------
        state = read_fleet_state(workdir)
        pool = (state or {}).get("replicas") or {}
        if len(pool) != 3:
            failures.append(
                "controller-crash: journal pool is %r, expected 3"
                % sorted(pool))
        res = run_stream(streams[0], r2["router_port"])
        if "error" in res:
            failures.append(
                "controller-crash: post-recovery routed stream "
                "failed: %r" % res["error"])

        # ---- strict gate across the adopted + respawned pool ---------
        steady = scraped = 0
        for rid in sorted(int(k) for k in pool):
            ep = read_endpoint(rid)
            port = (ep or {}).get("metrics_port")
            if not port:
                continue
            try:
                with urllib.request.urlopen(
                    "http://127.0.0.1:%d/metrics" % port, timeout=5
                ) as r:
                    parsed = _reg.parse_prometheus(
                        r.read().decode("utf-8"))
                scraped += 1
                steady += int(parsed.get(
                    ("serving_steady_recompiles", ""), 0))
            except Exception as e:  # noqa: BLE001
                failures.append(
                    "controller-crash metrics scrape failed: %r" % e)
        cc["steady_recompiles"] = steady
        if not scraped:
            failures.append("controller-crash: no replica metrics "
                            "scraped")
        if steady != 0:
            failures.append(
                "controller-crash: %d steady-state recompiles across "
                "the adopted pool" % steady)

        with open(os.path.join(workdir, "stop_runner"), "w") as f:
            f.write("1")
        if runner2.wait(timeout=120) != 0:
            failures.append(
                "controller-crash: recovery runner clean stop exited "
                "%r" % runner2.returncode)
        runner2 = None
    except RuntimeError:
        pass  # already booked a failure above
    finally:
        for p in (runner, runner2):
            if p is not None and p.poll() is None:
                p.kill()
                p.wait(timeout=30)

    # ---- phase D: rollout interrupted on both sides of the flip ------
    xd = np.random.RandomState(7).rand(1, 24).astype("float32")
    expected = {}
    for phase, want_version in (("spawning", 1), ("flipped", 2)):
        wd = os.path.join(tmp, "fleet_roll_%s" % phase)
        repo = os.path.join(tmp, "repo_roll_%s" % phase)
        modeldir.publish(os.path.join(tmp, "export_v1"), repo)
        key = "rollout_%s_version" % (
            "preflip" if phase == "spawning" else "postflip")
        cc[key] = None
        ready_r = os.path.join(tmp, "ctl_roll_%s_ready.json" % phase)
        roller = _spawn_runner(
            "rollout", wd, repo, ready_r, replicas=2,
            kill_at_phase=phase,
            deploy_dir=os.path.join(tmp, "export_v2"))
        rec_runner = None
        try:
            if _await_file(ready_r, 240, "rollout runner (%s)" % phase,
                           failures) is None:
                raise RuntimeError("rollout runner never came up")
            roller.wait(timeout=240)
            if roller.returncode != -signal.SIGKILL:
                failures.append(
                    "controller-crash: rollout(%s) runner exited %r, "
                    "not SIGKILL:\n%s"
                    % (phase, roller.returncode,
                       (roller.stdout.read() or "")[-500:]))
                raise RuntimeError("no kill")
            ready_r2 = os.path.join(
                tmp, "ctl_roll_%s_ready2.json" % phase)
            rec_runner = _spawn_runner("serve", wd, repo, ready_r2,
                                       replicas=2)
            r2 = _await_file(ready_r2, 240,
                             "rollout(%s) recovery ready" % phase,
                             failures)
            if r2 is None:
                raise RuntimeError("no recovery")
            ev = fleet_mod.load_events(wd)
            want_ev = ("rollout_abort" if phase == "spawning"
                       else "rollout_resume")
            if not any(e.get("event") == want_ev for e in ev):
                failures.append(
                    "controller-crash: rollout(%s) recovery logged no "
                    "%s" % (phase, want_ev))
            state = read_fleet_state(wd)
            got_v = ((state or {}).get("intent") or {}).get("version")
            cc[key] = got_v
            if got_v != want_version:
                failures.append(
                    "controller-crash: rollout(%s) landed on version "
                    "%r, expected %d" % (phase, got_v, want_version))
            vers = sorted(set(
                m.get("version")
                for m in ((state or {}).get("replicas") or {}).values()
            ))
            if vers != [want_version]:
                failures.append(
                    "controller-crash: rollout(%s) pool versions %r, "
                    "expected all %d" % (phase, vers, want_version))
            # the recovered fleet serves the landed version, exactly
            # (v1 = the published export_v1, v2 = the deployed
            # export_v2 — deploy() of a plain export dir serves it in
            # place, no publish)
            if want_version not in expected:
                pred = inference.create_paddle_predictor(
                    inference.AnalysisConfig(os.path.join(
                        tmp, "export_v%d" % want_version)))
                expected[want_version] = [np.asarray(o)
                                          for o in pred.run([xd])]
            from paddle_tpu.serving.gateway import (decode_tensor,
                                                    encode_tensor)
            st, b, h = _post(
                "http://127.0.0.1:%d/v1/infer" % r2["router_port"],
                {"inputs": [encode_tensor(xd)], "deadline_ms": 10000})
            got = ([decode_tensor(x) for x in b["outputs"]]
                   if st == 200 else None)
            if (st != 200
                    or int(h.get("X-Model-Version", 0)) != want_version
                    or not all(np.array_equal(g, e) for g, e in
                               zip(got, expected[want_version]))):
                failures.append(
                    "controller-crash: rollout(%s) recovered fleet "
                    "served wrong answer (status %r, version header "
                    "%r)" % (phase, st, h.get("X-Model-Version")))
            with open(os.path.join(wd, "stop_runner"), "w") as f:
                f.write("1")
            if rec_runner.wait(timeout=120) != 0:
                failures.append(
                    "controller-crash: rollout(%s) recovery runner "
                    "stop exited %r" % (phase, rec_runner.returncode))
            rec_runner = None
        except RuntimeError:
            pass  # already booked a failure above
        finally:
            for p in (roller, rec_runner):
                if p is not None and p.poll() is None:
                    p.kill()
                    p.wait(timeout=30)

    cc["wall_s"] = round(time.monotonic() - t0, 1)
    report["controller_crash"] = cc


def run_probe(fast=True, verbose=False, keep_workdir=False):
    import numpy as np

    from paddle_tpu import inference
    from paddle_tpu.checkpoint import modeldir
    from paddle_tpu.fluid import flags as _flags
    from paddle_tpu.serving import fleet as fleet_mod
    from paddle_tpu.serving.fleet import FleetController
    from paddle_tpu.serving.gateway import decode_tensor, encode_tensor

    report = {"schema_version": REPORT_SCHEMA_VERSION, "fast": bool(fast)}
    failures = []
    tmp = tempfile.mkdtemp(prefix="fleet_probe_")
    workdir = os.path.join(tmp, "fleet")
    repo = os.path.join(tmp, "repo")

    # -- two model versions + in-process oracles ---------------------------
    xd = build_model(os.path.join(tmp, "export_v1"), seed=1)
    build_model(os.path.join(tmp, "export_v2"), seed=2)
    v1, v1_dir = modeldir.publish(os.path.join(tmp, "export_v1"), repo)
    oracle = {}
    for v, d in ((1, v1_dir),):
        pred = inference.create_paddle_predictor(
            inference.AnalysisConfig(d)
        )
        oracle[v] = [np.asarray(o) for o in pred.run([xd])]

    # fleet policy: floor 2, ceiling 3, fast scrape cadence so the
    # closed loop fits the tier-1 budget. Each replica's capacity is
    # bounded by its per-tenant gateway rate limit (60 rps) — a
    # deliberately NON-CPU bottleneck, so on the 2-core driver box
    # adding a replica still adds real capacity: fleet throughput is
    # 60 rps x replicas per tenant, and the flood's 429 sheds are the
    # autoscaler's pressure signal (shed_delta in the scraped sample).
    # The cap is low enough that the pressure flood keeps shedding
    # even at 3 replicas — the pool must not go idle (and scale back
    # down) inside the post-scale-up measurement window.
    _flags.set_flags({
        "FLAGS_fleet_min_replicas": 2,
        "FLAGS_fleet_max_replicas": 3,
        "FLAGS_fleet_scale_interval_s": 0.4,
        "FLAGS_fleet_queue_high": 2.0,
        "FLAGS_fleet_queue_low": 0.5,
        "FLAGS_fleet_scale_up_ticks": 2,
        "FLAGS_fleet_scale_down_ticks": 6,
        "FLAGS_fleet_restart_backoff_s": 0.2,
        "FLAGS_router_health_interval_s": 0.25,
    })
    replica_env = {
        "FLAGS_serving_strict_compiles": "1",
        "FLAGS_serving_max_batch_size": "4",
        "FLAGS_serving_workers": "1",
        "FLAGS_serving_queue_depth": "64",
        "FLAGS_gateway_rate_limit_rps": "60",
        "FLAGS_gateway_rate_burst": "12",
        "FLAGS_obs_snapshot_interval_s": "1.0",
        # keep the WHOLE trial in the flight ring: the default 256 only
        # retains the tail of the flood, and a truncated recording is a
        # biased tape for the simulator to replay (--keep-workdir)
        "FLAGS_trace_flight_records": "8192",
    }
    body = {"inputs": [encode_tensor(xd)], "deadline_ms": 10000}

    ctrl = FleetController(
        model_dir=repo, workdir=workdir, replicas=2,
        replica_env=replica_env, autoscale=False, seed=0,
    )
    t_boot = time.monotonic()
    ctrl.start()
    url = None

    def check(resp_body, version):
        got = [decode_tensor(t) for t in resp_body["outputs"]]
        exp = oracle[version]
        return len(got) == len(exp) and all(
            np.array_equal(g, e) for g, e in zip(got, exp)
        )

    try:
        ctrl.wait_ready(timeout=120 if fast else 240)
        report["boot"] = {
            "replicas": 2,
            "ready_s": round(time.monotonic() - t_boot, 1),
        }
        url = ctrl.router.url("/v1/infer")

        # ---- router-hop overhead (PERF.md) ---------------------------
        # each phase uses its own tenant: the per-tenant rate buckets
        # (the capacity bound) must not couple phases to each other
        direct_port = ctrl.replica_info()[0]["gateway_port"]
        direct_url = "http://127.0.0.1:%d/v1/infer" % direct_port
        direct, routed = [], []
        for target, samples in ((direct_url, direct), (url, routed)):
            for _ in range(25):
                t0 = time.perf_counter()
                st, b, _h = _post(target, body,
                                  headers={"X-Tenant-Id": "ovh"})
                samples.append((time.perf_counter() - t0) * 1e3)
                if st != 200 or not check(b, 1):
                    failures.append("overhead phase: bad response "
                                    "(%s -> %s)" % (target, st))
                    break
                time.sleep(0.012)  # stay under the tenant rate bucket
        report["overhead"] = {
            "direct_p50_ms": _percentile(direct, 50),
            "router_p50_ms": _percentile(routed, 50),
            "hop_p50_ms": round(
                _percentile(routed, 50) - _percentile(direct, 50), 3
            ),
        }

        # ---- failover: SIGKILL a replica mid-load --------------------
        results = []
        res_lock = threading.Lock()
        stop_evt = threading.Event()

        def client(expect_versions, tag, pause=0.0):
            hdrs = {"X-Tenant-Id": tag}
            while not stop_evt.is_set():
                try:
                    st, b, h = _post(url, body, headers=hdrs, timeout=30)
                except Exception as e:  # noqa: BLE001
                    with res_lock:
                        results.append((time.monotonic(), -1, False, tag,
                                        repr(e)))
                    continue
                ok = False
                if st == 200:
                    ver = int(h.get("X-Model-Version", "0") or 0)
                    ok = ver in expect_versions and check(b, ver)
                with res_lock:
                    results.append((time.monotonic(), st, ok, tag, None))
                if pause:
                    time.sleep(pause)

        # 6 clients at ~38 rps total: comfortably under one replica's
        # 60 rps tenant bucket, so the kill window itself can never
        # manufacture a legitimate 429 — any non-200 is a DROP
        threads = [
            threading.Thread(target=client, args=((1,), "kill", 0.15))
            for _ in range(6)
        ]
        for t in threads:
            t.start()
        time.sleep(0.8)
        victim = ctrl.replica_info()[0]
        t_kill = time.monotonic()
        os.kill(victim["pid"], signal.SIGKILL)
        time.sleep(2.5)
        stop_evt.set()
        for t in threads:
            t.join()
        with res_lock:
            kill_res = [r for r in results if r[3] == "kill"]
        bad = [r for r in kill_res if r[1] != 200 or not r[2]]
        ctrl.wait_ready(timeout=120)
        recover_ms = (time.monotonic() - t_kill) * 1e3
        report["failover"] = {
            "requests": len(kill_res),
            "failed": len(bad),
            "killed_pid": victim["pid"],
            "recover_ms": round(recover_ms, 1),
        }
        if not kill_res:
            failures.append("failover phase produced no requests")
        if bad:
            failures.append(
                "replica kill dropped %d/%d client requests: %r"
                % (len(bad), len(kill_res), bad[:3])
            )
        events = fleet_mod.load_events(workdir)
        if not any(e.get("event") == "replica_crash" for e in events):
            failures.append("no replica_crash event after SIGKILL")

        # ---- autoscale up under queue pressure -----------------------
        # ~10x the 2-replica tenant capacity: sustained 429 sheds are
        # the pressure signal the autoscaler scrapes
        ctrl.autoscale = True
        results.clear()
        stop_evt.clear()
        threads = [
            threading.Thread(target=client, args=((1,), "press", 0.005))
            for _ in range(10)
        ]
        t_press = time.monotonic()
        for t in threads:
            t.start()
        t_up = None
        deadline = time.monotonic() + (60 if fast else 120)
        while time.monotonic() < deadline:
            if ctrl.ready_count() >= 3:
                t_up = time.monotonic()
                break
            time.sleep(0.05)
        if t_up is None:
            stop_evt.set()
            for t in threads:
                t.join()
            failures.append("queue pressure never scaled the pool up")
        else:
            time.sleep(2.7)  # measure with the 3rd replica serving
            stop_evt.set()
            for t in threads:
                t.join()
            with res_lock:
                press = [r for r in results if r[3] == "press"]
            errors = [r for r in press if r[1] not in (200, 429)]
            sheds = sum(1 for r in press if r[1] == 429)
            wrong = [r for r in press if r[1] == 200 and not r[2]]

            def rps(lo, hi):
                n = sum(1 for r in press
                        if r[1] == 200 and lo <= r[0] < hi)
                return n / max(1e-6, hi - lo)

            before_rps = rps(t_up - 2.2, t_up - 0.2)
            after_rps = rps(t_up + 0.5, t_up + 2.5)
            ratio = after_rps / max(1e-6, before_rps)
            report["autoscale"] = {
                "requests": len(press),
                "sheds_429": sheds,
                "errors": len(errors),
                "scale_up_ms": round((t_up - t_press) * 1e3, 1),
                "before_rps": round(before_rps, 1),
                "after_rps": round(after_rps, 1),
                "speedup": round(ratio, 3),
            }
            if errors or wrong:
                failures.append(
                    "pressure phase errors: %r" % (errors + wrong)[:3]
                )
            if not any(e.get("event") == "scale_up"
                       for e in fleet_mod.load_events(workdir)):
                failures.append("scale-up left no scale_up event")
            if ratio < 1.15:
                failures.append(
                    "throughput: scale-up did not raise throughput "
                    "(%.1f -> %.1f rps, %.2fx < 1.15x)"
                    % (before_rps, after_rps, ratio)
                )

        # ---- hysteresis scale-down with a live trickle ---------------
        results.clear()
        stop_evt.clear()
        trickle = threading.Thread(target=client,
                                   args=((1,), "down", 0.05))
        trickle.start()
        deadline = time.monotonic() + (45 if fast else 90)
        t_down0 = time.monotonic()
        while time.monotonic() < deadline:
            if ctrl.target == 2 and ctrl.ready_count() == 2:
                break
            time.sleep(0.05)
        down_ms = (time.monotonic() - t_down0) * 1e3
        stop_evt.set()
        trickle.join()
        with res_lock:
            down_res = [r for r in results if r[3] == "down"]
        bad = [r for r in down_res if r[1] != 200 or not r[2]]
        has_down = any(e.get("event") == "scale_down"
                       for e in fleet_mod.load_events(workdir))
        report["scale_down"] = {
            "happened": bool(has_down),
            "ms": round(down_ms, 1),
            "trickle_requests": len(down_res),
            "trickle_failed": len(bad),
        }
        if not has_down or ctrl.target != 2:
            failures.append("idle hysteresis never scaled back down")
        if bad:
            failures.append(
                "scale-down drain dropped %d/%d trickle requests: %r"
                % (len(bad), len(down_res), bad[:3])
            )

        # ---- zero-downtime rollout v1 -> v2 --------------------------
        v2, v2_dir = modeldir.publish(os.path.join(tmp, "export_v2"),
                                      repo)
        pred2 = inference.create_paddle_predictor(
            inference.AnalysisConfig(v2_dir)
        )
        oracle[2] = [np.asarray(o) for o in pred2.run([xd])]
        if all(np.array_equal(a, b)
               for a, b in zip(oracle[1], oracle[2])):
            failures.append("model versions are indistinguishable")
        results.clear()
        stop_evt.clear()
        rollers = [
            threading.Thread(target=client, args=((1, 2), "roll", 0.03))
            for _ in range(2)
        ]
        for t in rollers:
            t.start()
        t_roll = time.monotonic()
        deployed = ctrl.deploy(repo)
        roll_ms = (time.monotonic() - t_roll) * 1e3
        # post-flip traffic must be new-version only
        post = []
        for _ in range(8):
            st, b, h = _post(url, body, headers={"X-Tenant-Id": "post"})
            post.append((st, int(h.get("X-Model-Version", "0") or 0),
                         st == 200 and check(b, 2)))
            time.sleep(0.02)
        stop_evt.set()
        for t in rollers:
            t.join()
        with res_lock:
            roll_res = [r for r in results if r[3] == "roll"]
        bad = [r for r in roll_res if r[1] != 200 or not r[2]]
        post_bad = [p for p in post if p[0] != 200 or p[1] != 2
                    or not p[2]]
        report["rollout"] = {
            "deployed_version": deployed,
            "ms": round(roll_ms, 1),
            "during_requests": len(roll_res),
            "during_failed": len(bad),
            "post_requests": len(post),
            "post_wrong": len(post_bad),
        }
        if deployed != 2:
            failures.append("deploy returned version %r != 2" % deployed)
        if bad:
            failures.append(
                "rollout dropped or corrupted %d/%d in-flight requests: "
                "%r" % (len(bad), len(roll_res), bad[:3])
            )
        if post_bad:
            failures.append(
                "post-rollout traffic not all v2-correct: %r"
                % post_bad[:3]
            )
        ev = fleet_mod.load_events(workdir)
        if not any(e.get("event") == "rollout_done" for e in ev):
            failures.append("rollout left no rollout_done event")

        # ---- strict gate: 0 steady-state recompiles fleet-wide -------
        steady = {}
        for info in ctrl.replica_info():
            port = info.get("metrics_port")
            if not port or info["state"] != "ready":
                continue
            try:
                with urllib.request.urlopen(
                    "http://127.0.0.1:%d/metrics" % port, timeout=5
                ) as r:
                    text = r.read().decode("utf-8")
                from paddle_tpu.observability import registry as _reg

                steady[info["id"]] = int(_reg.parse_prometheus(text).get(
                    ("serving_steady_recompiles", ""), 0
                ))
            except Exception as e:  # noqa: BLE001
                failures.append("metrics scrape failed for replica %s: %r"
                                % (info["id"], e))
        report["strict"] = {
            "replicas_scraped": len(steady),
            "steady_recompiles": sum(steady.values()),
        }
        if not steady:
            failures.append("no replica metrics scraped")
        if sum(steady.values()) != 0:
            failures.append("%d steady-state recompiles across the fleet"
                            % sum(steady.values()))
    finally:
        try:
            ctrl.stop()
        except Exception as e:  # noqa: BLE001
            failures.append("controller stop failed: %r" % e)

    # ---- durable generations: mid-stream failover, token-exact -------
    _flags.set_flags({"FLAGS_router_generate_retries": 2})
    try:
        run_generate_failover_trial(
            tmp, os.path.join(tmp, "export_v1"), report, failures, fast
        )
    except Exception as e:  # noqa: BLE001 - the trial must report, not die
        failures.append("gen-failover trial crashed: %r" % e)

    # ---- fleet KV tier: affinity routing + host-spill churn ----------
    try:
        run_kv_tier_trial(
            tmp, os.path.join(tmp, "export_v1"), report, failures, fast
        )
    except Exception as e:  # noqa: BLE001 - the trial must report, not die
        failures.append("kv-tier trial crashed: %r" % e)

    # ---- controller durability: crash, adopt, reconcile --------------
    try:
        run_controller_crash_trial(tmp, report, failures, fast)
    except Exception as e:  # noqa: BLE001 - the trial must report, not die
        failures.append("controller-crash trial crashed: %r" % e)

    # ---- merged fleet report -----------------------------------------
    fr_path = os.path.join(workdir, "fleet_report.json")
    try:
        with open(fr_path) as f:
            fr = json.load(f)
        report["fleet_report"] = {
            "timeline_events": len(fr.get("replica_timeline", [])),
            "scale_ups": fr.get("scale_ups"),
            "scale_downs": fr.get("scale_downs"),
            "rollouts": len(fr.get("rollouts", [])),
            "crashes": fr.get("crashes"),
            "replicas_reporting": len(fr.get("per_replica", {})),
        }
        if not fr.get("replica_timeline"):
            failures.append("fleet_report has no replica timeline")
        if not fr.get("per_replica"):
            failures.append("fleet_report merged no replica snapshots")
        if not fr.get("scale_ups") or not fr.get("rollouts"):
            failures.append("fleet_report missing scale/rollout events")
    except (OSError, ValueError) as e:
        failures.append("fleet_report.json unreadable: %r" % e)

    if keep_workdir:
        # leave the flight dumps + fleet_report.json on disk so
        # ``tools/fleet_sim.py --obs-root <tmp>/fleet*/obs --compare``
        # can calibrate the simulator against this live run
        report["workdir"] = tmp
        print("WORKDIR %s" % tmp, flush=True)
    else:
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
    report["pass"] = not failures
    report["failures"] = failures
    if verbose:
        print(json.dumps(report, indent=1), file=sys.stderr)
    return report


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--fast", action="store_true",
                    help="tier-1 budget subset")
    ap.add_argument("--verbose", action="store_true")
    ap.add_argument("--keep-workdir", action="store_true",
                    help="don't delete the temp workdir; prints its "
                         "path so fleet_sim.py can replay the recording")
    # hidden: the controller-durability trial's runner child
    ap.add_argument("--runner", choices=("serve", "rollout"),
                    help=argparse.SUPPRESS)
    ap.add_argument("--workdir", help=argparse.SUPPRESS)
    ap.add_argument("--model-dir", help=argparse.SUPPRESS)
    ap.add_argument("--ready-file", help=argparse.SUPPRESS)
    ap.add_argument("--replicas", type=int, default=3,
                    help=argparse.SUPPRESS)
    ap.add_argument("--gpt-decode", help=argparse.SUPPRESS)
    ap.add_argument("--kill-at-phase", help=argparse.SUPPRESS)
    ap.add_argument("--deploy-dir", help=argparse.SUPPRESS)
    args = ap.parse_args(argv)
    if args.runner:
        return run_runner(args)
    report = run_probe(fast=args.fast, verbose=args.verbose,
                       keep_workdir=args.keep_workdir)
    print("REPORT " + json.dumps(report, sort_keys=True), flush=True)
    print("PROBE PASS" if report["pass"]
          else "PROBE FAIL: %s" % "; ".join(report["failures"]))
    return 0 if report["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
