"""Structural scan of the compiled training step's optimized HLO + cost
analysis (the PERF.md methodology, reproducible).

Builds the ResNet-50, BERT-base, or GPT-2-small training step exactly as
bench.py / bench_bert.py / bench_gpt.py do, compiles the executor's main
XLA segment ahead-of-time on the current backend, and prints ONE JSON
line:

  {"model", "batch", "backend", "flops", "bytes_accessed",
   "hlo_ops": {"transpose": N, "convert": N, "copy": N, "fusion": N,
               "dot": N, "convolution": N, "all-reduce": N}}

Usage (CPU structural scan — fusion hygiene and op census only):
  JAX_PLATFORMS=cpu python tools/hlo_scan.py --model resnet --batch 32
On a live TPU the same command (without JAX_PLATFORMS) gives the real
per-step FLOP / HBM-byte counts used for the MFU math in PERF.md.
NOTE: transpose/copy elimination is a TPU-backend layout-assignment
property — the CPU backend legitimately keeps them, so only the TPU run
can reproduce PERF.md's "0 transposes" claim.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build(model, batch, amp, remat, flash=False, seq=128):
    import numpy as np

    if model == "resnet":
        from paddle_tpu.models import resnet

        main, startup, feeds, loss, acc = resnet.build_resnet_train(
            depth=50, class_num=1000, image_size=224, use_amp=amp,
            recompute=remat,
        )
        rs = np.random.RandomState(0)
        feed = {
            "img": rs.rand(batch, 3, 224, 224).astype("float32"),
            "label": rs.randint(0, 1000, (batch, 1)).astype("int64"),
        }
    elif model == "bert":
        if remat:
            raise SystemExit(
                "--remat is only wired for resnet; a bert line would be a "
                "mislabeled non-remat census"
            )
        from paddle_tpu.models import bert

        cfg = bert.BertConfig()
        cfg.hidden_dropout = 0.0
        cfg.attention_dropout = 0.0
        cfg.use_flash_attention = flash
        S = seq
        main, startup, feeds, loss, acc = bert.build_bert_classifier(
            cfg, S, learning_rate=2e-5, use_amp=amp
        )
        rs = np.random.RandomState(0)
        feed = {
            "src_ids": rs.randint(0, cfg.vocab_size, (batch, S, 1)).astype("int64"),
            "pos_ids": np.tile(
                np.arange(S)[None, :, None], (batch, 1, 1)
            ).astype("int64"),
            "sent_ids": np.zeros((batch, S, 1), "int64"),
            "input_mask": np.ones((batch, S, 1), "float32"),
            "label": rs.randint(0, 2, (batch, 1)).astype("int64"),
        }
    elif model == "gpt":
        if remat:
            raise SystemExit(
                "--remat is only wired for resnet; a gpt line would be a "
                "mislabeled non-remat census"
            )
        from paddle_tpu.models import gpt

        cfg = gpt.GPTConfig(
            hidden_dropout=0.0, attention_dropout=0.0,
            use_flash_attention=flash,
            max_position_embeddings=max(1024, seq),
        )
        S = seq
        main, startup, feeds, loss = gpt.build_gpt_lm_train(
            cfg, S, use_amp=amp
        )
        rs = np.random.RandomState(0)
        feed = {
            "ids": rs.randint(0, cfg.vocab_size, (batch, S, 1)).astype("int64"),
            "pos_ids": np.tile(
                np.arange(S)[None, :, None], (batch, 1, 1)
            ).astype("int64"),
            "input_mask": np.ones((batch, S, 1), "float32"),
        }
    else:
        raise SystemExit("unknown model %r" % model)
    return main, startup, feed, loss


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet", choices=["resnet", "bert", "gpt"])
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--amp", type=int, default=1)
    ap.add_argument("--remat", type=int, default=0)
    ap.add_argument("--flash", type=int, default=0)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--out", default="", help="also write the JSON line here")
    args = ap.parse_args()

    def hb(msg):
        # watcher kills a hung scan at its hard timeout; heartbeats make
        # the log say WHICH stage the tunnel wedged in
        print("HB %s" % msg, file=sys.stderr, flush=True)

    import jax

    import bench

    bench.honor_jax_platforms(jax)

    # share the bench children's persistent XLA cache: when the ladder
    # already compiled this exact program in the same window, the census
    # compile is a cache hit instead of a fresh multi-minute tunnel
    # compile (the r5 hlo_bert scans died at the 700s cap exactly here)
    bench.enable_compilation_cache(jax)

    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import executor as _ex

    hb("build start (program construction)")
    prog, startup, feed, loss = build(
        args.model, args.batch, bool(args.amp), bool(args.remat),
        flash=bool(args.flash), seq=args.seq,
    )
    hb("build ok; device discovery next")
    # mirror bench.py's place choice: on a live TPU the lowering backend
    # (and with it the NHWC conv path) must match what bench.py compiles,
    # or the census describes a program the bench never runs
    place = (
        fluid.TPUPlace(0)
        if fluid.core.get_tpu_device_count() > 0
        else fluid.CPUPlace()
    )
    hb("device ok (%s); startup run next" % type(place).__name__)
    scope = fluid.core.Scope()
    exe = fluid.Executor(place)
    exe.run(startup, scope=scope)
    hb("startup ok; lowering main segment")

    cb = _ex._CompiledBlock(prog, 0, list(feed), [loss.name], place)
    xla = [p for k, _s, p in cb._plans if k == "xla"]
    # the training step is the LARGEST segment (feed/fetch host ops aside)
    plan = max(xla, key=lambda p: len(p["feeds"]) + len(p["mutable"])
               + len(p["const"]))

    import numpy as np

    feed_vals = tuple(feed[n] for n in plan["feeds"])
    mutable_vals = tuple(np.asarray(scope.get(n)) for n in plan["mutable"])
    const_map = {
        n: np.asarray(scope.get(n))
        for n in plan["const"]
        if scope.get(n) is not None
    }
    rng = jax.random.key(0)
    lowered = jax.jit(plan["raw_fn"]).lower(
        feed_vals, mutable_vals, (), const_map, rng
    )
    hb("lowered; compiling")
    compiled = lowered.compile()
    hb("compiled; cost analysis")

    # shared census library (observability/xla_stats.py): the always-on
    # device-plane telemetry and this one-off scan run the SAME cost
    # parsing + op-census regex, so they can never disagree. Output stays
    # byte-compatible with the pre-refactor scan.
    from paddle_tpu.observability import xla_stats

    census = xla_stats.executable_census(compiled)
    line = json.dumps({
        "model": args.model,
        "flash": bool(args.flash),
        "batch": args.batch,
        "seq": args.seq if args.model in ("bert", "gpt") else None,
        "backend": jax.default_backend(),
        "flops": census["flops"],
        "bytes_accessed": census["bytes_accessed"],
        "hlo_ops": xla_stats.interesting_ops(census["hlo_ops"]),
        "total_hlo_ops": census["total_hlo_ops"],
    })
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
