"""Closed-loop probe for the observability subsystem (ISSUE 5 acceptance).

Runs a short REAL train + serving workload with telemetry armed and then
verifies the three properties the subsystem promises:

  1. **Trace well-formedness** — the exported Chrome trace is valid
     JSON, carries spans from every wired layer (train step / executor /
     feeder / checkpoint snapshot + writer / serving dispatch +
     predictor / pserver RPC client / legacy RecordEvent), every span's
     claimed parent contains it in time on its thread, and per-thread
     events nest strictly (no partial overlap) — i.e. it loads in
     Perfetto as a sensible flame graph.
  2. **Metrics round-trip** — ``/metrics`` serves Prometheus text from
     which EVERY registered counter parses back to its exact live value,
     and every histogram exposes quantile + ``_sum``/``_count`` series;
     ``/healthz`` answers ok and ``/trace`` serves the timeline.
  3. **Overhead** — the tracer's cost on the step path, measured as the
     median step time over interleaved traced/untraced blocks on the
     SAME compiled program, is <2%.

Modes::

    python tools/obs_probe.py          # full: adds a supervised-gang
                                       # round (dist_crash_probe --fast)
                                       # and checks its merged
                                       # gang_report.json
    python tools/obs_probe.py --fast   # tier-1 subset (properties 1-3)

The fast subset runs inside tier-1 via tests/test_observability.py.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")
for _p in (REPO, TOOLS):
    if _p not in sys.path:
        sys.path.insert(0, _p)

REPORT_SCHEMA_VERSION = 1

# every layer the tracer is wired into -> the span name that proves it
EXPECTED_SPANS = {
    "train": "train_step",
    "exec": "executor_run",
    "feed": "feed_stage",
    "ckpt_snapshot": "ckpt_snapshot",
    "ckpt_write": "ckpt_write",
    "serving_dispatch": "serving_dispatch",
    "serving_predictor": "predictor_run",
    "rpc": "rpc_get_var",
    "legacy_record_event": "legacy_probe_event",
}


# -- workloads ---------------------------------------------------------------

def _run_train(tmp, steps=8, interval=3):
    """Real MultiTrainer loop: feeder + executor + interval checkpoints
    (+ one legacy RecordEvent, + a genuine RPC-client retry wrapper call)
    so every wired span fires."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu import checkpoint
    from paddle_tpu.fluid import profiler
    from paddle_tpu.fluid.ops import distributed_ops
    from paddle_tpu.fluid.trainer import MultiTrainer

    from ckpt_crash_probe import _StepDataset, _build

    fluid.set_flags({"FLAGS_ckpt_save_interval_steps": interval})
    main, startup, loss = _build()
    exe = fluid.Executor(fluid.CPUPlace())
    mgr = checkpoint.CheckpointManager(
        os.path.join(tmp, "ckpt"), keep_max=2
    )
    dataset = _StepDataset(
        [main.global_block().var("x"), main.global_block().var("y")],
        steps,
    )
    with profiler.RecordEvent("legacy_probe_event"):
        trained = MultiTrainer().train(
            exe, main, dataset, fetch_list=[loss], print_period=0,
            ckpt_manager=mgr, startup_program=startup,
        )
    mgr.close()
    # the pserver client's retry wrapper (the real rpc span host), with
    # a no-op payload: no sockets needed to prove the span fires
    distributed_ops._with_conn_retry("get_var(obs_probe)", lambda: b"ok")
    assert trained == steps, "train workload stopped at %d/%d" % (
        trained, steps
    )


def _run_serving(tmp, requests=6):
    """Tiny model through the full serving path (batcher -> buckets ->
    pool) so serving_dispatch/predictor_run spans and serving_* counters
    fire."""
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu import inference, serving

    d = os.path.join(tmp, "model")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.unique_name.guard():
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[8], dtype="float32")
            out = fluid.layers.softmax(fluid.layers.fc(x, size=3))
    scope = fluid.core.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup, scope=scope)
        fluid.io.save_inference_model(d, ["x"], [out], exe,
                                      main_program=main)
    pred = inference.create_paddle_predictor(inference.AnalysisConfig(d))
    server = serving.InferenceServer(
        pred, max_batch_size=4, batch_timeout_ms=1.0, num_workers=2
    )
    rng = np.random.RandomState(0)
    server.start(warmup_inputs=[rng.rand(1, 8).astype("float32")])
    try:
        for _ in range(requests):
            server.infer([rng.rand(1, 8).astype("float32")])
    finally:
        server.stop()


# -- property 1: trace well-formedness --------------------------------------

def _check_trace(tmp):
    from paddle_tpu.observability import trace

    path = trace.save_chrome_trace(os.path.join(tmp, "probe_trace.json"))
    with open(path) as f:
        doc = json.load(f)  # property: valid JSON on disk
    events = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert events, "trace exported no spans"
    names = {e["name"] for e in events}
    for layer, name in EXPECTED_SPANS.items():
        assert name in names, (
            "layer %r left no %r span (got %s)" % (layer, name,
                                                   sorted(names))
        )
    # claimed parents contain their children in time on the same thread
    spans = trace.get_spans()
    by_tid = {}
    for s in spans:
        by_tid.setdefault(s["tid"], []).append(s)
    parented = 0
    for s in spans:
        if not s["parent"]:
            continue
        parents = [
            p for p in by_tid[s["tid"]]
            if p["name"] == s["parent"]
            and p["start"] <= s["start"] and s["end"] <= p["end"]
        ]
        assert parents, (
            "span %r claims parent %r but no containing span exists"
            % (s["name"], s["parent"])
        )
        parented += 1
    assert parented, "no nested spans at all — nesting is untested"
    # strict per-thread nesting: sorted by start, spans either contain
    # or are disjoint — partial overlap would render as garbage
    for tid, ss in by_tid.items():
        stack = []
        for s in sorted(ss, key=lambda x: (x["start"], -x["end"])):
            while stack and s["start"] >= stack[-1]:
                stack.pop()
            assert not stack or s["end"] <= stack[-1], (
                "partial overlap on tid %d at span %r" % (tid, s["name"])
            )
            stack.append(s["end"])
    # nesting the timeline exists for: executor_run under train_step,
    # predictor_run under serving_dispatch
    parents = {(s["name"], s["parent"]) for s in spans}
    assert ("executor_run", "train_step") in parents
    assert ("predictor_run", "serving_dispatch") in parents
    return {"spans": len(spans), "layers": sorted(EXPECTED_SPANS)}


# -- property 2: /metrics round-trip ----------------------------------------

def _http_get(url, timeout=10.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read().decode("utf-8")


def _check_metrics_roundtrip(tmp):
    from paddle_tpu.fluid import profiler
    from paddle_tpu.observability import exporter, registry

    exp = exporter.Exporter(
        port=0, snapshot_dir=os.path.join(tmp, "obs"), rank=0
    ).start()
    try:
        health = json.loads(_http_get(exp.url("/healthz")))
        assert health["status"] == "ok", health
        text = _http_get(exp.url("/metrics"))
        # workloads are quiescent now, so live counters are stable:
        # every one must round-trip exactly through the text format
        parsed = registry.parse_prometheus(text)
        counters = profiler.get_counters()
        assert counters, "no counters registered — workloads ran?"
        for name, val in counters.items():
            key = (registry.prom_name(name), "")
            assert key in parsed, "counter %r missing from /metrics" % name
            assert parsed[key] == float(val), (
                "counter %r: /metrics says %r, live value %r"
                % (name, parsed[key], val)
            )
        hists = profiler.get_histograms()
        assert "train_step_ms" in hists and "serving_latency_ms" in hists
        for name, samples in hists.items():
            pn = registry.prom_name(name)
            assert parsed.get((pn + "_count", "")) == float(len(samples))
            for q in ("0.5", "0.95", "0.99"):
                assert (pn, 'quantile="%s"' % q) in parsed, (
                    "histogram %r lacks quantile %s" % (name, q)
                )
        trace_doc = json.loads(_http_get(exp.url("/trace")))
        assert trace_doc["traceEvents"], "/trace served an empty timeline"
        snap_path = exp.write_snapshot()
    finally:
        exp.stop()
    with open(snap_path) as f:
        snap = json.loads(f.readlines()[-1])
    assert snap["schema_version"] == registry.SCHEMA_VERSION
    assert snap["counters"] == {
        k: int(v) for k, v in profiler.get_counters().items()
    }
    return {"counters": len(counters), "histograms": len(hists)}


# -- property 3: tracer overhead --------------------------------------------

def _measure_overhead(pairs=100, warmup=15, span_bench_n=20000):
    """Tracer overhead on the step path, two ways on ONE compiled
    program (identical compile caches / allocator state):

    - **primary (the <2% gate)**: measured per-span cost (enabled
      enter/exit minus disabled, microbenchmarked over ``span_bench_n``
      iterations) x spans actually recorded per step / the median
      untraced step time. Deterministic to well under 0.1% — the effect
      being gated is a few µs against a multi-ms step, far below this
      shared CPU box's run-to-run step variance.
    - **secondary (reported, not gated)**: A/B medians over
      order-alternated traced/untraced step pairs. On a quiet box both
      agree; under load the A/B number is noise-dominated, which is
      exactly why it doesn't gate.
    """
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu.observability import trace

    from ckpt_crash_probe import _build

    main, startup, loss = _build(hidden=64)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    r = np.random.RandomState(7)
    feed = {
        "x": r.rand(64, 8).astype("float32"),
        "y": r.randint(0, 4, (64, 1)).astype("int64"),
    }

    def one_step():
        t0 = time.perf_counter()
        with trace.span("train_step", cat="train"):
            exe.run(main, feed=feed, fetch_list=[loss])
        return time.perf_counter() - t0

    def arm(enabled):
        fluid.set_flags({"FLAGS_obs_trace": enabled})
        return one_step()

    def span_cost(n):
        t0 = time.perf_counter()
        for _ in range(n):
            with trace.span("overhead_bench", cat="bench"):
                pass
        return (time.perf_counter() - t0) / n

    for _ in range(warmup):
        one_step()
    # spans per step on this path: count what one traced step records
    trace.reset()
    fluid.set_flags({"FLAGS_obs_trace": True})
    n_probe = 10
    for _ in range(n_probe):
        one_step()
    spans_per_step = len(trace.get_spans()) / float(n_probe)
    # paired A/B, order alternated within each pair to cancel drift +
    # position bias
    diffs, offs = [], []
    for i in range(pairs):
        if i % 2 == 0:
            a, b = arm(True), arm(False)
        else:
            b, a = arm(False), arm(True)
        diffs.append(a - b)
        offs.append(b)
    fluid.set_flags({"FLAGS_obs_trace": True})
    cost_on = span_cost(span_bench_n)
    fluid.set_flags({"FLAGS_obs_trace": False})
    cost_off = span_cost(span_bench_n)
    fluid.set_flags({"FLAGS_obs_trace": True})
    med_off = statistics.median(offs)
    span_us = max(cost_on - cost_off, 0.0)
    overhead_pct = span_us * spans_per_step / med_off * 100.0
    return {
        "span_cost_us": round(span_us * 1e6, 3),
        "spans_per_step": round(spans_per_step, 2),
        "step_ms_untraced": round(med_off * 1e3, 4),
        "overhead_pct": round(overhead_pct, 3),
        "ab_paired_diff_ms": round(statistics.median(diffs) * 1e3, 4),
        "ab_pairs": len(diffs),
    }


# -- full-mode extra: gang report closed loop -------------------------------

def _check_gang_report(tmp):
    """Run the elastic-training probe's fast subset and verify the
    supervisor emitted a merged gang report for a restarted gang."""
    import subprocess

    workdir = os.path.join(tmp, "gang")
    p = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "dist_crash_probe.py"),
         "--fast", "--workdir", workdir],
        cwd=REPO, capture_output=True, text=True, timeout=900,
    )
    assert p.returncode == 0, "dist_crash_probe failed:\n%s%s" % (
        p.stdout[-2000:], p.stderr[-2000:]
    )
    path = os.path.join(workdir, "kill_00", "gang_report.json")
    with open(path) as f:
        report = json.load(f)
    assert report["restarts"] >= 1 and report["outcome"] == "gang_done"
    assert report["ranks_reporting"] == [0, 1], report["ranks_reporting"]
    for r in ("0", "1"):
        assert report["per_rank"][r]["step_time_ms"]["count"] > 0
    return {"gang_restarts": report["restarts"],
            "ranks": report["ranks_reporting"]}


def run_probe(args):
    import tempfile

    from paddle_tpu.observability import trace

    tmp = args.workdir or tempfile.mkdtemp(prefix="obs_probe_")
    t0 = time.time()
    trace.reset()
    _run_train(tmp)
    _run_serving(tmp)
    report = {"workdir": tmp}
    report["trace"] = _check_trace(tmp)
    report["metrics"] = _check_metrics_roundtrip(tmp)
    report["overhead"] = _measure_overhead()
    if not args.fast:
        report["gang"] = _check_gang_report(tmp)
    report["wall_s"] = round(time.time() - t0, 1)
    report["schema_version"] = REPORT_SCHEMA_VERSION
    report["ts"] = time.time()
    report["ts_mono"] = time.monotonic()
    print("REPORT " + json.dumps(report, sort_keys=True), flush=True)
    ov = report["overhead"]
    assert ov["overhead_pct"] < 2.0, (
        "tracer overhead %.3f%% >= 2%% (%.3fus/span x %.1f spans/step"
        " on a %.3fms step)"
        % (ov["overhead_pct"], ov["span_cost_us"], ov["spans_per_step"],
           ov["step_ms_untraced"])
    )
    print(
        "PROBE PASS: %d spans across %d layers nest cleanly, %d counters"
        " + %d histograms round-trip /metrics, tracer overhead %.2f%%"
        " (%.2fus/span x %.1f spans/step on a %.2fms step; A/B paired"
        " diff %.4fms)%s (%.1fs)"
        % (report["trace"]["spans"], len(EXPECTED_SPANS),
           report["metrics"]["counters"], report["metrics"]["histograms"],
           ov["overhead_pct"], ov["span_cost_us"], ov["spans_per_step"],
           ov["step_ms_untraced"], ov["ab_paired_diff_ms"],
           "" if args.fast else "; gang report merged %d restarts"
           % report["gang"]["gang_restarts"],
           report["wall_s"])
    )
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--fast", action="store_true",
                    help="tier-1 subset: skip the supervised-gang round")
    ap.add_argument("--workdir", type=str, default=None)
    args = ap.parse_args(argv)
    return run_probe(args)


if __name__ == "__main__":
    sys.exit(main())
