"""CPU-runnable closed-loop probe for the autoregressive decode runtime.

Drives the KV-cache slot pool + continuous-batching engine
(paddle_tpu/serving/decode.py) — with prefix caching and chunked
prefill armed — against `gpt._reference_generate` (the
full-forward-per-token loop every GPT completion paid before this
subsystem existed) and asserts the decode acceptance bars:

- PARITY: engine output token-exact vs the oracle across prompt lengths,
  an EOS stop mid-stream, max-new-token truncation, and slot reuse after
  retirement (more requests than slots, churned through the pool);
- THROUGHPUT: >= 10x generated tokens/sec over the per-token-recompute
  baseline with 8 concurrent streams (the baseline serializes on the one
  device whatever its client concurrency, so its serial rate IS its
  8-stream rate);
- PREFIX CACHE (ISSUE 12): at a high prefix share (64 of 72 prompt
  tokens cached), a hit admission's TTFT beats a miss admission's by
  >= 2x — the cached prefix is COPIED (O(bytes)) instead of recomputed
  — and BOTH paths stay token-exact vs the oracle;
- CHUNKED PREFILL (ISSUE 12): while a max-bucket prompt admits as
  bucket-shaped resume windows, live streams' inter-token p99 stays
  under the monolithic counterfactual (one full-bucket prefill + one
  step — the stall a non-chunked admit inflicts), and the chunked
  prompt itself is token-exact;
- EVICTION CHURN: distinct prefixes overflowing the bounded block store
  force LRU evictions; an admission whose prefix was evicted falls
  through to the full-prefill path, still token-exact;
- ZERO RECOMPILES: with the PR 7 strict gate armed
  (`FLAGS_serving_strict_compiles`), the WHOLE schedule above — churned
  admissions/retirements, prefix hits, misses, evictions, chunked
  admits — finishes with `serving_steady_recompiles` unchanged: no
  compiled shape depends on slot liveness, block placement, or window
  offset;
- DECODE ENGINE V2 (ISSUE 16): a paged+speculative engine (block
  tables over one shared pool, k=4 draft/verify) runs the same parity
  gauntlet — miss, zero-copy prefix hit, chunked windows, resume,
  store eviction — token-exact vs the oracle, with the verify path
  exercised by the low-acceptance n-gram drafter (constant rejection
  rollback) AND by a recorded-continuation replay drafter at 90%
  accuracy, which must beat the legacy engine's per-stream rate on the
  identical workload; the whole v2 schedule adds ZERO steady-state
  recompiles (tables/positions are runtime data);
- METRICS: every decode_*/serving_slot_* counter/histogram/gauge —
  including the TTFT/inter-token histograms and prefix-cache counters —
  renders on the PR 5 exporter registry.

Run directly (prints one REPORT json line + PROBE PASS/FAIL)::

    JAX_PLATFORMS=cpu python tools/decode_probe.py --fast

or via tests/test_decode.py, which runs --fast as a tier-1 gate.
"""

import argparse
import json
import os
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPORT_SCHEMA_VERSION = 3


def run_probe(fast=True, verbose=False):
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import flags as _flags
    from paddle_tpu.fluid import profiler
    from paddle_tpu.models import gpt
    from paddle_tpu.observability import registry as obs_registry
    from paddle_tpu.serving.decode import DecodeEngine

    _flags.set_flags({"FLAGS_serving_strict_compiles": True})

    slots = 8
    max_len = 96 if fast else 160
    prefix_block = 32
    prefill_chunk = 16
    # sized so device compute (not per-run host dispatch) dominates both
    # loops — the regime the 10x bar is about; still compiles in seconds
    # on the CPU backend
    cfg = gpt.GPTConfig.tiny(
        hidden_dropout=0.0, attention_dropout=0.0,
        hidden_size=256, num_layers=2, intermediate_size=768,
    )
    cfg.max_position_embeddings = max_len
    # 12-block store: big enough for the shared-prefix trial, small
    # enough that the eviction trial's distinct prefixes overflow it
    prefix_mb = 12 * gpt.prefix_block_bytes(cfg, prefix_block) / 2.0 ** 20

    with fluid.unique_name.guard():
        infer, startup, _names, logits = gpt.build_gpt_infer(cfg, max_len)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    with fluid.executor.scope_guard(scope):
        exe.run(startup)

    def oracle(prompt):
        return gpt._reference_generate(
            exe, infer, logits, cfg, prompt, max_len, scope=scope
        )

    report = {"schema_version": REPORT_SCHEMA_VERSION, "fast": bool(fast),
              "slots": slots, "max_len": max_len,
              "prefix_block": prefix_block, "prefill_chunk": prefill_chunk}
    failures = []

    # ---- oracle outputs for parity (compiles the [1, max_len] program) ----
    rs = np.random.RandomState(7)
    prompts = [list(rs.randint(0, cfg.vocab_size, n))
               for n in (1, 7, 12)]
    oracle_out = {tuple(p): oracle(p) for p in prompts}

    # ---- engine up (warmup compiles prefill + resume ladders, the block
    # copy programs, and the decode step) ----
    engine = DecodeEngine(
        cfg, scope=scope, slots=slots, max_len=max_len,
        prefill_buckets=[16, max_len], param_program=infer,
        prefix_block=prefix_block, prefix_cache_mb=prefix_mb,
        prefill_chunk=prefill_chunk,
    ).start()
    try:
        c_warm = profiler.get_counters()

        # ---- parity: prompt lengths ----
        parity = {}
        for p in prompts:
            got = engine.generate(p).result(timeout=120)
            parity["len_%d" % len(p)] = got == oracle_out[tuple(p)]
        # EOS mid-stream: stop at (and including) a token the greedy
        # stream is known to emit a few steps in
        p = prompts[1]
        gen = oracle_out[tuple(p)][len(p):]
        eos = gen[3]
        stream = engine.generate(p, eos_id=eos)
        got = stream.tokens(timeout=120)
        parity["eos_midstream"] = (
            got == gen[: gen.index(eos) + 1]
            and stream.finish_reason == "eos"
        )
        # max-length truncation
        stream = engine.generate(p, max_new_tokens=5)
        parity["max_new_truncation"] = (
            stream.tokens(timeout=120) == gen[:5]
            and stream.finish_reason == "length"
        )
        # slot reuse after retirement: 2x slots sequential short requests
        # through the same pool, every one token-exact
        reuse_ok = True
        for i in range(2 * slots):
            p = prompts[i % len(prompts)]
            got = engine.generate(p, max_new_tokens=4).tokens(timeout=120)
            reuse_ok = reuse_ok and (
                got == oracle_out[tuple(p)][len(p):len(p) + 4]
            )
        parity["slot_reuse"] = reuse_ok
        report["parity"] = parity
        if not all(parity.values()):
            failures.append("parity: %r" % parity)

        # ---- prefix cache: shared-system-prompt trial. One miss
        # admission populates the store; hit admissions copy the cached
        # 64-token prefix and resume-prefill only the 8-token suffix —
        # TTFT must drop >= 2x, and both paths stay token-exact ----
        shared = list(rs.randint(0, cfg.vocab_size, 2 * prefix_block))
        miss_p = shared + list(rs.randint(0, cfg.vocab_size, 8))
        s_miss = engine.generate(miss_p, max_new_tokens=6)
        miss_toks = s_miss.tokens(timeout=120)
        miss_parity = miss_toks == oracle(miss_p)[len(miss_p):][:6]
        hit_ttfts, hit_parity, hit_cached = [], True, True
        for i in range(3):
            p = shared + list(rs.randint(0, cfg.vocab_size, 8))
            s = engine.generate(p, max_new_tokens=6)
            toks = s.tokens(timeout=120)
            if i == 0:  # one oracle check keeps the trial cheap
                hit_parity = toks == oracle(p)[len(p):][:6]
            hit_ttfts.append(s.ttft_ms)
            hit_cached = hit_cached and (
                s.cached_prefix_tokens == len(shared)
            )
        ttft_hit = sorted(hit_ttfts)[1]  # median of 3
        gain = s_miss.ttft_ms / max(ttft_hit, 1e-9)
        st = engine.stats()
        report["prefix"] = {
            "shared_tokens": len(shared),
            "prompt_tokens": len(miss_p),
            "ttft_miss_ms": round(s_miss.ttft_ms, 2),
            "ttft_hit_ms": round(ttft_hit, 2),
            "ttft_gain": round(gain, 2),
            "miss_parity": bool(miss_parity),
            "hit_parity": bool(hit_parity),
            "hit_cached_tokens_ok": bool(hit_cached),
            "hits": st["prefix_hits"],
            "cached_tokens": st["prefix_cached_tokens"],
        }
        if not (miss_parity and hit_parity and hit_cached):
            failures.append(
                "prefix parity: miss=%s hit=%s cached_ok=%s"
                % (miss_parity, hit_parity, hit_cached)
            )
        if gain < 2.0:
            failures.append("ttft gain %.2f < 2x (miss %.1fms hit %.1fms)"
                            % (gain, s_miss.ttft_ms, ttft_hit))

        # ---- chunked prefill: long-prompt interleave trial. Counter-
        # factual bound: a NON-chunked admit stalls every live stream
        # for (monolithic max-bucket prefill + one fused step) between
        # two of its tokens; chunked admission must keep the live p99
        # inter-token gap under that. Load-robust: best of 2 rounds
        # (external load on the shared 2-core box only ever adds) ----
        mono = []
        for _ in range(3):
            t0 = time.perf_counter()
            engine.session.prefill(0, list(rs.randint(
                0, cfg.vocab_size, max_len - 8)))
            mono.append((time.perf_counter() - t0) * 1e3)
        mono_ms = sorted(mono)[1]

        def interleave_round():
            live = [engine.generate(list(rs.randint(0, cfg.vocab_size, 4)),
                                    max_new_tokens=60) for _ in range(3)]
            stamps = [[] for _ in live]
            threads = [
                threading.Thread(
                    target=lambda i=i, s=s: [stamps[i].append(
                        time.monotonic()) for _ in s]
                )
                for i, s in enumerate(live)
            ]
            for t in threads:
                t.start()
            while min(len(v) for v in stamps) < 3:
                time.sleep(0.005)
            t_sub = time.monotonic()
            long_p = list(rs.randint(0, cfg.vocab_size, max_len - 8))
            s_long = engine.generate(long_p, max_new_tokens=4)
            long_toks = s_long.tokens(timeout=120)
            t_first = t_sub + s_long.ttft_ms / 1e3
            for t in threads:
                t.join()
            base_gaps, admit_gaps = [], []
            for v in stamps:
                for a, b in zip(v, v[1:]):
                    (admit_gaps if t_sub <= b <= t_first + 1e-3
                     else base_gaps).append((b - a) * 1e3)
            admit_gaps.sort()
            base_gaps.sort()
            p99 = admit_gaps[int(len(admit_gaps) * 0.99)] \
                if admit_gaps else float("inf")
            base = base_gaps[len(base_gaps) // 2] if base_gaps else 0.0
            return p99, base, long_p, long_toks, len(admit_gaps)

        best = None
        for _ in range(2):
            p99, base, long_p, long_toks, n_gaps = interleave_round()
            if best is None or p99 < best[0]:
                best = (p99, base, long_p, long_toks, n_gaps)
        p99, base, long_p, long_toks, n_gaps = best
        bound = mono_ms + base
        long_parity = long_toks == oracle(long_p)[len(long_p):][:4]
        report["chunked"] = {
            "long_prompt_tokens": len(long_p),
            "monolithic_prefill_ms": round(mono_ms, 2),
            "baseline_gap_ms": round(base, 2),
            "intertoken_p99_ms": round(p99, 2),
            "bound_ms": round(bound, 2),
            "admit_gaps": n_gaps,
            "long_parity": bool(long_parity),
        }
        if not long_parity:
            failures.append("chunked long-prompt parity failed")
        if n_gaps < 3:
            failures.append(
                "chunked admit produced only %d live gaps — streams did "
                "not interleave" % n_gaps
            )
        if p99 >= bound:
            failures.append(
                "intertoken p99 %.1fms >= monolithic counterfactual "
                "%.1fms while a max-bucket prompt admitted" % (p99, bound)
            )

        # ---- eviction churn: 8 distinct 64-token prefixes publish 16
        # blocks into the 12-block store — LRU must evict; an admission
        # whose prefix was evicted falls through to full prefill ----
        ev0 = profiler.get_counters().get("decode_prefix_evictions", 0)
        first_pre = list(rs.randint(0, cfg.vocab_size, 2 * prefix_block))
        churn_prefixes = [first_pre] + [
            list(rs.randint(0, cfg.vocab_size, 2 * prefix_block))
            for _ in range(7)
        ]
        evict_streams = [
            engine.generate(pre + [int(i)], max_new_tokens=2)
            for i, pre in enumerate(churn_prefixes)
        ]
        for s in evict_streams:
            s.tokens(timeout=120)
        evictions = (profiler.get_counters()
                     .get("decode_prefix_evictions", 0) - ev0)
        # the FIRST prefix is the LRU victim by now: re-admitting it is
        # a miss that must still be token-exact
        re_p = first_pre + [0]
        re_toks = engine.generate(re_p, max_new_tokens=4)\
            .tokens(timeout=120)
        evict_parity = re_toks == oracle(re_p)[len(re_p):][:4]
        report["evictions"] = {
            "evictions": int(evictions),
            "evicted_readmit_parity": bool(evict_parity),
            "store": engine.stats().get("prefix_store"),
        }
        if evictions < 1:
            failures.append("eviction churn produced no evictions")
        if not evict_parity:
            failures.append("post-eviction readmission parity failed")

        # ---- churn + throughput: 8 concurrent streams, requests
        # admitted/retired mid-flight under the strict gate. The shared
        # 2-core driver box drifts under external load (same finding as
        # serving_load_probe.py), so load-robust estimators: the
        # baseline takes the BEST of repeated short rounds (load only
        # ever subtracts throughput), and decode takes the best
        # >=0.7 s sliding window over the live decode_tokens counter —
        # the steady-state rate with every prefill stall inside the
        # window counted, without the admission ramp / drain tail ----
        churn_errors = 0
        base_prompt = list(rs.randint(0, cfg.vocab_size, max_len - 40))
        baseline_tps = 0.0

        def baseline_round():
            t0 = time.perf_counter()
            oracle(base_prompt)  # 40 full-forward tokens
            return 40 / (time.perf_counter() - t0)

        def tokens_now():
            return profiler.get_counters().get("decode_tokens", 0)

        baseline_tps = max(baseline_tps, baseline_round())
        n_requests = 36 if fast else 48
        churn = []
        for i in range(n_requests):
            p = prompts[i % len(prompts)]
            # staggered lengths churn the retirement order
            churn.append(engine.generate(
                p, max_new_tokens=24 + 8 * (i % 4)
            ))
        samples = [(time.perf_counter(), tokens_now())]
        while not all(s.done for s in churn):
            time.sleep(0.05)
            samples.append((time.perf_counter(), tokens_now()))
        samples.append((time.perf_counter(), tokens_now()))
        decode_tokens_total = 0
        for s in churn:
            try:
                decode_tokens_total += len(s.tokens(timeout=300))
            except Exception:  # noqa: BLE001 - counted, fails the probe
                churn_errors += 1
        from bench import best_window_rate

        decode_tps = best_window_rate(samples, 0.7)
        baseline_tps = max(baseline_tps, baseline_round())
        c_end = profiler.get_counters()
        # the steady-recompile delta covers EVERYTHING since warmup:
        # parity, prefix hits/misses, chunked admits, evictions, churn
        steady = (c_end.get("serving_steady_recompiles", 0)
                  - c_warm.get("serving_steady_recompiles", 0))
        speedup = decode_tps / baseline_tps
        report["throughput"] = {
            "streams": slots,
            "requests": n_requests,
            "decode_tokens": decode_tokens_total,
            "decode_tps": round(decode_tps, 1),
            "baseline_tps": round(baseline_tps, 1),
            "speedup": round(speedup, 2),
        }
        report["strict"] = {
            "steady_recompiles": int(steady),
            "churn_errors": churn_errors,
            "gate_armed": True,
        }
        if churn_errors:
            failures.append("%d churned streams failed" % churn_errors)
        if steady != 0:
            failures.append("%d steady-state recompiles" % steady)
        if speedup < 10.0:
            failures.append("speedup %.2f < 10x" % speedup)

        # ---- decode engine v2 (ISSUE 16): paged KV + speculation ----
        # A second engine on the same params: block tables (block 16)
        # over one shared pool, chunked windows (chunk 16), a 4-block
        # zero-copy prefix store, and the k=4 speculative verify with a
        # swappable drafter. max_len shrinks by k-1 so verify positions
        # stay inside the model's position table.
        from paddle_tpu.serving.decode import _ngram_draft

        draft = {"fn": _ngram_draft}
        engine2 = DecodeEngine(
            cfg, scope=scope, slots=slots, max_len=max_len - 3,
            param_program=infer, block_size=16, spec_tokens=4,
            prefill_chunk=prefill_chunk,
            prefix_cache_mb=4 * gpt.paged_block_bytes(cfg, 16) / 2.0 ** 20,
            drafter=lambda h, k: draft["fn"](h, k),
        ).start()
        v2_warm = profiler.get_counters()
        paged_parity = {}
        # miss + chunked: a 40-token prompt tiles as 16/16/8 windows
        p_long = list(rs.randint(0, cfg.vocab_size, 40))
        full_long = oracle(p_long)
        s = engine2.generate(p_long, max_new_tokens=6)
        paged_parity["miss"] = (
            s.tokens(timeout=120) == full_long[40:46]
            and s.cached_prefix_tokens == 0
        )
        paged_parity["chunked_windows"] = s.admit_windows == 3
        # zero-copy hit: 2 whole blocks of the same prompt
        s = engine2.generate(p_long, max_new_tokens=6)
        paged_parity["hit"] = (
            s.tokens(timeout=120) == full_long[40:46]
            and s.cached_prefix_tokens == 32
        )
        # resume: re-prefill prompt + suffix, continue token-exact
        s = engine2.generate(p_long, max_new_tokens=6,
                             resume_tokens=full_long[40:43])
        paged_parity["resume"] = s.tokens(timeout=120) == full_long[43:46]
        # eviction churn: 8 distinct 40-token prompts publish 16 blocks
        # into the 4-block store; the first prompt's re-admission falls
        # through to full prefill, still exact
        ev_p = [list(rs.randint(0, cfg.vocab_size, 40)) for _ in range(8)]
        for q in ev_p:
            engine2.generate(q, max_new_tokens=2).tokens(timeout=120)
        paged_parity["evictions"] = engine2.pindex.evictions >= 1
        s = engine2.generate(ev_p[0], max_new_tokens=4)
        paged_parity["evicted_readmit"] = (
            s.tokens(timeout=120)
            == oracle(ev_p[0])[40:44]
        )
        report["paged_parity"] = {k: bool(v)
                                  for k, v in paged_parity.items()}
        if not all(paged_parity.values()):
            failures.append("paged parity: %r" % paged_parity)

        # speculative speedup: identical workload through the SAME v2
        # engine at verify width 1 and at full width, drafting the
        # width-1 run's recorded continuations at 90% accuracy — greedy
        # determinism makes the recordings the exact future, so the
        # ratio isolates speculation (same paged step, same pool, same
        # gathers) and prices fused verify + rollback at that
        # acceptance.  A legacy-engine round rides along as an
        # informational rate only: on hosts where the paged gather is
        # the dominant per-tick cost it measures runtime overhead, not
        # speculation, so no bar hangs off it.
        # Load-robust like the 10x bar: best sliding window both sides.
        spec_pool = [list(rs.randint(0, cfg.vocab_size, 12))
                     for _ in range(6)]
        n_spec = 32 if fast else 40
        spec_new = 72  # decode-dominated rounds: 12+72 < max_len-3

        def spec_round(eng):
            hs = [eng.generate(spec_pool[i % len(spec_pool)],
                               max_new_tokens=spec_new)
                  for i in range(n_spec)]
            samples = [(time.perf_counter(), tokens_now())]
            while not all(h.done for h in hs):
                time.sleep(0.02)
                samples.append((time.perf_counter(), tokens_now()))
            samples.append((time.perf_counter(), tokens_now()))
            for h in hs:
                h.tokens(timeout=300)
            return best_window_rate(samples, 0.5), hs

        legacy_tps, _ = spec_round(engine)
        engine2.set_spec_width(1)
        base_tps, base_hs = spec_round(engine2)
        recorded = {}
        for h in base_hs:
            recorded[tuple(h.prompt_ids)] = (
                list(h.prompt_ids) + h.tokens(timeout=10)
            )
        engine2.set_spec_width(4)
        drs = np.random.RandomState(11)

        def replay_draft(hist, k):
            fullc = recorded.get(tuple(hist[:12]))
            if fullc is None:
                return [0] * k
            d = list(fullc[len(hist):len(hist) + k])
            d += [0] * (k - len(d))
            return [t if drs.random_sample() < 0.9
                    else (int(t) + 1) % cfg.vocab_size for t in d]

        draft["fn"] = replay_draft
        spec_tps, spec_hs = spec_round(engine2)
        spec_parity = all(
            list(h.prompt_ids) + h.tokens(timeout=10)
            == recorded[tuple(h.prompt_ids)]
            for h in spec_hs
        )
        st2 = engine2.stats()
        spec_gain = spec_tps / max(base_tps, 1e-9)
        v2_steady = (profiler.get_counters()
                     .get("serving_steady_recompiles", 0)
                     - v2_warm.get("serving_steady_recompiles", 0))
        report["spec"] = {
            "legacy_tps": round(legacy_tps, 1),
            "base_tps": round(base_tps, 1),
            "spec_tps": round(spec_tps, 1),
            "spec_gain": round(spec_gain, 2),
            "spec_parity": bool(spec_parity),
            "acceptance": round(st2.get("spec_acceptance", 0.0), 3),
            "drafted": st2["spec_drafted"],
            "accepted": st2["spec_accepted"],
            "steady_recompiles": int(v2_steady),
            "pool": st2["paged"],
        }
        if not spec_parity:
            failures.append("spec streams diverged from legacy run")
        if st2.get("spec_acceptance", 0.0) <= 0.5:
            failures.append(
                "spec acceptance %.3f <= 0.5 at 90%% draft accuracy"
                % st2.get("spec_acceptance", 0.0)
            )
        # CPU bar: the width-k verify tick pays ~2x the width-1 tick
        # here (per-token forward compute is not free on host), so the
        # host-side ceiling at ~0.75 acceptance is ~1.6x; the >= 2x
        # acceptance criterion is carried by the accelerator bench rung
        # (gpt_decode_spec), where verify FLOPs ride idle MXU capacity.
        if spec_gain < 1.3:
            failures.append(
                "speedup from speculation %.2fx < 1.3x over the same "
                "engine at width 1 on the identical workload"
                % spec_gain
            )
        if v2_steady != 0:
            failures.append(
                "%d steady-state recompiles in the paged/spec schedule"
                % v2_steady
            )

        # ---- metrics on the exporter registry ----
        rendered = obs_registry.render_prometheus()
        gauges = obs_registry.gauge_values()
        need = ("decode_tokens", "decode_steps", "decode_prefills",
                "decode_requests", "decode_step_ms", "decode_prefill_ms",
                "decode_ttft_ms", "decode_intertoken_ms",
                "decode_prefix_hits", "decode_prefix_misses",
                "decode_prefix_cached_tokens", "decode_prefix_evictions",
                "decode_spec_drafted", "decode_spec_accepted",
                "serving_slot_admissions", "serving_slot_retirements")
        missing = [m for m in need if m not in rendered]
        for g in ("serving_slot_occupancy", "decode_queue_depth",
                  "decode_blocks_free", "decode_blocks_shared",
                  "decode_spec_acceptance"):
            if g not in gauges:
                missing.append(g)
        report["metrics"] = {"missing": missing}
        if missing:
            failures.append("metrics missing: %r" % missing)
    finally:
        engine.stop()
        if "engine2" in locals():
            engine2.stop()

    report["pass"] = not failures
    report["failures"] = failures
    if verbose:
        print(json.dumps(report, indent=1), file=sys.stderr)
    return report


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--fast", action="store_true",
                    help="tier-1 budget subset (< 30 s)")
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)
    report = run_probe(fast=args.fast, verbose=args.verbose)
    print("REPORT " + json.dumps(report, sort_keys=True), flush=True)
    print("PROBE PASS" if report["pass"]
          else "PROBE FAIL: %s" % "; ".join(report["failures"]))
    return 0 if report["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
