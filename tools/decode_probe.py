"""CPU-runnable closed-loop probe for the autoregressive decode runtime.

Drives the KV-cache slot pool + continuous-batching engine
(paddle_tpu/serving/decode.py) against `gpt._reference_generate` — the
full-forward-per-token loop every GPT completion paid before this
subsystem existed — and asserts the decode acceptance bars:

- PARITY: engine output token-exact vs the oracle across prompt lengths,
  an EOS stop mid-stream, max-new-token truncation, and slot reuse after
  retirement (more requests than slots, churned through the pool);
- THROUGHPUT: >= 10x generated tokens/sec over the per-token-recompute
  baseline with 8 concurrent streams (the baseline serializes on the one
  device whatever its client concurrency, so its serial rate IS its
  8-stream rate);
- ZERO RECOMPILES: with the PR 7 strict gate armed
  (`FLAGS_serving_strict_compiles`), a churned admission/retirement
  schedule (3x more requests than slots, staggered lengths) must finish
  with `serving_steady_recompiles` unchanged and no stream failed — no
  compiled shape depends on which slots are live;
- METRICS: every decode_*/serving_slot_* counter/histogram/gauge renders
  on the PR 5 exporter registry.

Run directly (prints one REPORT json line + PROBE PASS/FAIL)::

    JAX_PLATFORMS=cpu python tools/decode_probe.py --fast

or via tests/test_decode.py, which runs --fast as a tier-1 gate.
"""

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPORT_SCHEMA_VERSION = 1


def run_probe(fast=True, verbose=False):
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import flags as _flags
    from paddle_tpu.fluid import profiler
    from paddle_tpu.models import gpt
    from paddle_tpu.observability import registry as obs_registry
    from paddle_tpu.serving.decode import DecodeEngine

    _flags.set_flags({"FLAGS_serving_strict_compiles": True})

    slots = 8
    max_len = 96 if fast else 160
    # sized so device compute (not per-run host dispatch) dominates both
    # loops — the regime the 10x bar is about; still compiles in seconds
    # on the CPU backend
    cfg = gpt.GPTConfig.tiny(
        hidden_dropout=0.0, attention_dropout=0.0,
        hidden_size=256, num_layers=2, intermediate_size=768,
    )
    cfg.max_position_embeddings = max_len

    with fluid.unique_name.guard():
        infer, startup, _names, logits = gpt.build_gpt_infer(cfg, max_len)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    with fluid.executor.scope_guard(scope):
        exe.run(startup)

    def oracle(prompt):
        return gpt._reference_generate(
            exe, infer, logits, cfg, prompt, max_len, scope=scope
        )

    report = {"schema_version": REPORT_SCHEMA_VERSION, "fast": bool(fast),
              "slots": slots, "max_len": max_len}
    failures = []

    # ---- oracle outputs for parity (compiles the [1, max_len] program) ----
    rs = np.random.RandomState(7)
    prompts = [list(rs.randint(0, cfg.vocab_size, n))
               for n in (1, 7, 12)]
    oracle_out = {tuple(p): oracle(p) for p in prompts}

    # ---- engine up (warmup compiles prefill ladder + decode step) ----
    engine = DecodeEngine(
        cfg, scope=scope, slots=slots, max_len=max_len,
        prefill_buckets=[16, max_len], param_program=infer,
    ).start()
    try:
        c_warm = profiler.get_counters()

        # ---- parity: prompt lengths ----
        parity = {}
        for p in prompts:
            got = engine.generate(p).result(timeout=120)
            parity["len_%d" % len(p)] = got == oracle_out[tuple(p)]
        # EOS mid-stream: stop at (and including) a token the greedy
        # stream is known to emit a few steps in
        p = prompts[1]
        gen = oracle_out[tuple(p)][len(p):]
        eos = gen[3]
        stream = engine.generate(p, eos_id=eos)
        got = stream.tokens(timeout=120)
        parity["eos_midstream"] = (
            got == gen[: gen.index(eos) + 1]
            and stream.finish_reason == "eos"
        )
        # max-length truncation
        stream = engine.generate(p, max_new_tokens=5)
        parity["max_new_truncation"] = (
            stream.tokens(timeout=120) == gen[:5]
            and stream.finish_reason == "length"
        )
        # slot reuse after retirement: 2x slots sequential short requests
        # through the same pool, every one token-exact
        reuse_ok = True
        for i in range(2 * slots):
            p = prompts[i % len(prompts)]
            got = engine.generate(p, max_new_tokens=4).tokens(timeout=120)
            reuse_ok = reuse_ok and (
                got == oracle_out[tuple(p)][len(p):len(p) + 4]
            )
        parity["slot_reuse"] = reuse_ok
        report["parity"] = parity
        if not all(parity.values()):
            failures.append("parity: %r" % parity)

        # ---- churn + throughput: 8 concurrent streams, requests
        # admitted/retired mid-flight under the strict gate. The shared
        # 2-core driver box drifts under external load (same finding as
        # serving_load_probe.py), so load-robust estimators: the
        # baseline takes the BEST of repeated short rounds (load only
        # ever subtracts throughput), and decode takes the best
        # >=0.7 s sliding window over the live decode_tokens counter —
        # the steady-state rate with every prefill stall inside the
        # window counted, without the admission ramp / drain tail ----
        churn_errors = 0
        base_prompt = list(rs.randint(0, cfg.vocab_size, max_len - 40))
        baseline_tps = 0.0

        def baseline_round():
            t0 = time.perf_counter()
            oracle(base_prompt)  # 40 full-forward tokens
            return 40 / (time.perf_counter() - t0)

        def tokens_now():
            return profiler.get_counters().get("decode_tokens", 0)

        baseline_tps = max(baseline_tps, baseline_round())
        n_requests = 36 if fast else 48
        churn = []
        for i in range(n_requests):
            p = prompts[i % len(prompts)]
            # staggered lengths churn the retirement order
            churn.append(engine.generate(
                p, max_new_tokens=24 + 8 * (i % 4)
            ))
        samples = [(time.perf_counter(), tokens_now())]
        while not all(s.done for s in churn):
            time.sleep(0.05)
            samples.append((time.perf_counter(), tokens_now()))
        samples.append((time.perf_counter(), tokens_now()))
        decode_tokens_total = 0
        for s in churn:
            try:
                decode_tokens_total += len(s.tokens(timeout=300))
            except Exception:  # noqa: BLE001 - counted, fails the probe
                churn_errors += 1
        from bench import best_window_rate

        decode_tps = best_window_rate(samples, 0.7)
        baseline_tps = max(baseline_tps, baseline_round())
        c_end = profiler.get_counters()
        steady = (c_end.get("serving_steady_recompiles", 0)
                  - c_warm.get("serving_steady_recompiles", 0))
        speedup = decode_tps / baseline_tps
        report["throughput"] = {
            "streams": slots,
            "requests": n_requests,
            "decode_tokens": decode_tokens_total,
            "decode_tps": round(decode_tps, 1),
            "baseline_tps": round(baseline_tps, 1),
            "speedup": round(speedup, 2),
        }
        report["strict"] = {
            "steady_recompiles": int(steady),
            "churn_errors": churn_errors,
            "gate_armed": True,
        }
        if churn_errors:
            failures.append("%d churned streams failed" % churn_errors)
        if steady != 0:
            failures.append("%d steady-state recompiles" % steady)
        if speedup < 10.0:
            failures.append("speedup %.2f < 10x" % speedup)

        # ---- metrics on the exporter registry ----
        rendered = obs_registry.render_prometheus()
        gauges = obs_registry.gauge_values()
        need = ("decode_tokens", "decode_steps", "decode_prefills",
                "decode_requests", "decode_step_ms", "decode_prefill_ms",
                "serving_slot_admissions", "serving_slot_retirements")
        missing = [m for m in need if m not in rendered]
        for g in ("serving_slot_occupancy", "decode_queue_depth"):
            if g not in gauges:
                missing.append(g)
        report["metrics"] = {"missing": missing}
        if missing:
            failures.append("metrics missing: %r" % missing)
    finally:
        engine.stop()

    report["pass"] = not failures
    report["failures"] = failures
    if verbose:
        print(json.dumps(report, indent=1), file=sys.stderr)
    return report


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--fast", action="store_true",
                    help="tier-1 budget subset (< 15 s)")
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)
    report = run_probe(fast=args.fast, verbose=args.verbose)
    print("REPORT " + json.dumps(report, sort_keys=True), flush=True)
    print("PROBE PASS" if report["pass"]
          else "PROBE FAIL: %s" % "; ".join(report["failures"]))
    return 0 if report["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
