"""Closed-loop crash/hang/restart probe for the elastic supervisor.

Proves the acceptance properties of distributed/supervisor.py on a real
OS-process boundary, with a 2-process gang of deterministic trainers
(the ckpt_crash_probe workload, one checkpoint dir per rank):

  1. **Recovery** — a worker SIGKILLed at a random moment, or hung
     mid-step (deterministic chaos injection), is detected (exit poll /
     heartbeat watchdog), the WHOLE gang is torn down (SIGTERM grace ->
     SIGKILL) and restarted, and every rank resumes through
     ``CheckpointManager.restore_or_initialize`` to finish with params
     byte-identical to an uninterrupted run. No trial may strand a gang
     (every spawned pid is dead when the supervisor returns).
  2. **Bounded retry** — a fault that re-fires every attempt exhausts
     ``max_restarts`` and exits non-zero with a structured ``giveup``
     failure report instead of looping forever.
  3. **Elastic resize (shrink -> regrow)** — a 3-proc gang whose slot 2
     is lost to a chaos ``lose_rank`` slice preemption (exit 143 + down
     marker) must resume at world size 2 WITHOUT consuming the crash
     restart budget, survive a crash while degraded, grow back to world
     size 3 once the availability marker expires, and converge every
     rank to the fixed-gang reference digest exactly (identical-replica
     DP: per-replica math is world-size independent, so the digest
     tolerance is zero).
  4. **Observability** — MTTR (failure detection -> next gang start)
     is measured from the structured supervisor.log events and the
     ``dist_downtime_ms`` histogram, and reported for PERF.md, split by
     cause (crash/hang vs preemption); resize decisions are read back
     from ``gang_resize`` events and the merged ``gang_report.json``.

Modes::

    # full probe: N trials of random-moment SIGKILL + N injected hangs
    # (+ the deterministic shrink/regrow + budget checks)
    python tools/dist_crash_probe.py --trials 5

    # fast deterministic subset (tier-1 via tests/test_dist_supervisor.py):
    # 1 fixed-step kill trial + 1 fixed-step hang trial + the
    # shrink->regrow elasticity trial + the restart-budget-exhaustion
    # check (one trial pair covers both detection paths; extra pairs
    # only vary the injection step and cost ~20 s of tier-1 budget)
    python tools/dist_crash_probe.py --fast

The worker is this same file with ``--worker`` (rank from
PADDLE_TRAINER_ID): the ckpt_crash_probe MLP trained through
``fluid.trainer.MultiTrainer`` — which also exercises the real
heartbeat hook and the SIGTERM step-boundary final save."""

from __future__ import annotations

import argparse
import json
import os
import random
import signal
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")
for _p in (REPO, TOOLS):
    if _p not in sys.path:
        sys.path.insert(0, _p)

STEPS = 9
INTERVAL = 3
REPORT_SCHEMA_VERSION = 1


def _finalize_report(report):
    """Stamp the machine-readable REPORT line: schema_version, wall-clock
    ``ts`` (for humans / cross-host correlation) and monotonic
    ``ts_mono`` (interval math that survives NTP steps) — the same
    contract as supervisor.log events and observability snapshots."""
    report["schema_version"] = REPORT_SCHEMA_VERSION
    report["ts"] = time.time()
    report["ts_mono"] = time.monotonic()
    return report


# -- worker ------------------------------------------------------------------

def run_worker(args):
    import paddle_tpu.fluid as fluid
    from paddle_tpu import checkpoint
    from paddle_tpu.fluid.trainer import MultiTrainer

    from ckpt_crash_probe import _build, _StepDataset, _params_digest

    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    fluid.set_flags({"FLAGS_ckpt_save_interval_steps": args.interval})
    main, startup, loss = _build()
    exe = fluid.Executor(fluid.CPUPlace())
    mgr = checkpoint.CheckpointManager(
        os.path.join(args.dir, "rank_%d" % rank), keep_max=3
    )
    resumed = mgr.latest_step()
    print("RESUMED %s" % ("FRESH" if resumed is None else resumed), flush=True)
    dataset = _StepDataset(
        [main.global_block().var("x"), main.global_block().var("y")],
        args.steps,
    )
    # MultiTrainer wires everything under test: restore_or_initialize,
    # heartbeat beats per step, interval saves, chaos step faults, and
    # the SIGTERM step-boundary final save
    trained = MultiTrainer().train(
        exe, main, dataset, fetch_list=[loss], print_period=0,
        ckpt_manager=mgr, startup_program=startup,
    )
    if trained < args.steps or checkpoint.preemption_requested():
        mgr.close()
        print("PREEMPTED %d" % trained, flush=True)
        return 143
    mgr.save(args.steps - 1, main, async_=False)
    mgr.close()
    digest = _params_digest(main, fluid.global_scope())
    path = os.path.join(args.dir, "digest_%d.txt" % rank)
    tmp = "%s.tmp.%d" % (path, os.getpid())
    with open(tmp, "w") as f:
        f.write(digest)
    os.replace(tmp, path)
    print("FINAL %s" % digest, flush=True)
    return 0


# -- driver ------------------------------------------------------------------

def _worker_cmd(dirname, steps, interval):
    return [
        sys.executable, os.path.abspath(__file__), "--worker",
        "--dir", dirname, "--steps", str(steps),
        "--interval", str(interval),
    ]


def _gang(trial_dir, args, chaos_env=None, max_restarts=2,
          hb_timeout_s=30.0, interval=None, grace_s=1.0, nranks=None,
          min_world_size=None, max_preempt_restarts=None):
    """Build a supervised gang (default 2 ranks) rooted at trial_dir.
    Returns the Supervisor (not yet run). ``min_world_size`` arms
    elastic resize (shrink to survivors / regrow at restart)."""
    from paddle_tpu.distributed.supervisor import Supervisor, WorkerSpec

    os.makedirs(trial_dir, exist_ok=True)
    nranks = args.nranks if nranks is None else nranks
    specs = []
    for r in range(nranks):
        env = {
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "",  # single-device CPU per worker
            "PADDLE_TRAINER_ID": str(r),
            "PADDLE_TRAINERS_NUM": str(nranks),
        }
        env.update(chaos_env or {})
        specs.append(WorkerSpec(
            _worker_cmd(
                trial_dir, args.steps,
                args.interval if interval is None else interval,
            ),
            env=env,
            log_path=os.path.join(trial_dir, "workerlog.%d" % r),
            rank=r,
        ))
    return Supervisor(
        specs, workdir=trial_dir, max_restarts=max_restarts,
        heartbeat_timeout_s=hb_timeout_s,
        startup_grace_s=args.startup_grace_s,
        backoff_base_s=0.1, backoff_max_s=0.5,
        sigterm_grace_s=grace_s, poll_s=0.05,
        min_world_size=min_world_size,
        max_preempt_restarts=max_preempt_restarts,
    )


def _chaos_env(kind, victim, step, trial_dir, one_shot=True):
    env = {
        "FLAGS_chaos_%s" % kind: str(step),
        "FLAGS_chaos_target_rank": str(victim),
    }
    if one_shot:
        env["FLAGS_chaos_marker_dir"] = os.path.join(trial_dir, "markers")
    return env


def _check_trial(trial_dir, args, sup, ref, expect_restart=True):
    """Post-trial invariants: no stranded workers, every committed
    checkpoint verifies, both ranks' digests match the reference."""
    from paddle_tpu.distributed import supervisor as _sup

    from ckpt_crash_probe import _validate_dir

    assert sup.alive_pids() == {}, "stranded gang: %s" % sup.alive_pids()
    if expect_restart:
        assert sup.restarts_used >= 1, (
            "fault never triggered a restart (events: %s)"
            % _sup.load_events(trial_dir)
        )
    for r in range(args.nranks):
        _validate_dir(os.path.join(trial_dir, "rank_%d" % r))
        dpath = os.path.join(trial_dir, "digest_%d.txt" % r)
        assert os.path.isfile(dpath), "rank %d wrote no digest" % r
        with open(dpath) as f:
            digest = f.read().strip()
        assert digest == ref, (
            "rank %d diverged from the uninterrupted run\n  ref   %s\n"
            "  trial %s" % (r, ref, digest)
        )


def _mttr(trial_dirs):
    """[(detect_ts, next gang_start_ts)] deltas in ms across trials."""
    from paddle_tpu.distributed.supervisor import load_events

    downtimes = []
    for d in trial_dirs:
        detect_ts = None
        for e in load_events(d):
            if e["event"] in ("crash_detected", "hang_detected"):
                detect_ts = e["ts"]
            elif e["event"] == "gang_start" and detect_ts is not None:
                downtimes.append((e["ts"] - detect_ts) * 1000.0)
                detect_ts = None
    return downtimes


def _reference_digest(tmp, args):
    """Uninterrupted single-worker run -> param digest (both ranks train
    identical replicas of the same deterministic stream, so one
    reference covers the gang)."""
    import subprocess

    d = os.path.join(tmp, "ref")
    os.makedirs(d, exist_ok=True)
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu", "XLA_FLAGS": "", "PADDLE_TRAINER_ID": "0",
    })
    env.pop("PADDLE_TPU_HEARTBEAT_FILE", None)
    p = subprocess.run(
        _worker_cmd(d, args.steps, args.interval), env=env,
        capture_output=True, text=True, timeout=600, cwd=REPO,
    )
    assert p.returncode == 0, "reference run failed:\n%s%s" % (
        p.stdout, p.stderr
    )
    with open(os.path.join(d, "digest_0.txt")) as f:
        return f.read().strip()


def _kill_randomly(sup, rng, delay_range, kills):
    """Probe killer thread: SIGKILL one random alive worker after a
    random delay (the supervisor must see it and heal the gang)."""

    def _run():
        deadline = time.monotonic() + 60.0
        while not sup.alive_pids():
            if time.monotonic() > deadline:
                return
            time.sleep(0.05)
        time.sleep(rng.uniform(*delay_range))
        pids = sup.alive_pids()
        if not pids:
            return  # gang already finished: the kill missed
        rank = rng.choice(sorted(pids))
        try:
            os.kill(pids[rank], signal.SIGKILL)
            kills.append(rank)
        except OSError:
            pass

    t = threading.Thread(target=_run, daemon=True)
    t.start()
    return t


def _budget_exhaustion_check(tmp, args):
    """A fault that re-fires every attempt (no one-shot marker, no
    checkpoints to make progress behind) must exhaust max_restarts and
    exit non-zero with a structured failure report."""
    d = os.path.join(tmp, "budget")
    chaos = _chaos_env("crash_at_step", victim=0, step=2, trial_dir=d,
                       one_shot=False)
    # 1-rank gang: budget accounting is rank-count independent, and the
    # check stays cheap enough for the tier-1 wiring
    sup = _gang(d, args, chaos_env=chaos, max_restarts=1, interval=0,
                nranks=1)
    rc = sup.run()
    assert rc != 0, "budget exhaustion must exit non-zero"
    assert sup.alive_pids() == {}, "giveup stranded the gang"
    report = sup.failure_report
    assert report is not None, "no structured failure report"
    assert report["restarts_used"] == 1
    assert report["last_failure"]["kind"] == "crash"
    from paddle_tpu.distributed.supervisor import load_events

    giveups = [e for e in load_events(d) if e["event"] == "giveup"]
    assert giveups and giveups[-1]["max_restarts"] == 1
    print("budget exhaustion OK: rc=%d report=%s" % (rc, report),
          flush=True)


def _shrink_regrow_trial(tmp, args, ref):
    """Deterministic elasticity closed loop (ISSUE 6 acceptance) on one
    supervised 3-proc gang:

      attempt 0 (world 3): chaos ``lose_rank`` preempts slot 2 early —
        it writes its down marker (one planning round) and exits 143.
        The preemption must NOT consume the crash restart budget.
      attempt 1 (world 2): the plan shrinks around the downed slot
        (resize 3->2, ranks remapped contiguously); the probe SIGKILLs
        the degraded gang's rank 0 (one crash budget consumed).
      attempt 2 (world 3): the marker has expired, the gang grows back
        (resize 2->3); every rank resumes and converges to the
        fixed-gang reference digest exactly.

    Returns the shrink metrics for the REPORT."""
    from paddle_tpu.distributed.supervisor import load_events

    d = os.path.join(tmp, "shrink_regrow")
    chaos = {
        # slice preemption: slot 2 drops at step 1 (marker + exit 143),
        # down for exactly one planning round, one-shot across restarts
        "FLAGS_chaos_lose_rank": "2",
        "FLAGS_chaos_lose_rank_at_step": "1",
        "FLAGS_chaos_lose_rank_for": "1",
        "FLAGS_chaos_marker_dir": os.path.join(d, "markers"),
    }
    sup = _gang(
        d, args, chaos_env=chaos, max_restarts=1, nranks=3,
        min_world_size=2, max_preempt_restarts=3,
    )
    # the degraded-attempt crash is driven from HERE, gated on the
    # OBSERVED world size (a chaos step-count crash would race worker
    # compile skew: a fast rank could reach the armed step in attempt 0
    # before the slot-2 preemption is even detected). The kill forces
    # the restart boundary the regrow happens at.
    killed = []

    def _kill_degraded_rank0():
        deadline = time.monotonic() + 180.0
        while time.monotonic() < deadline:
            starts = [
                e for e in load_events(d) if e["event"] == "gang_start"
            ]
            if starts and starts[-1]["world_size"] == 2:
                pid = starts[-1]["rank_pids"].get("0")
                if pid and pid in sup.alive_pids().values():
                    # let the degraded gang get past spawn (the kill is
                    # valid at any point of attempt 1; the sleep just
                    # makes "crash while degraded" the common shape)
                    time.sleep(0.5)
                    try:
                        os.kill(pid, signal.SIGKILL)
                        killed.append(pid)
                    except OSError:
                        pass
                    return
            time.sleep(0.05)

    killer = threading.Thread(target=_kill_degraded_rank0, daemon=True)
    killer.start()
    rc = sup.run()
    killer.join(timeout=5)
    assert killed, "the degraded-attempt kill never fired"
    assert rc == 0, "shrink/regrow trial: supervisor rc %d" % rc
    assert sup.restarts_used == 1, (
        "preemption leaked into the crash budget: restarts_used=%d"
        % sup.restarts_used
    )
    assert sup.preempt_restarts_used == 1, (
        "expected exactly 1 preempt restart, got %d"
        % sup.preempt_restarts_used
    )
    events = load_events(d)
    resizes = [
        (e["from_world"], e["to_world"])
        for e in events if e["event"] == "gang_resize"
    ]
    assert resizes == [(3, 2), (2, 3)], (
        "resize sequence %s != [(3, 2), (2, 3)]" % (resizes,)
    )
    worlds = [
        e["world_size"] for e in events if e["event"] == "gang_start"
    ]
    assert worlds == [3, 2, 3], "gang_start world sizes %s" % (worlds,)
    # every gang_start is auditable: world size + rank->pid map
    for e in events:
        if e["event"] == "gang_start":
            assert len(e["rank_pids"]) == e["world_size"], e
    # digest convergence at full size, via the standard invariants
    shrink_args = argparse.Namespace(**vars(args))
    shrink_args.nranks = 3
    _check_trial(d, shrink_args, sup, ref)
    # preemption-detection -> respawn MTTR from the structured events
    mttr_preempt = []
    detect = None
    for e in events:
        if e["event"] in ("worker_preempted", "crash_detected"):
            detect = e["ts_mono"]
        elif e["event"] == "gang_start" and detect is not None:
            mttr_preempt.append((e["ts_mono"] - detect) * 1000.0)
            detect = None
    # the merged gang report must tell the same story post-hoc
    report_path = os.path.join(d, "gang_report.json")
    assert os.path.isfile(report_path), "no gang_report.json"
    with open(report_path) as f:
        gang_report = json.load(f)
    assert gang_report["resizes"] == 2, gang_report["resizes"]
    assert gang_report["preemptions"] == 1
    assert [a["world_size"] for a in gang_report["attempts"]] == [3, 2, 3]
    assert gang_report["world_size_final"] == 3
    print(
        "shrink/regrow trial OK: world 3 -> 2 -> 3, crash budget 1/1, "
        "preempt budget 1/3, all digests == reference", flush=True,
    )
    return {
        "resizes": resizes,
        "world_sizes": worlds,
        "restarts_used": sup.restarts_used,
        "preempt_restarts_used": sup.preempt_restarts_used,
        "mttr_resize_ms": mttr_preempt,
        "digest_match": True,  # asserted exact above (tolerance: 0)
    }


def run_probe(args):
    import tempfile

    tmp = args.workdir or tempfile.mkdtemp(prefix="dist_crash_probe_")
    rng = random.Random(args.seed)
    t0 = time.time()
    ref = _reference_digest(tmp, args)
    ref_s = time.time() - t0
    print("reference digest %s (%.1fs)" % (ref[:16], ref_s), flush=True)
    kill_window = (0.5, max(2.0, ref_s * 0.9))

    trial_dirs = []
    kills = hangs = 0
    for trial in range(args.trials):
        # -- SIGKILL trial --
        d = os.path.join(tmp, "kill_%02d" % trial)
        trial_dirs.append(d)
        if args.fast:
            # deterministic "random moment": fixed victim + step via chaos
            step = [args.steps // 3, (2 * args.steps) // 3][trial % 2]
            sup = _gang(d, args, chaos_env=_chaos_env(
                "crash_at_step", victim=trial % args.nranks, step=step,
                trial_dir=d,
            ))
            rc = sup.run()
        else:
            while True:
                sup = _gang(d, args)
                got = []
                _kill_randomly(sup, rng, kill_window, got)
                rc = sup.run()
                if got or sup.restarts_used:
                    break  # a kill landed (or something else killed one)
                # gang beat the timer: clean dir and retry with a kill
                # window biased early so it MUST land
                import shutil

                shutil.rmtree(d, ignore_errors=True)
                kill_window = (0.5, max(2.0, kill_window[1] * 0.6))
        assert rc == 0, "kill trial %d: supervisor rc %d" % (trial, rc)
        _check_trial(d, args, sup, ref)
        kills += 1
        print("kill trial %d OK (restarts=%d)" % (trial, sup.restarts_used),
              flush=True)

        # -- hang trial --
        d = os.path.join(tmp, "hang_%02d" % trial)
        trial_dirs.append(d)
        if args.fast:
            victim = (trial + 1) % args.nranks
            step = [args.steps // 3, (2 * args.steps) // 3][trial % 2]
        else:
            victim = rng.randrange(args.nranks)
            step = rng.randrange(args.interval, args.steps - 1)
        sup = _gang(
            d, args,
            chaos_env=_chaos_env("hang_at_step", victim, step, d),
            hb_timeout_s=args.hang_timeout_s,
        )
        rc = sup.run()
        assert rc == 0, "hang trial %d: supervisor rc %d" % (trial, rc)
        _check_trial(d, args, sup, ref)
        hangs += 1
        print("hang trial %d OK (restarts=%d)" % (trial, sup.restarts_used),
              flush=True)

    shrink = _shrink_regrow_trial(tmp, args, ref)
    _budget_exhaustion_check(tmp, args)

    from paddle_tpu.fluid import profiler

    downtimes = _mttr(trial_dirs)
    report = {
        "trials_kill": kills,
        "trials_hang": hangs,
        "trials_shrink": 1,
        "restarts": len(downtimes),
        "mttr_ms": {
            "mean": sum(downtimes) / len(downtimes) if downtimes else 0.0,
            "max": max(downtimes) if downtimes else 0.0,
            "min": min(downtimes) if downtimes else 0.0,
        },
        "shrink_regrow": shrink,
        "dist_downtime_ms": profiler.summarize_histogram("dist_downtime_ms"),
        "dist_restarts": profiler.get_counter("dist_restarts"),
        "dist_hang_kills": profiler.get_counter("dist_hang_kills"),
        "dist_resizes": profiler.get_counter("dist_resizes"),
        "wall_s": time.time() - t0,
    }
    _finalize_report(report)
    print("REPORT " + json.dumps(report, sort_keys=True), flush=True)
    print(
        "PROBE PASS: %d kill + %d hang trials + shrink/regrow "
        "(world 3 -> 2 -> 3), %d gang restarts, 0 stranded gangs, all "
        "resumed digests == reference; MTTR mean %.0f ms / max %.0f ms "
        "(%.1fs)"
        % (kills, hangs, report["restarts"], report["mttr_ms"]["mean"],
           report["mttr_ms"]["max"], report["wall_s"])
    )
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--worker", action="store_true")
    ap.add_argument("--dir", type=str, default=None)
    ap.add_argument("--steps", type=int, default=STEPS)
    ap.add_argument("--interval", type=int, default=INTERVAL)
    ap.add_argument("--nranks", type=int, default=2)
    ap.add_argument("--trials", type=int, default=5,
                    help="kill trials + hang trials per unit")
    ap.add_argument("--fast", action="store_true",
                    help="deterministic 2+2-trial subset for tier-1")
    ap.add_argument("--seed", type=int, default=20260803)
    ap.add_argument("--hang_timeout_s", type=float, default=2.0,
                    help="heartbeat watchdog threshold for hang trials")
    ap.add_argument("--startup_grace_s", type=float, default=120.0)
    ap.add_argument("--workdir", type=str, default=None)
    args = ap.parse_args(argv)
    if args.worker:
        assert args.dir, "--worker needs --dir"
        return run_worker(args)
    if args.fast:
        args.trials = 1
    return run_probe(args)


if __name__ == "__main__":
    sys.exit(main())
