"""Trace-driven fleet simulator CLI (paddle_tpu/serving/sim front end).

Replays a workload — recorded flight-recorder journeys, a whole
controller obs tree, or a synthetic shape — through the REAL fleet
control-plane classes (autoscaler policy, gateway admission, router
pick) on a virtual clock: a full day of traffic in seconds, no
subprocesses, deterministic under ``--seed``.

Workload sources (exactly one):

    --journeys FILE        journey JSONL (observability.flight codec)
    --obs-root DIR         every flight dump under a fleet obs tree
    --synthetic KIND       flat | diurnal | skew | flash

What-if knobs: ``--scale 100`` replays the recorded day at 100x
volume; ``--policy slo`` swaps in the SLO-driven autoscaler;
``--slots/--min-replicas/--max-replicas`` reshape the simulated fleet.

``--compare WORKDIR`` calibrates the simulator against the live run
that produced the recording: it reads ``fleet_report.json`` (replica
trajectory, sheds) + the flight records under the workdir's obs tree,
replays the same journeys, and prints live vs predicted deltas — the
table PERF.md banks.

Examples::

    JAX_PLATFORMS=cpu python tools/fleet_sim.py \
        --synthetic flash --duration 600 --rps 4 --policy slo

    JAX_PLATFORMS=cpu python tools/fleet_sim.py \
        --obs-root /tmp/fleet/obs --scale 10 --out sim_report.json

Prints one REPORT json line; exit 0 unless the workload is empty or
(under ``--compare``) a calibration bar is missed.
"""

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# calibration bars for --compare (fractional error vs live)
CALIBRATION_TOL = 0.20


def _load_obs_journeys(obs_root):
    from paddle_tpu.observability import aggregate, flight

    return [flight.to_journey(dict(rec, process=label))
            for label, rec in aggregate.read_flight_records(obs_root)]


def _completed_journeys(journeys):
    """The rows that describe a full served request (have a duration)."""
    return [j for j in journeys if j.get("ms") is not None
            and j.get("status") in (None, 200)]


def build_workload(args):
    from paddle_tpu.serving import sim

    if args.synthetic:
        wl = sim.synthetic_workload(
            args.synthetic, duration_s=args.duration, rps=args.rps,
            seed=args.seed, batch_fraction=args.batch_fraction,
        )
        return wl, None
    if args.journeys:
        journeys = sim.load_journeys(args.journeys)
    else:
        journeys = _load_obs_journeys(args.obs_root)
    # the OFFERED load includes requests the live run shed (they have an
    # arrival stamp but no duration) — dropping them would make the sim
    # under-predict sheds; only the service-time FIT is completed-only.
    journeys = [j for j in journeys if j.get("ts") is not None]
    wl = sim.from_journeys(journeys, scale=args.scale, seed=args.seed)
    return wl, journeys


def run_sim(args, workload, journeys):
    from paddle_tpu.serving import sim

    fit_rows = _completed_journeys(journeys or [])
    model = (sim.ServiceModel.fit(fit_rows) if fit_rows
             else sim.ServiceModel())
    policy = sim.make_policy(args.policy,
                             min_replicas=args.min_replicas,
                             max_replicas=args.max_replicas)
    fs = sim.FleetSim(
        workload, model=model, policy=policy, seed=args.seed,
        slots=args.slots, queue_depth=args.queue_depth,
        min_replicas=args.min_replicas, max_replicas=args.max_replicas,
        scale_interval_s=args.scale_interval,
        rate_rps=args.rate_rps, burst=args.burst,
    )
    return fs.run()


def _pct_err(live, pred):
    if live is None or pred is None:
        return None
    live = float(live)
    if live == 0:
        return 0.0 if float(pred) == 0 else float("inf")
    return abs(float(pred) - live) / abs(live)


def compare_live(args, report):
    """Live workdir vs sim prediction: the calibration table."""
    from paddle_tpu.observability import aggregate, registry

    fr_path = os.path.join(args.compare, "fleet_report.json")
    with open(fr_path) as f:
        live = json.load(f)
    obs_root = os.path.join(args.compare, "obs")
    journeys = _completed_journeys(_load_obs_journeys(obs_root))
    # TTFT of a non-streaming (tokens-free) journey IS its duration —
    # the request's single response is its first token
    live_ttft = registry.percentiles(
        [j["ttft_ms"] if j.get("ttft_ms") is not None else j["ms"]
         for j in journeys
         if j.get("ttft_ms") is not None
         or (j.get("ms") is not None and not j.get("tokens"))]
    )
    # replica trajectory: the autoscaler's own scale decisions when the
    # report has them (a blue-green rollout transiently doubles READY
    # replicas without the policy asking for it); timeline otherwise
    ev = live.get("scale_events") or []
    if ev:
        live_max = max([e.get("to_replicas") or 0 for e in ev]
                       + [e.get("from_replicas") or 0 for e in ev])
    else:
        counts = [e.get("ready_replicas")
                  for e in live.get("replica_timeline", [])]
        live_max = max([c for c in counts if c is not None] or [0])
    live_shed = sum(
        int(j.get("status") == 429 or j.get("reason") in
            ("ratelimit", "quota", "overload"))
        for j in _load_obs_journeys(obs_root)
    )
    sim_max = max([n for _t, n in report["replica_trajectory"]] or [0])
    sim_shed = report["requests"]["shed"]
    sim_ttft = None
    for cls in ("interactive", "batch"):
        p = report["classes"][cls]["ttft_ms"].get("p95")
        if p is not None:
            sim_ttft = p if sim_ttft is None else max(sim_ttft, p)
    rows = [
        ("max_replicas", live_max, sim_max),
        ("shed_requests", live_shed, sim_shed),
        ("p95_ttft_ms", live_ttft.get("p95"), sim_ttft),
    ]
    table, failures = [], []
    for name, lv, pv in rows:
        err = _pct_err(lv, pv)
        table.append({"metric": name, "live": lv, "sim": pv,
                      "err": None if err is None else round(err, 3)})
        if err is not None and err > CALIBRATION_TOL:
            failures.append("%s: live=%s sim=%s err=%.0f%%"
                            % (name, lv, pv, err * 100))
    return {"table": table, "tolerance": CALIBRATION_TOL,
            "failures": failures}


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--journeys", help="journey JSONL file")
    src.add_argument("--obs-root", help="fleet obs tree with flight dumps")
    src.add_argument("--synthetic",
                     choices=["flat", "diurnal", "skew", "flash"])
    ap.add_argument("--duration", type=float, default=600.0,
                    help="synthetic duration (virtual seconds)")
    ap.add_argument("--rps", type=float, default=2.0,
                    help="synthetic nominal request rate")
    ap.add_argument("--batch-fraction", type=float, default=0.3)
    ap.add_argument("--scale", type=float, default=1.0,
                    help="replay the recorded day at Nx volume")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--policy", default=None,
                    help="streak | slo (default FLAGS_fleet_policy)")
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--queue-depth", type=int, default=64)
    ap.add_argument("--rate-rps", type=float, default=0.0,
                    help="per-replica admission rate limit (0 = off); "
                         "match the live FLAGS_gateway_rate_limit_rps "
                         "when calibrating")
    ap.add_argument("--burst", type=int, default=1,
                    help="per-replica admission burst capacity")
    ap.add_argument("--min-replicas", type=int, default=1)
    ap.add_argument("--max-replicas", type=int, default=4)
    ap.add_argument("--scale-interval", type=float, default=2.0,
                    help="virtual seconds between policy ticks")
    ap.add_argument("--out", help="write the full report json here")
    ap.add_argument("--compare",
                    help="live fleet workdir to calibrate against")
    args = ap.parse_args(argv)

    workload, journeys = build_workload(args)
    if not workload:
        print("REPORT " + json.dumps({"error": "empty workload"}))
        return 1
    report = run_sim(args, workload, journeys)

    from paddle_tpu.fluid import profiler as _profiler

    _profiler.bump_counter("sim_requests_replayed",
                           report["requests"]["injected"])
    _profiler.bump_counter("sim_requests_shed", report["requests"]["shed"])
    _profiler.bump_counter("sim_preemptions", report["preemptions"])

    rc = 0
    if args.compare:
        report["calibration"] = compare_live(args, report)
        if report["calibration"]["failures"]:
            rc = 1
    if args.out:
        tmp = args.out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
        os.replace(tmp, args.out)
    line = {
        "requests": report["requests"],
        "preemptions": report["preemptions"],
        "policy": report["policy"],
        "final_target": report["final_target"],
        "virtual_s": report["virtual_s"],
        "interactive_p95_ttft_ms":
            report["classes"]["interactive"]["ttft_ms"].get("p95"),
        "batch_p95_ttft_ms":
            report["classes"]["batch"]["ttft_ms"].get("p95"),
    }
    if args.compare:
        line["calibration"] = report["calibration"]
    print("REPORT " + json.dumps(line, sort_keys=True))
    print("SIM PASS" if rc == 0 else "SIM FAIL")
    return rc


if __name__ == "__main__":
    sys.exit(main())
