"""CPU-runnable closed-loop probe for fleet-wide distributed tracing.

Drives a REAL serving fleet — FleetController + Router fronting two GPT
decode replicas (seeded identical params), strict compile gate armed —
with concurrent ``/v1/infer`` + ``/v1/generate`` traffic while the
chaos harness SIGKILLs one replica mid-stream, then pulls and merges
every process's ``/trace`` and asserts the ISSUE 15 bars:

- ROUND-TRIP: every response carries ``X-Trace-Id``; every SSE done
  event's ``trace_id`` matches its stream's header; the router's and
  gateways' access logs carry the same ids (with backend / retries /
  failover counts on the router lines);
- ONE TREE PER REQUEST: after clock alignment the merged fleet trace
  resolves every driven request to a single CONNECTED cross-process
  span tree — the router span time-contains the gateway span contains
  the engine spans (zero containment violations within slack);
- FAILOVER SEAM: the chaos-killed generation's tree holds BOTH
  replicas' segments under ONE trace_id (the victim's engine spans
  arrive via its black-box dump; orphans attach to the synthetic
  process root, never dropped) plus the router's ``generate_failover``
  instant event naming from/to backends;
- FLIGHT RECORDER: ``fleet_report.json`` merges every process's flight
  dumps into a slowest-requests table whose rows carry trace ids;
- OVERHEAD: tracer + propagation cost, measured as (span cost inside a
  trace_scope x spans-per-request + traceparent parse/format), stays
  under 2% of the measured request p50 (the PR 5 gate), with 0
  steady-state recompiles fleet-wide while tracing is armed.

Run directly (prints one REPORT json line + PROBE PASS/FAIL)::

    JAX_PLATFORMS=cpu python tools/trace_probe.py --fast

or via tests/test_fleet_trace.py (tier-1, subprocess). Overhead-only
misses are prefixed "throughput" so the shared retry policy can re-run
a probe squeezed by a loaded box without retrying correctness.
"""

import argparse
import json
import os
import sys
import tempfile
import threading
import time
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from gateway_probe import _post, _percentile  # noqa: E402
from fleet_probe import _sse_collect, build_model  # noqa: E402

REPORT_SCHEMA_VERSION = 1

# cross-process containment slack: same-host wall clocks are identical,
# so the only noise is anchor sampling + NTP slew over the probe's run
_SLACK_S = 0.15


def _read_jsonl(path):
    out = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
    except OSError:
        pass
    return out


def _measure_overhead(report, failures, request_p50_ms, spans_per_request):
    """The PR 5 gate, extended with propagation: span cost INSIDE an
    armed trace_scope (ids minted + chained) x the spans a request
    actually opens, plus one traceparent parse+format per hop, as a
    percentage of the measured request p50."""
    from paddle_tpu.observability import trace

    n = 20000
    tid = trace.new_trace_id()
    with trace.trace_scope(tid, "ab" * 8):
        t0 = time.perf_counter()
        for _ in range(n):
            with trace.span("overhead_bench", cat="bench"):
                pass
        span_us = (time.perf_counter() - t0) / n * 1e6
    tp = trace.format_traceparent(tid, "cd" * 8)
    t0 = time.perf_counter()
    for _ in range(n):
        trace.parse_traceparent(tp)
        trace.format_traceparent(tid, "cd" * 8)
    prop_us = (time.perf_counter() - t0) / n * 1e6
    per_request_us = span_us * spans_per_request + prop_us
    pct = per_request_us / max(request_p50_ms * 1e3, 1e-9) * 100.0
    report["overhead"] = {
        "span_cost_us": round(span_us, 3),
        "propagation_cost_us": round(prop_us, 3),
        "spans_per_request": round(spans_per_request, 1),
        "request_p50_ms": round(request_p50_ms, 3),
        "overhead_pct": round(pct, 4),
    }
    if pct >= 2.0:
        failures.append(
            "throughput: tracer+propagation overhead %.3f%% >= 2%% "
            "(%.2fus/span x %.1f spans + %.2fus propagation vs "
            "p50 %.1fms)"
            % (pct, span_us, spans_per_request, prop_us, request_p50_ms)
        )


def run_probe(fast=True, verbose=False):
    import numpy as np

    from paddle_tpu.fluid import flags as _flags
    from paddle_tpu.observability import exporter as _obs_exporter
    from paddle_tpu.observability import fleet_trace
    from paddle_tpu.observability import registry as _reg
    from paddle_tpu.serving import fleet as fleet_mod
    from paddle_tpu.serving.fleet import FleetController

    report = {"schema_version": REPORT_SCHEMA_VERSION, "fast": bool(fast)}
    failures = []
    tmp = tempfile.mkdtemp(prefix="trace_probe_")
    workdir = os.path.join(tmp, "fleet")
    model_dir = os.path.join(tmp, "export_v1")
    xd = build_model(model_dir, seed=1)

    spec = {"seed": 17, "vocab_size": 97, "hidden_size": 32,
            "num_layers": 2, "num_heads": 2, "intermediate_size": 64,
            "max_len": 48, "slots": 8, "prefill_buckets": [8, 16, 48]}
    router_log = os.path.join(tmp, "router_access.jsonl")
    gateway_log = os.path.join(tmp, "gateway_access.jsonl")
    ctrl_obs = os.path.join(workdir, "obs", "controller")

    # the CONTROLLER process (the router lives here) arms its own
    # exporter: /trace for the merge pull, obs_dir for its black box
    _flags.set_flags({
        "FLAGS_obs_http_port": 0,
        "FLAGS_obs_dir": ctrl_obs,
        "FLAGS_router_access_log": router_log,
        "FLAGS_router_generate_retries": 2,
        "FLAGS_router_health_interval_s": 0.25,
    })
    gen_env = {
        "FLAGS_serving_strict_compiles": "1",
        "FLAGS_decode_prefill_chunk": "8",
        "FLAGS_decode_prefix_cache_mb": "2",
        "FLAGS_decode_prefix_block": "8",
        # replica 0 SIGKILLs itself after its 6th stream token — the
        # mid-stream chaos seam the merged trace must survive
        "FLAGS_chaos_die_after_tokens": "6",
        "FLAGS_chaos_die_replica": "0",
        "FLAGS_obs_snapshot_interval_s": "1.0",
        # both replicas append whole lines to one shared gateway log
        # (O_APPEND, line-atomic at this size)
        "FLAGS_gateway_access_log": gateway_log,
    }
    ctrl = FleetController(
        model_dir=model_dir, workdir=workdir, replicas=2,
        replica_env=gen_env, autoscale=False, seed=0,
        replica_args=["--gpt-decode", json.dumps(spec)],
    )
    t_boot = time.monotonic()
    ctrl.start()
    try:
        ctrl.wait_ready(timeout=180 if fast else 300)
        report["boot_s"] = round(time.monotonic() - t_boot, 1)
        gen_url = ctrl.router.url("/v1/generate")
        inf_url = ctrl.router.url("/v1/infer")

        # ---- concurrent traffic: streams + infer, one chaos kill -----
        from paddle_tpu.serving.gateway import encode_tensor

        rs = np.random.RandomState(23)
        streams = []
        for i in range(4):
            prompt = [int(t) for t in rs.randint(0, spec["vocab_size"],
                                                 10 + i)]
            knobs = ({} if i % 2 == 0 else
                     {"temperature": 1.3, "top_k": 20, "seed": 100 + i})
            streams.append({"prompt": prompt, "knobs": knobs})
        gen_results = [None] * len(streams)
        inf_results = [None] * 8

        def gen_client(i):
            s = streams[i]
            body = dict(prompt_ids=s["prompt"], max_new_tokens=10,
                        deadline_ms=60000, **s["knobs"])
            try:
                st, events, comments, _gaps, hdrs = _sse_collect(
                    gen_url, body, timeout=90)
                gen_results[i] = {"status": st, "events": events,
                                  "comments": comments, "headers": hdrs}
            except Exception as e:  # noqa: BLE001 - surfaced below
                gen_results[i] = {"error": repr(e)}

        inf_body = {"inputs": [encode_tensor(xd)], "deadline_ms": 30000}

        def inf_client(i):
            try:
                st, b, h = _post(inf_url, inf_body, timeout=30)
                inf_results[i] = {"status": st, "body": b, "headers": h}
            except Exception as e:  # noqa: BLE001
                inf_results[i] = {"error": repr(e)}

        ths = [threading.Thread(target=gen_client, args=(i,))
               for i in range(len(streams))]
        ths += [threading.Thread(target=inf_client, args=(i,))
                for i in range(len(inf_results))]
        for t in ths:
            t.start()
        for t in ths:
            t.join()

        # ---- round-trip: headers == SSE events == access logs --------
        gen_traces, failovers = [], 0
        for i, res in enumerate(gen_results):
            if res is None or "error" in res:
                failures.append("gen stream %d transport error: %r"
                                % (i, res))
                continue
            hdr_tid = res["headers"].get("X-Trace-Id")
            done = [e for e in res["events"] if e.get("done")]
            errs = [e for e in res["events"] if "error" in e]
            if errs:
                failures.append("gen stream %d in-band error: %r"
                                % (i, errs[:1]))
            if not hdr_tid:
                failures.append("gen stream %d missing X-Trace-Id" % i)
                continue
            if not done or done[0].get("trace_id") != hdr_tid:
                failures.append(
                    "gen stream %d trace id did not round-trip through "
                    "the SSE done event: header=%r done=%r"
                    % (i, hdr_tid, done[:1])
                )
            gen_traces.append(hdr_tid)
            if res["comments"]:
                failovers += 1
        inf_traces = []
        for i, res in enumerate(inf_results):
            if res is None or "error" in res or res["status"] != 200:
                failures.append("infer %d failed: %r" % (i, res))
                continue
            tid = res["headers"].get("X-Trace-Id")
            if not tid:
                failures.append("infer %d missing X-Trace-Id" % i)
                continue
            inf_traces.append(tid)
        if failovers == 0:
            failures.append(
                "no stream failed over (the chaos kill never hit a "
                "pinned stream)"
            )
        report["traffic"] = {
            "streams": len(streams), "failovers_seen": failovers,
            "infer_ok": len(inf_traces),
        }

        # the handler writes its log line AFTER the client saw the
        # response end — give the lines a moment to land
        want = set(gen_traces + inf_traces)
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            router_lines = _read_jsonl(router_log)
            logged = {r.get("trace_id") for r in router_lines}
            if want <= logged:
                break
            time.sleep(0.1)
        missing = [t for t in gen_traces + inf_traces if t not in logged]
        if missing:
            failures.append(
                "router access log missing %d/%d trace ids"
                % (len(missing), len(gen_traces) + len(inf_traces))
            )
        if not any(r.get("backend") for r in router_lines):
            failures.append("router access log lines carry no backend")
        fo_logged = sum(r.get("failovers", 0) for r in router_lines)
        if failovers and not fo_logged:
            failures.append("router access log counted no failovers")
        gw_lines = _read_jsonl(gateway_log)
        gw_logged = {r.get("trace_id") for r in gw_lines}
        gw_missing = [t for t in inf_traces if t not in gw_logged]
        if gw_missing:
            failures.append(
                "gateway access log missing %d infer trace ids"
                % len(gw_missing)
            )

        # ---- wait out crash detection + pool recovery ----------------
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if any(e.get("event") == "replica_crash"
                   for e in fleet_mod.load_events(workdir)):
                break
            time.sleep(0.1)
        else:
            failures.append("no replica_crash event after the kill")
        try:
            ctrl.wait_ready(timeout=120)
        except Exception as e:  # noqa: BLE001
            failures.append("pool never recovered: %r" % e)

        # ---- direct-request p50 (overhead denominator) ---------------
        live = [i for i in ctrl.replica_info() if i["state"] == "ready"]
        direct = []
        if live:
            durl = "http://127.0.0.1:%d/v1/infer" % live[0]["gateway_port"]
            for _ in range(20):
                t0 = time.perf_counter()
                st, _b, _h = _post(durl, inf_body, timeout=30)
                if st == 200:
                    direct.append((time.perf_counter() - t0) * 1e3)
                time.sleep(0.01)
        p50 = _percentile(direct, 50) if direct else 0.0

        # ---- pull + merge the fleet trace ----------------------------
        exp = _obs_exporter.global_exporter()
        pulls = []
        if exp is None or exp.port is None:
            failures.append("controller exporter never started")
        else:
            pulls.append(fleet_trace.pull_trace(
                "http://127.0.0.1:%d" % exp.port, label="controller"))
        pulled_live = set()
        for info in ctrl.replica_info():
            port = info.get("metrics_port")
            if info["state"] != "ready" or not port:
                continue
            try:
                pulls.append(fleet_trace.pull_trace(
                    "http://127.0.0.1:%d" % port,
                    label="replica_%s" % info["id"]))
                pulled_live.add(int(info["id"]))
            except Exception as e:  # noqa: BLE001
                failures.append("live pull of replica %s failed: %r"
                                % (info["id"], e))
        # dead (and any unpulled) processes merge from their black-box
        # dumps — the chaos victim's segment lives ONLY there
        for label, path in fleet_trace.find_trace_dumps(
                os.path.join(workdir, "obs")):
            rid = label.split("/")[0].replace("replica_", "")
            if rid.isdigit() and int(rid) in pulled_live:
                continue
            if label.startswith("controller"):
                continue
            pulls.append(fleet_trace.load_trace_dump(path, label=label))
        t_merge = time.perf_counter()
        merged = fleet_trace.merge(pulls)
        merge_ms = (time.perf_counter() - t_merge) * 1e3
        out_path = os.path.join(tmp, "fleet_trace.json")
        fleet_trace.write_merged(out_path, merged)
        trees = merged["trees"]

        # every driven request: ONE connected cross-process tree whose
        # parents time-contain their children after alignment
        connected = contained = linked2 = 0
        for tid in gen_traces + inf_traces:
            tree = trees.get(tid)
            if tree is None:
                failures.append("trace %s absent from the merge" % tid)
                continue
            if not tree["connected"]:
                failures.append(
                    "trace %s is not a single connected tree "
                    "(root=%r, %d spans, %d orphans)"
                    % (tid, tree["root"], len(tree["nodes"]),
                       tree["orphans"])
                )
            else:
                connected += 1
            if len(tree["processes"]) >= 2:
                linked2 += 1
            viol = fleet_trace.containment_violations(tree,
                                                      slack_s=_SLACK_S)
            if viol:
                failures.append(
                    "trace %s containment violations after alignment: "
                    "%r" % (tid, viol[:3])
                )
            else:
                contained += 1
        # the failover generations: the router's instant event naming
        # the seam in every one, and — for generations killed truly
        # MID-stream (tokens already emitted on the victim, i.e. the
        # instant's resume_at > 0; a stream that died while still
        # prefilling has no victim-side spans to show by construction)
        # — BOTH replicas' segments under the one trace_id
        fo_traces = [
            t for t in gen_traces
            if trees.get(t) is not None
            and any(i["name"] == "generate_failover"
                    for i in trees[t]["instants"])
        ]
        if failovers and not fo_traces:
            failures.append(
                "no generate_failover instant event in any merged tree"
            )
        midstream = 0
        for t in fo_traces:
            tree = trees[t]
            inst = [i for i in tree["instants"]
                    if i["name"] == "generate_failover"][0]
            if not (inst["args"].get("from_backend")
                    and inst["args"].get("to_backend")):
                failures.append(
                    "failover instant lacks from/to backends: %r"
                    % inst["args"]
                )
            if not inst["args"].get("resume_at"):
                continue
            midstream += 1
            replica_procs = {p for p in tree["processes"]
                             if "replica" in str(p)}
            if len(replica_procs) < 2:
                failures.append(
                    "mid-stream failover trace %s holds %d replica "
                    "segments, wanted both (processes=%r)"
                    % (t, len(replica_procs), sorted(tree["processes"]))
                )
        if failovers and not midstream:
            failures.append(
                "no failover happened truly mid-stream (resume_at > 0)"
            )
        spans_per_req = (
            sum(len(trees[t]["nodes"]) + len(trees[t]["ticks"])
                for t in inf_traces if t in trees)
            / max(len(inf_traces), 1)
        )
        report["merge"] = {
            "processes": len(pulls),
            "traces": len(trees),
            "driven": len(gen_traces) + len(inf_traces),
            "connected": connected,
            "contained": contained,
            "cross_process": linked2,
            "failover_traces": len(fo_traces),
            "midstream_failovers": midstream,
            "orphan_spans": merged["orphan_spans"],
            "requests_linked": merged["requests_linked"],
            "merged_spans": len(merged["spans"]),
            "merge_ms": round(merge_ms, 1),
        }

        # ---- strict gate with tracing armed --------------------------
        steady = scraped = 0
        for info in ctrl.replica_info():
            port = info.get("metrics_port")
            if not port or info["state"] != "ready":
                continue
            try:
                with urllib.request.urlopen(
                    "http://127.0.0.1:%d/metrics" % port, timeout=5
                ) as r:
                    parsed = _reg.parse_prometheus(r.read().decode("utf-8"))
                scraped += 1
                steady += int(parsed.get(
                    ("serving_steady_recompiles", ""), 0))
            except Exception as e:  # noqa: BLE001
                failures.append("metrics scrape failed: %r" % e)
        if not scraped:
            failures.append("no replica metrics scraped")
        if steady != 0:
            failures.append(
                "%d steady-state recompiles with tracing armed" % steady
            )
        report["strict"] = {"replicas_scraped": scraped,
                            "steady_recompiles": steady}

        # ---- overhead gate -------------------------------------------
        if not direct:
            failures.append("no direct requests for the overhead "
                            "denominator")
        else:
            _measure_overhead(report, failures, p50,
                              max(spans_per_req, 1.0))
    finally:
        try:
            ctrl.stop()
        except Exception as e:  # noqa: BLE001
            failures.append("controller stop failed: %r" % e)

    # ---- flight recorder -> slowest-requests table -------------------
    try:
        with open(os.path.join(workdir, "fleet_report.json")) as f:
            fr = json.load(f)
        slowest = fr.get("slowest_requests") or []
        report["flight"] = {
            "slowest_rows": len(slowest),
            "with_trace_id": sum(1 for r in slowest
                                 if r.get("trace_id")),
        }
        if not slowest:
            failures.append("fleet_report has no slowest_requests table")
        elif not any(r.get("trace_id") for r in slowest):
            failures.append("slowest_requests rows carry no trace ids")
    except (OSError, ValueError) as e:
        failures.append("fleet_report.json unreadable: %r" % e)

    import shutil

    shutil.rmtree(tmp, ignore_errors=True)
    report["pass"] = not failures
    report["failures"] = failures
    if verbose:
        print(json.dumps(report, indent=1), file=sys.stderr)
    return report


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--fast", action="store_true",
                    help="tier-1 budget subset")
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)
    report = run_probe(fast=args.fast, verbose=args.verbose)
    print("REPORT " + json.dumps(report, sort_keys=True), flush=True)
    print("PROBE PASS" if report["pass"]
          else "PROBE FAIL: %s" % "; ".join(report["failures"]))
    return 0 if report["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
